#!/usr/bin/env python
"""Opportunistic TPU-window experiments, run AFTER bench.py has landed its
number (tools/tpu_bench_loop.sh exits on success).  Each experiment is
independently guarded — one failure (OOM, tunnel drop) never kills the
rest — and every result appends a JSON line to the output file as soon as
it is measured, so a mid-run tunnel drop keeps everything already done.

Experiments (why):
- bert batch ladder 32/64: the dry-compile pass flagged b64 s128 as
  borderline on HBM — measure which is actually faster per chip.
- resnet50 batch 64/128: batch scaling headroom on the MXU.
- gpt2 flash vs composite attention at s512: the Pallas kernel's
  measured win on real hardware (the whole point of ops/pallas/).
- flash-attention op microbench fwd+bwd at s512/s1024 vs composite.

Usage: python tools/tpu_window.py [--out TPU_WINDOW.jsonl] [--budget 1200]
"""
import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _append(path, rec):
    rec["ts"] = round(time.time(), 1)
    with open(path, "a") as f:
        f.write(json.dumps(rec) + "\n")
    sys.stderr.write(f"tpu_window: {rec}\n")


def _sync_scalar(x):
    return float(np.asarray(x._data if hasattr(x, "_data") else x).ravel()[0])


def _time(step, sync, warmup=2, iters=8):
    for _ in range(warmup):
        step()
    sync()
    t0 = time.perf_counter()
    for _ in range(iters):
        step()
    sync()
    return (time.perf_counter() - t0) / iters


def exp_bert_batches(out, batches=(32, 64, 128)):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models.bert import BertForPretraining, BertConfig
    from paddle_tpu.parallel.env import build_mesh
    from paddle_tpu.parallel.hybrid import CompiledTrainStep

    for B in batches:
        try:
            paddle.seed(0)
            cfg = BertConfig(dropout=0.1, scan_layers=True)
            model = BertForPretraining(cfg)
            opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                         parameters=model.parameters())
            mesh = build_mesh({"data": len(jax.devices())})
            tr = CompiledTrainStep(model, lambda m, i, l: m.loss(i, l), opt,
                                   mesh, amp_dtype=jnp.bfloat16,
                                   zero_shard_states=False)
            rng = np.random.RandomState(0)
            ids = paddle.to_tensor(rng.randint(
                0, cfg.vocab_size, (B, 128)).astype(np.int32))
            lbl = paddle.to_tensor(rng.randint(
                0, cfg.vocab_size, (B, 128)).astype(np.int32))
            holder = {}

            def step():
                holder["loss"] = tr.step(ids, lbl)

            agg = _time(step, lambda: _sync_scalar(holder["loss"]))
            cost = tr.cost_analysis(ids, lbl) or {}
            _append(out, {"exp": "bert_batch", "batch": B,
                          "samples_per_sec": round(B / agg, 2),
                          "step_s": round(agg, 4),
                          "flops": cost.get("flops")})
        except Exception as e:
            _append(out, {"exp": "bert_batch", "batch": B,
                          "error": str(e)[:300]})


def exp_resnet_batches(out, batches=(64, 128)):
    import paddle_tpu as paddle
    import paddle_tpu.static as static
    from bench import _build_static_resnet50

    for B in batches:
        try:
            paddle.seed(0)
            main, startup, loss, fwd_flops = _build_static_resnet50(
                static, B)
            exe = static.Executor()
            exe.run(startup)
            rng = np.random.RandomState(0)
            import jax.numpy as jnp

            feed = {"image": jnp.asarray(
                        rng.rand(B, 3, 224, 224).astype(np.float32)),
                    "label": jnp.asarray(
                        rng.randint(0, 1000, (B, 1)).astype(np.int64))}

            def step():
                return exe.run(main, feed=feed, fetch_list=[loss])

            # Executor.run returns fetched numpy — already synced
            agg = _time(step, lambda: None, warmup=2, iters=6)
            _append(out, {"exp": "resnet50_batch", "batch": B,
                          "imgs_per_sec": round(B / agg, 2),
                          "step_s": round(agg, 4)})
        except Exception as e:
            _append(out, {"exp": "resnet50_batch", "batch": B,
                          "error": str(e)[:300]})


def exp_gpt_flash(out):
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForPretraining, GPTConfig
    from paddle_tpu.parallel.env import build_mesh
    from paddle_tpu.parallel.hybrid import CompiledTrainStep

    for use_flash in (True, False):
        try:
            paddle.seed(0)
            cfg = GPTConfig(vocab_size=50257, hidden_size=768,
                            num_layers=12, num_heads=12, max_seq_len=512,
                            dropout=0.1, attn_dropout=0.0,
                            use_flash=use_flash, scan_layers=True)
            model = GPTForPretraining(cfg)
            opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                         parameters=model.parameters())
            mesh = build_mesh({"data": len(jax.devices())})
            tr = CompiledTrainStep(model, lambda m, i, l: m.loss(i, l),
                                   opt, mesh, amp_dtype=jnp.bfloat16,
                                   zero_stage=1, remat=True)
            rng = np.random.RandomState(0)
            ids = paddle.to_tensor(rng.randint(
                0, cfg.vocab_size, (8, 512)).astype(np.int32))
            holder = {}

            def step():
                holder["loss"] = tr.step(ids, ids)

            agg = _time(step, lambda: _sync_scalar(holder["loss"]))
            _append(out, {"exp": "gpt2_attention_path",
                          "flash": use_flash,
                          "tokens_per_sec": round(8 * 512 / agg, 1),
                          "step_s": round(agg, 4)})
        except Exception as e:
            _append(out, {"exp": "gpt2_attention_path",
                          "flash": use_flash, "error": str(e)[:300]})


def exp_flash_microbench(out, seqs=(512, 1024, 2048)):
    """fwd+bwd attention-only latency: Pallas flash vs composite einsum,
    value-and-grad through each, B=8 H=12 d=64 (GPT-2 geometry)."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.pallas import flash_attention as fa

    def flash(qv, kv, vv, causal=True):
        # call the kernel beneath the eager-tape wrapper (tracers inside
        # jit/grad can't cross apply_op)
        b, h, L, d = qv.shape
        scale = 1.0 / np.sqrt(d)
        km = jnp.zeros((1, L), jnp.float32)
        out = fa._flash((qv * scale).reshape(b * h, L, d),
                        kv.reshape(b * h, L, d), vv.reshape(b * h, L, d),
                        km, causal, h, False)
        return out.reshape(b, h, L, d)

    def composite_attention(qv, kv, vv, causal=True):
        scale = 1.0 / np.sqrt(qv.shape[-1])
        logits = jnp.einsum("bhqd,bhkd->bhqk", qv, kv) * scale
        if causal:
            L = logits.shape[-1]
            tri = jnp.tril(jnp.ones((L, L), bool))
            logits = jnp.where(tri, logits, -1e9)
        return jnp.einsum("bhqk,bhkd->bhqd",
                          jax.nn.softmax(logits, axis=-1), vv)

    for S in seqs:
        rng = np.random.RandomState(0)
        q = jnp.asarray(rng.randn(8, 12, S, 64).astype(np.float32),
                        jnp.bfloat16)
        k = jnp.asarray(rng.randn(8, 12, S, 64).astype(np.float32),
                        jnp.bfloat16)
        v = jnp.asarray(rng.randn(8, 12, S, 64).astype(np.float32),
                        jnp.bfloat16)
        for name, fn in (("flash", flash),
                         ("composite", composite_attention)):
            try:
                def loss_fn(a, b, c):
                    return jnp.sum(fn(a, b, c, causal=True)
                                   .astype(jnp.float32))

                g = jax.jit(jax.grad(loss_fn, argnums=(0, 1, 2)))
                outv = None

                def step():
                    nonlocal outv
                    outv = g(q, k, v)

                agg = _time(step,
                            lambda: jax.block_until_ready(outv),
                            warmup=2, iters=10)
                _append(out, {"exp": "attention_fwd_bwd", "impl": name,
                              "seq": S, "ms": round(agg * 1e3, 3)})
            except Exception as e:
                _append(out, {"exp": "attention_fwd_bwd", "impl": name,
                              "seq": S, "error": str(e)[:300]})


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="/root/repo/TPU_WINDOW.jsonl")
    ap.add_argument("--budget", type=float, default=1500.0)
    args = ap.parse_args()
    t0 = time.perf_counter()

    import jax

    plat = jax.devices()[0].platform
    _append(args.out, {"exp": "session", "platform": plat,
                       "device_kind": getattr(jax.devices()[0],
                                              "device_kind", "?")})
    if plat == "cpu":
        sys.stderr.write("tpu_window: no TPU — refusing to burn time\n")
        return 1
    for fn in (exp_bert_batches, exp_resnet_batches, exp_gpt_flash,
               exp_flash_microbench):
        if time.perf_counter() - t0 > args.budget:
            _append(args.out, {"exp": "budget_exhausted",
                               "after": fn.__name__})
            break
        fn(args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
