#!/usr/bin/env python
"""Decode microbench: tokens/s across batch x context for the
paddle_tpu.generation engine (BENCH-style JSON to stdout).

Measures the paged-KV continuous-batching decode loop end to end —
prefill, paged decode attention (Pallas kernel on TPU, jnp reference on
CPU), sampling, scheduling — with the `generation.*` StatRegistry
snapshot embedded in the artifact (the stats_snapshot() export), so a
TPU-window run leaves the same evidence trail as BENCH_TPU_SESSION.json.

Usage:
    python tools/gen_bench.py                    # default grid
    python tools/gen_bench.py --batches 1,4,8 --contexts 32,128 \
        --new-tokens 32 --out BENCH_GEN.json
    python tools/gen_bench.py --pool device --decode both
        # eager vs fused single-dispatch decode A/B: steady-state
        # steps/s + tokens/s per cell with per-step dispatch/sync
        # counts; compile/warmup wall time in the separate warmup_s
        # column, never folded into the rate
"""
import argparse
import json
import os
import sys
import time

import numpy as np

# runnable as `python tools/gen_bench.py` from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor JAX_PLATFORMS=cpu *before* backend init (see op_bench.py)
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")


def bench_cell(model, batch, context, new_tokens, num_pages, page_size,
               pool, decode):
    from paddle_tpu import generation as g
    from paddle_tpu.generation import metrics as gmetrics
    from paddle_tpu.profiler.monitor import StatRegistry

    eng = g.GenerationEngine(
        model,
        g.GenerationConfig(max_decode_slots=batch, num_pages=num_pages,
                           page_size=page_size, queue_depth=batch * 2,
                           kv_backend=pool, decode=decode),
        start=False)
    rng = np.random.default_rng(batch * 1000 + context)
    prompts = [rng.integers(0, model.vocab_size, context).tolist()
               for _ in range(batch)]

    def run_once():
        t0 = time.perf_counter()
        handles = [eng.submit(p, max_new_tokens=new_tokens)
                   for p in prompts]
        eng.run_until_idle()
        dt = time.perf_counter() - t0
        return dt, [h.result(timeout=1) for h in handles]

    # warmup pass: same shapes as the measured pass, so it pays every
    # trace/compile (fused decode buckets, jit_prefill buckets) exactly
    # once — compile time is REPORTED, never folded into the
    # steady-state rate below
    warmup_s, _ = run_once()
    reg = StatRegistry.instance()
    kv_stat = reg.get_stat(gmetrics.KV_BYTES_MOVED)
    pf_stat = reg.get_stat(gmetrics.PREFILL_TOKENS_TOTAL)
    steps_stat = reg.get_stat(gmetrics.STEPS_TOTAL)
    kv_before, pf_before = kv_stat.get(), pf_stat.get()
    steps_before = steps_stat.get()
    dt, results = run_once()
    generated = sum(len(r.token_ids) for r in results)
    steps = int(steps_stat.get() - steps_before)
    kv_bytes = int(kv_stat.get() - kv_before)
    # prefill writes (incl. preemption re-prefills) are exactly the
    # prefill token count x K+V payload; subtracting them leaves the
    # decode-side traffic the O(pool)-vs-O(tokens) A/B is about
    prefill_bytes = (int(pf_stat.get() - pf_before) * 2 * model.num_layers
                     * model.num_heads * model.head_dim * 4)
    snap = eng.metrics.snapshot()
    eng.shutdown()
    return {
        "pool": pool,
        "decode": decode,
        "batch": batch,
        "context": context,
        "new_tokens": new_tokens,
        "generated": generated,
        "wall_s": round(dt, 4),
        "warmup_s": round(warmup_s, 4),      # compile+trace, separate
        "tokens_per_s": round(generated / dt, 2) if dt > 0 else 0.0,
        "steps": steps,
        "steps_per_s": round(steps / dt, 2) if dt > 0 else 0.0,
        # per-step gauges from the steady-state pass: the fused-vs-eager
        # dispatch-collapse A/B per cell (fused: 1 and 1)
        "dispatches_per_step": snap.get(
            "generation.decode_dispatches_per_step", 0),
        "host_syncs_per_step": snap.get(
            "generation.decode_host_syncs_per_step", 0),
        "decode_compiles": snap.get("generation.decode_compiles_total", 0),
        "preemptions": sum(r.preemptions for r in results),
        "kv_bytes_moved": kv_bytes,          # total, prefill included
        "kv_prefill_bytes": prefill_bytes,
        # decode-side bytes per generated token: O(pool) for host pools,
        # O(batch x layers x heads x head_dim) for DeviceKVPool —
        # context-independent by construction for the device backend
        "kv_decode_bytes_per_token": round(
            (kv_bytes - prefill_bytes) / max(generated, 1), 1),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", default="1,4,8")
    ap.add_argument("--contexts", default="32,128")
    ap.add_argument("--new-tokens", type=int, default=32)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--pool", choices=("host", "device", "both"),
                    default="both",
                    help="KV backend A/B: host numpy pools vs "
                         "device-resident DeviceKVPool (donated "
                         "scatter appends); 'both' emits one tokens/s "
                         "series per backend")
    ap.add_argument("--decode", choices=("eager", "fused", "both"),
                    default="eager",
                    help="decode-path A/B: eager per-layer attend "
                         "callbacks vs the fused single-dispatch "
                         "FusedDecodeStep (device pools only — "
                         "host-pool fused cells are skipped); steps/s "
                         "is steady-state with compile/warmup time in "
                         "the separate warmup_s column")
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--head-dim", type=int, default=32)
    ap.add_argument("--out", default=None,
                    help="also write the JSON document to this path")
    args = ap.parse_args()

    import jax

    from paddle_tpu import generation as g
    from paddle_tpu.profiler.monitor import StatRegistry

    batches = [int(b) for b in args.batches.split(",")]
    contexts = [int(c) for c in args.contexts.split(",")]
    model = g.TinyCausalLM(vocab_size=args.vocab, num_layers=args.layers,
                           num_heads=args.heads, head_dim=args.head_dim,
                           max_positions=max(contexts) + args.new_tokens + 1,
                           seed=0)
    pools = (("host", "device") if args.pool == "both" else (args.pool,))
    decodes = (("eager", "fused") if args.decode == "both"
               else (args.decode,))
    grid = []
    stats_by_series = {}
    reg = StatRegistry.instance()
    for pool in pools:
        for decode in decodes:
            if decode == "fused" and pool != "device":
                continue  # fused requires donated device pools
            # per-series snapshot: reset generation.* so each
            # (pool, decode) combo's stats land separately
            for name in list(reg.stats()):
                if name.startswith("generation."):
                    reg.get_stat(name).reset()
            for b in batches:
                for ctx in contexts:
                    # pool sized to fit the cell w/o preemption noise
                    pages = ((ctx + args.new_tokens) // args.page_size
                             + 2) * b
                    grid.append(bench_cell(model, b, ctx,
                                           args.new_tokens, pages,
                                           args.page_size, pool, decode))
            stats_by_series[f"{pool}/{decode}"] = \
                reg.stats_snapshot("generation.")
    doc = {
        "bench": "generation_decode",
        "platform": jax.devices()[0].platform,
        "model": {"vocab": args.vocab, "layers": args.layers,
                  "heads": args.heads, "head_dim": args.head_dim},
        "pools": list(pools),
        "decodes": list(decodes),
        "grid": grid,
        "stats": stats_by_series,
    }
    line = json.dumps(doc)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
