#!/usr/bin/env python
"""Decode microbench: tokens/s across batch x context for the
paddle_tpu.generation engine (BENCH-style JSON to stdout).

Measures the paged-KV continuous-batching decode loop end to end —
prefill, paged decode attention (Pallas kernel on TPU, jnp reference on
CPU), sampling, scheduling — with the `generation.*` StatRegistry
snapshot embedded in the artifact (the stats_snapshot() export), so a
TPU-window run leaves the same evidence trail as BENCH_TPU_SESSION.json.

Usage:
    python tools/gen_bench.py                    # default grid
    python tools/gen_bench.py --batches 1,4,8 --contexts 32,128 \
        --new-tokens 32 --out BENCH_GEN.json
    python tools/gen_bench.py --pool device --decode both
        # eager vs fused single-dispatch decode A/B: steady-state
        # steps/s + tokens/s per cell with per-step dispatch/sync
        # counts; compile/warmup wall time in the separate warmup_s
        # column, never folded into the rate
    python tools/gen_bench.py --prefill both --chunk-tokens 32
        # full vs CHUNKED prefill A/B: every series gains an
        # "interleave" cell — batch-1 short requests decode while one
        # long prompt streams in — reporting time-to-first-token per
        # request, decode tokens/s DURING the long prefill, and the
        # prefill compile count (chunked: O(1) in prompt length)
    python tools/gen_bench.py --step both
        # legacy vs RAGGED mixed-batch step A/B: the FusedDecodeStep +
        # ChunkedPrefillStep pair (dummy-padded decode buckets, two
        # dispatches per interleaved step) vs ONE ragged dispatch
        # packing decode rows and the prefill chunk into a fixed token
        # axis — steady-state tokens/s, dispatches/step, measured
        # row_utilization, padded_token_waste (ragged: 0), and a ragged
        # TTFT-under-interleave cell
    python tools/gen_bench.py --prefix both
        # prefix-cache A/B: a shared-system-prompt workload (N users,
        # one long system prefix, distinct short suffixes) run with
        # the cache off and on — per-cell prefix hit tokens, cold vs
        # warm TTFT, prefill tokens computed, live shared_pages and
        # COW copies; warm cells pay prefill only for the divergent
        # suffix
    python tools/gen_bench.py --replicas both
        # fleet-tier A/B: a shared-system-prompt multi-turn session
        # workload through serving.FleetRouter at 1 and N replicas,
        # with the affinity routing ladder (session -> prefix ->
        # least-loaded) against a random-routing baseline — per-replica
        # prefix hit rate, shed rate, TTFT p50/p95, and the
        # prefix-routing confirmation split per cell
    python tools/gen_bench.py --replicas N --fleet-transport both
        # DISAGGREGATED fleet A/B: the same fleet cells behind the
        # in-process transport vs one-OS-process-per-replica
        # (SubprocTransport pickled RPC), plus a drain-migration probe
        # pair per transport — a mid-decode stream's replica drains
        # and the cell reports stream-gap p95 across the drain,
        # migrated_replay_tokens (LIVE migration must report 0 vs the
        # cold-resubmit baseline's full replay), and the page-service
        # adoption counters
    python tools/gen_bench.py --page-transfer both --page-codec both
        # cross-host DATA-PLANE A/B: one warm-prefix adoption cell per
        # (relay vs p2p) x (raw vs compressed) combo — wire bytes,
        # router relay bytes (p2p cells must report 0: pages dial the
        # holder's data port, the router only books the index), raw
        # bytes + measured compression ratio (bitwise-lossless delta+
        # zlib; the synthetic model's KV is near-incompressible, so
        # the ratio is honest, not a marketing 2x), the async transfer
        # wall, and the importer's warm TTFT after adoption
    python tools/gen_bench.py --mesh both
        # single-chip vs TENSOR-PARALLEL sharded decode A/B: the same
        # grid run unsharded (tp_degree 1) and over a head-sharded
        # mesh of every visible device (GenerationConfig.mesh, fused
        # decode only) — tokens/s and dispatches/step vs tp_degree,
        # plus generation.collective_bytes_per_step, mesh_devices and
        # kernel_path in each cell; GSPMD compile wall stays in
        # warmup_s.  Every SHARDED combo runs twice — use_kernel False
        # (jnp reference, GSPMD-partitioned) vs True (the shard_map'd
        # Pallas kernel: per-shard program over num_heads/tp heads) —
        # the kernel-vs-reference A/B under the mesh.  On CPU
        # an --xla_force_host_platform_device_count=8 mesh is forced
        # automatically when XLA_FLAGS doesn't already carry one
        # (collectives over loopback: a semantics/dispatch A/B, not a
        # speedup).  --mesh also takes an explicit tp_degree integer.

Steady-state accounting: every cell pre-warms its decode buckets (and
pays its prefill/chunk compiles in a full warmup pass) BEFORE the
measured window; compile wall time lands in `warmup_s`, and the cell's
`measured_compiles` field records any executable built inside the timed
region (0 in the steady state — a nonzero value means the bucket menu
was exercised mid-run and the rate is polluted).
"""
import argparse
import json
import os
import sys
import time

import numpy as np

# runnable as `python tools/gen_bench.py` from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor JAX_PLATFORMS=cpu *before* backend init (see op_bench.py)
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")


def _prewarm_decode_buckets(eng, batch, context, new_tokens, page_size):
    """Pre-compile every fused-decode bucket the run can touch (all
    batch buckets <= batch x all pages buckets up to the final context)
    OUTSIDE the measured window — a new bucket appearing mid-run (batch
    decay on finishes, pages growth as sequences lengthen) otherwise
    lands its compile wall time in the timed region.  No-op on the
    eager path.  Returns elapsed seconds (reported under warmup_s)."""
    t0 = time.perf_counter()
    max_pages = -(-(context + new_tokens + 1) // page_size)
    pages = 1
    while True:
        for b in range(1, batch + 1):
            eng.prewarm_decode(b, pages, greedy=True)
        if pages >= max_pages:
            break
        pages *= 2
    return time.perf_counter() - t0


def _pool_byte_facts(model, num_pages, page_size, context, new_tokens,
                     kv_dtype):
    """Pool-capacity arithmetic for the kv-quant A/B: bytes per page at
    this dtype (scales included for int8), and the resident-sequence
    capacity a FIXED byte budget (the bf16 pool at this page count)
    buys — the "~2x resident sequences per pool byte" headline."""
    import numpy as np

    ll, h, d = model.num_layers, model.num_heads, model.head_dim

    def page_bytes(dt):
        b = 2 * ll * page_size * h * d * np.dtype(dt).itemsize
        if np.dtype(dt) == np.dtype(np.int8):
            b += 2 * ll * h * 4            # [P, H] f32 scales per pool
        return b

    budget = page_bytes("bfloat16") * num_pages
    pages_at_budget = budget // page_bytes(kv_dtype)
    pages_per_seq = -(-(context + new_tokens) // page_size)
    return {
        "kv_page_bytes": int(page_bytes(kv_dtype)),
        "kv_pool_bytes": int(page_bytes(kv_dtype) * num_pages),
        "pool_byte_budget": int(budget),
        "pages_at_fixed_budget": int(pages_at_budget),
        "resident_seqs_at_fixed_budget": int(pages_at_budget
                                             // pages_per_seq),
    }


def bench_cell(model, batch, context, new_tokens, num_pages, page_size,
               pool, decode, prefill="full", chunk_tokens=0, tp=1,
               step="legacy", use_kernel=None, kv_dtype=None,
               quant_collectives=False):
    from paddle_tpu import generation as g
    from paddle_tpu.generation import metrics as gmetrics
    from paddle_tpu.parallel import tp_mesh
    from paddle_tpu.profiler.monitor import StatRegistry

    mesh = tp_mesh(tp) if tp > 1 else None
    kv_kwargs = {}
    if kv_dtype is not None:
        kv_kwargs["kv_dtype"] = kv_dtype
    eng = g.GenerationEngine(
        model,
        g.GenerationConfig(max_decode_slots=batch, num_pages=num_pages,
                           page_size=page_size, queue_depth=batch * 2,
                           kv_backend=pool, mesh=mesh,
                           # the kernel-vs-reference A/B under the mesh:
                           # None = auto (pallas on TPU), False = jnp
                           # reference, True = the shard_map'd kernel
                           use_kernel=use_kernel,
                           # the ragged step replaces the decode path:
                           # one mixed-batch executable per pages bucket
                           decode=(None if step == "ragged" else decode),
                           step_mode=step,
                           quantized_collectives=quant_collectives,
                           prefill_chunk_tokens=(chunk_tokens
                                                 if prefill == "chunked"
                                                 else 0),
                           **kv_kwargs),
        start=False)
    rng = np.random.default_rng(batch * 1000 + context)
    prompts = [rng.integers(0, model.vocab_size, context).tolist()
               for _ in range(batch)]

    def run_once():
        t0 = time.perf_counter()
        handles = [eng.submit(p, max_new_tokens=new_tokens)
                   for p in prompts]
        eng.run_until_idle()
        dt = time.perf_counter() - t0
        return dt, [h.result(timeout=1) for h in handles]

    # warmup pass: same shapes as the measured pass, so it pays every
    # trace/compile (fused decode buckets, jit_prefill buckets) exactly
    # once — compile time is REPORTED, never folded into the
    # steady-state rate below.  The explicit bucket pre-warm then covers
    # signatures the warmup pass may have missed (scheduling jitter can
    # shift which buckets a pass touches).
    warmup_s, _ = run_once()
    warmup_s += _prewarm_decode_buckets(eng, batch, context, new_tokens,
                                        page_size)
    reg = StatRegistry.instance()
    kv_stat = reg.get_stat(gmetrics.KV_BYTES_MOVED)
    pf_stat = reg.get_stat(gmetrics.PREFILL_TOKENS_TOTAL)
    steps_stat = reg.get_stat(gmetrics.STEPS_TOTAL)
    pfc_stat = reg.get_stat(gmetrics.PREFILL_COMPILES_TOTAL)
    dcc_stat = reg.get_stat(gmetrics.DECODE_COMPILES_TOTAL)
    sb_stat = reg.get_stat(gmetrics.STEP_SCORE_BLOCKS)
    sbu_stat = reg.get_stat(gmetrics.STEP_SCORE_BLOCKS_UNTILED)
    kv_before, pf_before = kv_stat.get(), pf_stat.get()
    steps_before = steps_stat.get()
    compiles_before = pfc_stat.get() + dcc_stat.get()
    sb_before, sbu_before = sb_stat.get(), sbu_stat.get()
    dt, results = run_once()
    measured_compiles = int(pfc_stat.get() + dcc_stat.get()
                            - compiles_before)
    generated = sum(len(r.token_ids) for r in results)
    steps = int(steps_stat.get() - steps_before)
    kv_bytes = int(kv_stat.get() - kv_before)
    # prefill writes (incl. preemption re-prefills) are exactly the
    # prefill token count x K+V payload at the POOL itemsize (the cache
    # counts writes at storage precision — int8 cells write 1-byte
    # payloads); subtracting them leaves the decode-side traffic the
    # O(pool)-vs-O(tokens) A/B is about
    prefill_bytes = (int(pf_stat.get() - pf_before) * 2 * model.num_layers
                     * model.num_heads * model.head_dim
                     * np.dtype(kv_dtype or np.float32).itemsize)
    snap = eng.metrics.snapshot()
    eng.shutdown()
    return {
        "pool": pool,
        "decode": decode,
        "prefill": prefill,
        # legacy vs ragged step A/B: the one-dispatch mixed-batch path
        # reports its measured packed-axis utilization and the ZERO of
        # padded_token_waste; legacy cells report their dummy-row bill.
        # Utilization is the CUMULATIVE useful/dispatched ratio over the
        # cell (the per-step gauge would report whatever the drain-tail
        # step happened to pack).
        "step": step,
        "row_utilization": round(
            snap.get("generation.step_rows_useful", 0)
            / max(snap.get("generation.step_rows_dispatched", 0), 1), 3),
        "padded_token_waste": snap.get(
            "generation.padded_token_waste", 0),
        # tensor-parallel degree of the cell's mesh (1 = unsharded) and
        # the per-dispatch allreduce estimate — the tokens/s-vs-tp A/B
        # plus the collective-cost baseline the EQuARX-style quantized
        # allreduce follow-on is measured against
        "tp_degree": tp,
        "collective_bytes_per_step": snap.get(
            "generation.collective_bytes_per_step", 0),
        # which attention implementation actually dispatched — the
        # silent-fallback tripwire (a mesh cell reporting jnp-reference
        # when pallas was requested is a bug, not a detail)
        "kernel_path": snap.get("generation.kernel_path", ""),
        # precision facts: the pool storage dtype this cell measured,
        # the split-out scale traffic (int8: scales are bytes in flight
        # too, already folded into kv_bytes_moved), and whether the
        # EQuARX-style quantized ring actually carried the allreduces
        # (a silent fp32 fallback is a stats fact, like kernel_path)
        "kv_quant_dtype": snap.get("generation.kv_quant_dtype", ""),
        "kv_scale_bytes": snap.get("generation.kv_scale_bytes", 0),
        "collective_quantized": snap.get(
            "generation.collective_quantized", 0),
        # fixed-pool-byte capacity arithmetic (the int8 headline:
        # ~2x resident sequences vs bf16 at the same byte budget)
        **_pool_byte_facts(model, num_pages, page_size, context,
                           new_tokens,
                           kv_dtype if kv_dtype is not None
                           else "float32"),
        # the query-tiling FLOP proxy (ragged KERNEL cells; 0 when the
        # jnp reference dispatched — the /ref-vs-/kernel tripwire):
        # score blocks the tiled kernel computed vs the untiled bill,
        # DELTAS over the measured pass (the counters are cumulative
        # per series, like kv_bytes)
        "score_blocks": int(sb_stat.get() - sb_before),
        "score_blocks_untiled": int(sbu_stat.get() - sbu_before),
        "batch": batch,
        "context": context,
        "new_tokens": new_tokens,
        "generated": generated,
        "wall_s": round(dt, 4),
        "warmup_s": round(warmup_s, 4),      # compile+trace+prewarm
        # executables built INSIDE the timed region (steady state: 0 —
        # pre-warm moved every bucket compile into warmup_s)
        "measured_compiles": measured_compiles,
        "tokens_per_s": round(generated / dt, 2) if dt > 0 else 0.0,
        "steps": steps,
        "steps_per_s": round(steps / dt, 2) if dt > 0 else 0.0,
        # per-step gauges from the steady-state pass: the fused-vs-eager
        # dispatch-collapse A/B per cell (fused: 1 and 1)
        "dispatches_per_step": snap.get(
            "generation.decode_dispatches_per_step", 0),
        "host_syncs_per_step": snap.get(
            "generation.decode_host_syncs_per_step", 0),
        "decode_compiles": snap.get("generation.decode_compiles_total", 0),
        "preemptions": sum(r.preemptions for r in results),
        "kv_bytes_moved": kv_bytes,          # total, prefill included
        "kv_prefill_bytes": prefill_bytes,
        # decode-side bytes per generated token: O(pool) for host pools,
        # O(batch x layers x heads x head_dim) for DeviceKVPool —
        # context-independent by construction for the device backend
        "kv_decode_bytes_per_token": round(
            (kv_bytes - prefill_bytes) / max(generated, 1), 1),
    }


def bench_interleave(model, batch, context, long_context, new_tokens,
                     page_size, pool, decode, prefill, chunk_tokens,
                     step="legacy", pack=True):
    """The chunked-prefill A/B scenario: `batch - 1` short requests
    decode while ONE long prompt streams in.  Reports time-to-first-
    token per request and the decode tokens/s the short requests
    sustained DURING the long prompt's prefill window — the
    head-of-line stall full prefill causes and chunking removes.

    Measured on the second pass (the first pays every compile); the
    prefill window is [long submit, long first token], probed via the
    GenerationHandle submitted_s/first_token_s monotonic stamps."""
    from paddle_tpu import generation as g
    from paddle_tpu.generation import metrics as gmetrics
    from paddle_tpu.profiler.monitor import StatRegistry

    # one slot past the decode batch: reserved for the LATE short
    # request the packing probe submits behind the long prompt
    pages = (-(-(long_context + new_tokens) // page_size) + 2) * (batch + 1)
    eng = g.GenerationEngine(
        model,
        g.GenerationConfig(max_decode_slots=batch + 1, num_pages=pages,
                           page_size=page_size, queue_depth=batch * 2 + 2,
                           kv_backend=pool, prefill_pack=pack,
                           decode=(None if step == "ragged" else decode),
                           step_mode=step,
                           prefill_chunk_tokens=(chunk_tokens
                                                 if prefill == "chunked"
                                                 else 0)),
        start=False)
    rng = np.random.default_rng(batch * 7 + context)
    shorts = [rng.integers(0, model.vocab_size, context).tolist()
              for _ in range(batch - 1)]
    late_short = rng.integers(0, model.vocab_size, context).tolist()
    long_prompt = rng.integers(0, model.vocab_size, long_context).tolist()
    reg = StatRegistry.instance()
    tok_stat = reg.get_stat(gmetrics.TOKENS_TOTAL)
    chunk_stat = reg.get_stat(gmetrics.PREFILL_CHUNKS_TOTAL)

    def run_once():
        hs = [eng.submit(p, max_new_tokens=new_tokens) for p in shorts]
        # get every short request decoding before the long prompt lands;
        # chunked mode streams ONE chunk per step FIFO, so the cap must
        # cover every short's whole prefill or the measured window would
        # silently include leftover short-prefill chunks
        warm_cap = 64 + len(shorts) * (
            -(-context // max(chunk_tokens, 1))
            if prefill == "chunked" else 1)
        for _ in range(warm_cap):
            eng.step()
            if all(h.first_token_s is not None for h in hs):
                break
        if not all(h.first_token_s is not None for h in hs):
            raise RuntimeError(
                "interleave warm-up did not finish the short requests' "
                "prefills; the window metrics would be mis-scoped")
        tokens_before = tok_stat.get()
        chunks_before = chunk_stat.get()
        h_long = eng.submit(long_prompt, max_new_tokens=new_tokens)
        # the multi-prompt PACKING probe: a short prompt admitted
        # BEHIND the long one.  With chunked prefill its first chunk
        # rides the very next step's leftover token-axis room
        # (plan_pack), so its TTFT is a couple of steps; with full
        # prefill it pays the long prompt's whole forward pass first —
        # the head-of-line number packing removes
        h_late = eng.submit(late_short, max_new_tokens=new_tokens)
        # count short-request tokens from steps that finished BEFORE the
        # long prompt's first token: the snapshot taken before the step
        # that produced it excludes that step's own decode output, which
        # lands after the window closes in both prefill modes
        before_step = tok_stat.get()
        # capped like the warm-up loop: if the long prompt can never
        # yield a first token (page exhaustion resolves its handle with
        # an exception, pathological config), fail THIS cell instead of
        # spinning until the harness timeout kills the whole artifact
        window_cap = 256 + 4 * (
            -(-long_context // max(chunk_tokens, 1))
            if prefill == "chunked" else 1)
        for _ in range(window_cap):
            if h_long.first_token_s is not None:
                break
            before_step = tok_stat.get()
            eng.step()
        if h_long.first_token_s is None:
            raise RuntimeError(
                "interleave cell: the long prompt produced no first "
                "token within the step cap (config cannot fit it?)")
        decode_tokens = int(before_step - tokens_before)
        # chunks dispatched inside the window: the long prompt's plus
        # the late short's (its pack rides the same steps when chunked)
        window_chunks = int(chunk_stat.get() - chunks_before)
        eng.run_until_idle()
        for h in hs:
            h.result(timeout=1)
        h_long.result(timeout=1)
        h_late.result(timeout=1)
        window = h_long.first_token_s - h_long.submitted_s
        return {
            "ttft_long_s": round(window, 4),
            "ttft_short_avg_s": round(
                sum(h.first_token_s - h.submitted_s for h in hs)
                / max(len(hs), 1), 4),
            # the packing headline: TTFT of the short admitted BEHIND
            # the long prompt (chunked+packed strictly below full
            # prefill's head-of-line wait)
            "ttft_short_behind_long_s": round(
                h_late.first_token_s - h_late.submitted_s, 4),
            "decode_tokens_during_prefill": decode_tokens,
            "decode_tps_during_prefill": round(
                decode_tokens / window, 2) if window > 0 else 0.0,
            "prefill_chunks": window_chunks,
        }

    run_once()                                   # compile/trace pass
    warm_t0 = time.perf_counter()
    # batch + 1: the late packing probe can decode alongside the full
    # short batch + the long prompt, one slot past the nominal batch
    _prewarm_decode_buckets(eng, batch + 1, long_context, new_tokens,
                            page_size)
    warmup_s = time.perf_counter() - warm_t0
    pfc = reg.get_stat(gmetrics.PREFILL_COMPILES_TOTAL)
    pfc_before = pfc.get()
    cell = run_once()                            # measured pass
    snap = eng.metrics.snapshot()
    cell.update({
        "scenario": "interleave",
        "pool": pool,
        "decode": decode,
        "prefill": prefill,
        # multi-prompt chunk packing on (default) or the one-chunk-
        # per-step ablation baseline — the packing TTFT A/B pairs a
        # pack=True cell with a pack=False one on the same traffic
        "pack": pack,
        # the TTFT-under-interleave A/B rung for the ragged step, with
        # its measured mixed-batch row utilization (decode rows + chunk
        # rows share the packed axis, cumulative over the cell) and
        # dummy-row bill (ragged: 0)
        "step": step,
        "row_utilization": round(
            snap.get("generation.step_rows_useful", 0)
            / max(snap.get("generation.step_rows_dispatched", 0), 1), 3),
        "padded_token_waste": snap.get(
            "generation.padded_token_waste", 0),
        "kernel_path": snap.get("generation.kernel_path", ""),
        "dispatches_per_step": snap.get(
            "generation.decode_dispatches_per_step", 0),
        "batch": batch,
        "context": context,
        "long_context": long_context,
        "new_tokens": new_tokens,
        "warmup_s": round(warmup_s, 4),
        # compile reuse across passes: 0 new prefill executables in the
        # measured pass for BOTH modes; the absolute count per series
        # is in the stats snapshot (chunked: O(1) in prompt length)
        "measured_prefill_compiles": int(pfc.get() - pfc_before),
    })
    eng.shutdown()
    return cell


def bench_prefix(model, users, sys_tokens, user_tokens, new_tokens,
                 page_size, pool, prefix_on, chunk_tokens):
    """The prefix-cache A/B scenario: `users` requests share one
    `sys_tokens`-token system prompt with distinct `user_tokens`-token
    suffixes — the production shape (system prompts, few-shot
    templates, multi-turn history re-sent per request).  Reports the
    cold TTFT (the request that seeds the cache), the warm-wave TTFT
    average, prefill tokens computed for the warm wave (warm: suffix
    only), per-request hit tokens, and the LIVE shared-page count
    while every user holds its slot — the one-physical-copy proof.

    Compile/trace cost is paid by a throwaway request with the same
    shapes but disjoint tokens (it can never warm the measured
    prompts), so cold-vs-warm TTFT is prefill work, not compile
    wall."""
    from paddle_tpu import generation as g
    from paddle_tpu.generation import metrics as gmetrics
    from paddle_tpu.profiler.monitor import StatRegistry

    total = sys_tokens + user_tokens + new_tokens
    pages = (-(-total // page_size) + 2) * (users + 1)
    eng = g.GenerationEngine(
        model,
        g.GenerationConfig(max_decode_slots=users, num_pages=pages,
                           page_size=page_size, queue_depth=users * 2,
                           kv_backend=pool, prefix_cache=prefix_on,
                           prefill_chunk_tokens=chunk_tokens),
        start=False)
    rng = np.random.default_rng(sys_tokens * 31 + users)
    half = model.vocab_size // 2
    system = rng.integers(0, half, sys_tokens).tolist()
    suffixes = [rng.integers(0, half, user_tokens).tolist()
                for _ in range(users)]
    # throwaway: same shapes, tokens from the other half of the vocab
    # (disjoint from `system`, so it cannot pre-warm the measured wave)
    throwaway = rng.integers(half, model.vocab_size, total
                             - new_tokens).tolist()
    eng.submit(throwaway, max_new_tokens=new_tokens)
    eng.run_until_idle()
    reg = StatRegistry.instance()
    pf_stat = reg.get_stat(gmetrics.PREFILL_TOKENS_TOTAL)
    # cold request: seeds the cache (when on) and is the cold baseline
    pf_before = pf_stat.get()
    h_cold = eng.submit(system + suffixes[0], max_new_tokens=new_tokens)
    eng.run_until_idle()
    h_cold.result(timeout=5)
    cold_prefill = int(pf_stat.get() - pf_before)
    # warm wave: every user shares the system prompt
    pf_before = pf_stat.get()
    hs = [eng.submit(system + sfx, max_new_tokens=new_tokens)
          for sfx in suffixes[1:]]
    shared_live = 0
    for _ in range(64 + users * (-(-total // max(chunk_tokens, 1)))):
        eng.step()
        shared_live = max(shared_live, eng.cache.shared_pages)
        if all(h.first_token_s is not None for h in hs):
            break
    eng.run_until_idle()
    for h in hs:
        h.result(timeout=5)
    warm_prefill = int(pf_stat.get() - pf_before)
    snap = eng.metrics.snapshot()
    eng.shutdown()
    return {
        "scenario": "prefix",
        "prefix": "on" if prefix_on else "off",
        "pool": pool,
        "users": users,
        "sys_tokens": sys_tokens,
        "user_tokens": user_tokens,
        "new_tokens": new_tokens,
        "ttft_cold_s": round(h_cold.first_token_s - h_cold.submitted_s, 4),
        "ttft_warm_avg_s": round(
            sum(h.first_token_s - h.submitted_s for h in hs)
            / max(len(hs), 1), 4),
        # prefill tokens computed: cold pays the whole prompt; a warm
        # hit pays only the divergent suffix
        "cold_prefill_tokens": cold_prefill,
        "warm_prefill_tokens": warm_prefill,
        "warm_prefill_tokens_per_user": round(
            warm_prefill / max(len(hs), 1), 1),
        "hit_tokens": sum(h.prefix_hit_tokens or 0 for h in hs),
        "hit_rate": snap.get("generation.prefix_cache_hit_rate", 0.0),
        # one physical copy: peak pages aliased by >1 sequence while
        # the whole wave held slots
        "shared_pages_live": shared_live,
        "cow_copies": snap.get("generation.cow_copies", 0),
        "prefix_evictions": snap.get("generation.prefix_evictions", 0),
    }


def bench_fleet(model, n_replicas, sessions, sys_tokens, user_tokens,
                new_tokens, page_size, routing, chunk_tokens, turns=2,
                transport="inproc"):
    """The fleet-tier A/B scenario: `sessions` multi-turn sessions share
    one system prompt; each session's turn 2 re-sends turn 1's prompt
    PLUS the streamed answer (the production multi-turn shape that
    decode-tail indexing warm-hits).  Run once per routing mode —
    'affinity' (session -> prefix -> least-loaded ladder) vs 'random'
    (uniform baseline) — reporting per-replica prefix hit rate, shed
    rate, and TTFT p50/p95: affinity keeps a session's warm pages and a
    prompt's prefix index on ONE replica, random splits them and pays
    cold prefills per replica."""
    from paddle_tpu import generation as g
    from paddle_tpu.profiler.monitor import StatRegistry
    from paddle_tpu.serving import fleet as fleet_mod
    from paddle_tpu.serving.fleet import (FleetConfig, FleetRouter,
                                          ReplicaSpec)

    # reset fleet.* so each cell's routing counters stand alone (the
    # per-replica generation.* registries are fresh per FleetRouter)
    reg = StatRegistry.instance()

    def reset_fleet_stats():
        for name in list(reg.stats()):
            if name.startswith(fleet_mod.PREFIX):
                reg.get_stat(name).reset()

    reset_fleet_stats()
    total = sys_tokens + turns * (user_tokens + new_tokens)
    pages = (-(-total // page_size) + 2) * (sessions + 1)
    specs = [
        ReplicaSpec(
            f"r{i}", model,
            g.GenerationConfig(max_decode_slots=4, num_pages=pages,
                               page_size=page_size,
                               queue_depth=sessions * turns + 4,
                               prefix_cache=True,
                               prefill_chunk_tokens=chunk_tokens),
            transport=transport)
        for i in range(n_replicas)]
    fl = FleetRouter(specs, FleetConfig(routing=routing,
                                        start=(transport == "proc"),
                                        seed=7))
    rng = np.random.default_rng(sys_tokens * 17 + sessions)
    half = model.vocab_size // 2

    def run_waves(system, tag, lo, hi):
        """`turns` waves of `sessions` multi-turn requests.  Each wave
        submits CONCURRENTLY (queues build, the least-loaded rung sees
        real depths, TTFT includes queueing) and drains once per turn —
        the barrier only exists because turn t+1 needs turn t's
        answers."""
        handles, history = [], {}
        for turn in range(turns):
            wave = []
            for sess in range(sessions):
                sfx = rng.integers(lo, hi, user_tokens).tolist()
                prompt = history.get(sess, list(system)) + sfx
                h = fl.submit(prompt, max_new_tokens=new_tokens,
                              session=f"{tag}{sess}")
                wave.append((sess, prompt, h))
            fl.run_until_idle()
            for sess, prompt, h in wave:
                history[sess] = prompt + h.result(timeout=10).token_ids
                handles.append(h)
        return handles

    # warmup: the EXACT measured structure (same wave shapes, batched
    # prefill buckets included) with tokens from the other half of the
    # vocab, so every per-shape op warm-up is paid before the timed
    # waves and nothing it registers can warm the measured prompts.
    # Then flush the residue and reset the counters: measured waves
    # start cold with clean books.
    run_waves(rng.integers(half, model.vocab_size, sys_tokens).tolist(),
              "w", half, model.vocab_size)
    for name, rep in fl._replicas.items():
        rep.transport.flush_prefix()
        rep.transport.reset_stats()
        rep.transport.take_prefix_deltas()   # the flush's drop deltas
        fl._page_index.drop_replica(name)    # warmup residue forgotten
    reset_fleet_stats()
    system = rng.integers(0, half, sys_tokens).tolist()
    handles = run_waves(system, "s", 0, half)
    ttfts = sorted(h.first_token_s - h.submitted_s for h in handles)
    snap = fl.stats_snapshot()
    per_replica = {}
    for name, rep in snap["replicas"].items():
        gstats = rep.get("generation", {})
        per_replica[name] = {
            "requests": gstats.get("generation.requests_total", 0),
            "hit_tokens":
                gstats.get("generation.prefix_cache_hit_tokens", 0),
            "hit_rate":
                gstats.get("generation.prefix_cache_hit_rate", 0.0),
            "prefill_tokens":
                gstats.get("generation.prefill_tokens_total", 0),
        }
    fl.shutdown()
    fsnap = snap["fleet"]
    n_requests = len(handles)
    return {
        "scenario": "fleet",
        "replicas": n_replicas,
        "routing": routing,
        "transport": transport,
        "page_adoptions": fsnap.get("fleet.page_adoptions", 0),
        "pages_adopted": fsnap.get("fleet.pages_adopted", 0),
        "sessions": sessions,
        "turns": turns,
        "sys_tokens": sys_tokens,
        "user_tokens": user_tokens,
        "new_tokens": new_tokens,
        "ttft_p50_s": round(float(np.percentile(ttfts, 50)), 4),
        "ttft_p95_s": round(float(np.percentile(ttfts, 95)), 4),
        "hit_tokens": sum(h.prefix_hit_tokens or 0 for h in handles),
        "shed_total": fsnap.get("fleet.shed_total", 0),
        "shed_rate": round(fsnap.get("fleet.shed_total", 0)
                           / max(n_requests, 1), 3),
        "routed_affinity": fsnap.get("fleet.routed_affinity", 0),
        "routed_prefix": fsnap.get("fleet.routed_prefix", 0),
        "routed_spill": fsnap.get("fleet.routed_spill", 0),
        "prefix_routed_confirmed":
            fsnap.get("fleet.prefix_routed_confirmed", 0),
        "prefix_routed_missed":
            fsnap.get("fleet.prefix_routed_missed", 0),
        "per_replica": per_replica,
    }


def bench_drain_migration(model, transport, live, sys_tokens, new_tokens,
                          page_size, chunk_tokens):
    """The drain-migration probe: one long stream is mid-decode when
    its replica drains; a consumer thread stamps every token arrival
    so the cell reports STREAM-GAP p95 (time-to-next-token across the
    drain) alongside `migrated_replay_tokens` — live migration must
    report 0 (the sibling RESUMES the decode) vs the cold-resubmit
    baseline's full replay of every already-streamed token — and the
    page-service adoption counters.  Runs with started workers so the
    gap measures real wall time, per transport."""
    import threading

    from paddle_tpu import generation as g
    from paddle_tpu.profiler.monitor import StatRegistry
    from paddle_tpu.serving import fleet as fleet_mod
    from paddle_tpu.serving.fleet import (FleetConfig, FleetRouter,
                                          ReplicaSpec)

    reg = StatRegistry.instance()
    for name in list(reg.stats()):
        if name.startswith(fleet_mod.PREFIX):
            reg.get_stat(name).reset()
    total = sys_tokens + new_tokens
    pages = (-(-total // page_size) + 2) * 3
    specs = [
        ReplicaSpec(
            f"r{i}", model,
            g.GenerationConfig(max_decode_slots=4, num_pages=pages,
                               page_size=page_size, prefix_cache=True,
                               prefill_chunk_tokens=chunk_tokens),
            transport=transport)
        for i in range(2)]
    fl = FleetRouter(specs, FleetConfig(start=True, seed=7,
                                        live_migration=live))
    rng = np.random.default_rng(31)
    prompt = rng.integers(0, model.vocab_size, sys_tokens).tolist()
    h = fl.submit(prompt, max_new_tokens=new_tokens, session="probe")
    arrivals = []

    def consume():
        for _ in h.tokens(timeout=60):
            arrivals.append(time.monotonic())

    consumer = threading.Thread(target=consume, daemon=True)
    consumer.start()
    # let the stream establish, then pull the replica out mid-decode
    deadline = time.monotonic() + 60
    while len(arrivals) < max(4, new_tokens // 8) \
            and time.monotonic() < deadline:
        time.sleep(0.002)
    drained_at = len(arrivals)
    t0 = time.monotonic()
    fl.drain(fl.replica_of("probe"), migrate=True)
    drain_s = time.monotonic() - t0
    consumer.join(timeout=120)
    result = h.result(timeout=10)
    gaps = np.diff(np.asarray(arrivals))
    snap = fl.stats_snapshot()["fleet"]
    fl.shutdown()

    def pct(q):
        # a starved cell (fewer than 2 arrivals before the deadline)
        # reports null gaps instead of crashing the whole artifact
        return (None if gaps.size == 0
                else round(float(np.percentile(gaps, q)), 4))

    return {
        "scenario": "fleet_drain",
        "transport": transport,
        "migration": "live" if live else "cold-resubmit",
        "tokens_streamed": len(result.token_ids),
        "tokens_before_drain": drained_at,
        "drain_wall_s": round(drain_s, 4),
        "stream_gap_p50_s": pct(50),
        "stream_gap_p95_s": pct(95),
        "stream_gap_max_s": (None if gaps.size == 0
                             else round(float(np.max(gaps)), 4)),
        "migrated_total": snap.get("fleet.migrated_total", 0),
        "live_migrated_total":
            snap.get("fleet.live_migrated_total", 0),
        "migrated_replay_tokens":
            snap.get("fleet.migrated_replay_tokens", 0),
        "page_adoptions": snap.get("fleet.page_adoptions", 0),
        "pages_adopted": snap.get("fleet.pages_adopted", 0),
    }


def bench_page_transfer(model, transfer, codec, sys_tokens, new_tokens,
                        page_size, chunk_tokens):
    """One DATA-PLANE A/B cell: a 2-replica fleet seeds a warm prefix
    on the holder, then a request lands on the importer and the page
    transfer ships it over — once per (page_transfer, page_codec)
    combo.  Reports the wire bytes the transfer actually moved, the
    ROUTER-RELAY bytes (the p2p acceptance number: must be 0 — pages
    dial the holder's data port directly, the router only books the
    index), the raw-byte baseline and the measured compression ratio
    (raw / wire; the synthetic bench model's int8-grid KV is
    near-incompressible, so this cell reports the honest ratio for
    THIS data — the codec's >= 2x capacity on low-entropy pages is
    pinned by tests/test_data_plane.py), the async transfer wall, and
    the warm TTFT the importer serves after adoption."""
    from paddle_tpu import generation as g
    from paddle_tpu.profiler.monitor import StatRegistry
    from paddle_tpu.serving import fleet as fleet_mod
    from paddle_tpu.serving.fleet import (FleetConfig, FleetRouter,
                                          ReplicaSpec)

    reg = StatRegistry.instance()
    for name in list(reg.stats()):
        if name.startswith(fleet_mod.PREFIX):
            reg.get_stat(name).reset()
    total = sys_tokens + new_tokens
    pages = (-(-total // page_size) + 2) * 4
    specs = [
        ReplicaSpec(
            f"r{i}", model,
            g.GenerationConfig(max_decode_slots=4, num_pages=pages,
                               page_size=page_size, prefix_cache=True,
                               prefill_chunk_tokens=chunk_tokens))
        for i in range(2)]
    fl = FleetRouter(specs, FleetConfig(start=False, seed=7,
                                        page_transfer=transfer,
                                        page_codec=codec))
    rng = np.random.default_rng(sys_tokens * 11 + 3)
    system = rng.integers(0, model.vocab_size, sys_tokens).tolist()
    sfx = rng.integers(0, model.vocab_size, (2, 4)).tolist()
    # seed the warm prefix on the holder (cold TTFT baseline) — the
    # first pass also pays every per-shape compile on both replicas
    fl._sessions["seed"] = "r0"
    h_cold = fl.submit(system + sfx[0], max_new_tokens=new_tokens,
                       session="seed")
    fl.run_until_idle()
    h_cold.result(timeout=60)
    fl.stats_snapshot()            # flush prefix deltas into the index
    # the adoption: a shared-prefix request lands on the importer;
    # routing returns immediately, the transfer ships asynchronously
    fl._sessions["imp"] = "r1"
    t0 = time.perf_counter()
    h_warm = fl.submit(system + sfx[1], max_new_tokens=new_tokens,
                       session="imp")
    transferred = fl.wait_transfers(timeout=60)
    transfer_wall = time.perf_counter() - t0
    fl.run_until_idle()
    h_warm.result(timeout=60)
    snap = fl.stats_snapshot()["fleet"]
    fl.shutdown()
    wire = (snap.get("fleet.page_p2p_bytes", 0)
            + snap.get("fleet.page_relay_bytes", 0))
    # the relay path ships the un-encoded payload, so its raw
    # baseline IS its wire bill (the codec only rides the p2p port)
    raw = snap.get("fleet.page_raw_bytes", 0) or wire
    return {
        "scenario": "page_transfer",
        "page_transfer": transfer,
        "page_codec": codec,
        "sys_tokens": sys_tokens,
        "new_tokens": new_tokens,
        "transfer_drained": bool(transferred),
        "page_adoptions": snap.get("fleet.page_adoptions", 0),
        "pages_adopted": snap.get("fleet.pages_adopted", 0),
        "wire_bytes": wire,
        # the p2p acceptance counter: page payload bytes that crossed
        # the ROUTER socket (p2p cells must report 0)
        "router_relay_bytes": snap.get("fleet.page_relay_bytes", 0),
        "raw_bytes": raw,
        "compression_ratio": (round(raw / wire, 3) if wire else None),
        "transfer_wall_s": round(transfer_wall, 4),
        "cold_ttft_s": round(
            h_cold.first_token_s - h_cold.submitted_s, 4),
        "warm_ttft_after_adoption_s": round(
            h_warm.first_token_s - h_warm.submitted_s, 4),
        "warm_hit_tokens": h_warm.prefix_hit_tokens or 0,
        "transfers_failed": snap.get("fleet.page_transfers_failed", 0),
        "transfers_cancelled":
            snap.get("fleet.page_transfers_cancelled", 0),
    }


def bench_spec(model, batch, context, new_tokens, page_size, spec_mode,
               spec_tokens, workload):
    """One SPECULATIVE-decoding A/B cell: the ragged engine with
    spec_mode off vs "ngram" (prompt-lookup proposer, k-token verify
    in the one ragged dispatch, on-device accept).

    Two workload shapes bound the story from both sides:

    - "repeat": code/RAG-shaped prompts — a short random pattern tiled
      to the context length, so the token history is dense with n-gram
      recurrences and prompt lookup HITS (the free-win cell);
    - "random": the plain rng workload of the main grid, where lookup
      mostly misses — the overhead-bound cell (the spec axis is wider
      and every miss is a proposer scan; the acceptance criterion is
      "no regression worse than ~10%", not a win).

    Reports steady-state tokens/s, acceptance rate, mean accepted
    drafts per verify row (accepted / spec_draft_rows), rewind tokens,
    and dispatches/step (must stay 1 — speculation may never add a
    dispatch)."""
    from paddle_tpu import generation as g
    from paddle_tpu.generation import metrics as gmetrics
    from paddle_tpu.profiler.monitor import StatRegistry

    rng = np.random.default_rng(7000 + batch)
    if workload == "repeat":
        prompts = []
        for _ in range(batch):
            base = rng.integers(0, model.vocab_size, 8).tolist()
            reps = -(-context // len(base))
            prompts.append((base * reps)[:context])
    else:
        prompts = [rng.integers(0, model.vocab_size, context).tolist()
                   for _ in range(batch)]
    pages = ((context + new_tokens + spec_tokens)
             // page_size + 2) * batch
    eng = g.GenerationEngine(
        model,
        g.GenerationConfig(max_decode_slots=batch, num_pages=pages,
                           page_size=page_size, queue_depth=batch * 2,
                           kv_backend="device", step_mode="ragged",
                           spec_mode=spec_mode,
                           spec_tokens=spec_tokens),
        start=False)

    def run_once():
        t0 = time.perf_counter()
        handles = [eng.submit(p, max_new_tokens=new_tokens)
                   for p in prompts]
        eng.run_until_idle()
        dt = time.perf_counter() - t0
        return dt, [h.result(timeout=1) for h in handles]

    warmup_s, _ = run_once()
    reg = StatRegistry.instance()
    stats = {name: reg.get_stat(name) for name in (
        gmetrics.STEPS_TOTAL, gmetrics.SPEC_PROPOSED_TOKENS,
        gmetrics.SPEC_ACCEPTED_TOKENS, gmetrics.SPEC_REWIND_TOKENS,
        gmetrics.SPEC_DRAFT_ROWS,
        gmetrics.DECODE_COMPILES_TOTAL, gmetrics.PREFILL_COMPILES_TOTAL)}
    before = {name: s.get() for name, s in stats.items()}
    dt, results = run_once()
    delta = {name: int(s.get() - before[name])
             for name, s in stats.items()}
    generated = sum(len(r.token_ids) for r in results)
    steps = delta[gmetrics.STEPS_TOTAL]
    proposed = delta[gmetrics.SPEC_PROPOSED_TOKENS]
    accepted = delta[gmetrics.SPEC_ACCEPTED_TOKENS]
    snap = eng.metrics.snapshot()
    cell = {
        "cell": "spec",
        "workload": workload,
        "spec_mode": spec_mode or "off",
        "spec_tokens": spec_tokens,
        "batch": batch,
        "context": context,
        "new_tokens": new_tokens,
        "warmup_s": round(warmup_s, 4),
        "elapsed_s": round(dt, 4),
        "generated": int(generated),
        "tokens_per_s": round(generated / dt, 1) if dt > 0 else None,
        "steps": steps,
        "tokens_per_step": round(generated / steps, 3) if steps else None,
        "spec_proposed": proposed,
        "spec_accepted": accepted,
        "spec_rewind": delta[gmetrics.SPEC_REWIND_TOKENS],
        "acceptance_rate": (round(accepted / proposed, 3)
                            if proposed else None),
        # mean accepted drafts per VERIFY ROW (one row per drafting
        # sequence per step — the true mean accepted length; the
        # per-dispatch bonus token is excluded)
        "mean_accepted_len": (
            round(accepted / delta[gmetrics.SPEC_DRAFT_ROWS], 3)
            if delta[gmetrics.SPEC_DRAFT_ROWS] else None),
        "dispatches_per_step":
            snap["generation.decode_dispatches_per_step"],
        "host_syncs_per_step":
            snap["generation.decode_host_syncs_per_step"],
        "measured_compiles": delta[gmetrics.DECODE_COMPILES_TOTAL]
            + delta[gmetrics.PREFILL_COMPILES_TOTAL],
    }
    eng.shutdown()
    return cell


def bench_loop(model, batch, context, new_tokens, page_size, loop_steps,
               spec_tokens=0, stochastic=False, ttft_probe=False):
    """One HOST-FREE DECODE LOOP A/B cell: the ragged engine at
    loop_steps=N (N ragged iterations fused into ONE dispatch, ONE
    host fetch per N tokens per row) vs the per-step N=1 baseline.

    The decode-bound cell the loop exists for: short prompts, long
    generations, so nearly every engine boundary is decode-only and
    takes the fused loop.  Reports steady-state tokens/s, host
    fetches per token (<= 1/N is the acceptance floor), dispatches
    per boundary (must stay 1), early exits and wasted iterations
    (rows finishing mid-loop), and — with `ttft_probe` — the TTFT of
    a prompt submitted mid-stream, which can only join at a loop
    boundary: the join-latency cost the N knob trades against
    throughput (docs/GENERATION.md "Host-free decode loop")."""
    from paddle_tpu import generation as g
    from paddle_tpu.generation import metrics as gmetrics
    from paddle_tpu.profiler.monitor import StatRegistry

    rng = np.random.default_rng(9000 + batch)
    prompts = [rng.integers(0, model.vocab_size, context).tolist()
               for _ in range(batch)]
    horizon = new_tokens + loop_steps + spec_tokens
    pages = ((context + horizon) // page_size + 2) * (batch + 1)
    kw = {}
    if spec_tokens:
        kw.update(spec_mode="ngram", spec_tokens=spec_tokens)
    eng = g.GenerationEngine(
        model,
        g.GenerationConfig(max_decode_slots=batch, num_pages=pages,
                           page_size=page_size, queue_depth=batch * 2,
                           kv_backend="device", step_mode="ragged",
                           loop_steps=loop_steps, **kw),
        start=False)
    samp = (g.SamplingParams(temperature=0.9, top_k=16, seed=5)
            if stochastic else None)

    def run_once():
        t0 = time.perf_counter()
        handles = [eng.submit(p, max_new_tokens=new_tokens,
                              sampling=samp or g.SamplingParams())
                   for p in prompts]
        eng.run_until_idle()
        dt = time.perf_counter() - t0
        return dt, [h.result(timeout=1) for h in handles]

    warmup_s, _ = run_once()
    reg = StatRegistry.instance()
    counters = {name: reg.get_stat(name) for name in (
        gmetrics.STEPS_TOTAL, gmetrics.LOOP_EARLY_EXITS,
        gmetrics.LOOP_WASTED_STEPS,
        gmetrics.DECODE_COMPILES_TOTAL, gmetrics.PREFILL_COMPILES_TOTAL)}
    before = {name: s.get() for name, s in counters.items()}
    dt, results = run_once()
    delta = {name: int(s.get() - before[name])
             for name, s in counters.items()}
    ttft_join_s = None
    if ttft_probe:
        # a prompt submitted while the batch decodes joins at the next
        # loop boundary: its TTFT carries up to N-1 steps of wait
        bg = [eng.submit(p, max_new_tokens=new_tokens)
              for p in prompts[:max(1, batch - 1)]]
        while not eng.scheduler.decode_ready():
            eng.step()
        probe = eng.submit(prompts[-1][:4], max_new_tokens=4)
        eng.run_until_idle()
        for h in bg + [probe]:
            h.result(timeout=1)
        ttft_join_s = probe.first_token_s - probe.submitted_s
    generated = sum(len(r.token_ids) for r in results)
    steps = delta[gmetrics.STEPS_TOTAL]
    snap = eng.metrics.snapshot()
    cell = {
        "cell": "loop",
        "loop_steps": loop_steps,
        "spec_tokens": spec_tokens,
        "stochastic": bool(stochastic),
        "batch": batch,
        "context": context,
        "new_tokens": new_tokens,
        "warmup_s": round(warmup_s, 4),
        "elapsed_s": round(dt, 4),
        "generated": int(generated),
        "tokens_per_s": round(generated / dt, 1) if dt > 0 else None,
        "steps": steps,
        "tokens_per_step": round(generated / steps, 3) if steps else None,
        # the acceptance ratio: cumulative host fetches over decode
        # tokens for THIS engine (stamped 0.0 at build, so the N=1
        # baseline reports 0.0 — it never takes the loop path)
        "host_fetches_per_token":
            snap["generation.decode_host_fetches_per_token"],
        "loop_early_exits": delta[gmetrics.LOOP_EARLY_EXITS],
        "loop_wasted_steps": delta[gmetrics.LOOP_WASTED_STEPS],
        "dispatches_per_step":
            snap["generation.decode_dispatches_per_step"],
        "host_syncs_per_step":
            snap["generation.decode_host_syncs_per_step"],
        "ttft_join_s": (round(ttft_join_s, 4)
                        if ttft_join_s is not None else None),
        "measured_compiles": delta[gmetrics.DECODE_COMPILES_TOTAL]
            + delta[gmetrics.PREFILL_COMPILES_TOTAL],
    }
    eng.shutdown()
    return cell


def bench_chaos(model, seed, n_replicas, requests, new_tokens):
    """The chaos-soak bench cell: a seeded KILL + STALL schedule over
    a subprocess fleet under concurrent streams (serving/disagg/
    chaos.py drill) — stream-gap p50/p95 across the faults, recovery
    wall, breaker trips, wedge kills, replay tokens, and the no-hang/
    no-leak/token-identity invariants as cell facts.  Environments
    without fd-inheriting subprocesses emit a skipped cell instead of
    sinking the whole artifact."""
    from paddle_tpu.serving.disagg.chaos import (chaos_drill,
                                                 kill_stall_plans)

    names = [f"c{i}" for i in range(n_replicas)]
    try:
        report = chaos_drill(
            model, seed=seed, n_replicas=n_replicas,
            n_requests=requests, new_tokens=new_tokens,
            plans=kill_stall_plans(seed, names), watchdog_s=120.0,
            restart_dead=True)
    except AssertionError as e:
        return {"cell": "chaos", "invariant_broken": str(e)}
    except Exception as e:   # noqa: BLE001 — a sandbox without
        # subprocess replicas must not sink the artifact
        return {"cell": "chaos", "skipped": f"{type(e).__name__}: {e}"}
    return {"cell": "chaos", "schedule": "kill+stall", **report}


def bench_pd(model, mode, sessions, long_tokens, new_tokens, page_size,
             chunk_tokens):
    """The P/D-disaggregation A/B cell: a LONG-prompt prefill wave
    arriving concurrently with SHORT interactive requests, run once
    per fleet shape — 'split' (one prefill-class + one decode-class
    replica: longs prefill on one side, hand off, and decode next to
    the shorts) vs 'mixed' (two role-less replicas, the ablation
    baseline where shorts queue behind whatever prefill landed on
    their replica).  The headline number is the SHORT-request
    (decode-class) TTFT p95: split keeps the interactive path clear of
    prefill head-of-line blocking, and the handoff books must show
    pd_handoffs > 0 with migrated_replay_tokens == 0 (the import
    resumes at base, never replays)."""
    from paddle_tpu import generation as g
    from paddle_tpu.profiler.monitor import StatRegistry
    from paddle_tpu.serving import fleet as fleet_mod
    from paddle_tpu.serving.fleet import (FleetConfig, FleetRouter,
                                          ReplicaSpec)

    reg = StatRegistry.instance()
    for name in list(reg.stats()):
        if name.startswith(fleet_mod.PREFIX):
            reg.get_stat(name).reset()
    short_tokens = 4
    total = long_tokens + new_tokens
    pages = (-(-total // page_size) + 2) * (2 * sessions + 2)
    roles = (("prefill", "decode") if mode == "split"
             else ("mixed", "mixed"))
    specs = [
        ReplicaSpec(
            f"{role[:2]}{i}", model,
            g.GenerationConfig(max_decode_slots=4, num_pages=pages,
                               page_size=page_size,
                               queue_depth=2 * sessions + 4,
                               prefix_cache=True,
                               prefill_chunk_tokens=chunk_tokens),
            role=role)
        for i, role in enumerate(roles)]
    fl = FleetRouter(specs, FleetConfig(
        start=True, seed=7,
        pd_prefill_threshold_tokens=max(16, long_tokens // 4)))
    rng = np.random.default_rng(long_tokens * 13 + sessions)
    half = model.vocab_size // 2

    def run_wave(lo, hi):
        longs = [fl.submit(rng.integers(lo, hi, long_tokens).tolist(),
                           max_new_tokens=new_tokens)
                 for _ in range(sessions)]
        shorts = [fl.submit(rng.integers(lo, hi,
                                         short_tokens).tolist(),
                            max_new_tokens=new_tokens)
                  for _ in range(sessions)]
        for h in longs + shorts:
            h.result(timeout=300)
        return longs, shorts

    # warmup from the other vocab half: every per-shape jit is paid
    # before the timed wave, nothing it prefilled warms the real one
    run_wave(half, model.vocab_size)
    for name, rep in fl._replicas.items():
        rep.transport.flush_prefix()
        rep.transport.reset_stats()
        rep.transport.take_prefix_deltas()
        fl._page_index.drop_replica(name)
    for name in list(reg.stats()):
        if name.startswith(fleet_mod.PREFIX):
            reg.get_stat(name).reset()
    longs, shorts = run_wave(0, half)
    snap = fl.stats_snapshot()["fleet"]
    fl.shutdown()

    def ttft(handles):
        gaps = sorted(h.first_token_s - h.submitted_s for h in handles)
        return (round(float(np.percentile(gaps, 50)), 4),
                round(float(np.percentile(gaps, 95)), 4))

    s50, s95 = ttft(shorts)
    l50, l95 = ttft(longs)
    return {
        "scenario": "pd_disagg",
        "mode": mode,
        "replicas": 2,
        "long_prompts": sessions,
        "short_prompts": sessions,
        "long_tokens": long_tokens,
        "short_tokens": short_tokens,
        "new_tokens": new_tokens,
        "decode_class_ttft_p50_s": s50,
        "decode_class_ttft_p95_s": s95,
        "long_ttft_p50_s": l50,
        "long_ttft_p95_s": l95,
        "pd_handoffs": snap.get("fleet.pd_handoffs", 0),
        "pd_handoff_tokens": snap.get("fleet.pd_handoff_tokens", 0),
        "routed_role": snap.get("fleet.routed_role", 0),
        "replay_tokens": snap.get("fleet.migrated_replay_tokens", 0),
        "shed_total": snap.get("fleet.shed_total", 0),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", default="1,4,8")
    ap.add_argument("--contexts", default="32,128")
    ap.add_argument("--new-tokens", type=int, default=32)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--pool", choices=("host", "device", "both"),
                    default="both",
                    help="KV backend A/B: host numpy pools vs "
                         "device-resident DeviceKVPool (donated "
                         "scatter appends); 'both' emits one tokens/s "
                         "series per backend")
    ap.add_argument("--decode", choices=("eager", "fused", "both"),
                    default="eager",
                    help="decode-path A/B: eager per-layer attend "
                         "callbacks vs the fused single-dispatch "
                         "FusedDecodeStep (device pools only — "
                         "host-pool fused cells are skipped); steps/s "
                         "is steady-state with compile/warmup time in "
                         "the separate warmup_s column")
    ap.add_argument("--prefill", choices=("full", "chunked", "both"),
                    default="full",
                    help="prefill-path A/B: one monolithic bucketed "
                         "prefill per prompt vs CHUNKED prefill "
                         "(fixed-size chunks interleaved with decode "
                         "under the step token budget); each series "
                         "adds an 'interleave' cell measuring TTFT and "
                         "decode tokens/s while a long prompt streams "
                         "in")
    ap.add_argument("--chunk-tokens", type=int, default=32,
                    help="chunk size for --prefill chunked/both")
    ap.add_argument("--step", choices=("legacy", "ragged", "both"),
                    default="legacy",
                    help="step-executable A/B: the legacy pair "
                         "(FusedDecodeStep / ChunkedPrefillStep per "
                         "--decode/--prefill) vs the RAGGED mixed-batch "
                         "step (decode rows + the prefill chunk packed "
                         "into ONE dispatch, one executable per pages "
                         "bucket TOTAL, zero dummy rows); ragged cells "
                         "run device pools, report steady-state "
                         "tokens/s, dispatches/step, measured "
                         "row_utilization and padded_token_waste, and "
                         "add their own TTFT-under-interleave cell")
    ap.add_argument("--prefix", choices=("off", "on", "both"),
                    default="off",
                    help="prefix-cache A/B: a shared-system-prompt "
                         "workload (one long system prefix, distinct "
                         "short user suffixes) per pool backend — warm "
                         "vs cold TTFT, prefill tokens computed, hit "
                         "tokens, live shared_pages, COW copies; "
                         "'both' emits an off and an on cell")
    ap.add_argument("--prefix-users", type=int, default=8,
                    help="concurrent users sharing the system prompt "
                         "in the --prefix scenario")
    ap.add_argument("--replicas", default="0",
                    help="fleet-tier A/B: '1' (single-replica "
                         "baseline), 'N' (a 2-replica fleet), 'both', "
                         "or an explicit replica count; '0' (default) "
                         "skips the scenario.  Multi-replica cells run "
                         "TWICE — affinity routing (session -> prefix "
                         "-> least-loaded) vs random — over a "
                         "shared-system-prompt multi-turn session "
                         "workload, reporting per-replica hit rate, "
                         "shed rate, and TTFT p50/p95")
    ap.add_argument("--fleet-sessions", type=int, default=8,
                    help="concurrent sessions in the --replicas "
                         "scenario (each runs 2 turns)")
    ap.add_argument("--fleet-transport",
                    choices=("inproc", "proc", "tcp", "both"),
                    default="inproc",
                    help="replica process boundary A/B for the fleet "
                         "cells: 'inproc' (direct-object engines), "
                         "'proc' (one OS process per replica behind "
                         "the SubprocTransport RPC boundary), 'tcp' "
                         "(the same worker dialing back over a real "
                         "TCP socket — the cross-host rung), or "
                         "'both' (inproc + proc).  Each transport "
                         "also emits a "
                         "DRAIN-MIGRATION probe cell pair — live "
                         "migration vs cold resubmit — reporting "
                         "stream-gap p95 across the drain, "
                         "migrated_replay_tokens (live must report 0) "
                         "and page-service adoption counters")
    ap.add_argument("--page-transfer",
                    choices=("off", "relay", "p2p", "both"),
                    default="off",
                    help="data-plane A/B: a 2-replica fleet ships one "
                         "warm prefix to the importer per cell — "
                         "'relay' (page payloads ride the router "
                         "socket) vs 'p2p' (the importer dials the "
                         "holder's data port; router_relay_bytes must "
                         "report 0), or 'both'.  Each cell reports "
                         "wire bytes, raw bytes, compression ratio, "
                         "async transfer wall, and the warm TTFT the "
                         "importer serves after adoption")
    ap.add_argument("--page-codec",
                    choices=("raw", "compressed", "both"),
                    default="compressed",
                    help="page payload codec for the --page-transfer "
                         "cells: 'raw' (byte-exact baseline, wire == "
                         "raw) vs 'compressed' (per-page delta filter "
                         "+ zlib, bitwise-lossless, raw fallback per "
                         "array), or 'both' for the codec A/B pair "
                         "per transfer mode")
    ap.add_argument("--mesh", default="1",
                    help="tensor-parallel A/B: '1' (unsharded), 'N' "
                         "(head-sharded over every visible device), "
                         "'both', or an explicit tp_degree.  Sharded "
                         "cells run device pools + fused decode "
                         "(GenerationConfig.mesh — ONE GSPMD dispatch "
                         "per step) and report tp_degree + "
                         "collective_bytes_per_step + kernel_path per "
                         "cell; every sharded combo runs TWICE — jnp "
                         "reference vs the shard_map'd Pallas kernel "
                         "(the kernel-vs-reference A/B under the mesh)")
    ap.add_argument("--kv-quant", choices=("off", "bf16", "int8", "both"),
                    default="off",
                    help="KV storage precision A/B on device pools: "
                         "bf16 vs INT8 pools (per-page per-head abs-max "
                         "scales, in-kernel dequant) — per-cell "
                         "tokens/s, kv_bytes_moved (+ split-out "
                         "kv_scale_bytes), and resident-sequence "
                         "capacity at a FIXED pool byte budget "
                         "(resident_seqs_at_fixed_budget: int8 ~2x "
                         "bf16).  'both' runs the pair; int8 also "
                         "emits a kv_quality cell (max-logit drift + "
                         "greedy-token agreement vs the fp32 oracle — "
                         "the quality gate the lossy path ships under)")
    ap.add_argument("--spec", choices=("off", "ngram", "both"),
                    default="off",
                    help="speculative-decoding A/B on the ragged step: "
                         "spec_mode off vs 'ngram' (prompt-lookup "
                         "proposer, k-token verify in ONE dispatch, "
                         "on-device accept) over a repetition-heavy "
                         "workload (tiled code-like prompts, where "
                         "lookup hits) AND the plain rng workload (the "
                         "overhead-bound cell) — tokens/s, acceptance "
                         "rate, mean tokens/step, rewind tokens, "
                         "dispatches/step (still 1) per cell")
    ap.add_argument("--spec-tokens", type=int, default=3,
                    help="draft cap per speculating row for --spec "
                         "(3 measured best on CPU, where the packed "
                         "axis is real FLOPs; sweep upward on TPU)")
    ap.add_argument("--loop-steps", default="0",
                    help="host-free decode loop A/B on the ragged "
                         "step: comma list of N values (each one cell "
                         "at loop_steps=N; 1 = the per-step baseline) "
                         "or 'both' for the 1,4,8 ladder — decode-"
                         "bound cells reporting tokens/s, host "
                         "fetches/token (<= 1/N), dispatches/step "
                         "(still 1), early exits, wasted iterations, "
                         "and the TTFT of a mid-stream join (which "
                         "waits for a loop boundary); '0' disables")
    ap.add_argument("--loop-stochastic", action="store_true",
                    help="sample the --loop-steps cells at temperature "
                         "0.9/top-k 16 instead of greedy: the "
                         "on-device sampler's cost inside the loop "
                         "vs the host sampler at N=1")
    ap.add_argument("--quant-collectives", action="store_true",
                    help="EQuARX-style quantized-allreduce A/B: every "
                         "SHARDED (tp > 1) combo runs an extra cell "
                         "with quantized_collectives=True — same grid, "
                         "collective_bytes_per_step ~4x lower, "
                         "collective_quantized=1 stamped — paired "
                         "against its fp32-collective sibling")
    ap.add_argument("--pd", choices=("off", "mixed", "split", "both"),
                    default="off",
                    help="prefill/decode disaggregation A/B: a "
                         "long-prompt prefill wave concurrent with "
                         "short interactive requests over a 2-replica "
                         "fleet — 'split' (prefill-class + "
                         "decode-class, longs hand off at "
                         "prompt-consumed) vs 'mixed' (role-less "
                         "ablation baseline), or 'both'.  Reports "
                         "decode-class (short-request) TTFT p50/p95, "
                         "pd_handoffs / pd_handoff_tokens, and "
                         "replay_tokens (must be 0: the import "
                         "resumes at base)")
    ap.add_argument("--chaos", action="store_true",
                    help="chaos-soak cell: a seeded kill+stall fault "
                         "schedule over a 3-replica subprocess fleet "
                         "under concurrent streams — stream-gap "
                         "p50/p95, recovery wall, breaker trips, "
                         "wedge kills, replay tokens; the cell also "
                         "asserts the no-hang / token-identity / "
                         "zero-leak invariants")
    ap.add_argument("--chaos-seed", type=int, default=7,
                    help="fault-schedule seed for --chaos")
    ap.add_argument("--long-context", type=int, default=None,
                    help="long-prompt length for the interleave cell "
                         "(default: 8x the largest --contexts entry)")
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--head-dim", type=int, default=32)
    ap.add_argument("--out", default=None,
                    help="also write the JSON document to this path")
    args = ap.parse_args()

    # a multi-device CPU mesh needs forced host devices, and the flag
    # must land before the backend initializes (no devices have been
    # touched yet — the top-of-module import only sets jax_platforms)
    if (args.mesh != "1" and os.environ.get("JAX_PLATFORMS") == "cpu"
            and "xla_force_host_platform_device_count"
            not in os.environ.get("XLA_FLAGS", "")):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()

    import jax

    from paddle_tpu import generation as g
    from paddle_tpu.profiler.monitor import StatRegistry

    batches = [int(b) for b in args.batches.split(",")]
    contexts = [int(c) for c in args.contexts.split(",")]
    long_ctx = args.long_context or max(contexts) * 8
    model = g.TinyCausalLM(vocab_size=args.vocab, num_layers=args.layers,
                           num_heads=args.heads, head_dim=args.head_dim,
                           max_positions=(max(max(contexts), long_ctx)
                                          + args.new_tokens + 1),
                           seed=0)
    pools = (("host", "device") if args.pool == "both" else (args.pool,))
    decodes = (("eager", "fused") if args.decode == "both"
               else (args.decode,))
    prefills = (("full", "chunked") if args.prefill == "both"
                else (args.prefill,))
    ndev = len(jax.devices())

    def shardable(n):
        # the head axis is the shard axis: the auto degree is the
        # largest device count that divides --heads (an explicit
        # integer skips this and fails loudly in the engine instead)
        while n > 1 and args.heads % n:
            n -= 1
        return n

    if args.mesh == "both":
        tps = sorted({1, shardable(ndev)})
    elif args.mesh == "N":
        tps = [shardable(ndev)]
    else:
        tps = [int(args.mesh)]
    def mesh_kernel_variants(tp):
        # the kernel-vs-reference A/B under the mesh: every sharded
        # combo runs TWICE — the jnp reference (GSPMD-partitioned) and
        # the shard_map'd Pallas kernel — so the artifact carries the
        # first sharded-kernel numbers instead of inferring them.
        # Unsharded cells keep the auto policy (None).
        return (False, True) if tp > 1 else (None,)

    combos = []
    for pool in pools:
        for decode in decodes:
            if decode == "fused" and pool != "device":
                continue  # fused requires donated device pools
            for prefill in prefills:
                for tp in tps:
                    if tp > 1 and (pool, decode) != ("device", "fused"):
                        continue  # sharded decode IS device + fused
                    combos += [(pool, decode, prefill, tp, "legacy", k)
                               for k in mesh_kernel_variants(tp)]
    if max(tps) > 1 and not any(tp > 1 for *_, tp, _, _ in combos):
        # the mesh A/B must not silently vanish because the requested
        # --pool/--decode combo can't shard: force the one that can
        combos += [("device", "fused", prefill, tp, "legacy", k)
                   for prefill in prefills for tp in tps if tp > 1
                   for k in mesh_kernel_variants(tp)]
    if args.step == "legacy":
        pass
    else:
        # the ragged mixed-batch step: one series per prefill mode on
        # device pools (the ragged step's `decode` label IS 'ragged' —
        # the one executable replaces the eager/fused choice), at every
        # requested tp degree — the shard_map'd kernel made mesh cells
        # real kernel cells, so sharded ragged runs the A/B too
        ragged = [("device", "ragged", prefill, tp, "ragged", k)
                  for prefill in prefills for tp in tps
                  for k in mesh_kernel_variants(tp)]
        combos = ragged if args.step == "ragged" else combos + ragged
    grid = []
    stats_by_series = {}
    reg = StatRegistry.instance()

    def reset_gen_stats():
        for name in list(reg.stats()):
            if name.startswith("generation."):
                reg.get_stat(name).reset()

    for pool, decode, prefill, tp, step, kern in combos:
        # per-series snapshot: reset generation.* so each
        # (pool, decode, prefill, tp, step, kernel) combo's stats land
        # apart
        reset_gen_stats()
        for b in batches:
            for ctx in contexts:
                # pool sized to fit the cell w/o preemption noise
                pages = ((ctx + args.new_tokens)
                         // args.page_size + 2) * b
                grid.append(bench_cell(
                    model, b, ctx, args.new_tokens, pages,
                    args.page_size, pool, decode, prefill,
                    args.chunk_tokens, tp=tp, step=step,
                    use_kernel=kern))
        # the prefill/decode-interleave cell: decode throughput
        # while a long prompt streams in (the chunked-prefill
        # headline number; unsharded — the mesh A/B is the grid's)
        ib = max(batches)
        if ib > 1 and tp == 1:
            grid.append(bench_interleave(
                model, ib, min(contexts), long_ctx,
                args.new_tokens, args.page_size, pool, decode,
                prefill, args.chunk_tokens, step=step))
            if prefill == "chunked":
                # the multi-prompt packing A/B: the same interleave
                # traffic with packing OFF (one chunk per step) — the
                # late short's ttft_short_behind_long_s is the paired
                # number packing strictly improves
                grid.append(bench_interleave(
                    model, ib, min(contexts), long_ctx,
                    args.new_tokens, args.page_size, pool, decode,
                    prefill, args.chunk_tokens, step=step, pack=False))
        series = f"{pool}/{decode}/{prefill}" + (
            f"/tp{tp}" if tp > 1 else "") + (
            "" if kern is None else
            ("/kernel" if kern else "/ref"))
        stats_by_series[series] = reg.stats_snapshot("generation.")

    if args.kv_quant != "off":
        # KV precision A/B on device pools (fused decode — the
        # CPU-forced fast path, so the bytes numbers are the device
        # story): bf16 vs int8 cells at the SAME page count; the
        # capacity headline is the per-cell
        # resident_seqs_at_fixed_budget arithmetic
        kv_menu = {"bf16": ("bfloat16",), "int8": ("int8",),
                   "both": ("bfloat16", "int8")}[args.kv_quant]
        for dt in kv_menu:
            reset_gen_stats()
            for b in batches:
                for ctx in contexts:
                    pages = ((ctx + args.new_tokens)
                             // args.page_size + 2) * b
                    grid.append(bench_cell(
                        model, b, ctx, args.new_tokens, pages,
                        args.page_size, "device", "fused", "full",
                        args.chunk_tokens, kv_dtype=dt))
            stats_by_series[f"device/fused/kvq-{dt}"] = \
                reg.stats_snapshot("generation.")
        if "int8" in kv_menu:
            # the quality gate as a bench artifact: drift + agreement
            # vs the fp32 oracle on the seeded workload — the contract
            # the lossy cells ship under travels WITH their numbers
            from paddle_tpu.generation.quality import kv_quality_report

            ctx0 = min(contexts)
            pages = ((ctx0 + args.new_tokens)
                     // args.page_size + 2) * max(batches)
            mk = lambda **kw: g.GenerationConfig(  # noqa: E731
                max_decode_slots=max(batches), num_pages=pages,
                page_size=args.page_size, kv_backend="device", **kw)
            grid.append({
                "cell": "kv_quality",
                "kv_quant_dtype": "int8",
                **kv_quality_report(model, mk(), mk(kv_dtype="int8"),
                                    max_new_tokens=args.new_tokens),
            })
    if args.quant_collectives:
        # the quantized-allreduce A/B: every sharded degree reruns the
        # grid with quantized_collectives=True — pair each /qcol cell
        # with its fp32-collective sibling from the main grid and read
        # collective_bytes_per_step (~4x lower) + tokens/s
        q_step = "ragged" if args.step in ("ragged", "both") else "legacy"
        q_decode = "ragged" if q_step == "ragged" else "fused"
        for tp in [t for t in tps if t > 1]:
            reset_gen_stats()
            for b in batches:
                for ctx in contexts:
                    pages = ((ctx + args.new_tokens)
                             // args.page_size + 2) * b
                    grid.append(bench_cell(
                        model, b, ctx, args.new_tokens, pages,
                        args.page_size, "device", q_decode, "full",
                        args.chunk_tokens, tp=tp, step=q_step,
                        use_kernel=True, quant_collectives=True,
                        kv_dtype=("int8" if args.kv_quant
                                  in ("int8", "both") else None)))
            stats_by_series[f"device/{q_decode}/tp{tp}/qcol"] = \
                reg.stats_snapshot("generation.")
    if args.spec != "off":
        # the speculative-decoding A/B: ragged engine, off vs ngram,
        # repeat-heavy (prompt lookup hits) and random (overhead-bound)
        spec_modes = ((None, "ngram") if args.spec == "both"
                      else ("ngram",))
        sb = max(batches)
        for workload in ("repeat", "random"):
            for mode in spec_modes:
                reset_gen_stats()
                grid.append(bench_spec(
                    model, sb, min(contexts), args.new_tokens,
                    args.page_size, mode, args.spec_tokens, workload))
                stats_by_series[
                    f"device/spec-{mode or 'off'}/{workload}"] = \
                    reg.stats_snapshot("generation.")
    if args.loop_steps != "0":
        # the host-free decode loop A/B: one decode-bound cell per N,
        # N=1 as the per-step baseline of the same ragged engine
        ns = ([1, 4, 8] if args.loop_steps == "both"
              else sorted({int(x) for x in args.loop_steps.split(",")}))
        lb = max(batches)
        for n in ns:
            reset_gen_stats()
            grid.append(bench_loop(
                model, lb, min(contexts), args.new_tokens,
                args.page_size, n, stochastic=args.loop_stochastic,
                ttft_probe=True))
            stats_by_series[f"device/loop-{n}"] = \
                reg.stats_snapshot("generation.")
    if args.prefix != "off":
        # the shared-system-prompt A/B: chunked prefill (warm hits
        # resume mid-prompt through the chunk loop), one cell per
        # (pool, cache mode); system prompt 2x the largest context
        modes = (("off", "on") if args.prefix == "both"
                 else (args.prefix,))
        sys_tokens = max(contexts) * 2
        for pool in pools:
            for mode in modes:
                reset_gen_stats()
                grid.append(bench_prefix(
                    model, args.prefix_users, sys_tokens, 8,
                    args.new_tokens, args.page_size, pool,
                    prefix_on=(mode == "on"),
                    chunk_tokens=args.chunk_tokens))
                stats_by_series[f"{pool}/prefix-{mode}"] = \
                    reg.stats_snapshot("generation.")
    if args.replicas != "0":
        # the fleet-tier A/B: multi-turn sessions over a shared system
        # prompt, affinity vs random routing per replica count
        if args.replicas == "both":
            counts = [1, 2]
        elif args.replicas == "N":
            counts = [2]
        else:
            counts = [int(args.replicas)]
        sys_tokens = max(contexts)
        transports = (("inproc", "proc")
                      if args.fleet_transport == "both"
                      else (args.fleet_transport,))
        for transport in transports:
            for n in counts:
                routings = ("affinity",) if n == 1 \
                    else ("affinity", "random")
                for routing in routings:
                    grid.append(bench_fleet(
                        model, n, args.fleet_sessions, sys_tokens, 8,
                        args.new_tokens, args.page_size, routing,
                        args.chunk_tokens, transport=transport))
            # the drain-migration probe: live vs cold-resubmit per
            # transport (stream-gap p95, migrated_replay_tokens — the
            # live-migration acceptance number is the 0)
            for live in (True, False):
                grid.append(bench_drain_migration(
                    model, transport, live, sys_tokens,
                    max(32, args.new_tokens), args.page_size,
                    args.chunk_tokens))
    if args.page_transfer != "off":
        # the data-plane A/B: relay vs p2p wire x raw vs compressed
        # codec — one adoption cell per combo, router_relay_bytes the
        # p2p acceptance number (0) and compression_ratio the honest
        # measured ratio on this model's pages
        pt_modes = (("relay", "p2p") if args.page_transfer == "both"
                    else (args.page_transfer,))
        pc_modes = (("raw", "compressed") if args.page_codec == "both"
                    else (args.page_codec,))
        for transfer in pt_modes:
            for codec in pc_modes:
                grid.append(bench_page_transfer(
                    model, transfer, codec, max(contexts),
                    args.new_tokens, args.page_size,
                    args.chunk_tokens))
    if args.pd != "off":
        # P/D disaggregation A/B: split (prefill + decode classes)
        # vs mixed (role-less baseline) under the same long-wave +
        # interactive workload — the decode-class TTFT p95 is the
        # headline, the handoff books are the proof of mechanism
        pd_modes = (("mixed", "split") if args.pd == "both"
                    else (args.pd,))
        for mode in pd_modes:
            grid.append(bench_pd(
                model, mode, args.fleet_sessions, max(contexts),
                args.new_tokens, args.page_size, args.chunk_tokens))
    if args.chaos:
        # the chaos soak: seeded kill+stall over a subprocess fleet —
        # the robustness sibling of the drain probe (faults INJECTED,
        # not administered)
        grid.append(bench_chaos(model, args.chaos_seed, 3, 8,
                                max(8, min(16, args.new_tokens))))
    doc = {
        "bench": "generation_decode",
        "platform": jax.devices()[0].platform,
        "model": {"vocab": args.vocab, "layers": args.layers,
                  "heads": args.heads, "head_dim": args.head_dim},
        "pools": list(pools),
        "decodes": list(decodes),
        "prefills": list(prefills),
        "tp_degrees": list(tps),
        "step": args.step,
        "spec": args.spec,
        "chunk_tokens": args.chunk_tokens,
        "prefix": args.prefix,
        "replicas": args.replicas,
        "fleet_transport": args.fleet_transport,
        "pd": args.pd,
        "page_transfer": args.page_transfer,
        "page_codec": args.page_codec,
        "chaos": bool(args.chaos),
        "grid": grid,
        "stats": stats_by_series,
    }
    line = json.dumps(doc)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
