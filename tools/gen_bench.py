#!/usr/bin/env python
"""Decode microbench: tokens/s across batch x context for the
paddle_tpu.generation engine (BENCH-style JSON to stdout).

Measures the paged-KV continuous-batching decode loop end to end —
prefill, paged decode attention (Pallas kernel on TPU, jnp reference on
CPU), sampling, scheduling — with the `generation.*` StatRegistry
snapshot embedded in the artifact (the stats_snapshot() export), so a
TPU-window run leaves the same evidence trail as BENCH_TPU_SESSION.json.

Usage:
    python tools/gen_bench.py                    # default grid
    python tools/gen_bench.py --batches 1,4,8 --contexts 32,128 \
        --new-tokens 32 --out BENCH_GEN.json
"""
import argparse
import json
import os
import sys
import time

import numpy as np

# runnable as `python tools/gen_bench.py` from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor JAX_PLATFORMS=cpu *before* backend init (see op_bench.py)
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")


def bench_cell(model, batch, context, new_tokens, num_pages, page_size,
               pool):
    from paddle_tpu import generation as g
    from paddle_tpu.generation import metrics as gmetrics
    from paddle_tpu.profiler.monitor import StatRegistry

    eng = g.GenerationEngine(
        model,
        g.GenerationConfig(max_decode_slots=batch, num_pages=num_pages,
                           page_size=page_size, queue_depth=batch * 2,
                           kv_backend=pool),
        start=False)
    rng = np.random.default_rng(batch * 1000 + context)
    prompts = [rng.integers(0, model.vocab_size, context).tolist()
               for _ in range(batch)]
    reg = StatRegistry.instance()
    kv_stat = reg.get_stat(gmetrics.KV_BYTES_MOVED)
    pf_stat = reg.get_stat(gmetrics.PREFILL_TOKENS_TOTAL)
    kv_before, pf_before = kv_stat.get(), pf_stat.get()
    t0 = time.perf_counter()
    handles = [eng.submit(p, max_new_tokens=new_tokens) for p in prompts]
    eng.run_until_idle()
    dt = time.perf_counter() - t0
    results = [h.result(timeout=1) for h in handles]
    generated = sum(len(r.token_ids) for r in results)
    kv_bytes = int(kv_stat.get() - kv_before)
    # prefill writes (incl. preemption re-prefills) are exactly the
    # prefill token count x K+V payload; subtracting them leaves the
    # decode-side traffic the O(pool)-vs-O(tokens) A/B is about
    prefill_bytes = (int(pf_stat.get() - pf_before) * 2 * model.num_layers
                     * model.num_heads * model.head_dim * 4)
    eng.shutdown()
    return {
        "pool": pool,
        "batch": batch,
        "context": context,
        "new_tokens": new_tokens,
        "generated": generated,
        "wall_s": round(dt, 4),
        "tokens_per_s": round(generated / dt, 2) if dt > 0 else 0.0,
        "preemptions": sum(r.preemptions for r in results),
        "kv_bytes_moved": kv_bytes,          # total, prefill included
        "kv_prefill_bytes": prefill_bytes,
        # decode-side bytes per generated token: O(pool) for host pools,
        # O(batch x layers x heads x head_dim) for DeviceKVPool —
        # context-independent by construction for the device backend
        "kv_decode_bytes_per_token": round(
            (kv_bytes - prefill_bytes) / max(generated, 1), 1),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", default="1,4,8")
    ap.add_argument("--contexts", default="32,128")
    ap.add_argument("--new-tokens", type=int, default=32)
    ap.add_argument("--page-size", type=int, default=16)
    ap.add_argument("--pool", choices=("host", "device", "both"),
                    default="both",
                    help="KV backend A/B: host numpy pools vs "
                         "device-resident DeviceKVPool (donated "
                         "scatter appends); 'both' emits one tokens/s "
                         "series per backend")
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--head-dim", type=int, default=32)
    ap.add_argument("--out", default=None,
                    help="also write the JSON document to this path")
    args = ap.parse_args()

    import jax

    from paddle_tpu import generation as g
    from paddle_tpu.profiler.monitor import StatRegistry

    batches = [int(b) for b in args.batches.split(",")]
    contexts = [int(c) for c in args.contexts.split(",")]
    model = g.TinyCausalLM(vocab_size=args.vocab, num_layers=args.layers,
                           num_heads=args.heads, head_dim=args.head_dim,
                           max_positions=max(contexts) + args.new_tokens + 1,
                           seed=0)
    pools = (("host", "device") if args.pool == "both" else (args.pool,))
    grid = []
    stats_by_pool = {}
    reg = StatRegistry.instance()
    for pool in pools:
        # per-pool snapshot: reset generation.* so each backend's stats
        # (kv_bytes_moved above all) land separately in the artifact
        for name in list(reg.stats()):
            if name.startswith("generation."):
                reg.get_stat(name).reset()
        for b in batches:
            for ctx in contexts:
                # pool sized to fit the cell without preemption noise
                pages = ((ctx + args.new_tokens) // args.page_size + 2) * b
                grid.append(bench_cell(model, b, ctx, args.new_tokens,
                                       pages, args.page_size, pool))
        stats_by_pool[pool] = reg.stats_snapshot("generation.")
    doc = {
        "bench": "generation_decode",
        "platform": jax.devices()[0].platform,
        "model": {"vocab": args.vocab, "layers": args.layers,
                  "heads": args.heads, "head_dim": args.head_dim},
        "pools": list(pools),
        "grid": grid,
        "stats": stats_by_pool,
    }
    line = json.dumps(doc)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")


if __name__ == "__main__":
    main()
