"""CPU perf rails: committed numbers that catch regressions without TPU.

VERDICT r2 #6: when the TPU pool is down, the only perf signal the
project has must live in-repo.  This tool measures (a) the op_bench
jitted-op latencies and (b) compile-time rails — time-to-first-step for
12-layer BERT/GPT CompiledTrainSteps, scan_layers on vs off (the
scan-vs-unrolled compile claim in docs/PERF.md) — and writes
BENCH_CPU_RAILS.json at the repo root.  tests/test_perf_rails.py
re-measures a fast subset and fails on >2x regressions vs the committed
file.

Run:  python tools/cpu_rails.py          # refresh the committed rails
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _force_cpu():
    """Standalone runs force CPU before first backend init; under pytest
    the conftest already did (import-time config flips would be
    ineffective or would hijack later tests in the same process)."""
    import jax

    jax.config.update("jax_platforms", "cpu")

RAILS_PATH = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "BENCH_CPU_RAILS.json")

OP_SUITE = [
    {"op": "matmul", "shapes": [[512, 512], [512, 512]], "repeat": 20},
    {"op": "elementwise_add", "shapes": [[2048, 512], [2048, 512]],
     "repeat": 30},
    {"op": "softmax", "shapes": [[256, 512]], "repeat": 30},
    {"op": "reduce_sum", "shapes": [[2048, 512]], "repeat": 30},
    {"op": "layer_norm", "shapes": [[256, 512]], "repeat": 20},
    {"op": "conv2d", "shapes": [[4, 32, 28, 28], [32, 32, 3, 3]],
     "repeat": 10},
]


def measure_ops(repeat_scale=1.0):
    from tools.op_bench import bench_one

    out = {}
    for cfg in OP_SUITE:
        cfg = dict(cfg)
        cfg["repeat"] = max(3, int(cfg["repeat"] * repeat_scale))
        rec = bench_one(cfg)
        out[rec["op"]] = {"jit_us": rec["jit_us"],
                          "eager_us": rec["eager_us"]}
    return out


def time_to_first_step(model_kind, scan_layers, num_layers=12, hidden=256):
    """Seconds from trainer construction to the first completed step —
    dominated by trace+compile; the scan_layers rail keeps the
    'depth-constant HLO compiles ~3x faster' claim measured."""
    import paddle_tpu as paddle
    from paddle_tpu.parallel.env import build_mesh
    from paddle_tpu.parallel.hybrid import CompiledTrainStep

    paddle.seed(0)
    if model_kind == "bert":
        from paddle_tpu.models.bert import BertForPretraining, BertConfig

        cfg = BertConfig(vocab_size=1024, hidden_size=hidden,
                         num_layers=num_layers, num_heads=4,
                         ffn_hidden=hidden * 4, dropout=0.0,
                         scan_layers=scan_layers)
        model = BertForPretraining(cfg)
    else:
        from paddle_tpu.models.gpt import GPTForPretraining, GPTConfig

        cfg = GPTConfig(vocab_size=1024, hidden_size=hidden,
                        num_layers=num_layers, num_heads=4,
                        max_seq_len=64, dropout=0.0,
                        scan_layers=scan_layers)
        model = GPTForPretraining(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    mesh = build_mesh({"data": 1})
    tr = CompiledTrainStep(model, lambda m, i, l: m.loss(i, l), opt, mesh)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (2, 64)).astype(np.int32)
    t0 = time.perf_counter()
    loss = tr.step(paddle.to_tensor(ids), paddle.to_tensor(ids))
    float(np.asarray(loss._data))
    return time.perf_counter() - t0


def measure_compile():
    return {
        "bert12_scan_s": round(time_to_first_step("bert", True), 2),
        "bert12_noscan_s": round(time_to_first_step("bert", False), 2),
        "gpt12_scan_s": round(time_to_first_step("gpt", True), 2),
    }


def main():
    import datetime

    _force_cpu()
    import jax

    rails = {
        "schema": 1,
        "date": datetime.date.today().isoformat(),
        "jax": jax.__version__,
        "ops": measure_ops(),
        "compile": measure_compile(),
    }
    with open(RAILS_PATH, "w") as f:
        json.dump(rails, f, indent=1, sort_keys=True)
    print(json.dumps(rails, indent=1, sort_keys=True))


if __name__ == "__main__":
    main()
