#!/usr/bin/env python
"""Wide dy2static property-fuzz sweep (CPU-forced).

The committed suite (tests/test_dy2static_fuzz.py) pins 18 seeds; this
tool sweeps an arbitrary range for pre-commit confidence when touching
the transformer:

    python tools/d2s_fuzz_sweep.py 0 500

Prints one line per failure (seed, exception, message) and a summary;
exit code 1 on any failure.  Always CPU-forced — never touches the TPU
tunnel.
"""
import os
import sys

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tests"))

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
from test_dy2static_fuzz import _compile_fn, _gen_program  # noqa: E402


def main():
    lo = int(sys.argv[1]) if len(sys.argv) > 1 else 0
    hi = int(sys.argv[2]) if len(sys.argv) > 2 else lo + 100
    xs = [np.linspace(-1.0, 1.0, 6).astype(np.float32).reshape(2, 3),
          -np.ones((2, 3), np.float32),
          np.full((2, 3), 2.0, np.float32)]
    fails = []
    for seed in range(lo, hi):
        src = _gen_program(seed)
        try:
            f = _compile_fn(src)
            eager = [np.asarray(f(paddle.to_tensor(x)).numpy())
                     for x in xs]
            jf = paddle.jit.to_static(_compile_fn(src))
            for x, want in zip(xs, eager):
                got = np.asarray(jf(paddle.to_tensor(x)).numpy())
                np.testing.assert_allclose(got, want, rtol=1e-5,
                                           atol=1e-6)
        except Exception as e:  # noqa: BLE001 — report and continue
            fails.append((seed, type(e).__name__, str(e)[:160]))
            print(f"FAIL seed={seed}: {type(e).__name__}: "
                  f"{str(e)[:160]}", flush=True)
    print(f"{len(fails)} failures of {hi - lo}")
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
