#!/usr/bin/env python
"""Config-driven single-op timing harness.

Reference: paddle/fluid/operators/benchmark/op_tester.cc (+op_tester.cfg):
time one op from a config of {op, shapes, dtype, repeat}.  TPU-native: each
op is timed twice — eager (per-call dispatch, tracer path) and jitted
(compiled, what production steps see) — with block_until_ready fencing.

Usage:
    python tools/op_bench.py                      # built-in suite
    python tools/op_bench.py --config ops.json    # custom suite
    python tools/op_bench.py --op matmul --shape 1024x1024 --repeat 50
"""
import argparse
import json
import os
import sys
import time

import numpy as np

# runnable as `python tools/op_bench.py` from anywhere
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# honor JAX_PLATFORMS=cpu *before* backend init: the env var alone does not
# override an installed TPU plugin's platform selection
if os.environ.get("JAX_PLATFORMS") == "cpu":
    import jax as _jax

    _jax.config.update("jax_platforms", "cpu")


DEFAULT_SUITE = [
    {"op": "matmul", "shapes": [[1024, 1024], [1024, 1024]], "repeat": 30},
    {"op": "elementwise_add", "shapes": [[4096, 1024], [4096, 1024]],
     "repeat": 50},
    {"op": "softmax", "shapes": [[256, 1024]], "repeat": 50},
    {"op": "reduce_sum", "shapes": [[4096, 1024]], "repeat": 50},
    {"op": "relu", "shapes": [[4096, 1024]], "repeat": 50},
    {"op": "layer_norm", "shapes": [[256, 1024]], "repeat": 30},
    {"op": "conv2d", "shapes": [[8, 64, 56, 56], [64, 64, 3, 3]],
     "repeat": 10},
    # attention-shaped batched matmul (scores: [B*H, S, d] x [B*H, d, S])
    {"op": "matmul", "shapes": [[96, 512, 64], [96, 64, 512]],
     "repeat": 20},
    {"op": "gelu", "shapes": [[4096, 1024]], "repeat": 50},
    {"op": "tanh", "shapes": [[4096, 1024]], "repeat": 50},
    {"op": "transpose", "shapes": [[64, 12, 128, 64]], "repeat": 30,
     "kwargs": {"perm": [0, 2, 1, 3]}},
]


def _resolve(op_name):
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    if op_name == "layer_norm":
        def ln(x):
            return F.layer_norm(x, x.shape[-1:])

        return ln
    if op_name == "conv2d":
        return lambda x, w: F.conv2d(x, w, None, padding=1)
    fn = getattr(paddle, op_name, None) or getattr(F, op_name, None)
    if fn is None:
        # reference registry names (reduce_sum, ...) live in _C_ops
        from paddle_tpu import _C_ops

        try:
            fn = getattr(_C_ops, op_name)
        except NotImplementedError as e:
            raise SystemExit(str(e)) from e  # absent-with-rationale
        except AttributeError:
            raise SystemExit(f"unknown op {op_name!r}")
    return fn


def bench_one(cfg):
    import jax

    import paddle_tpu as paddle

    op = _resolve(cfg["op"])
    rng = np.random.RandomState(0)
    dtype = cfg.get("dtype", "float32")
    kwargs = dict(cfg.get("kwargs", {}))
    args = [paddle.to_tensor(rng.randn(*s).astype(dtype))
            for s in cfg["shapes"]]
    repeat = int(cfg.get("repeat", 30))

    def run_eager():
        out = op(*args, **kwargs)
        jax.block_until_ready(out._data if hasattr(out, "_data") else out)

    raw = None if kwargs else getattr(op, "raw_fn", None)
    if raw is None:
        # wrapper ops without a registered raw kernel: jit the whole
        # eager call over raw arrays (Tensors wrap tracers fine)
        from paddle_tpu.core import autograd
        from paddle_tpu.core.tensor import _wrap_data

        def raw(*vs):
            with autograd.no_grad():
                out = op(*[_wrap_data(v) for v in vs], **kwargs)
            return out._data if hasattr(out, "_data") else out

    arrs = [a._data for a in args]
    jitted = jax.jit(raw)

    run_eager()  # warm
    t0 = time.perf_counter()
    for _ in range(repeat):
        run_eager()
    eager_us = (time.perf_counter() - t0) / repeat * 1e6

    jit_us = None
    jit_error = None
    try:
        jax.block_until_ready(jitted(*arrs))  # compile
        t0 = time.perf_counter()
        for _ in range(repeat):
            jax.block_until_ready(jitted(*arrs))
        jit_us = (time.perf_counter() - t0) / repeat * 1e6
    except Exception as e:  # host-side/untraceable op: eager timing only,
        # but record WHY so kernel regressions stay distinguishable
        jit_error = f"{type(e).__name__}: {e}"[:200]

    rec = {"op": cfg["op"], "shapes": cfg["shapes"], "dtype": dtype,
           "repeat": repeat, "eager_us": round(eager_us, 2),
           "jit_us": round(jit_us, 2) if jit_us is not None else None}
    if jit_error:
        rec["jit_error"] = jit_error
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", help="json list of op configs")
    ap.add_argument("--op")
    ap.add_argument("--shape", help="AxB[,CxD...] per input")
    ap.add_argument("--repeat", type=int, default=30)
    args = ap.parse_args()
    if args.op:
        shapes = [[int(d) for d in s.split("x")]
                  for s in (args.shape or "256x256").split(",")]
        suite = [{"op": args.op, "shapes": shapes, "repeat": args.repeat}]
    elif args.config:
        with open(args.config) as f:
            suite = json.load(f)
    else:
        suite = DEFAULT_SUITE
    for cfg in suite:
        print(json.dumps(bench_one(cfg)))


if __name__ == "__main__":
    sys.exit(main())
