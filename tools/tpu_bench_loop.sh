#!/bin/bash
# Retry bench.py against the (intermittently available) TPU pool until a
# real TPU number lands, then stop.  Probes first — the full bench (and
# its CPU fallback) only runs when the tunnel answers.  One jax client at
# a time: the axon tunnel is single-client and concurrent probes wedge it.
# Usage: tools/tpu_bench_loop.sh [max_attempts] [sleep_s]
set -u
MAX=${1:-20}
SLEEP=${2:-600}
OUT=${TPU_BENCH_OUT:-/tmp/bench_tpu_attempt.json}
for i in $(seq 1 "$MAX"); do
  echo "[tpu-bench-loop] attempt $i/$MAX $(date -u +%H:%M:%S)"
  plat=$(timeout 150 python -c \
    "import jax; print('PLATFORM=' + jax.devices()[0].platform)" \
    2>/dev/null | grep PLATFORM= | cut -d= -f2)
  if [ "$plat" != "tpu" ]; then
    echo "[tpu-bench-loop] pool unreachable (got '${plat:-none}'); sleeping ${SLEEP}s"
    sleep "$SLEEP"
    continue
  fi
  echo "[tpu-bench-loop] pool up — running bench"
  line=$(PTN_BENCH_PROBE_TIMEOUT=150 PTN_BENCH_BUDGET_S=1500 \
         timeout 1800 python bench.py 2>"$OUT.stderr" | tail -1)
  echo "$line" > "$OUT.last"
  if echo "$line" | grep -q '"platform": "tpu"' \
     && ! echo "$line" | grep -q '"value": 0.0'; then
    echo "$line" > "$OUT"
    echo "[tpu-bench-loop] SUCCESS on attempt $i"
    # bonus while the window is open: the op-latency table
    # (op_tester.cc analogue — VERDICT r3 missing #6)
    timeout 900 python tools/op_bench.py > "${OUT%.json}_ops.jsonl" \
      2>/dev/null \
      && echo "[tpu-bench-loop] op table -> ${OUT%.json}_ops.jsonl"
    # and the decode microbench (tokens/s grid + generation.* stats
    # snapshot embedded via StatRegistry.stats_snapshot); --pool both
    # lands the host-vs-device KV pool A/B (kv_bytes_moved per token:
    # O(pool) host pools vs O(tokens) DeviceKVPool), --decode both
    # lands the eager-vs-fused single-dispatch A/B (steps/s +
    # dispatches_per_step per cell, warmup/compile time separate),
    # --prefill both lands the full-vs-chunked prefill A/B (TTFT +
    # decode tokens/s during a long-prompt prefill via the interleave
    # cell, prefill compile counts), --mesh both lands the
    # single-chip-vs-tensor-parallel sharded decode A/B (tokens/s and
    # dispatches/step vs tp_degree over the real multi-chip mesh, plus
    # collective_bytes_per_step — the first hardware number for the
    # GSPMD decode collectives), and --prefix both lands the
    # prefix-cache A/B (shared-system-prompt workload: warm vs cold
    # TTFT, prefill tokens computed, hit tokens, live shared_pages)
    # in the same artifact, and --replicas both lands the fleet-tier
    # A/B (multi-replica FleetRouter over a shared-system-prompt
    # multi-turn session workload: per-replica hit rate, shed rate,
    # TTFT p50/p95 with the affinity routing ladder vs random), and
    # --step both lands the legacy-vs-RAGGED mixed-batch step A/B
    # (one packed dispatch serving decode + the MULTI-PROMPT chunk
    # pack: tokens/s, dispatches/step, measured row_utilization,
    # query-tiling score_blocks vs the untiled bill, and — on every
    # SHARDED cell, legacy and ragged alike — the kernel-vs-reference
    # A/B: use_kernel False (GSPMD jnp) vs True (the shard_map'd
    # Pallas kernel) with kernel_path stamped per cell, the first
    # hardware numbers for the mesh-native kernels;
    # padded_token_waste == 0, ragged TTFT under interleave — the
    # first hardware numbers for the ragged Pallas kernel)
    # budget grew with the prefix + fleet + ragged + disagg A/B cells
    # (--fleet-transport both adds proc-replica fleets — each child
    # process pays its own jax import — plus 4 drain-migration probe
    # cells, plus the --chaos soak cell: a seeded kill+stall schedule
    # over a 3-replica subprocess fleet reporting stream-gap p50/p95,
    # recovery wall, breaker trips and replay tokens under the
    # no-hang/no-leak invariants; --loop-steps both lands the
    # host-free decode loop ladder — N in {1, 4, 8} ragged
    # iterations fused into ONE dispatch with on-device sampling and
    # stop matching, reporting tokens/s, host fetches/token <= 1/N,
    # mid-stream-join TTFT — the first hardware numbers for the
    # dispatch-overhead story the loop exists for; --page-transfer
    # both --page-codec both adds the 4-cell data-plane A/B: relay vs
    # p2p wire x raw vs compressed pages, router_relay_bytes == 0 on
    # the p2p cells and the honest measured compression ratio): a
    # timeout kill here drops the WHOLE gen artifact (mesh/prefill
    # numbers included), so the cap tracks the scenario count and a
    # kill at least says so
    timeout 5700 python tools/gen_bench.py --pool both --decode both \
      --prefill both --mesh both --prefix both --replicas both \
      --step both --fleet-transport both --pd both \
      --kv-quant both --quant-collectives --spec both --chaos \
      --loop-steps both --page-transfer both --page-codec both \
      --out "${OUT%.json}_gen.json" \
      >/dev/null 2>&1 \
      && echo "[tpu-bench-loop] gen bench (pool + decode + prefill + mesh + prefix + fleet + ragged-step + disagg-transport + pd-disagg + kv-quant + quant-collectives + spec + chaos + decode-loop + data-plane A/B) -> ${OUT%.json}_gen.json" \
      || echo "[tpu-bench-loop] gen bench failed/timed out; no gen artifact"
    exit 0
  fi
  echo "[tpu-bench-loop] bench ran but no TPU number (tail: ${line:0:120}); sleeping ${SLEEP}s"
  sleep "$SLEEP"
done
echo "[tpu-bench-loop] exhausted $MAX attempts without a TPU number"
exit 1
