#!/usr/bin/env python
"""Seeded chaos soak over a subprocess fleet — the CLI half of
paddle_tpu.serving.disagg.chaos.

Runs the fault schedule (full kind x point matrix by default, or the
kill+stall schedule with --schedule kill-stall) against a
process-per-replica fleet and prints the JSON report: resolve/typed/
hung counts, oracle token identity, leak check, stream-gap
percentiles, recovery wall, and every fleet.* robustness counter.
A non-zero exit means an INVARIANT broke (hung stream, diverged
stream, leaked pages) — this is the command a CI chaos stage runs.

    python tools/chaos_drill.py --seed 7 --replicas 3 --requests 8
    python tools/chaos_drill.py --schedule kill-stall --kv-dtype int8 \
        --pool-layout kernel
    python tools/chaos_drill.py --transport tcp --seed 11

Docs: docs/SERVING.md "Failure model".
"""
import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--replicas", type=int, default=3)
    ap.add_argument("--requests", type=int, default=8)
    ap.add_argument("--prompt-tokens", type=int, default=24)
    ap.add_argument("--new-tokens", type=int, default=10)
    ap.add_argument("--schedule", choices=("matrix", "kill-stall"),
                    default="matrix",
                    help="'matrix' = every fault kind at every named "
                         "protocol point; 'kill-stall' = the "
                         "gen_bench --chaos schedule")
    ap.add_argument("--kv-dtype", choices=("fp32", "bfloat16", "int8"),
                    default="fp32",
                    help="replica KV pool precision (int8 exercises "
                         "the scale-carrying migration payloads under "
                         "chaos)")
    ap.add_argument("--pool-layout", choices=("token", "kernel"),
                    default=None,
                    help="device-pool layout (implies "
                         "kv_backend='device')")
    ap.add_argument("--transport", choices=("proc", "tcp"),
                    default="proc",
                    help="replica wire: 'proc' = pipe-per-child, "
                         "'tcp' = loopback sockets with dial-back "
                         "(the cross-host frame path)")
    ap.add_argument("--watchdog-s", type=float, default=120.0,
                    help="global no-hang budget per stream")
    ap.add_argument("--restart-dead", action="store_true",
                    help="restart dead replicas at the end (exercises "
                         "the respawn-backoff ladder)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    from paddle_tpu.generation import TinyCausalLM
    from paddle_tpu.serving.disagg.chaos import (chaos_drill,
                                                 full_matrix_plans,
                                                 kill_stall_plans)

    model = TinyCausalLM(vocab_size=48, num_layers=2, num_heads=2,
                         head_dim=8, seed=3)
    names = [f"c{i}" for i in range(args.replicas)]
    plans = (kill_stall_plans(args.seed, names)
             if args.schedule == "kill-stall"
             else full_matrix_plans(args.seed, names))
    engine_kw = {}
    if args.pool_layout is not None:
        engine_kw.update(kv_backend="device",
                         pool_layout=args.pool_layout)
    if args.kv_dtype != "fp32":
        engine_kw["kv_dtype"] = args.kv_dtype
        engine_kw.setdefault("kv_backend", "device")
    try:
        report = chaos_drill(
            model, seed=args.seed, n_replicas=args.replicas,
            n_requests=args.requests,
            prompt_tokens=args.prompt_tokens,
            new_tokens=args.new_tokens, plans=plans,
            engine_kw=engine_kw or None,
            fleet_kw=({"transport": "tcp"}
                      if args.transport == "tcp" else None),
            watchdog_s=args.watchdog_s,
            restart_dead=args.restart_dead)
    except AssertionError as e:
        print(json.dumps({"drill": "chaos", "schedule": args.schedule,
                          "invariant_broken": str(e)}))
        return 1
    report = {"drill": "chaos", "schedule": args.schedule,
              "transport": args.transport,
              "kv_dtype": args.kv_dtype,
              "pool_layout": args.pool_layout, **report}
    line = json.dumps(report)
    print(line)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
