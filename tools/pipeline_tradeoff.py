"""Measure the GPipe+remat vs no-remat pipeline tradeoff (VERDICT r2 #3).

pipeline_compile.py's docstring argues the compiled scan+ppermute pipeline
matches 1F1B's bubble fraction and that per-block remat provides 1F1B's
activation-memory bound compiler-side.  This script backs that math with
numbers on the 8-device virtual mesh: per-config compiled temp memory
(activation+workspace), parameter memory, and wall-clock step time for
remat x num_micro combinations.  Output: a markdown table for docs/PERF.md.

Run: python tools/pipeline_tradeoff.py  (CPU-forced, safe alongside TPU use)
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def measure(remat, num_micro, steps=6):
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTForPretraining, GPTConfig
    from paddle_tpu.parallel.env import build_mesh
    from paddle_tpu.parallel.pipeline_compile import (
        GPTPipeAdapter, PipelinedTrainStep,
    )

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=8,
                    num_heads=4, max_seq_len=128, dropout=0.0)
    model = GPTForPretraining(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters())
    mesh = build_mesh({"pipe": 4, "data": 2})
    tr = PipelinedTrainStep(GPTPipeAdapter(model), opt, mesh,
                            num_micro=num_micro, remat=remat)
    rng = np.random.RandomState(0)
    B, L = 16, 128
    ids = rng.randint(0, cfg.vocab_size, (B, L)).astype(np.int32)
    lbl = rng.randint(0, cfg.vocab_size, (B, L)).astype(np.int32)
    ma = tr.memory_analysis(ids, lbl)
    # warmup (compile) + timed dependent steps
    loss = tr.step(ids, lbl)
    float(np.asarray(loss._data))
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = tr.step(ids, lbl)
    float(np.asarray(loss._data))
    dt = (time.perf_counter() - t0) / steps
    return {
        "remat": remat,
        "num_micro": num_micro,
        "temp_mb": ma.temp_size_in_bytes / 2**20 if ma else None,
        "args_mb": ma.argument_size_in_bytes / 2**20 if ma else None,
        "step_s": dt,
        "loss": float(np.asarray(loss._data)),
    }


def main():
    rows = []
    for remat in (False, True):
        for m in (4, 8):
            r = measure(remat, m)
            rows.append(r)
            r["temp_str"] = (f"{r['temp_mb']:.1f}" if r["temp_mb"] is not None
                             else "n/a")
            print(f"# remat={r['remat']} M={r['num_micro']} "
                  f"temp={r['temp_str']}MiB step={r['step_s'] * 1e3:.0f}ms "
                  f"loss={r['loss']:.4f}", file=sys.stderr)
    losses = [r["loss"] for r in rows]
    print("| remat | micro-batches M | temp (activation+workspace) MiB "
          "| step time ms |")
    print("|---|---|---|---|")
    for r in rows:
        print(f"| {r['remat']} | {r['num_micro']} | {r['temp_str']} "
              f"| {r['step_s'] * 1e3:.0f} |")
    print(f"\nloss agreement across configs: "
          f"max|Δ| = {max(losses) - min(losses):.2e}")


if __name__ == "__main__":
    main()
