"""paddle.tensor.creation: tensor creation ops (re-export)."""
from ..ops.creation import *  # noqa: F401,F403
