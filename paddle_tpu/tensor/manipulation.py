"""paddle.tensor.manipulation: reshape/concat/split family (re-export)."""
from ..ops.manipulation import *  # noqa: F401,F403
