"""paddle.tensor.linalg: matmul/cholesky/norm family (re-export)."""
from ..ops.linalg_extra import *  # noqa: F401,F403
from ..ops.math import matmul, norm, dot, mv, bmm, addmm, kron, t  # noqa: F401
