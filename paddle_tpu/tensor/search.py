"""paddle.tensor.search: argmax/topk/where family (re-export)."""
from ..ops.math import (  # noqa: F401
    argmax, argmin, argsort, sort, topk, where, nonzero, masked_select,
)
from ..ops.manipulation import index_select, index_sample  # noqa: F401
