"""paddle.tensor.logic: comparisons and boolean ops (re-export)."""
from ..ops.math import (  # noqa: F401
    equal, not_equal, less_than, less_equal, greater_than, greater_equal,
    logical_and, logical_or, logical_xor, logical_not,
    bitwise_and, bitwise_or, bitwise_xor, bitwise_not,
    equal_all, allclose, isclose,
)
