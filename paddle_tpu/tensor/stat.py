"""paddle.tensor.stat: mean/std/var family (re-export)."""
from ..ops.math import mean  # noqa: F401
from ..ops.linalg_extra import std, var, median  # noqa: F401
from ..ops.math import numel_t as numel  # noqa: F401
