"""paddle.tensor.math: elementwise/reduction math (re-export)."""
from ..ops.math import *  # noqa: F401,F403
