"""paddle.tensor.random: rng creation ops (re-export)."""
from ..ops.creation import (  # noqa: F401
    uniform, rand, randn, normal, randint, randperm, bernoulli, multinomial,
)
