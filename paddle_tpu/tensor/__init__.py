"""paddle.tensor namespace (python/paddle/tensor/): the functional Tensor
API grouped by family.  Our op implementations live in paddle_tpu.ops;
this package re-exports them under the reference's module layout so
`paddle.tensor.math.add`-style imports keep working.
"""
from ..ops.creation import *  # noqa: F401,F403
from ..ops.math import *  # noqa: F401,F403
from ..ops.manipulation import *  # noqa: F401,F403
from ..ops.linalg_extra import *  # noqa: F401,F403

from . import creation  # noqa: F401
from . import linalg  # noqa: F401
from . import logic  # noqa: F401
from . import manipulation  # noqa: F401
from . import math  # noqa: F401
from . import random  # noqa: F401
from . import search  # noqa: F401
from . import stat  # noqa: F401
