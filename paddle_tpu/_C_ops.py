"""paddle._C_ops parity: one callable per reference registry op name.

Reference: pybind/op_function_generator.cc:254-519 code-generates a C fast
path `core.ops.<op_type>` for every registered operator at BUILD time;
python/paddle/_C_ops.py:20 re-exports them.  Dygraph functional APIs call
these names directly.

TPU-native analogue: ops are already Python (pure-jax kernels dispatched
through core.registry.apply_op), so the "generated" surface is a binding
table from canonical reference op names -> our public implementations.
Names the reference spells differently (reshape2, lookup_table_v2, ...)
alias the same callables.  Ops that are intentionally absent raise with
the ABSENT.md rationale instead of AttributeError, so callers get a
actionable error.

The table is also the coverage manifest the op-surface test audits
(tests/test_c_ops_surface.py): every name here must resolve to a real
callable.
"""
import importlib

import paddle_tpu

_ALIASES = {
    # canonical reference name -> attribute path under paddle_tpu
    "abs": "abs", "acos": "acos", "acosh": "acosh", "addmm": "addmm",
    "affine_channel": "affine_channel", "affine_grid": "nn.functional.affine_grid",
    "add_position_encoding": "add_position_encoding",
    "allclose": "allclose", "arg_max": "argmax", "arg_min": "argmin",
    "argsort": "argsort", "asin": "asin", "asinh": "asinh",
    "atanh": "atanh", "assign": "assign",
    "average_accumulates": "incubate.optimizer.average_accumulates",
    "assign_value": "assign_value", "atan": "atan", "atan2": "atan2",
    "batch_norm": "nn.functional.batch_norm", "bce_loss": "nn.functional.binary_cross_entropy",
    "beam_search": "beam_search", "beam_search_decode": "beam_search_decode",
    "bernoulli": "bernoulli", "bilinear_tensor_product": "bilinear_tensor_product",
    "bitwise_and": "bitwise_and", "bitwise_not": "bitwise_not",
    "bitwise_or": "bitwise_or", "bitwise_xor": "bitwise_xor",
    "bmm": "bmm", "bpr_loss": "bpr_loss",
    "broadcast_tensors": "broadcast_tensors", "cast": "cast",
    "ceil": "ceil", "center_loss": "center_loss", "cholesky": "cholesky",
    "chunk_eval": "chunk_eval", "clip": "clip",
    "clip_by_norm": "clip_by_norm", "coalesce_tensor": "coalesce_tensor",
    "concat": "concat", "conj": "conj", "conv2d": "nn.functional.conv2d",
    "conv3d": "nn.functional.conv3d", "conv2d_transpose": "nn.functional.conv2d_transpose",
    "conv3d_transpose": "nn.functional.conv3d_transpose",
    "conv_shift": "conv_shift", "cos": "cos", "cos_sim": "cos_sim",
    "cosh": "cosh", "crf_decoding": "crf_decoding", "crop": "crop",
    "crop_tensor": "crop", "cross": "cross",
    "cross_entropy": "nn.functional.cross_entropy",
    "ctc_align": "ctc_align", "cumprod": "cumprod", "cumsum": "cumsum",
    "cvm": "cvm", "data_norm": "data_norm",
    "deformable_conv": "deformable_conv",
    "deformable_conv_v1": "deformable_conv",
    "deformable_psroi_pooling": "deformable_psroi_pooling",
    "diag": "diag", "diag_v2": "diag", "diag_embed": "nn.functional.diag_embed",
    "diagonal": "diagonal", "digamma": "digamma", "dist": "dist",
    "dot": "dot", "dropout": "nn.functional.dropout",
    "edit_distance": "edit_distance",
    "elementwise_add": "elementwise_add", "elementwise_div": "elementwise_div",
    "elementwise_floordiv": "floor_divide", "elementwise_max": "maximum",
    "elementwise_min": "minimum", "elementwise_mod": "remainder",
    "elementwise_mul": "elementwise_mul", "elementwise_pow": "pow",
    "elementwise_sub": "elementwise_sub", "elu": "nn.functional.elu",
    "empty": "empty", "equal": "equal", "equal_all": "equal_all",
    "erf": "erf", "exp": "exp", "expand_v2": "expand",
    "expand_as_v2": "expand_as", "expm1": "expm1", "eye": "eye",
    "fill_any_like": "full_like", "fill_constant": "full",
    "fill_constant_batch_size_like": "full",
    "fill_zeros_like": "zeros_like", "flatten2": "flatten",
    "flatten_contiguous_range": "flatten", "flip": "flip",
    "floor": "floor", "fsp": "fsp_matrix",
    "fused_softmax_mask_upper_triangle": "softmax_mask_fuse_upper_triangle",
    "gather": "gather", "gather_nd": "gather_nd",
    "get_tensor_from_selected_rows": "get_tensor_from_selected_rows",
    "gather_tree": "nn.functional.gather_tree",
    "gaussian_random": "normal",
    "gaussian_random_batch_size_like": "gaussian_random_batch_size_like",
    "gelu": "nn.functional.gelu", "grid_sampler": "nn.functional.grid_sample",
    "greater_equal": "greater_equal", "greater_than": "greater_than",
    "group_norm": "nn.functional.group_norm", "hard_sigmoid": "nn.functional.hardsigmoid",
    "hard_swish": "nn.functional.hardswish", "hard_tanh": "nn.functional.hardtanh",
    "hierarchical_sigmoid": "nn.functional.hsigmoid_loss",
    "hinge_loss": "nn.functional.hinge_loss", "histogram": "histogram",
    "huber_loss": "huber_loss", "im2sequence": "im2sequence",
    "imag": "imag", "increment": "increment", "index_sample": "index_sample",
    "index_select": "index_select", "instance_norm": "nn.functional.instance_norm",
    "interpolate": "nn.functional.interpolate",
    "interpolate_v2": "nn.functional.interpolate",
    "inverse": "inverse", "isfinite_v2": "isfinite", "isinf_v2": "isinf",
    "isnan_v2": "isnan", "kldiv_loss": "nn.functional.kl_div", "kron": "kron",
    "l1_norm": "l1_norm", "label_smooth": "nn.functional.label_smooth",
    "layer_norm": "nn.functional.layer_norm", "leaky_relu": "nn.functional.leaky_relu",
    "lerp": "lerp", "less_equal": "less_equal", "less_than": "less_than",
    "lgamma": "lgamma", "linear_chain_crf": "linear_chain_crf",
    "linspace": "linspace", "log": "log", "log10": "log10",
    "log1p": "log1p", "log2": "log2", "log_loss": "nn.functional.log_loss",
    "log_softmax": "nn.functional.log_softmax",
    "logical_and": "logical_and", "logical_not": "logical_not",
    "logical_or": "logical_or", "logical_xor": "logical_xor",
    "logsumexp": "logsumexp", "lookup_table": "nn.functional.embedding",
    "lookup_table_v2": "nn.functional.embedding",
    "lrn": "nn.functional.local_response_norm",
    "margin_rank_loss": "nn.functional.margin_ranking_loss",
    "masked_select": "masked_select", "matmul": "matmul",
    "matmul_v2": "matmul", "maxout": "nn.functional.maxout",
    "mean": "mean", "mean_iou": "mean_iou", "memcpy": "memcpy",
    "merge_selected_rows": "merge_selected_rows", "meshgrid": "meshgrid",
    "mish": "nn.functional.mish", "modified_huber_loss": "modified_huber_loss",
    "mul": "matmul", "multinomial": "multinomial", "multiplex": "multiplex",
    "mv": "mv", "nce": "nce", "nll_loss": "nn.functional.nll_loss",
    "norm": "nn.functional.normalize", "not_equal": "not_equal",
    "one_hot": "nn.functional.one_hot", "one_hot_v2": "nn.functional.one_hot",
    "p_norm": "norm", "pad": "nn.functional.pad", "pad2d": "nn.functional.pad",
    "pad3d": "nn.functional.pad", "pad_constant_like": "pad_constant_like",
    "partial_concat": "partial_concat", "partial_sum": "partial_sum",
    "pixel_shuffle": "nn.functional.pixel_shuffle",
    "pool2d": "nn.functional.max_pool2d", "pool3d": "nn.functional.max_pool3d",
    "pool2d_avg": "nn.functional.avg_pool2d",
    "max_pool2d_with_index": "max_pool2d_with_index",
    "positive_negative_pair": "positive_negative_pair",
    "prelu": "nn.functional.prelu", "prroi_pool": "prroi_pool",
    "psroi_pool": "psroi_pool", "py_func": "py_func",
    "randint": "randint", "random_crop": "random_crop",
    "randperm": "randperm", "range": "arange", "rank_loss": "rank_loss",
    "real": "real", "reciprocal": "reciprocal",
    "reduce_all": "all", "reduce_any": "any", "reduce_max": "amax",
    "reduce_mean": "mean", "reduce_min": "amin", "reduce_prod": "prod",
    "reduce_sum": "sum", "relu": "nn.functional.relu",
    "relu6": "nn.functional.relu6", "reshape2": "reshape",
    "reverse": "reverse", "roi_align": "vision.ops.roi_align",
    "roi_pool": "vision.ops.roi_pool", "roll": "roll",
    "row_conv": "row_conv", "rsqrt": "rsqrt", "sample_logits": "sample_logits",
    "sampling_id": "sampling_id", "scale": "scale", "scatter": "scatter",
    "scatter_nd_add": "scatter_nd_add", "seed": "seed",
    "segment_pool": "segment_pool", "selu": "nn.functional.selu",
    "sequence_conv": "sequence_conv", "sequence_expand": "sequence_expand",
    "sequence_mask": "nn.functional.sequence_mask",
    "sequence_pad": "sequence_pad", "sequence_pool": "sequence_pool",
    "sequence_reverse": "sequence_reverse",
    "sequence_softmax": "sequence_softmax", "sequence_unpad": "sequence_unpad",
    "shape": "shape", "shard_index": "shard_index",
    "share_data": "share_data", "shuffle_channel": "shuffle_channel",
    "sigmoid": "nn.functional.sigmoid",
    "sigmoid_cross_entropy_with_logits":
        "nn.functional.binary_cross_entropy_with_logits",
    "sign": "sign", "sin": "sin", "sinh": "sinh", "size": "size",
    "slice": "slice", "smooth_l1_loss": "nn.functional.smooth_l1_loss",
    "softmax": "nn.functional.softmax",
    "softmax_with_cross_entropy": "nn.functional.softmax_with_cross_entropy",
    "softplus": "nn.functional.softplus", "softshrink": "nn.functional.softshrink",
    "softsign": "nn.functional.softsign", "space_to_depth": "space_to_depth",
    "spectral_norm": "ops.nn_extra.spectral_norm_apply",
    "split": "split", "spp": "spp", "sqrt": "sqrt", "square": "square",
    "squared_l2_distance": "squared_l2_distance",
    "squared_l2_norm": "squared_l2_norm", "squeeze2": "squeeze",
    "stack": "stack", "stanh": "stanh", "strided_slice": "strided_slice",
    "sum": "add_n", "t": "t", "tan": "tan", "tanh": "tanh",
    "tanh_shrink": "nn.functional.tanhshrink",
    "teacher_student_sigmoid_loss": "teacher_student_sigmoid_loss",
    "temporal_shift": "nn.functional.temporal_shift",
    "tensor_array_to_tensor": "tensor_array_to_tensor",
    "tile": "tile", "top_k": "topk", "top_k_v2": "topk", "trace": "trace",
    "transpose2": "transpose", "tril_triu": "tril", "trunc": "trunc",
    "truncated_gaussian_random": "normal", "unbind": "unbind",
    "unfold": "nn.functional.unfold",
    "uniform_random": "uniform",
    "uniform_random_batch_size_like": "uniform_random_batch_size_like",
    "unique": "unique", "unique_with_counts": "unique_with_counts",
    "unpool": "max_unpool2d", "unsqueeze2": "unsqueeze",
    "unstack": "unstack", "warpctc": "nn.functional.ctc_loss",
    "where": "where", "where_index": "nonzero",
}

# intentionally-absent reference ops -> one-line rationale (docs/ABSENT.md)
_ABSENT = {
    "ascend_trigger": "Ascend NPU backend is out of scope (ABSENT.md)",
    "pull_box_sparse": "BoxPS CTR embedding service is out of scope",
    "pull_box_extended_sparse": "BoxPS CTR embedding service is out of scope",
    "pull_sparse": "pslib sparse-table pull; ps/embedding.py is the analogue",
    "pull_sparse_v2": "pslib sparse-table pull; ps/embedding.py is the analogue",
    "push_dense": "pslib dense push; ps/communicator.py is the analogue",
    "tdm_child": "tree-based deep-match CTR ops are out of scope",
    "tdm_sampler": "tree-based deep-match CTR ops are out of scope",
    "pyramid_hash": "pyramid-hash text matching is out of scope",
    "filter_by_instag": "instag filtering (CTR pipelines) is out of scope",
    "shuffle_batch": "PS-side batch shuffling; io.dataset shuffles host-side",
    "rank_attention": "CTR GPU-specific attention is out of scope",
    "batch_fc": "CTR GPU batched-fc is out of scope",
    "hash": "CPU murmur-hash embedding trick is out of scope",
    "lookup_table_dequant": "int8 dequant embedding is out of scope (quant/qat.py covers QAT)",
    "match_matrix_tensor": "legacy pyramid text-matching op",
    "var_conv_2d": "legacy pyramid text-matching op",
    "tree_conv": "tree convolution is out of scope",
    "bilateral_slice": "HDRNet CUDA op is out of scope",
    "correlation": "optical-flow correlation CUDA op is out of scope",
    "inplace_abn": "CUDA in-place activated BN; use batch_norm (XLA fuses)",
    "attention_lstm": "legacy fused CPU LSTM; nn.LSTM is the path",
    "lstmp": "projection LSTM fused CPU kernel; compose nn.LSTM + Linear",
    "fusion_lstm": "legacy fused CPU LSTM",
    "lod_reset": "LoD lives at the Python boundary (sequence_pad/unpad)",
    "lod_rank_table": "LoD machinery absent by design (SURVEY §7.3)",
    "lod_tensor_to_array": "LoD tensor-array machinery absent by design",
    "array_to_lod_tensor": "LoD tensor-array machinery absent by design",
    "merge_lod_tensor": "LoD machinery absent by design",
    "split_lod_tensor": "LoD machinery absent by design",
    "reorder_lod_tensor_by_rank": "LoD machinery absent by design",
    "max_sequence_len": "LoD machinery absent by design",
    "lod_array_length": "LoD machinery absent by design",
    "shrink_rnn_memory": "dynamic-RNN memory shrink; StaticRNN/lax.scan path",
    "rnn_memory_helper": "recurrent-op plumbing; StaticRNN/lax.scan path",
    "copy_cross_scope": "Ascend pipeline scope copy; XLA dataflow instead",
    "marker": "profiler marker is paddle_tpu.marker (host RecordEvent)",
    "decode_jpeg": "GPU nvjpeg decode; vision.transforms decodes host-side",
    "read_file": "raw-bytes file read op; io.dataset reads host-side",
    "similarity_focus": "legacy attention visualization op",
    "teacher_student_sigmoid_loss": None,  # implemented — keep out of absent
    "dgc": "DGC momentum is the fleet dgc meta-optimizer",
    "dgc_clip_by_norm": "DGC momentum is the fleet dgc meta-optimizer",
    "dequantize": "MKLDNN int8 path; quant/qat.py fake-quant is the analogue",
    "requantize": "MKLDNN int8 path",
    "quantize": "MKLDNN int8 path; quant/qat.py fake-quant is the analogue",
    "dequantize_abs_max": "int8 inference dequant; quant/qat.py",
    "dequantize_log": "int8 inference dequant",
    "delete_var": "executor GC owns variable lifetime (native planner)",
}
_ABSENT = {k: v for k, v in _ABSENT.items() if v is not None}


def _resolve(path):
    obj = paddle_tpu
    for part in path.split("."):
        obj = getattr(obj, part)
    return obj


def __getattr__(name):
    if name in _ALIASES:
        return _resolve(_ALIASES[name])
    if name in _ABSENT:
        raise NotImplementedError(
            f"_C_ops.{name} is intentionally absent: {_ABSENT[name]}")
    raise AttributeError(f"_C_ops has no op {name!r}")


def __dir__():
    return sorted(_ALIASES)


def op_names():
    """Every canonical reference op name this namespace serves."""
    return sorted(_ALIASES)


def absent_ops():
    """Reference ops intentionally not served, with rationale."""
    return dict(_ABSENT)
