"""paddle.hub (python/paddle/hapi/hub.py): load models from a repo's
hubconf.py.  source='local' is fully supported (import hubconf.py from a
directory and call its entrypoints); 'github'/'gitee' need network egress
and raise with that rationale — publish the repo to a mounted path and
load it locally instead.
"""
import importlib.util
import os
import sys

__all__ = ["list", "help", "load"]

MODULE_HUBCONF = "hubconf.py"
VAR_DEPENDENCY = "dependencies"


def _load_hubconf(repo_dir):
    path = os.path.join(os.path.expanduser(repo_dir), MODULE_HUBCONF)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no {MODULE_HUBCONF} in {repo_dir}")
    spec = importlib.util.spec_from_file_location("paddle_tpu_hubconf", path)
    mod = importlib.util.module_from_spec(spec)
    sys.path.insert(0, os.path.dirname(path))
    try:
        spec.loader.exec_module(mod)
    finally:
        sys.path.pop(0)
    deps = getattr(mod, VAR_DEPENDENCY, [])
    missing = [d for d in deps if importlib.util.find_spec(d) is None]
    if missing:
        raise RuntimeError(f"hubconf dependencies not installed: {missing}")
    return mod


def _check_source(source):
    if source not in ("local",):
        raise NotImplementedError(
            f"hub source {source!r} needs network egress; clone the repo "
            "to a local path and use source='local'")


def list(repo_dir, source="local", force_reload=False):
    """Entrypoint names exported by the repo's hubconf.py."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    return [n for n in dir(mod)
            if callable(getattr(mod, n)) and not n.startswith("_")]


def help(repo_dir, model, source="local", force_reload=False):
    """Docstring of one entrypoint."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(f"no entrypoint {model!r} in {repo_dir}")
    return getattr(mod, model).__doc__


def load(repo_dir, model, source="local", force_reload=False, **kwargs):
    """Call an entrypoint and return its model object."""
    _check_source(source)
    mod = _load_hubconf(repo_dir)
    if not hasattr(mod, model):
        raise ValueError(f"no entrypoint {model!r} in {repo_dir}")
    return getattr(mod, model)(**kwargs)
