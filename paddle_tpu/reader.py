"""Legacy reader-decorator API (python/paddle/reader/decorator.py): pure
composition utilities over "reader creators" (zero-arg callables returning
a sample generator).  Host-side only — the TPU data path feeds batches via
io.DataLoader / the native C++ feed; these exist for API parity with code
written against paddle.reader.
"""
import itertools
import queue
import random as _pyrandom
import threading

__all__ = [
    "cache", "map_readers", "buffered", "compose", "chain", "shuffle",
    "firstn", "xmap_readers",
]


class _ReaderError:
    """Queue envelope carrying a producer/mapper exception to the consumer
    (a plain type check — samples can be arbitrary values, including
    tuples of ndarrays, so no tag-comparison is safe)."""

    __slots__ = ("exc",)

    def __init__(self, exc):
        self.exc = exc


def cache(reader):
    """Cache one full pass in memory; every iteration replays it.  The
    pass is read EAGERLY on first use (the reference caches at decoration
    time) so a partially-consumed first iterator can never corrupt the
    cache."""
    state = {"data": None}

    def creator():
        if state["data"] is None:
            state["data"] = tuple(reader())
        return iter(state["data"])

    return creator


def map_readers(func, *readers):
    """Element-wise map over zipped readers (map_readers:92)."""

    def creator():
        rs = [r() for r in readers]
        for items in zip(*rs):
            yield func(*items)

    return creator


def shuffle(reader, buf_size):
    """Buffered shuffle (shuffle:134): fill buf_size, emit shuffled."""

    def creator():
        buf = []
        for item in reader():
            buf.append(item)
            if len(buf) >= buf_size:
                _pyrandom.shuffle(buf)
                for b in buf:
                    yield b
                buf = []
        if buf:
            _pyrandom.shuffle(buf)
            for b in buf:
                yield b

    return creator


def chain(*readers):
    """Concatenate readers sequentially (chain:183)."""

    def creator():
        return itertools.chain(*[r() for r in readers])

    return creator


def compose(*readers, **kwargs):
    """Zip readers into flattened tuples (compose:248).  check_alignment
    raises if the readers end at different lengths."""
    check_alignment = kwargs.pop("check_alignment", True)

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def creator():
        rs = [r() for r in readers]
        if not check_alignment:
            for outputs in zip(*rs):
                yield sum((make_tuple(o) for o in outputs), ())
            return
        for outputs in itertools.zip_longest(*rs):
            if any(o is None for o in outputs):
                raise ValueError("readers have different lengths")
            yield sum((make_tuple(o) for o in outputs), ())

    return creator


def buffered(reader, size):
    """Read-ahead thread with a bounded queue (buffered:308).  A producer
    exception is forwarded through the queue and re-raised in the
    consumer instead of silently truncating the stream."""
    _end = object()

    def creator():
        q = queue.Queue(maxsize=size)

        def producer():
            try:
                for item in reader():
                    q.put(item)
            except BaseException as e:  # forward to the consumer
                q.put(_ReaderError(e))
            finally:
                q.put(_end)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is _end:
                break
            if isinstance(item, _ReaderError):
                raise item.exc
            yield item

    return creator


def firstn(reader, n):
    """First n samples (firstn:367)."""

    def creator():
        for i, item in enumerate(reader()):
            if i >= n:
                break
            yield item

    return creator


def xmap_readers(mapper, reader, process_num, buffer_size, order=False):
    """Parallel map via threads (xmap_readers:412; thread-based here — the
    mapper typically releases the GIL in numpy/IO, and TPU feeding is not
    CPU-bound the way the reference's decode pipelines were)."""
    _end = object()

    def creator():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)

        def feed():
            for i, item in enumerate(reader()):
                in_q.put((i, item))
            for _ in range(process_num):
                in_q.put(_end)

        def work():
            while True:
                got = in_q.get()
                if got is _end:
                    out_q.put(_end)
                    break
                i, item = got
                try:
                    out_q.put((i, mapper(item)))
                except BaseException as e:
                    # forward mapper errors; the sentinel still follows so
                    # the consumer's done-count converges (no deadlock)
                    out_q.put(_ReaderError(e))
                    out_q.put(_end)
                    break

        threading.Thread(target=feed, daemon=True).start()
        for _ in range(process_num):
            threading.Thread(target=work, daemon=True).start()

        def check_err(got):
            if isinstance(got, _ReaderError):
                raise got.exc
            return got

        done = 0
        if order:
            pending, want = {}, 0
            while done < process_num:
                got = out_q.get()
                if got is _end:
                    done += 1
                    continue
                i, item = check_err(got)
                pending[i] = item
                while want in pending:
                    yield pending.pop(want)
                    want += 1
            for i in sorted(pending):
                yield pending[i]
        else:
            while done < process_num:
                got = out_q.get()
                if got is _end:
                    done += 1
                    continue
                yield check_err(got)[1]

    return creator
