"""Framework-level utilities: unified flags, save/load, mode switches.

Reference parity: the reference has four config systems (SURVEY §5.6) — gflags
(platform/flags.cc), DistributedStrategy proto, Build/ExecutionStrategy,
TrainerDesc.  Consolidated here into ONE registry (`set_flags`/`get_flags`,
framework.py:5863 parity) with env pickup (FLAGS_* like the reference's gflags
env behavior).  save/load: paddle.save/paddle.load of state_dict pickles
(fluid/io.py:1840/1948 and dygraph checkpoint semantics).
"""
import os
import pickle

import numpy as np

from .core.tensor import Tensor

# ---- flags (SURVEY §5.6 consolidation) ----

_FLAGS = {
    # defaults mirroring the reference's core set (platform/flags.cc:33-241)
    "FLAGS_check_nan_inf": False,
    "FLAGS_benchmark": False,
    # accepted no-ops under PJRT-owned HBM (SURVEY §7.1): buffer
    # lifetime is XLA liveness + donation, not a GC threshold/strategy
    "FLAGS_eager_delete_tensor_gb": 0.0,
    "FLAGS_allocator_strategy": "pjrt",
    "FLAGS_use_bf16_matmul": True,
    "FLAGS_flash_attention": False,
    "FLAGS_profile": False,
    "FLAGS_seed": 0,
}


def _apply_flag_side_effects(k, v):
    """Effects of EXPLICITLY-set flags (set_flags or FLAGS_* env vars) —
    defaults apply no side effect: bf16 matmul is already the TPU
    backend's native default, and seeding only happens on request."""
    if k == "FLAGS_use_bf16_matmul":
        # matmul input precision: bf16 (MXU-native) vs float32 (3-pass
        # emulation, slower but exact)
        import jax

        jax.config.update("jax_default_matmul_precision",
                          "bfloat16" if v else "float32")
    elif k == "FLAGS_seed":
        # any explicitly-set integer (including 0) reseeds
        from .core import random as _random

        _random.seed(int(v))


def _env_pickup():
    for k in list(_FLAGS):
        if k in os.environ:
            v = os.environ[k]
            cur = _FLAGS[k]
            if isinstance(cur, bool):
                _FLAGS[k] = v.lower() in ("1", "true", "yes")
            elif isinstance(cur, float):
                _FLAGS[k] = float(v)
            elif isinstance(cur, int):
                _FLAGS[k] = int(v)
            else:
                _FLAGS[k] = v
            _apply_flag_side_effects(k, _FLAGS[k])


_env_pickup()


def set_flags(flags):
    for k, v in flags.items():
        _FLAGS[k] = v
        _apply_flag_side_effects(k, v)


def get_flags(flags):
    if isinstance(flags, str):
        flags = [flags]
    return {k: _FLAGS.get(k) for k in flags}


def register_flag(name, default):
    _FLAGS.setdefault(name, default)
    return _FLAGS[name]


# ---- save / load ----

def _to_saveable(obj):
    if isinstance(obj, Tensor):
        return np.asarray(obj.numpy())
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    return obj


def save(obj, path, protocol=4):
    """paddle.save parity (fluid/io.py:1840; dygraph state_dict pickles)."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, **configs):
    """paddle.load parity (fluid/io.py:1948)."""
    with open(path, "rb") as f:
        obj = pickle.load(f)
    return _from_saved(obj)


def _from_saved(obj):
    if isinstance(obj, np.ndarray):
        return Tensor(obj)
    if isinstance(obj, dict):
        return {k: _from_saved(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_saved(v) for v in obj)
    return obj


def in_dygraph_mode():
    from .static import program as _p

    return _p._dygraph_mode


# name parity aliases
ParamBase = Tensor
EagerParamBase = Tensor


class CPUPlace:  # re-export for fluid-style code
    pass
