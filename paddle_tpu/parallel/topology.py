"""Hybrid-parallel topology: N-d rank grid over mesh axes.

Reference parity: python/paddle/distributed/fleet/base/topology.py
(CommunicateTopology:36, HybridCommunicateGroup:117, ParallelMode:29).  The
rank math is identical; the difference is what a "comm group" materializes to —
a named axis of the device mesh instead of an NCCL ring.
"""
import collections
import itertools

import numpy as np

from . import env as _env
from .collective import Group


class ParallelMode:
    DATA_PARALLEL = 0
    TENSOR_PARALLEL = 1
    PIPELINE_PARALLEL = 2
    SHARDING_PARALLEL = 3


class CommunicateTopology:
    """Cartesian rank grid (topology.py:36 parity)."""

    def __init__(self, hybrid_group_names=None, dims=None):
        self._parallel_names = list(hybrid_group_names or
                                    ["data", "pipe", "sharding", "model"])
        self._dims = list(dims or [1, 1, 1, 1])
        self.coordinate = collections.namedtuple("Coordinate",
                                                 self._parallel_names)
        self._world_size = int(np.prod(self._dims))
        ranges = [range(d) for d in self._dims]
        all_coords = [self.coordinate(*c) for c in itertools.product(*ranges)]
        self._coord2rank = dict(zip(all_coords, range(len(all_coords))))
        self._rank2coord = dict(zip(self._coord2rank.values(),
                                    self._coord2rank.keys()))

    def get_hybrid_group_names(self):
        return self._parallel_names

    def get_dim(self, axis_name):
        return self._dims[self._parallel_names.index(axis_name)]

    get_dim_size = get_dim

    def world_size(self):
        return self._world_size

    def get_rank(self, **args):
        assert len(args) == len(self._dims)
        return self._coord2rank[self.coordinate(**args)]

    def get_coord(self, rank):
        return self._rank2coord[rank]

    def get_axis_list(self, axis_name, index):
        axis = self._parallel_names.index(axis_name)
        return [r for r, c in self._rank2coord.items() if c[axis] == index]

    def get_comm_list(self, axis_name):
        """All rank groups along axis_name (topology.py get_comm_list parity)."""
        axis = self._parallel_names.index(axis_name)
        other_ranges = [
            range(d) for i, d in enumerate(self._dims) if i != axis
        ]
        comm_list = []
        for other in itertools.product(*other_ranges):
            ranks = []
            for k in range(self._dims[axis]):
                coord = list(other)
                coord.insert(axis, k)
                ranks.append(self._coord2rank[self.coordinate(*coord)])
            comm_list.append(ranks)
        return comm_list

    def get_rank_from_stage(self, global_rank, **kwargs):
        coord = self.get_coord(global_rank)
        tf = coord._replace(**kwargs)._asdict()
        return self.get_rank(**tf)


class HybridCommunicateGroup:
    """topology.py:117 parity: per-axis comm groups + p2p neighbors.

    TPU-native: also exposes the jax Mesh whose axes ARE the groups
    (get_mesh()), used by pjit/shard_map paths.
    """

    def __init__(self, topology):
        self._topo = topology
        self.global_rank = _env.get_rank()
        self._dp_degree = self._topo.get_dim("data")
        self._mp_degree = self._topo.get_dim("model")
        self._pp_degree = self._topo.get_dim("pipe")
        self._sharding_degree = self._topo.get_dim("sharding")
        self.nranks = self._topo.world_size()

        self._dp_group = self._build_group("data")
        self._mp_group = self._build_group("model")
        self._pp_group = self._build_group("pipe")
        self._sharding_group = self._build_group("sharding")

        self.stage_id = self._get_axis_index("pipe")
        self._mp_rank = self._get_axis_index("model")
        self._dp_rank = self._get_axis_index("data")
        self._sharding_rank = self._get_axis_index("sharding")

        self.is_first_stage = self.stage_id == 0
        self.is_last_stage = self.stage_id == (self._pp_degree - 1)
        self._p2p_next, self._p2p_prev = self._build_p2p()

    def _get_axis_index(self, name):
        if self.global_rank >= self.nranks:
            return 0
        coord = self._topo.get_coord(self.global_rank)
        return getattr(coord, name)

    def _build_group(self, axis_name):
        comm_lists = self._topo.get_comm_list(axis_name)
        my = self.global_rank if self.global_rank < self.nranks else 0
        for ranks in comm_lists:
            if my in ranks:
                return Group(
                    rank=ranks.index(my), nranks=len(ranks), ranks=ranks,
                    axis={"data": "data", "model": "model", "pipe": "pipe",
                          "sharding": "sharding"}[axis_name],
                )
        return Group(0, 1, ranks=[my], axis=axis_name)

    def _build_p2p(self):
        if self._pp_degree <= 1:
            return None, None
        my = self.global_rank if self.global_rank < self.nranks else 0
        coord = self._topo.get_coord(my)
        next_stage = (coord.pipe + 1) % self._pp_degree
        prev_stage = (coord.pipe - 1) % self._pp_degree
        nxt = self._topo.get_rank_from_stage(my, pipe=next_stage)
        prv = self._topo.get_rank_from_stage(my, pipe=prev_stage)
        return nxt, prv

    # ---- parity accessors ----
    def get_parallel_mode(self):
        if self._mp_degree == 1 and self._pp_degree == 1 and \
                self._sharding_degree == 1:
            return ParallelMode.DATA_PARALLEL
        if self._mp_degree > 1 and self._pp_degree == 1:
            return ParallelMode.TENSOR_PARALLEL
        if self._pp_degree > 1:
            return ParallelMode.PIPELINE_PARALLEL
        return ParallelMode.SHARDING_PARALLEL

    def topology(self):
        return self._topo

    def get_global_rank(self):
        return self.global_rank

    def get_data_parallel_rank(self):
        return self._dp_rank

    def get_data_parallel_world_size(self):
        return self._dp_degree

    def get_data_parallel_group(self):
        return self._dp_group

    def get_data_parallel_group_src_rank(self):
        return self._dp_group.ranks[0]

    def get_model_parallel_rank(self):
        return self._mp_rank

    def get_model_parallel_world_size(self):
        return self._mp_degree

    def get_model_parallel_group(self):
        return self._mp_group

    def get_model_parallel_group_src_rank(self):
        return self._mp_group.ranks[0]

    def get_stage_id(self):
        return self.stage_id

    def get_pipe_parallel_world_size(self):
        return self._pp_degree

    def get_pipe_parallel_group(self):
        return self._pp_group

    def get_p2p_groups(self):
        return self._p2p_next, self._p2p_prev

    def get_sharding_parallel_rank(self):
        return self._sharding_rank

    def get_sharding_parallel_world_size(self):
        return self._sharding_degree

    def get_sharding_parallel_group(self):
        return self._sharding_group

    def get_sharding_parallel_group_src_rank(self):
        return self._sharding_group.ranks[0]

    def get_check_parallel_group(self):
        return Group(0, 1, ranks=[self.global_rank], axis="check")

    def get_rank_from_stage(self, stage_id, **kwargs):
        return self._topo.get_rank_from_stage(self.global_rank, pipe=stage_id)

    # ---- TPU-native ----
    def get_mesh(self):
        """Device mesh whose axes mirror the topology dims (for pjit)."""
        from .env import build_mesh

        dims = {}
        for name, d in zip(self._topo.get_hybrid_group_names(),
                           self._topo._dims):
            if d > 1 or name == "data":
                dims[name] = d
        if not dims:
            dims = {"data": 1}
        return build_mesh(dims)
