"""Activation sharding annotations (sequence/data parallel inside pjit).

TPU-native building block with no reference analogue op: under pjit tracing,
`with_sharding_constraint` pins an intermediate's layout so GSPMD places the
collectives where the model author intends (e.g. sequence-parallel layernorm
regions).  Outside a mesh context it is the identity.
"""
import jax
from jax.sharding import PartitionSpec, NamedSharding

from ..core.registry import apply_op
from ..core.tensor import Tensor

_active_mesh = []


class mesh_context:
    """Installs the mesh consulted by shard_activation during tracing."""

    def __init__(self, mesh):
        self.mesh = mesh

    def __enter__(self):
        _active_mesh.append(self.mesh)
        return self.mesh

    def __exit__(self, *exc):
        _active_mesh.pop()
        return False


def current_mesh():
    return _active_mesh[-1] if _active_mesh else None


def named_sharding(mesh, *axes):
    """``NamedSharding(mesh, PartitionSpec(*axes))`` — the one-liner the
    generation engine and pool builders use everywhere."""
    return NamedSharding(mesh, PartitionSpec(*axes))


def kv_pool_spec(pool_layout, tp_axis):
    """PartitionSpec sharding one KV pool's HEAD axis over `tp_axis`.

    The head axis is the tensor-parallel shard axis of the whole decode
    stack (each device owns num_heads / tp_degree heads of every page),
    so the spec depends only on where the layout stores heads:

    - ``"token"``:  ``[P, page_size, H, D]`` -> P(None, None, tp, None)
    - ``"kernel"``: ``[H, P, page_size, D]`` -> P(tp, None, None, None)
    """
    if pool_layout == "kernel":
        return PartitionSpec(tp_axis, None, None, None)
    return PartitionSpec(None, None, tp_axis, None)


def kv_scale_spec(tp_axis):
    """PartitionSpec for one pool's per-page per-head int8 scale array
    ``[num_pages, num_heads]`` (layout-independent — the scale array is
    ``[P, H]`` whatever the pool layout stores): heads are the
    tensor-parallel shard axis, exactly like the pools themselves."""
    return PartitionSpec(None, tp_axis)


def constrain(x, mesh, *axes):
    """`with_sharding_constraint` under `mesh` (identity when mesh is
    None) — the in-trace pin the sharded decode step uses to anchor
    GSPMD propagation (pools keep the pool sharding across the donation
    chain, logits come back replicated so the host fetch is legal)."""
    if mesh is None:
        return x
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, PartitionSpec(*axes)))


def shard_activation(x, spec):
    """Annotate activation sharding (identity when no mesh is active)."""
    mesh = current_mesh()
    if mesh is None:
        return x
    names = set(mesh.axis_names)
    clean = PartitionSpec(*(
        axis if (axis is None or (isinstance(axis, str) and axis in names)
                 or (isinstance(axis, tuple) and all(a in names for a in axis)))
        else None
        for axis in spec
    ))

    def fn(v):
        if v.ndim < len([s for s in clean]):
            return v
        return jax.lax.with_sharding_constraint(
            v, NamedSharding(mesh, clean)
        )

    if isinstance(x, Tensor):
        return apply_op("shard_activation", fn, (x,), {})
    return fn(x)
