"""Compiled hybrid-parallel training step (the TPU performance path).

Capability parity: the reference's fleet hybrid runtime — DP allreduce with
gradient bucketing (imperative/reducer.cc FusedAllReduceSchedule:798), TP
rings (mp_layers), ZeRO sharding (sharding_optimizer.py) — re-designed for
XLA: ONE jit(shard_map)-compiled step over a named mesh where
- dp: batch sharded on 'data'; gradients are flattened into a single buffer
  and reduced with ONE pmean (the Reducer's fused bucket, as one ICI
  collective instead of per-tensor NCCL calls),
- tp: params carry PartitionSpecs ('model' axis); inside shard_map the TP
  layers' own collectives (psum/all_gather in mp_layers.py) are live,
- ZeRO-1: optimizer states shard over 'data' (each rank updates its slice of
  the fused gradient buffer, then all_gathers the params),
- remat: jax.checkpoint around the loss, bf16 autocast via cast-at-entry.
Donation replaces in-place update kernels (SURVEY §7.1 in-place row).
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree
from jax.sharding import PartitionSpec as P, NamedSharding

from .collective import shard_map as _shard_map  # version-compat wrapper

from ..core.tensor import Tensor, _wrap_data
from ..core import autograd, random as _random
from .sharding_annotations import mesh_context


def make_fused_update(optimizer):
    """Flat-param optimizer update with the weight-decay convention baked in
    (L2-style grad add for coupled decay, AdamW post-update subtract for
    decoupled).  Shared by the hybrid and pipeline compiled steps."""
    wd = optimizer._weight_decay_coeff()
    decoupled = optimizer._decoupled_weight_decay

    def fused_update(pflat, gflat, state, lr):
        if wd and not decoupled:
            gflat = gflat + wd * pflat
        new_p, new_state = optimizer.update(pflat, gflat, state, lr)
        if wd and decoupled:
            new_p = new_p - lr * wd * pflat
        return new_p, new_state

    return fused_update


def zero_shard_update(gflat, state, lr, dp_axis, dp, shard_len,
                      fused_update, pflat=None, pshard=None):
    """Shared ZeRO core (used by both CompiledTrainStep and
    PipelinedTrainStep): ONE reduce-scatter of the padded fused grad
    buffer over `dp_axis` (the reduce-to-owner placement), then a local
    update of this rank's range shard.  The shard source is either a
    dynamic slice of the padded full buffer `pflat` (stages 1/2) or the
    persistent shard `pshard` itself (stage 3).  Gathering updated params
    back — or not, for stage 3 — is the caller's business."""
    gshard = jax.lax.psum_scatter(
        gflat.reshape(dp, shard_len), dp_axis,
        scatter_dimension=0, tiled=False) / dp
    if pshard is None:
        idx = jax.lax.axis_index(dp_axis)
        pshard = jax.lax.dynamic_slice_in_dim(
            pflat, idx * shard_len, shard_len)
    return fused_update(pshard, gshard, state, lr)


def _clean_spec(spec, mesh, shape):
    """Validate a dist spec against the mesh: unknown axes or non-divisible
    dims fall back to replication."""
    if spec is None:
        return P()
    names = set(mesh.axis_names)
    axes = list(spec) + [None] * (len(shape) - len(list(spec)))
    out = []
    for i, ax in enumerate(axes[: len(shape)]):
        ok = (
            ax is not None
            and (ax in names if isinstance(ax, str)
                 else all(a in names for a in ax))
        )
        if ok:
            size = mesh.shape[ax] if isinstance(ax, str) else int(
                np.prod([mesh.shape[a] for a in ax])
            )
            ok = size > 1 and shape[i] % size == 0
        out.append(ax if ok else None)
    return P(*out)


class CompiledTrainStep:
    """Build once, call per step.  loss_fn(model_view, *batch) -> scalar.

    zero_stage (sharding_optimizer.py:479-746 compiled analogue):
    - 0: no ZeRO; per-leaf optimizer state sharded like its param.
    - 1/2: optimizer state range-sharded over 'data'; the step does ONE
      reduce-scatter of the fused grad buffer, a local shard update, and
      one all-gather of params.  Stages 1 and 2 coincide here by
      construction: gradients are values inside one XLA computation, never
      persistent storage, so the full reduced gradient is never
      materialized (the psum_scatter IS the reduce-to-owner placement).
    - 3: parameters are *stored* range-sharded over 'data' too (persistent
      param memory drops by dp); the step all-gathers params before use —
      the compiled analogue of _add_broadcast_allreduce's
      broadcast-before-use — reduce-scatters grads, and updates only the
      local shard.  Transient peak still materializes the gathered params
      inside the step (XLA owns the schedule); the persistent-state win is
      what stage 3 buys.
    """

    def __init__(self, model, loss_fn, optimizer, mesh, batch_specs=None,
                 amp_dtype=None, remat=False, donate=True,
                 zero_shard_states=None, zero_stage=None):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self.amp_dtype = amp_dtype
        self.remat = remat
        self.donate = donate
        self._batch_specs = batch_specs
        self._step_count = 0
        self.dp_axis = "data" if "data" in mesh.axis_names else None
        # context parallelism: a 'seq' mesh axis shards the sequence dim of
        # the batch; params are replicated over it, so grads get one extra
        # pmean (parallel/context_parallel.py provides the attention)
        self.seq_axis = (
            "seq" if "seq" in mesh.axis_names and mesh.shape["seq"] > 1
            else None
        )
        if zero_stage is None:
            zero_stage = 1 if (zero_shard_states is None or zero_shard_states) \
                else 0
        dp_live = self.dp_axis is not None and mesh.shape[self.dp_axis] > 1
        self.zero_stage = int(zero_stage) if dp_live else 0
        self.zero = self.zero_stage >= 1

        named = dict(model.named_parameters())
        self._named = named
        self.param_specs = {
            n: _clean_spec(getattr(p, "dist_spec", None), mesh, p._data.shape)
            for n, p in named.items()
        }
        # ZeRO state buffers carry one leading dim per mesh axis the flat
        # param space varies over: 'data' (the range shard) plus every
        # param-sharding axis (TP 'model' shards make the local flat
        # CONTENT differ per model rank — a buffer declared replicated
        # over 'model' would be inconsistent).  'seq' never shards params.
        self._buf_axes = tuple(
            ax for ax in mesh.axis_names
            if ax == self.dp_axis
            or any(ax == a or (isinstance(a, tuple) and ax in a)
                   for spec in self.param_specs.values() for a in spec)
        )
        dp = mesh.shape[self.dp_axis] if self.dp_axis else 1

        self._local_shapes = {}
        self._param_dtypes = {}
        local_flat = 0
        for n, p in named.items():
            shape = list(p._data.shape)
            for i, ax in enumerate(list(self.param_specs[n])):
                if ax is not None:
                    size = mesh.shape[ax] if isinstance(ax, str) else int(
                        np.prod([mesh.shape[a] for a in ax])
                    )
                    shape[i] //= size
            self._local_shapes[n] = tuple(shape)
            self._param_dtypes[n] = p._data.dtype
            local_flat += int(np.prod(shape)) if shape else 1
        self._local_flat = local_flat
        # pad the fused flat buffer to a multiple of lcm(dp, 1024): dp for
        # the ZeRO shard split, 1024 (= 8x128 TPU tile) so XLA's layout
        # factorization of the 1-D buffer lands on tile boundaries — an odd
        # length factors as [N/2, 2] and tile-pads the trailing dim 2->128,
        # a 64x HBM blowup that OOMs BERT-base at compile time
        align = int(np.lcm(dp, 1024))
        self._pad = (-local_flat) % align
        padded = local_flat + self._pad
        shard_len = padded // dp
        self._shard_len = shard_len
        from ..core.tensor import _wrap_data as _w

        if self.zero_stage >= 3:
            self._param_buf_spec = P(*self._buf_axes, None)
            self.params = jax.device_put(
                self._build_param_buffer(),
                NamedSharding(mesh, self._param_buf_spec))
        else:
            self.params = {
                n: jax.device_put(p._data,
                                  NamedSharding(mesh, self.param_specs[n]))
                for n, p in named.items()
            }
        if self.zero:
            # ZeRO keeps the FUSED flat buffer: it range-shards evenly
            # over 'data' regardless of param boundaries
            fake = _w(jnp.zeros((shard_len,), jnp.float32))
            self._flat_state_template = optimizer._init_state(fake)
            buf_dims = tuple(mesh.shape[a] for a in self._buf_axes)
            self.flat_opt_state = {
                # jnp.array copy: state entries may alias one buffer (e.g.
                # Adam's two zero moments) and donation forbids duplicates
                k: jax.device_put(
                    jnp.array(jnp.broadcast_to(v, buf_dims + v.shape))
                    if v.ndim else jnp.array(v),
                    NamedSharding(
                        mesh,
                        P(*self._buf_axes, None) if v.ndim else P()),
                )
                for k, v in self._flat_state_template.items()
            }
        else:
            # per-leaf optimizer state, sharded exactly like its param —
            # no raveled mega-buffer (a 100M+-element 1-D array makes the
            # TPU backend pick a catastrophic tiled layout, and XLA's
            # all-reduce combiner already buckets the per-leaf grad
            # reductions, which is the Reducer-fusion parity)
            self._flat_state_template = None
            self._tree_state_specs = {}
            self.flat_opt_state = {}
            for n, p in named.items():
                st = optimizer._init_state_arrays(p._data)
                specs, vals = {}, {}
                for k, v in st.items():
                    spec = self.param_specs[n] if v.ndim == p._data.ndim \
                        and v.ndim > 0 else P()
                    specs[k] = spec
                    vals[k] = jax.device_put(
                        jnp.array(v), NamedSharding(mesh, spec))
                self._tree_state_specs[n] = specs
                self.flat_opt_state[n] = vals
        self._jit_step = None

    # ---- ZeRO-3 param buffer (host-side pack/unpack) ----
    def _extra_axes(self):
        return [a for a in self._buf_axes if a != self.dp_axis]

    def _local_tree_np(self, combo, extra_axes):
        """Local (TP-shard) param values for the given extra-axis ranks."""
        tree = {}
        for n, p in self._named.items():
            arr = np.asarray(p._data)
            for dim, ax in enumerate(list(self.param_specs[n])):
                if ax is None:
                    continue
                if isinstance(ax, tuple):
                    raise NotImplementedError(
                        "zero_stage=3 with tuple-axis param specs")
                if ax == self.dp_axis or ax == self.seq_axis:
                    raise NotImplementedError(
                        f"zero_stage=3 with param sharded on {ax!r}")
                j = combo[extra_axes.index(ax)]
                w = arr.shape[dim] // self.mesh.shape[ax]
                arr = np.take(arr, range(j * w, (j + 1) * w), axis=dim)
            tree[n] = arr
        return tree

    def _build_param_buffer(self):
        """(buf_dims..., shard_len) ndarray: for every extra-axis rank
        combo, the padded local flat params split into dp range shards."""
        import itertools

        dp = self.mesh.shape[self.dp_axis]
        extra_axes = self._extra_axes()
        extra_sizes = [self.mesh.shape[a] for a in extra_axes]
        buf_dims = tuple(self.mesh.shape[a] for a in self._buf_axes)
        full = None
        for combo in itertools.product(*[range(s) for s in extra_sizes]):
            tree = self._local_tree_np(combo, extra_axes)
            flat, _ = ravel_pytree(
                {n: jnp.asarray(v) for n, v in tree.items()})
            flat = np.asarray(flat)
            if self._pad:
                flat = np.concatenate(
                    [flat, np.zeros(self._pad, flat.dtype)])
            flat2d = flat.reshape(dp, self._shard_len)
            if full is None:
                full = np.zeros(buf_dims + (self._shard_len,), flat.dtype)
            idx = tuple(
                slice(None) if a == self.dp_axis
                else combo[extra_axes.index(a)]
                for a in self._buf_axes)
            full[idx] = flat2d
        return full

    def _unpack_param_buffer(self, buf):
        """Inverse of _build_param_buffer: full (unsharded) param dict."""
        import itertools

        extra_axes = self._extra_axes()
        extra_sizes = [self.mesh.shape[a] for a in extra_axes]
        template = {n: jnp.zeros(self._local_shapes[n],
                                 self._param_dtypes[n])
                    for n in self._named}
        _, unravel = ravel_pytree(template)
        out = {n: np.zeros(p._data.shape, self._param_dtypes[n])
               for n, p in self._named.items()}
        for combo in itertools.product(*[range(s) for s in extra_sizes]):
            idx = tuple(
                slice(None) if a == self.dp_axis
                else combo[extra_axes.index(a)]
                for a in self._buf_axes)
            flat = np.asarray(buf)[idx].reshape(-1)[: self._local_flat]
            tree = unravel(jnp.asarray(flat))
            for n, v in tree.items():
                tgt = [slice(None)] * v.ndim
                for dim, ax in enumerate(list(self.param_specs[n])):
                    if ax is None:
                        continue
                    j = combo[extra_axes.index(ax)]
                    w = v.shape[dim]
                    tgt[dim] = slice(j * w, (j + 1) * w)
                out[n][tuple(tgt)] = np.asarray(v)
        return out

    # ---- step construction ----
    def _build(self, batch_avals):
        model, loss_fn, optimizer = self.model, self.loss_fn, self.optimizer
        mesh = self.mesh
        amp_dtype = self.amp_dtype
        dp_axis = self.dp_axis
        seq_axis = self.seq_axis
        zero = self.zero
        dp = mesh.shape[dp_axis] if dp_axis else 1
        pad = self._pad

        def local_loss(params, batch_vals, key):
            with _random.rng_guard(key), autograd.no_grad():
                if amp_dtype is not None:
                    use = {
                        n: v.astype(amp_dtype)
                        if jnp.issubdtype(v.dtype, jnp.floating) and v.ndim > 1
                        else v
                        for n, v in params.items()
                    }
                else:
                    use = params
                tensors = [_wrap_data(v) for v in batch_vals]
                out = loss_fn(_FunctionalModel(model, use), *tensors)
            return out._data.astype(jnp.float32)

        if self.remat:
            local_loss = jax.checkpoint(local_loss)

        fused_update = make_fused_update(optimizer)

        zero3 = self.zero_stage >= 3
        local_shapes = dict(self._local_shapes)
        param_dtypes = dict(self._param_dtypes)
        local_size = self._local_flat
        n_buf_dims = len(self._buf_axes)
        shard_len_s = self._shard_len

        def spmd_step(params, opt_state, batch_vals, key, step, lr):
            # the step folds INSIDE the compiled fn: an eager fold_in per
            # step was most of the per-step host overhead
            key = jax.random.fold_in(key, step)
            if dp_axis is not None:
                key = jax.random.fold_in(key, jax.lax.axis_index(dp_axis))
            if seq_axis is not None:
                key = jax.random.fold_in(key, jax.lax.axis_index(seq_axis))
            if zero3:
                # stage 3: params live range-sharded; gather before use
                # (the _add_broadcast_allreduce broadcast-before-use)
                pshard0 = params.reshape(-1)
                pflat = jax.lax.all_gather(pshard0, dp_axis, tiled=True)
                template = {n: jnp.zeros(local_shapes[n], param_dtypes[n])
                            for n in local_shapes}
                _, unravel_local = ravel_pytree(template)
                params_tree = unravel_local(pflat[:local_size])
            else:
                params_tree = params
            loss, grads = jax.value_and_grad(local_loss)(
                params_tree, batch_vals, key
            )
            if seq_axis is not None:
                loss = jax.lax.pmean(loss, seq_axis)
            if zero:
                gflat, _ = ravel_pytree(grads)
                if seq_axis is not None:
                    # params replicated over 'seq': average per-chunk grads
                    gflat = jax.lax.pmean(gflat, seq_axis)
                if pad:
                    gflat = jnp.concatenate(
                        [gflat, jnp.zeros((pad,), gflat.dtype)])
                shard_len = shard_len_s
                if not zero3:
                    pflat, unravel_local = ravel_pytree(params_tree)
                    if pad:
                        pflat = jnp.concatenate(
                            [pflat, jnp.zeros((pad,), pflat.dtype)])
                # state buffers arrive as (1,...,1,shard_len) local blocks
                local_state = {
                    k: v.reshape(-1) if v.ndim else v
                    for k, v in opt_state.items()
                }
                new_p, new_state = zero_shard_update(
                    gflat, local_state, lr, dp_axis, dp, shard_len,
                    fused_update,
                    pflat=None if zero3 else pflat,
                    pshard=pshard0 if zero3 else None,
                )
                new_state = {
                    k: v.reshape((1,) * n_buf_dims + (shard_len,))
                    if v.ndim else v
                    for k, v in new_state.items()
                }
                if zero3:
                    # stage 3: only the shard persists — no gather-back
                    new_params_tree = new_p.reshape(
                        (1,) * n_buf_dims + (shard_len,))
                else:
                    pflat_new = jax.lax.all_gather(new_p, dp_axis,
                                                   tiled=True)
                    new_params_tree = unravel_local(pflat_new[:local_size])
            else:
                # per-leaf grads + update; XLA's all-reduce combiner fuses
                # the per-leaf pmeans into bucketed collectives (the
                # reducer.cc fused-bucket parity), folding in the 'seq'
                # reduction when context parallelism is active
                axes = None
                if dp_axis is not None and seq_axis is not None:
                    axes = (seq_axis, dp_axis)
                elif dp_axis is not None:
                    axes = dp_axis
                elif seq_axis is not None:
                    axes = seq_axis
                if axes is not None:
                    grads = jax.tree_util.tree_map(
                        lambda g: jax.lax.pmean(g, axes), grads)
                new_params_tree, new_state = optimizer.fused_update(
                    params, grads, opt_state, lr)
            if dp_axis is not None:
                loss = jax.lax.pmean(loss, dp_axis)
            return loss, new_params_tree, new_state

        if self.zero:
            state_specs = {
                k: (P(*self._buf_axes, None) if v.ndim else P())
                for k, v in self._flat_state_template.items()}
        else:
            state_specs = self._tree_state_specs
        param_specs = (self._param_buf_spec if self.zero_stage >= 3
                       else {n: s for n, s in self.param_specs.items()})
        in_specs = (
            param_specs,
            state_specs,
            self._batch_pspecs(batch_avals),
            P(),
            P(),
            P(),
        )
        out_specs = (P(), in_specs[0], in_specs[1])
        fn = _shard_map(spmd_step, mesh, in_specs, out_specs)
        donate = (0, 1) if self.donate else ()
        # declare batch shardings on the jit itself: host arrays place
        # directly at dispatch instead of an eager device_put per value
        # per step (params/state already live committed-sharded)
        batch_sh = tuple(NamedSharding(mesh, sp)
                         for sp in self._batch_pspecs(batch_avals))
        scalar_sh = NamedSharding(mesh, P())
        in_sh = (None, None, batch_sh, scalar_sh, scalar_sh, scalar_sh)
        return jax.jit(fn, donate_argnums=donate, in_shardings=in_sh)

    def _batch_pspecs(self, batch_avals):
        out = []
        for i, v in enumerate(batch_avals):
            if self._batch_specs is not None:
                out.append(_clean_spec(self._batch_specs[i], self.mesh,
                                       v.shape))
            elif (
                v.ndim and self.dp_axis
                and v.shape[0] % self.mesh.shape[self.dp_axis] == 0
            ):
                axes = [self.dp_axis] + [None] * (v.ndim - 1)
                # token-id style [B, L] inputs also shard the sequence dim
                # when a 'seq' axis is present (pass batch_specs to override)
                if (
                    self.seq_axis and v.ndim == 2
                    and jnp.issubdtype(v.dtype, jnp.integer)
                    and v.shape[1] % self.mesh.shape[self.seq_axis] == 0
                ):
                    axes[1] = self.seq_axis
                out.append(P(*axes))
            else:
                out.append(P())
        def _uses_seq(spec):
            return any(
                a == self.seq_axis
                or (isinstance(a, tuple) and self.seq_axis in a)
                for a in spec
            )

        if self.seq_axis is not None and not any(_uses_seq(s) for s in out):
            raise ValueError(
                "mesh has a 'seq' axis but no batch input is sharded on it; "
                "the model would run ring/Ulysses attention over replicated "
                "full sequences and compute garbage. Shard a batch dim on "
                "'seq' via batch_specs, or drop the axis from the mesh."
            )
        return tuple(out)

    # ---- public API ----
    def step(self, *batch):
        vals = tuple(
            b._data if isinstance(b, Tensor) else jnp.asarray(b)
            for b in batch
        )
        if self._jit_step is None:
            self._jit_step = self._build(vals)
        self._step_count += 1
        key = _random.get_rng_state()
        # numpy scalars: jit converts at dispatch, skipping two eager
        # device ops per step; batch placement rides the jit's declared
        # in_shardings instead of an eager per-value device_put
        step = np.uint32(self._step_count)
        lr = np.float32(self.optimizer.get_lr())
        loss, self.params, self.flat_opt_state = self._jit_step(
            self.params, self.flat_opt_state, vals, key, step, lr
        )
        from ..framework import _FLAGS

        if _FLAGS.get("FLAGS_check_nan_inf"):
            lv = np.asarray(loss)
            if not np.isfinite(lv).all():
                raise FloatingPointError(
                    "FLAGS_check_nan_inf: non-finite loss "
                    f"{float(lv):.6g} at step {self._step_count}")
        from ..optimizer.lr import LRScheduler

        if isinstance(self.optimizer._lr, LRScheduler):
            self.optimizer._lr.step()
        return _wrap_data(loss)

    def _lowered(self, *batch):
        vals = tuple(
            b._data if isinstance(b, Tensor) else jnp.asarray(b)
            for b in batch
        )
        if self._jit_step is None:
            self._jit_step = self._build(vals)
        key = _random.get_rng_state()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        return self._jit_step.lower(
            self.params, self.flat_opt_state, vals, key, jnp.uint32(0), lr)

    def cost_analysis(self, *batch):
        """XLA cost analysis of the compiled step (the reference's
        operators/benchmark/op_tester.cc role, but for the whole fused
        step).  Returns the lowered computation's stats dict (keys like
        'flops', 'bytes accessed') or None when the backend can't say.
        Measured FLOPs from here beat hand 2*N*tokens models: embedding
        lookups aren't counted as matmuls and remat FLOPs are included.
        Build errors (bad mesh/spec) propagate — they would fail step()
        identically."""
        from ..core.device import lowered_cost_stats

        return lowered_cost_stats(self._lowered(*batch))

    def memory_analysis(self, *batch):
        """CompiledMemoryStats of the fused step (peak/temp HBM), or None
        when the backend can't report it."""
        try:
            return self._lowered(*batch).compile().memory_analysis()
        except Exception:
            return None

    def sync_to_model(self):
        named = dict(self.model.named_parameters())
        if self.zero_stage >= 3:
            for n, v in self._unpack_param_buffer(self.params).items():
                named[n]._data = jnp.asarray(v)
            return
        for n, v in self.params.items():
            named[n]._data = v

    def state_dict(self):
        self.sync_to_model()
        return self.model.state_dict()


class _FunctionalModel:
    """View of a Layer with parameter values substituted (pure w.r.t. jit)."""

    # swap-restore mutates the live module's param slots; serialize it so
    # concurrent (or re-entrant, via RLock) calls can't interleave a
    # restore into another call's swapped state (VERDICT r1 weak-9)
    _swap_lock = __import__("threading").RLock()

    def __init__(self, model, params):
        self._model = model
        self._params = params

    def __call__(self, *inputs, **kwargs):
        return self._model.functional_call(self._params, *inputs, **kwargs)

    def __getattr__(self, item):
        attr = getattr(self.__dict__["_model"], item)
        if callable(attr) and not isinstance(attr, Tensor):
            model, params = self.__dict__["_model"], self.__dict__["_params"]

            def bound(*a, **k):
                with _FunctionalModel._swap_lock:
                    named = dict(model.named_parameters())
                    saved = {n: p._data for n, p in named.items()}
                    try:
                        for n, v in params.items():
                            if n in named:
                                named[n]._data = v
                        return attr(*a, **k)
                    finally:
                        for n, v in saved.items():
                            named[n]._data = v

            return bound
        return attr
