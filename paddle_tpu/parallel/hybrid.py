"""Compiled hybrid-parallel training step (the TPU performance path).

Capability parity: the reference's fleet hybrid runtime — DP allreduce with
gradient bucketing (imperative/reducer.cc FusedAllReduceSchedule:798), TP
rings (mp_layers), ZeRO sharding (sharding_optimizer.py) — re-designed for
XLA: ONE jit(shard_map)-compiled step over a named mesh where
- dp: batch sharded on 'data'; gradients are flattened into a single buffer
  and reduced with ONE pmean (the Reducer's fused bucket, as one ICI
  collective instead of per-tensor NCCL calls),
- tp: params carry PartitionSpecs ('model' axis); inside shard_map the TP
  layers' own collectives (psum/all_gather in mp_layers.py) are live,
- ZeRO-1: optimizer states shard over 'data' (each rank updates its slice of
  the fused gradient buffer, then all_gathers the params),
- remat: jax.checkpoint around the loss, bf16 autocast via cast-at-entry.
Donation replaces in-place update kernels (SURVEY §7.1 in-place row).
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree
from jax.sharding import PartitionSpec as P, NamedSharding

from .collective import shard_map as _shard_map  # version-compat wrapper

from ..core.tensor import Tensor, _wrap_data
from ..core import autograd, random as _random
from .sharding_annotations import mesh_context


def make_fused_update(optimizer):
    """Flat-param optimizer update with the weight-decay convention baked in
    (L2-style grad add for coupled decay, AdamW post-update subtract for
    decoupled).  Shared by the hybrid and pipeline compiled steps."""
    wd = optimizer._weight_decay_coeff()
    decoupled = optimizer._decoupled_weight_decay

    def fused_update(pflat, gflat, state, lr):
        if wd and not decoupled:
            gflat = gflat + wd * pflat
        new_p, new_state = optimizer.update(pflat, gflat, state, lr)
        if wd and decoupled:
            new_p = new_p - lr * wd * pflat
        return new_p, new_state

    return fused_update


def _clean_spec(spec, mesh, shape):
    """Validate a dist spec against the mesh: unknown axes or non-divisible
    dims fall back to replication."""
    if spec is None:
        return P()
    names = set(mesh.axis_names)
    axes = list(spec) + [None] * (len(shape) - len(list(spec)))
    out = []
    for i, ax in enumerate(axes[: len(shape)]):
        ok = (
            ax is not None
            and (ax in names if isinstance(ax, str)
                 else all(a in names for a in ax))
        )
        if ok:
            size = mesh.shape[ax] if isinstance(ax, str) else int(
                np.prod([mesh.shape[a] for a in ax])
            )
            ok = size > 1 and shape[i] % size == 0
        out.append(ax if ok else None)
    return P(*out)


class CompiledTrainStep:
    """Build once, call per step.  loss_fn(model_view, *batch) -> scalar."""

    def __init__(self, model, loss_fn, optimizer, mesh, batch_specs=None,
                 amp_dtype=None, remat=False, donate=True,
                 zero_shard_states=True):
        self.model = model
        self.loss_fn = loss_fn
        self.optimizer = optimizer
        self.mesh = mesh
        self.amp_dtype = amp_dtype
        self.remat = remat
        self.donate = donate
        self._batch_specs = batch_specs
        self._step_count = 0
        self.dp_axis = "data" if "data" in mesh.axis_names else None
        # context parallelism: a 'seq' mesh axis shards the sequence dim of
        # the batch; params are replicated over it, so grads get one extra
        # pmean (parallel/context_parallel.py provides the attention)
        self.seq_axis = (
            "seq" if "seq" in mesh.axis_names and mesh.shape["seq"] > 1
            else None
        )
        self.zero = (
            zero_shard_states and self.dp_axis is not None
            and mesh.shape[self.dp_axis] > 1
        )

        named = dict(model.named_parameters())
        self.param_specs = {
            n: _clean_spec(getattr(p, "dist_spec", None), mesh, p._data.shape)
            for n, p in named.items()
        }
        self.params = {
            n: jax.device_put(p._data, NamedSharding(mesh, self.param_specs[n]))
            for n, p in named.items()
        }
        # Optimizer state for the FUSED flat parameter space.  Inside
        # shard_map each device sees its LOCAL param shards, so the flat
        # buffer length is the sum of local sizes.  ZeRO-1 range-shards that
        # buffer over 'data' (each rank updates one slice).
        dp = mesh.shape[self.dp_axis] if self.dp_axis else 1
        local_flat = 0
        for n, p in named.items():
            shape = list(p._data.shape)
            for i, ax in enumerate(list(self.param_specs[n])):
                if ax is not None:
                    size = mesh.shape[ax] if isinstance(ax, str) else int(
                        np.prod([mesh.shape[a] for a in ax])
                    )
                    shape[i] //= size
            local_flat += int(np.prod(shape)) if shape else 1
        self._local_flat = local_flat
        # pad the fused flat buffer to a multiple of lcm(dp, 1024): dp for
        # the ZeRO shard split, 1024 (= 8x128 TPU tile) so XLA's layout
        # factorization of the 1-D buffer lands on tile boundaries — an odd
        # length factors as [N/2, 2] and tile-pads the trailing dim 2->128,
        # a 64x HBM blowup that OOMs BERT-base at compile time
        align = int(np.lcm(dp, 1024))
        self._pad = (-local_flat) % align
        padded = local_flat + self._pad
        shard_len = padded // dp
        from ..core.tensor import _wrap_data as _w

        if self.zero:
            # ZeRO-1 keeps the FUSED flat buffer: it range-shards evenly
            # over 'data' regardless of param boundaries
            fake = _w(jnp.zeros((shard_len,), jnp.float32))
            self._flat_state_template = optimizer._init_state(fake)
            self.flat_opt_state = {
                # jnp.array copy: state entries may alias one buffer (e.g.
                # Adam's two zero moments) and donation forbids duplicates
                k: jax.device_put(
                    jnp.array(jnp.tile(v, dp) if v.ndim else v),
                    NamedSharding(mesh, P(self.dp_axis) if v.ndim else P()),
                )
                for k, v in self._flat_state_template.items()
            }
        else:
            # per-leaf optimizer state, sharded exactly like its param —
            # no raveled mega-buffer (a 100M+-element 1-D array makes the
            # TPU backend pick a catastrophic tiled layout, and XLA's
            # all-reduce combiner already buckets the per-leaf grad
            # reductions, which is the Reducer-fusion parity)
            self._flat_state_template = None
            self._tree_state_specs = {}
            self.flat_opt_state = {}
            for n, p in named.items():
                st = optimizer._init_state_arrays(p._data)
                specs, vals = {}, {}
                for k, v in st.items():
                    spec = self.param_specs[n] if v.ndim == p._data.ndim \
                        and v.ndim > 0 else P()
                    specs[k] = spec
                    vals[k] = jax.device_put(
                        jnp.array(v), NamedSharding(mesh, spec))
                self._tree_state_specs[n] = specs
                self.flat_opt_state[n] = vals
        self._jit_step = None

    # ---- step construction ----
    def _build(self, batch_avals):
        model, loss_fn, optimizer = self.model, self.loss_fn, self.optimizer
        mesh = self.mesh
        amp_dtype = self.amp_dtype
        dp_axis = self.dp_axis
        seq_axis = self.seq_axis
        zero = self.zero
        dp = mesh.shape[dp_axis] if dp_axis else 1
        pad = self._pad

        def local_loss(params, batch_vals, key):
            with _random.rng_guard(key), autograd.no_grad():
                if amp_dtype is not None:
                    use = {
                        n: v.astype(amp_dtype)
                        if jnp.issubdtype(v.dtype, jnp.floating) and v.ndim > 1
                        else v
                        for n, v in params.items()
                    }
                else:
                    use = params
                tensors = [_wrap_data(v) for v in batch_vals]
                out = loss_fn(_FunctionalModel(model, use), *tensors)
            return out._data.astype(jnp.float32)

        if self.remat:
            local_loss = jax.checkpoint(local_loss)

        fused_update = make_fused_update(optimizer)

        def spmd_step(params, opt_state, batch_vals, key, lr):
            if dp_axis is not None:
                key = jax.random.fold_in(key, jax.lax.axis_index(dp_axis))
            if seq_axis is not None:
                key = jax.random.fold_in(key, jax.lax.axis_index(seq_axis))
            loss, grads = jax.value_and_grad(local_loss)(
                params, batch_vals, key
            )
            if seq_axis is not None:
                loss = jax.lax.pmean(loss, seq_axis)
            if zero:
                gflat, _ = ravel_pytree(grads)
                if seq_axis is not None:
                    # params replicated over 'seq': average per-chunk grads
                    gflat = jax.lax.pmean(gflat, seq_axis)
                pflat, unravel_local = ravel_pytree(params)
                if pad:
                    gflat = jnp.concatenate(
                        [gflat, jnp.zeros((pad,), gflat.dtype)])
                    pflat = jnp.concatenate(
                        [pflat, jnp.zeros((pad,), pflat.dtype)])
                local_size = pflat.shape[0] - pad
                # ZeRO-1: ONE reduce_scatter of the fused grad buffer; each
                # data rank updates its slice, then one all_gather of params
                shard_len = pflat.shape[0] // dp
                gshard = jax.lax.psum_scatter(
                    gflat.reshape(dp, shard_len), dp_axis,
                    scatter_dimension=0, tiled=False,
                ) / dp
                idx = jax.lax.axis_index(dp_axis)
                pshard = jax.lax.dynamic_slice_in_dim(
                    pflat, idx * shard_len, shard_len
                )
                new_p, new_state = fused_update(
                    pshard, gshard, opt_state, lr
                )
                pflat_new = jax.lax.all_gather(new_p, dp_axis, tiled=True)
                new_params_tree = unravel_local(pflat_new[:local_size])
            else:
                # per-leaf grads + update; XLA's all-reduce combiner fuses
                # the per-leaf pmeans into bucketed collectives (the
                # reducer.cc fused-bucket parity), folding in the 'seq'
                # reduction when context parallelism is active
                axes = None
                if dp_axis is not None and seq_axis is not None:
                    axes = (seq_axis, dp_axis)
                elif dp_axis is not None:
                    axes = dp_axis
                elif seq_axis is not None:
                    axes = seq_axis
                if axes is not None:
                    grads = jax.tree_util.tree_map(
                        lambda g: jax.lax.pmean(g, axes), grads)
                new_params_tree, new_state = optimizer.fused_update(
                    params, grads, opt_state, lr)
            if dp_axis is not None:
                loss = jax.lax.pmean(loss, dp_axis)
            return loss, new_params_tree, new_state

        if self.zero:
            state_specs = {k: (P(dp_axis) if v.ndim else P())
                           for k, v in self._flat_state_template.items()}
        else:
            state_specs = self._tree_state_specs
        in_specs = (
            {n: s for n, s in self.param_specs.items()},
            state_specs,
            self._batch_pspecs(batch_avals),
            P(),
            P(),
        )
        out_specs = (P(), in_specs[0], in_specs[1])
        fn = _shard_map(spmd_step, mesh, in_specs, out_specs)
        donate = (0, 1) if self.donate else ()
        return jax.jit(fn, donate_argnums=donate)

    def _batch_pspecs(self, batch_avals):
        out = []
        for i, v in enumerate(batch_avals):
            if self._batch_specs is not None:
                out.append(_clean_spec(self._batch_specs[i], self.mesh,
                                       v.shape))
            elif (
                v.ndim and self.dp_axis
                and v.shape[0] % self.mesh.shape[self.dp_axis] == 0
            ):
                axes = [self.dp_axis] + [None] * (v.ndim - 1)
                # token-id style [B, L] inputs also shard the sequence dim
                # when a 'seq' axis is present (pass batch_specs to override)
                if (
                    self.seq_axis and v.ndim == 2
                    and jnp.issubdtype(v.dtype, jnp.integer)
                    and v.shape[1] % self.mesh.shape[self.seq_axis] == 0
                ):
                    axes[1] = self.seq_axis
                out.append(P(*axes))
            else:
                out.append(P())
        def _uses_seq(spec):
            return any(
                a == self.seq_axis
                or (isinstance(a, tuple) and self.seq_axis in a)
                for a in spec
            )

        if self.seq_axis is not None and not any(_uses_seq(s) for s in out):
            raise ValueError(
                "mesh has a 'seq' axis but no batch input is sharded on it; "
                "the model would run ring/Ulysses attention over replicated "
                "full sequences and compute garbage. Shard a batch dim on "
                "'seq' via batch_specs, or drop the axis from the mesh."
            )
        return tuple(out)

    # ---- public API ----
    def step(self, *batch):
        vals = tuple(
            b._data if isinstance(b, Tensor) else jnp.asarray(b)
            for b in batch
        )
        if self._jit_step is None:
            self._jit_step = self._build(vals)
        self._step_count += 1
        key = jax.random.fold_in(_random.get_rng_state(), self._step_count)
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        pspecs = self._batch_pspecs(vals)
        vals = tuple(
            jax.device_put(v, NamedSharding(self.mesh, s))
            for v, s in zip(vals, pspecs)
        )
        loss, self.params, self.flat_opt_state = self._jit_step(
            self.params, self.flat_opt_state, vals, key, lr
        )
        from ..framework import _FLAGS

        if _FLAGS.get("FLAGS_check_nan_inf"):
            lv = np.asarray(loss)
            if not np.isfinite(lv).all():
                raise FloatingPointError(
                    "FLAGS_check_nan_inf: non-finite loss "
                    f"{float(lv):.6g} at step {self._step_count}")
        from ..optimizer.lr import LRScheduler

        if isinstance(self.optimizer._lr, LRScheduler):
            self.optimizer._lr.step()
        return _wrap_data(loss)

    def sync_to_model(self):
        named = dict(self.model.named_parameters())
        for n, v in self.params.items():
            named[n]._data = v

    def state_dict(self):
        self.sync_to_model()
        return self.model.state_dict()


class _FunctionalModel:
    """View of a Layer with parameter values substituted (pure w.r.t. jit)."""

    def __init__(self, model, params):
        self._model = model
        self._params = params

    def __call__(self, *inputs, **kwargs):
        return self._model.functional_call(self._params, *inputs, **kwargs)

    def __getattr__(self, item):
        attr = getattr(self.__dict__["_model"], item)
        if callable(attr) and not isinstance(attr, Tensor):
            model, params = self.__dict__["_model"], self.__dict__["_params"]

            def bound(*a, **k):
                named = dict(model.named_parameters())
                saved = {n: p._data for n, p in named.items()}
                try:
                    for n, v in params.items():
                        if n in named:
                            named[n]._data = v
                    return attr(*a, **k)
                finally:
                    for n, v in saved.items():
                        named[n]._data = v

            return bound
        return attr
