"""paddle_tpu.parallel — mesh-based distributed runtime (SURVEY §2.3, §5.8)."""
from .env import (  # noqa: F401
    init_parallel_env, get_rank, get_world_size, ParallelEnv, global_mesh,
    set_global_mesh, build_mesh, is_initialized, tp_mesh,
)
from .sharding_annotations import (  # noqa: F401
    named_sharding, kv_pool_spec, constrain, shard_activation, mesh_context,
)
from .collective import (  # noqa: F401
    ReduceOp, Group, new_group, get_group, wait, all_reduce, reduce,
    broadcast, all_gather, reduce_scatter, scatter, alltoall, send, recv,
    isend, irecv, barrier, P2POp, batch_isend_irecv, psum, pmean, ppermute,
    axis_index, all_to_all_in_mesh,
)
from .topology import CommunicateTopology, HybridCommunicateGroup, ParallelMode  # noqa: F401
from .data_parallel import DataParallel  # noqa: F401
from .hybrid import CompiledTrainStep  # noqa: F401
from .pipeline_compile import (  # noqa: F401
    PipelinedTrainStep, GPTPipeAdapter, PipeStagePlan,
)
from .context_parallel import (  # noqa: F401
    context_parallel_attention, seq_axis_in_scope, seq_chunk_offset,
)
