"""DataParallel wrapper.

Reference parity: python/paddle/fluid/dygraph/parallel.py:382 (DataParallel,
scale_loss:588, apply_collective_grads:597) + C++ Reducer (reducer.cc) gradient
bucketing.  TPU-native design (SURVEY §7.1 "Reducer" row): in the
single-controller mesh model the global batch is sharded over the 'data' axis
and XLA inserts the gradient AllReduce when the step is compiled (pjit); eager
mode computes grads on the global batch directly, which is numerically the
allreduced result.  The Reducer's bucketing/overlap role is played by XLA's
collective scheduling, so this wrapper's job is API parity: parameter sync at
construction, loss scaling, and no_sync.
"""
import contextlib

from ..nn.layer import Layer
from . import env as _env
from . import collective as C


class DataParallel(Layer):
    def __init__(self, layers, strategy=None, comm_buffer_size=25,
                 last_comm_buffer_size=1, find_unused_parameters=False,
                 group=None):
        super().__init__()
        self._layers = layers
        self._group = group
        self.find_unused_parameters = find_unused_parameters
        self._grad_sync_enabled = True
        # parameter broadcast from rank 0 (reducer.cc construction parity):
        # single-controller arrays are already consistent across the mesh.

    @property
    def nranks(self):
        return _env.get_world_size()

    def forward(self, *inputs, **kwargs):
        return self._layers(*inputs, **kwargs)

    def scale_loss(self, loss):
        # dygraph/parallel.py:588 — under mesh execution the mean over the
        # global batch already includes the 1/nranks factor.
        return loss

    def apply_collective_grads(self):
        # grads of a global-batch backward are already cross-replica reduced
        pass

    @contextlib.contextmanager
    def no_sync(self):
        prev = self._grad_sync_enabled
        self._grad_sync_enabled = False
        try:
            yield
        finally:
            self._grad_sync_enabled = prev

    def state_dict(self, *args, **kwargs):
        return self._layers.state_dict(*args, **kwargs)

    def set_state_dict(self, state_dict, *args, **kwargs):
        return self._layers.set_state_dict(state_dict, *args, **kwargs)

    def parameters(self, include_sublayers=True):
        return self._layers.parameters(include_sublayers)

    def named_parameters(self, prefix="", include_sublayers=True):
        return self._layers.named_parameters(prefix, include_sublayers)

    def train(self):
        self._layers.train()
        return self

    def eval(self):
        self._layers.eval()
        return self
