"""Compiled pipeline parallelism over a 'pipe' mesh axis.

Reference parity: the static PipelineOptimizer + SectionWorker micro-batch
schedules (optimizer.py:4135; section_worker.cc:134 F-then-B, :167 1F1B) and
the dygraph PipelineParallel.train_batch (pipeline_parallel.py:114).
TPU-native design — one jitted SPMD program instead of per-stage processes:

- the transformer's homogeneous block stack is STACKED along a leading layer
  axis and sharded over 'pipe', so each chip holds `layers/S` blocks;
- a `lax.scan` over `M + S - 1` ticks rotates micro-batch activations around
  the ring with `ppermute` (stage s processes micro-batch t-s at tick t) —
  the GPipe/1F1B dataflow expressed as a collective-permute pipeline, which
  XLA overlaps with the per-stage compute on ICI;
- embedding/head ("other") params are replicated over 'pipe'; only the
  owning stage's compute contributes their grads, so a psum over 'pipe'
  recovers exact gradients (embedding-tying just works: stage 0's embed grad
  and the last stage's head grad sum);
- composes with 'data' (batch) and 'model' (tensor-parallel) mesh axes, grads
  pmean over 'data'; remat wraps each block for activation memory.

Why there is no separate "1F1B" schedule flag: in this compiled SPMD
formulation the backward pass is jax.vjp's reverse scan over the same
ring, and XLA already overlaps each tick's ppermute with compute — the
bubble fraction equals 1F1B's ((S-1)/(M+S-1)).  1F1B's remaining benefit
over GPipe is peak activation memory (depth S instead of M); here remat
(per-block jax.checkpoint) provides the same bound compiler-side, so a
hand-written interleaved adjoint schedule would add complexity without
changing the bubble or the memory ceiling (section_worker.cc:167 context).

Per-chip flat param/opt-state buffers follow the hybrid-step convention
(device-local buffers carried with replicated out-specs, parallel/hybrid.py).
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax.flatten_util import ravel_pytree
from jax.sharding import PartitionSpec as P, NamedSharding

from .collective import shard_map as _shard_map
from .hybrid import _clean_spec, _FunctionalModel
from ..core.tensor import Tensor, _wrap_data
from ..core import autograd, random as _random


class PipeStagePlan:
    """Splits a model's params into a stacked homogeneous block group
    (sharded over 'pipe') and the replicated remainder.

    `block_param_prefix` is the common prefix of per-layer param names, e.g.
    'gpt.blocks.' for names like 'gpt.blocks.3.ln1.weight'."""

    def __init__(self, model, block_param_prefix):
        self.model = model
        self.prefix = block_param_prefix
        named = dict(model.named_parameters())
        per_layer = {}
        other = {}
        for n, p in named.items():
            if n.startswith(self.prefix):
                rest = n[len(self.prefix):]
                idx, rel = rest.split(".", 1)
                per_layer.setdefault(int(idx), {})[rel] = p
            else:
                other[n] = p
        self.num_layers = len(per_layer)
        if self.num_layers == 0:
            raise ValueError(f"no params under prefix {self.prefix!r}")
        self.rel_names = sorted(per_layer[0])
        for i in range(self.num_layers):
            if sorted(per_layer[i]) != self.rel_names:
                raise ValueError("pipeline blocks must be homogeneous")
        self.per_layer = per_layer
        self.other = other

    def stacked_block_arrays(self):
        return {
            rel: jnp.stack([self.per_layer[i][rel]._data
                            for i in range(self.num_layers)])
            for rel in self.rel_names
        }

    def unstack_into_model(self, stacked):
        for rel, arr in stacked.items():
            for i in range(self.num_layers):
                self.per_layer[i][rel]._data = arr[i]


class GPTPipeAdapter:
    """Binds GPTForPretraining's embed / block / head pieces to raw-array
    functions usable inside the SPMD pipeline program."""

    def __init__(self, model):
        self.model = model
        self.plan = PipeStagePlan(model, "gpt.blocks.")
        self.template_block = model.gpt.blocks[0]

    def _swap(self, params, fn):
        named = dict(self.model.named_parameters())
        saved = {n: p._data for n, p in named.items()}
        try:
            for n, v in params.items():
                if n in named:
                    named[n]._data = v
            return fn()
        finally:
            for n, v in saved.items():
                named[n]._data = v

    def embed(self, other_params, ids):
        return self._swap(
            other_params,
            lambda: self.model.gpt.embed(_wrap_data(ids))._data,
        )

    def block(self, rel_params, x):
        return self.template_block.functional_call(
            {k: _wrap_data(v) for k, v in rel_params.items()},
            _wrap_data(x),
        )._data

    def head_loss(self, other_params, h, labels):
        return self._swap(
            other_params,
            lambda: self.model.head_loss(
                _wrap_data(h), _wrap_data(labels))._data,
        )


class PipelinedTrainStep:
    """Build once, call `.step(ids, labels)` per global batch.

    mesh must have a 'pipe' axis; 'data' and 'model' axes compose.  The
    global batch B splits into `num_micro` micro-batches of B/num_micro
    (further sharded over 'data')."""

    def __init__(self, adapter, optimizer, mesh, num_micro,
                 amp_dtype=None, remat=True, donate=True, zero_stage=1):
        self.adapter = adapter
        self.plan = adapter.plan
        self.optimizer = optimizer
        self.mesh = mesh
        self.num_micro = num_micro
        self.amp_dtype = amp_dtype
        self.remat = remat
        self.donate = donate
        if "pipe" not in mesh.axis_names:
            raise ValueError("mesh needs a 'pipe' axis")
        self.S = mesh.shape["pipe"]
        if self.plan.num_layers % self.S != 0:
            raise ValueError(
                f"{self.plan.num_layers} layers not divisible by "
                f"pipe={self.S}")
        self.dp_axis = "data" if "data" in mesh.axis_names else None
        dp_live = self.dp_axis is not None and mesh.shape[self.dp_axis] > 1
        # ZeRO composition (VERDICT r1: pipe step had opt state replicated
        # P()): optimizer states range-shard over 'data' like hybrid.py
        self.zero_stage = int(zero_stage) if dp_live else 0
        self.zero = self.zero_stage >= 1
        self._step_count = 0
        self._jit_step = None

        # other (replicated-over-pipe) params keep their own specs
        self.other_specs = {
            n: _clean_spec(getattr(p, "dist_spec", None), mesh, p._data.shape)
            for n, p in self.plan.other.items()
        }
        self.other_params = {
            n: jax.device_put(p._data,
                              NamedSharding(mesh, self.other_specs[n]))
            for n, p in self.plan.other.items()
        }
        # stacked blocks: leading layer dim sharded over 'pipe', the rest
        # follows the block param's own (e.g. tensor-parallel) spec
        tmpl = {n: p for n, p in
                self.adapter.template_block.named_parameters()}
        self.block_specs = {}
        stacked = self.plan.stacked_block_arrays()
        for rel, arr in stacked.items():
            inner = _clean_spec(getattr(tmpl[rel], "dist_spec", None), mesh,
                                arr.shape[1:])
            self.block_specs[rel] = P("pipe", *inner)
        self.block_params = {
            rel: jax.device_put(arr,
                                NamedSharding(mesh, self.block_specs[rel]))
            for rel, arr in stacked.items()
        }

        # fused flat optimizer state per group (device-local convention)
        def local_len(specs, shapes):
            total = 0
            for n, shape in shapes.items():
                shape = list(shape)
                for i, ax in enumerate(list(specs[n])):
                    if ax is None:
                        continue
                    size = (mesh.shape[ax] if isinstance(ax, str)
                            else int(np.prod([mesh.shape[a] for a in ax])))
                    shape[i] //= size
                total += int(np.prod(shape)) if shape else 1
            return total

        n_other = local_len(self.other_specs,
                            {n: p._data.shape
                             for n, p in self.plan.other.items()})
        n_block = local_len(self.block_specs,
                            {r: a.shape for r, a in stacked.items()})
        # fused flat buffers align to the 8x128 TPU tile (see hybrid.py:
        # odd lengths factor into a tile-padded [N/k, k] layout, blowing
        # up HBM at compile time); with ZeRO also to dp for the range split
        dp = mesh.shape[self.dp_axis] if self.dp_axis else 1
        align = int(np.lcm(dp, 1024)) if self.zero else 1024
        self._pads = {"other": (-n_other) % align, "block": (-n_block) % align}
        n_other += self._pads["other"]
        n_block += self._pads["block"]

        # state-buffer axes per group (hybrid.py convention: one leading
        # dim per mesh axis the flat content varies over, plus 'data' for
        # the ZeRO range shard).  'block' content differs per pipe rank;
        # either group differs per 'model' rank when TP specs exist.
        def content_axes(specs, with_pipe):
            used = set()
            for spec in specs.values():
                for a in spec:
                    if isinstance(a, tuple):
                        used.update(a)
                    elif a is not None:
                        used.add(a)
            if with_pipe:
                used.add("pipe")
            used.discard(self.dp_axis)
            return [ax for ax in mesh.axis_names if ax in used]

        self._buf_axes = {}
        self._shard_lens = {"other": n_other // dp if self.zero else n_other,
                            "block": n_block // dp if self.zero else n_block}
        self._opt_state = {}
        self._state_template = {}
        for group, ln, specs, with_pipe in (
                ("other", n_other, self.other_specs, False),
                ("block", n_block, self.block_specs, True)):
            axes = ([self.dp_axis] if self.zero else []) + \
                content_axes(specs, with_pipe)
            # keep mesh axis order
            axes = [ax for ax in mesh.axis_names if ax in axes]
            self._buf_axes[group] = tuple(axes)
            shard_len = self._shard_lens[group]
            fake = _wrap_data(jnp.zeros((shard_len,), jnp.float32))
            tpl = optimizer._init_state(fake)
            self._state_template[group] = tpl
            buf_dims = tuple(mesh.shape[a] for a in axes)
            self._opt_state[group] = {
                k: jax.device_put(
                    jnp.array(jnp.broadcast_to(v, buf_dims + v.shape))
                    if v.ndim else jnp.array(v),
                    NamedSharding(mesh, P(*axes, None) if v.ndim else P()))
                for k, v in tpl.items()
            }

    # ---- SPMD program ----
    def _build(self, ids_aval, labels_aval):
        adapter, optimizer = self.adapter, self.optimizer
        mesh, amp_dtype = self.mesh, self.amp_dtype
        S, M = self.S, self.num_micro
        dp_axis = self.dp_axis
        pads = self._pads

        def cast(params):
            if amp_dtype is None:
                return params
            return {
                n: v.astype(amp_dtype)
                if jnp.issubdtype(v.dtype, jnp.floating) and v.ndim > 1
                else v
                for n, v in params.items()
            }

        def stage_apply(block_params_local, x, key):
            # run this chip's layers/S blocks in order; each layer gets its
            # own folded rng key so dropout masks decorrelate across layers
            per = jax.tree_util.tree_leaves(block_params_local)[0].shape[0]

            def one(x, xs):
                rel_params, li = xs
                k = jax.random.fold_in(key, li)
                with _random.rng_guard(k), autograd.no_grad():
                    return adapter.block(cast(rel_params), x).astype(
                        x.dtype), None

            if self.remat:
                one = jax.checkpoint(one)
            out, _ = jax.lax.scan(one, x,
                                  (block_params_local, jnp.arange(per)))
            return out

        def local_loss(other, blocks, ids_mb, labels_mb, key):
            """Full pipelined forward: returns summed micro losses (nonzero
            only on the last stage)."""
            stage = jax.lax.axis_index("pipe")
            ids_m = ids_mb.reshape((M, -1) + ids_mb.shape[1:])
            lbl_m = labels_mb.reshape((M, -1) + labels_mb.shape[1:])
            mb = ids_m.shape[1]
            co = cast(other)

            with autograd.no_grad(), _random.rng_guard(key):
                e_shape = adapter.embed(co, ids_m[0]).shape
            x0 = jnp.zeros(e_shape, amp_dtype or jnp.float32)
            perm = [(i, (i + 1) % S) for i in range(S)]

            def tick(carry, t):
                """One pipeline tick.  embed runs ONLY on stage 0 and
                head_loss ONLY on the last stage, via lax.cond on the
                device-varying stage index (check_rep is off, so each
                stage takes its own branch at runtime) — VERDICT r1
                weak-5: the jnp.where formulation computed the vocab-size
                head matmul on every stage every tick and discarded it."""
                x_in, loss_acc = carry
                kt = jax.random.fold_in(key, t)
                with _random.rng_guard(kt), autograd.no_grad():
                    ti = jnp.clip(t, 0, M - 1)
                    emb = jax.lax.cond(
                        stage == 0,
                        lambda: adapter.embed(
                            co, jax.lax.dynamic_index_in_dim(
                                ids_m, ti, 0, keepdims=False)
                        ).astype(x_in.dtype),
                        lambda: jnp.zeros(e_shape, x_in.dtype))
                    inp = jnp.where(stage == 0, emb, x_in)
                    out = stage_apply(blocks, inp, kt).astype(x_in.dtype)
                    mi = t - (S - 1)
                    lbl = jax.lax.dynamic_index_in_dim(
                        lbl_m, jnp.clip(mi, 0, M - 1), 0, keepdims=False)
                    l = jax.lax.cond(
                        (stage == S - 1) & (mi >= 0),
                        lambda: adapter.head_loss(co, out, lbl).astype(
                            jnp.float32),
                        lambda: jnp.float32(0.0))
                    x_next = jax.lax.ppermute(out, "pipe", perm)
                return (x_next, loss_acc + l), None

            (x_last, loss_sum), _ = jax.lax.scan(
                tick, (x0, jnp.float32(0.0)), jnp.arange(M + S - 1))
            return loss_sum / M

        from .hybrid import make_fused_update, zero_shard_update

        fused_update = make_fused_update(optimizer)

        zero = self.zero
        dp = mesh.shape[dp_axis] if dp_axis else 1
        shard_lens = dict(self._shard_lens)
        buf_axes = dict(self._buf_axes)

        def spmd_step(other, blocks, st_other, st_block, ids, labels, key,
                      step, lr):
            # step folds in-graph (same host-overhead fix as hybrid.py)
            key = jax.random.fold_in(key, step)
            key = jax.random.fold_in(key, jax.lax.axis_index("pipe"))
            if dp_axis is not None:
                key = jax.random.fold_in(key, jax.lax.axis_index(dp_axis))
            loss, grads = jax.value_and_grad(local_loss, argnums=(0, 1))(
                other, blocks, ids, labels, key)
            g_other, g_blocks = grads
            # 'other' params: only the owning stage produced nonzero grads
            g_other = jax.tree_util.tree_map(
                lambda g: jax.lax.psum(g, "pipe"), g_other)
            loss = jax.lax.psum(loss, "pipe")
            if dp_axis is not None:
                loss = jax.lax.pmean(loss, dp_axis)
                if not zero:
                    g_other = jax.tree_util.tree_map(
                        lambda g: jax.lax.pmean(g, dp_axis), g_other)
                    g_blocks = jax.tree_util.tree_map(
                        lambda g: jax.lax.pmean(g, dp_axis), g_blocks)

            new_params = []
            new_states = []
            for group, (params, gtree, state) in {
                "other": (other, g_other, st_other),
                "block": (blocks, g_blocks, st_block),
            }.items():
                pflat, unravel = ravel_pytree(params)
                gflat, _ = ravel_pytree(gtree)
                orig_len = pflat.shape[0]
                padn = pads[group]
                if padn:
                    pflat = jnp.concatenate(
                        [pflat, jnp.zeros((padn,), pflat.dtype)])
                    gflat = jnp.concatenate(
                        [gflat, jnp.zeros((padn,), gflat.dtype)])
                # state buffers arrive as (1,...,1,shard_len) local blocks
                local_state = {k: v.reshape(-1) if v.ndim else v
                               for k, v in state.items()}
                shard_len = shard_lens[group]
                if zero:
                    # ZeRO-1 per group: reduce-scatter grads over 'data',
                    # update only the local range shard, gather params back
                    pshard_new, snew = zero_shard_update(
                        gflat, local_state, lr, dp_axis, dp, shard_len,
                        fused_update, pflat=pflat)
                    pnew = jax.lax.all_gather(
                        pshard_new, dp_axis, tiled=True)[:orig_len]
                else:
                    pnew, snew = fused_update(pflat, gflat, local_state, lr)
                    pnew = pnew[:orig_len]
                snew = {
                    k: v.reshape((1,) * len(buf_axes[group]) + (shard_len,))
                    if v.ndim else v
                    for k, v in snew.items()
                }
                new_params.append(unravel(pnew))
                new_states.append(snew)
            return loss, new_params[0], new_params[1], new_states[0], \
                new_states[1]

        state_spec = {
            k: (P(*self._buf_axes["other"], None) if v.ndim else P())
            for k, v in self._state_template["other"].items()}
        bstate_spec = {
            k: (P(*self._buf_axes["block"], None) if v.ndim else P())
            for k, v in self._state_template["block"].items()}
        batch_axes = [None]
        if dp_axis and ids_aval.shape[0] % (
                self.num_micro * mesh.shape[dp_axis]) == 0:
            batch_axes = [dp_axis]
        bspec = P(*batch_axes)
        in_specs = (self.other_specs, self.block_specs, state_spec,
                    bstate_spec, bspec, bspec, P(), P(), P())
        out_specs = (P(), self.other_specs, self.block_specs, state_spec,
                     bstate_spec)
        fn = _shard_map(spmd_step, mesh, in_specs, out_specs)
        donate = (0, 1, 2, 3) if self.donate else ()
        return jax.jit(fn, donate_argnums=donate)

    # ---- public API ----
    def step(self, ids, labels):
        iv = ids._data if isinstance(ids, Tensor) else jnp.asarray(ids)
        lv = labels._data if isinstance(labels, Tensor) else \
            jnp.asarray(labels)
        if iv.shape[0] % self.num_micro != 0:
            raise ValueError(
                f"batch {iv.shape[0]} not divisible by "
                f"num_micro={self.num_micro}")
        if self._jit_step is None:
            self._jit_step = self._build(iv, lv)
        self._step_count += 1
        key = _random.get_rng_state()
        step = np.uint32(self._step_count)
        lr = np.float32(self.optimizer.get_lr())
        (loss, self.other_params, self.block_params,
         self._opt_state["other"], self._opt_state["block"]) = \
            self._jit_step(self.other_params, self.block_params,
                           self._opt_state["other"],
                           self._opt_state["block"], iv, lv, key, step, lr)
        from ..optimizer.lr import LRScheduler

        if isinstance(self.optimizer._lr, LRScheduler):
            self.optimizer._lr.step()
        return _wrap_data(loss)

    def _lowered(self, ids, labels):
        iv = ids._data if isinstance(ids, Tensor) else jnp.asarray(ids)
        lv = labels._data if isinstance(labels, Tensor) else \
            jnp.asarray(labels)
        if self._jit_step is None:
            self._jit_step = self._build(iv, lv)
        key = _random.get_rng_state()
        lr = jnp.asarray(self.optimizer.get_lr(), jnp.float32)
        return self._jit_step.lower(
            self.other_params, self.block_params, self._opt_state["other"],
            self._opt_state["block"], iv, lv, key, jnp.uint32(0), lr)

    def cost_analysis(self, ids, labels):
        """XLA cost stats of the compiled pipelined step, or None."""
        from ..core.device import lowered_cost_stats

        try:
            return lowered_cost_stats(self._lowered(ids, labels))
        except Exception:
            return None

    def memory_analysis(self, ids, labels):
        """CompiledMemoryStats of the pipelined step; temp_size_in_bytes is
        the activation+workspace footprint — the quantity the GPipe+remat
        vs 1F1B tradeoff is about (section_worker.cc:167-183 context; the
        measured numbers live in docs/PERF.md)."""
        try:
            return self._lowered(ids, labels).compile().memory_analysis()
        except Exception:
            return None

    def sync_to_model(self):
        for n, v in self.other_params.items():
            self.plan.other[n]._data = v
        self.plan.unstack_into_model(
            {r: jnp.asarray(a) for r, a in self.block_params.items()})

    def state_dict(self):
        self.sync_to_model()
        return self.adapter.model.state_dict()
