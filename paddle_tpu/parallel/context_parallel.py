"""Sequence / context parallelism: ring attention + Ulysses all-to-all.

TPU-native extension with no reference analogue (SURVEY §2.3 "Absent in
reference" row, §5.7): the reference's longest-sequence story is recompute +
pipeline micro-batching; here long sequences shard over a 'seq' mesh axis so
activation memory scales 1/S per chip and attention runs as ICI collectives:

- **ring attention**: K/V chunks rotate around the 'seq' ring via
  `lax.ppermute` while each chip accumulates online-softmax partial results
  for its local Q chunk.  S steps, each an [Lq/S x Lk/S] block matmul on the
  MXU; peak score memory is L^2/S^2 per step instead of L^2.
- **Ulysses**: `lax.all_to_all` re-shards [B, H, L/S, D] -> [B, H/S, L, D]
  (heads scatter, sequence gathers), runs dense/flash attention on full
  sequence with the local head group, and all-to-alls back.  Two collectives
  per call; attention itself can use the Pallas flash kernel.

Both are pure-jax and differentiable (ppermute/all_to_all transpose to their
inverses under vjp), so they compose with jax.checkpoint, bf16 autocast and
the fused hybrid step in parallel/hybrid.py.  Causal masking uses *global*
positions derived from `axis_index('seq')`.
"""
import math

import jax
import jax.numpy as jnp

from ..core.registry import apply_op

NEG_INF = -1e30
SEQ_AXIS = "seq"


def seq_axis_in_scope(axis_name=SEQ_AXIS):
    """True when called under shard_map/pmap tracing with `axis_name` bound
    to a non-trivial (size > 1) axis — matching CompiledTrainStep, which
    ignores a size-1 'seq' placeholder axis."""
    try:
        return jax.lax.psum(1, axis_name) > 1
    except (NameError, KeyError, ValueError):
        return False


def seq_chunk_offset(local_len, axis_name=SEQ_AXIS, dtype="int32"):
    """Tensor scalar: this chip's global sequence offset (rank * local_len);
    0 outside a seq-parallel region.  Used for global position ids."""
    if not seq_axis_in_scope(axis_name):
        from ..ops.creation import zeros

        return zeros([], dtype=dtype)

    def fn():
        return (jax.lax.axis_index(axis_name) * local_len).astype(dtype)

    return apply_op("seq_chunk_offset", fn, (), {})


# ------------------------------ ring attention ---------------------------


def _ring_attention_raw(q, k, v, axis_name, causal):
    """q,k,v: [B, H, Lq_local, D] local chunks of a sequence sharded over
    `axis_name`.  Returns [B, H, Lq_local, D]."""
    S = jax.lax.psum(1, axis_name)          # static axis size
    rank = jax.lax.axis_index(axis_name)
    B, H, Lq, D = q.shape
    Lk = k.shape[2]
    scale = 1.0 / math.sqrt(D)
    qs = (q * scale).astype(jnp.float32)
    perm = [(i, (i + 1) % S) for i in range(S)]

    def one_block(qs, kc, src):
        s = jnp.einsum("bhqd,bhkd->bhqk", qs, kc.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        if causal:
            qpos = rank * Lq + jnp.arange(Lq)
            kpos = src * Lk + jnp.arange(Lk)
            msk = qpos[:, None] >= kpos[None, :]
            s = jnp.where(msk, s, NEG_INF)
        return s

    def step(carry, _):
        acc, m, l, kc, vc, i = carry
        src = (rank - i) % S               # global chunk id currently held
        s = one_block(qs, kc, src)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        # rows with nothing visible yet keep m=NEG_INF; exp(s-m) with both at
        # NEG_INF would be 1, so re-mask p explicitly
        p = jnp.exp(s - m_new[..., None])
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        # m <= m_new always, so alpha in (0, 1]; when both are NEG_INF
        # (row saw nothing yet) alpha=1 but acc and l are still 0
        alpha = jnp.exp(m - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1)
        acc_new = acc * alpha[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vc.astype(jnp.float32),
            preferred_element_type=jnp.float32)
        kc2 = jax.lax.ppermute(kc, axis_name, perm)
        vc2 = jax.lax.ppermute(vc, axis_name, perm)
        return (acc_new, m_new, l_new, kc2, vc2, i + 1), None

    init = (
        jnp.zeros((B, H, Lq, D), jnp.float32),
        jnp.full((B, H, Lq), NEG_INF, jnp.float32),
        jnp.zeros((B, H, Lq), jnp.float32),
        k, v, jnp.int32(0),
    )
    # remat the step so the backward recomputes block scores instead of
    # saving S score tensors
    (acc, m, l, _, _, _), _ = jax.lax.scan(
        jax.checkpoint(step), init, None, length=S)
    safe_l = jnp.where(l > 0.0, l, 1.0)
    return (acc / safe_l[..., None]).astype(q.dtype)


# ------------------------------ Ulysses ----------------------------------


def _ulysses_attention_raw(q, k, v, axis_name, causal, use_flash):
    """All-to-all sequence parallelism: [B,H,L/S,D] -> heads sharded,
    sequence gathered -> local attention -> inverse all-to-all."""
    S = jax.lax.psum(1, axis_name)
    H = q.shape[1]
    if H % S != 0:
        raise ValueError(
            f"ulysses requires heads ({H}) divisible by seq-axis size ({S})")

    def fwd_a2a(x):   # [B, H, Lloc, D] -> [B, H/S, L, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                                  tiled=True)

    def inv_a2a(x):   # [B, H/S, L, D] -> [B, H, Lloc, D]
        return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                                  tiled=True)

    qg, kg, vg = fwd_a2a(q), fwd_a2a(k), fwd_a2a(v)
    scale = 1.0 / math.sqrt(qg.shape[-1])
    if use_flash:
        from ..ops.pallas.flash_attention import _flash

        b, h, lq, d = qg.shape
        lk = kg.shape[2]
        out = _flash(
            (qg * scale).reshape(b * h, lq, d),
            kg.reshape(b * h, lk, d), vg.reshape(b * h, lk, d),
            jnp.zeros((1, lk), jnp.float32), causal, h, False,
        ).reshape(b, h, lq, d)
    else:
        s = jnp.einsum("bhqd,bhkd->bhqk", (qg * scale).astype(jnp.float32),
                       kg.astype(jnp.float32),
                       preferred_element_type=jnp.float32)
        if causal:
            Lq, Lk = s.shape[-2], s.shape[-1]
            cm = jnp.tril(jnp.ones((Lq, Lk), bool), k=Lk - Lq)
            s = jnp.where(cm, s, NEG_INF)
        w = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", w, vg.astype(jnp.float32)
                         ).astype(qg.dtype)
    return inv_a2a(out)


# ------------------------------ public entry ------------------------------


def context_parallel_attention(q, k, v, mode="ring", axis_name=SEQ_AXIS,
                               causal=True, use_flash=False):
    """Tensor-level sequence-parallel attention.  q,k,v: [B, H, Lloc, D]
    Tensors holding this chip's sequence chunk.  Falls back to dense
    attention when no `axis_name` mesh axis is in scope."""
    if not seq_axis_in_scope(axis_name):
        from ..ops.attention import scaled_dot_product_attention

        out, _ = scaled_dot_product_attention(q, k, v, is_causal=causal,
                                              use_flash=use_flash)
        return out

    if mode == "ring":
        def fn(qv, kv, vv):
            return _ring_attention_raw(qv, kv, vv, axis_name, causal)
    elif mode == "ulysses":
        def fn(qv, kv, vv):
            return _ulysses_attention_raw(qv, kv, vv, axis_name, causal,
                                          use_flash)
    else:
        raise ValueError(f"unknown context-parallel mode: {mode!r}")

    return apply_op(f"{mode}_attention", fn, (q, k, v), {})
