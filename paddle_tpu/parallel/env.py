"""Parallel environment: device mesh bootstrap.

Reference parity: python/paddle/distributed/parallel.py:58 init_parallel_env
(env check -> KV bootstrap -> NCCLParallelContext::Init -> default ring) and
platform/collective_helper.h ring registry.  TPU-native design (SURVEY §5.8):
the ring_id-keyed NCCL comm world is replaced by ONE named-axis
jax.sharding.Mesh over ICI/DCN; "rings" become named mesh axes; bootstrap is
jax.distributed.initialize (coordination service) on multi-host.  Groups
(new_group) are sub-axes of the mesh rather than new communicators.
"""
import os
import threading

import numpy as np
import jax
from jax.sharding import Mesh, PartitionSpec, NamedSharding

_lock = threading.Lock()
_global_mesh = None
_initialized = False


class ParallelEnv:
    """Parity: fluid/dygraph/parallel.py ParallelEnv (PADDLE_* env)."""

    def __init__(self):
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", jax.process_index()))
        self._device_id = 0

    @property
    def rank(self):
        return self._rank

    @property
    def local_rank(self):
        return self._rank

    @property
    def world_size(self):
        return int(os.environ.get("PADDLE_TRAINERS_NUM", max(jax.device_count(), 1)))

    @property
    def nranks(self):
        return self.world_size

    @property
    def device_id(self):
        return self._device_id

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "")


def init_parallel_env(mesh_shape=None, axis_names=None):
    """Create the global device mesh (replaces NCCL ring-0 creation).

    On multi-host, callers should have run jax.distributed.initialize (the
    coordination-service analogue of c_gen_nccl_id's TCP bootstrap,
    gen_comm_id_helper.cc:297).
    """
    global _global_mesh, _initialized
    with _lock:
        devices = np.array(jax.devices())
        if mesh_shape is None:
            mesh_shape = (len(devices),)
            axis_names = axis_names or ("data",)
        devices = devices.reshape(mesh_shape)
        _global_mesh = Mesh(devices, axis_names)
        _initialized = True
    return ParallelEnv()


def is_initialized():
    return _initialized


def global_mesh():
    global _global_mesh
    if _global_mesh is None:
        init_parallel_env()
    return _global_mesh


def set_global_mesh(mesh):
    global _global_mesh, _initialized
    _global_mesh = mesh
    _initialized = True


def get_rank(group=None):
    return ParallelEnv().rank


def get_world_size(group=None):
    if group is not None and getattr(group, "nranks", None):
        return group.nranks
    return ParallelEnv().world_size


def build_mesh(shape_dict):
    """Build a named mesh, e.g. {'data': 2, 'model': 4} (hybrid topology).

    Axis order follows insertion order; total must divide available devices.
    """
    names = tuple(shape_dict.keys())
    sizes = tuple(int(v) for v in shape_dict.values())
    n = int(np.prod(sizes))
    devices = np.array(jax.devices()[:n]).reshape(sizes)
    return Mesh(devices, names)
