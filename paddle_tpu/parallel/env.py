"""Parallel environment: device mesh bootstrap.

Reference parity: python/paddle/distributed/parallel.py:58 init_parallel_env
(env check -> KV bootstrap -> NCCLParallelContext::Init -> default ring) and
platform/collective_helper.h ring registry.  TPU-native design (SURVEY §5.8):
the ring_id-keyed NCCL comm world is replaced by ONE named-axis
jax.sharding.Mesh over ICI/DCN; "rings" become named mesh axes; bootstrap is
jax.distributed.initialize (coordination service) on multi-host.  Groups
(new_group) are sub-axes of the mesh rather than new communicators.
"""
import os
import threading

import numpy as np
import jax
from jax.sharding import Mesh, PartitionSpec, NamedSharding

_lock = threading.Lock()
_global_mesh = None
_initialized = False


class ParallelEnv:
    """Parity: fluid/dygraph/parallel.py ParallelEnv (PADDLE_* env)."""

    def __init__(self):
        self._rank = int(os.environ.get("PADDLE_TRAINER_ID", jax.process_index()))
        self._device_id = 0

    @property
    def rank(self):
        return self._rank

    @property
    def local_rank(self):
        return self._rank

    @property
    def world_size(self):
        return int(os.environ.get("PADDLE_TRAINERS_NUM", max(jax.device_count(), 1)))

    @property
    def nranks(self):
        return self.world_size

    @property
    def device_id(self):
        return self._device_id

    @property
    def trainer_endpoints(self):
        eps = os.environ.get("PADDLE_TRAINER_ENDPOINTS", "")
        return eps.split(",") if eps else []

    @property
    def current_endpoint(self):
        return os.environ.get("PADDLE_CURRENT_ENDPOINT", "")


def init_parallel_env(mesh_shape=None, axis_names=None):
    """Create the global device mesh (replaces NCCL ring-0 creation).

    On multi-host, callers should have run jax.distributed.initialize (the
    coordination-service analogue of c_gen_nccl_id's TCP bootstrap,
    gen_comm_id_helper.cc:297).
    """
    global _global_mesh, _initialized
    with _lock:
        devices = np.array(jax.devices())
        if mesh_shape is None:
            mesh_shape = (len(devices),)
            axis_names = axis_names or ("data",)
        devices = devices.reshape(mesh_shape)
        _global_mesh = Mesh(devices, axis_names)
        _initialized = True
    return ParallelEnv()


def is_initialized():
    return _initialized


def global_mesh():
    global _global_mesh
    if _global_mesh is None:
        init_parallel_env()
    return _global_mesh


def set_global_mesh(mesh):
    global _global_mesh, _initialized
    _global_mesh = mesh
    _initialized = True


def get_rank(group=None):
    return ParallelEnv().rank


def get_world_size(group=None):
    if group is not None and getattr(group, "nranks", None):
        return group.nranks
    return ParallelEnv().world_size


def build_mesh(shape_dict, dcn_shape_dict=None):
    """Build a named mesh, e.g. {'data': 2, 'model': 4} (hybrid topology).

    Axis order follows insertion order; total must divide available
    devices.  On real TPUs the device layout comes from
    jax.experimental.mesh_utils so trailing (fast-varying) axes land on
    ICI-adjacent chips; `dcn_shape_dict` (same keys, per-axis slice
    counts) places those factors across slices over DCN
    (create_hybrid_device_mesh) — the multi-slice recipe.  On CPU (the
    virtual test mesh) the layout is a plain reshape, byte-stable for
    the parity tests.
    """
    names = tuple(shape_dict.keys())
    sizes = tuple(int(v) for v in shape_dict.values())
    n = int(np.prod(sizes))
    devs = jax.devices()
    if dcn_shape_dict is not None:
        unknown = set(dcn_shape_dict) - set(names)
        if unknown:
            raise ValueError(
                f"dcn_shape_dict keys {sorted(unknown)} are not mesh "
                f"axes {list(names)}")
        dcn_sizes = tuple(int(dcn_shape_dict.get(k, 1)) for k in names)
        for k, s, d in zip(names, sizes, dcn_sizes):
            if d <= 0 or s % d:
                raise ValueError(
                    f"DCN factor {d} does not divide axis {k!r} size {s}")
        ici_sizes = tuple(s // d for s, d in zip(sizes, dcn_sizes))
        if all(hasattr(d, "slice_index") for d in devs[:n]):
            from jax.experimental import mesh_utils

            devices = mesh_utils.create_hybrid_device_mesh(
                ici_sizes, dcn_sizes, devices=devs)
        else:
            # no slice topology (CPU test mesh / single slice): manual
            # slice-major layout — DCN factors are the slowest-varying
            # dims of each axis, the same placement the hybrid helper
            # produces modulo intra-slice ICI optimization
            arr = np.array(devs[:n]).reshape(dcn_sizes + ici_sizes)
            k = len(names)
            order = [i for pair in ((d, d + k) for d in range(k))
                     for i in pair]
            devices = arr.transpose(order).reshape(sizes)
        return Mesh(devices, names)
    if devs and devs[0].platform == "tpu" and n == len(devs):
        try:
            from jax.experimental import mesh_utils

            devices = mesh_utils.create_device_mesh(sizes, devices=devs)
            return Mesh(devices, names)
        except Exception:
            pass  # odd topologies: fall through to the plain reshape
    devices = np.array(devs[:n]).reshape(sizes)
    return Mesh(devices, names)


def tp_mesh(tp_degree=None, axis_name="model"):
    """A 1-D tensor-parallel mesh over the first `tp_degree` devices —
    the mesh the sharded generation engine takes (GenerationConfig.mesh;
    docs/GENERATION.md "Sharded decode").  Defaults to every visible
    device.  Goes through build_mesh, so on real TPUs the devices come
    ICI-ordered from mesh_utils and on CPU (the forced-host-device test
    mesh, ``--xla_force_host_platform_device_count=N``) it is a plain
    stable reshape."""
    n = len(jax.devices()) if tp_degree is None else int(tp_degree)
    if n < 1:
        raise ValueError(f"tp_degree must be >= 1, got {tp_degree}")
    if n > len(jax.devices()):
        raise ValueError(
            f"tp_degree={n} exceeds the {len(jax.devices())} visible "
            f"device(s)")
    return build_mesh({axis_name: n})
