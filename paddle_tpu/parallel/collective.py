"""Collective communication API.

Reference parity: python/paddle/distributed/collective.py:348-1630
(broadcast/all_reduce/reduce/all_gather/scatter/alltoall/send/recv/barrier,
ReduceOp, Group, new_group:209) over operators/collective/ kernels keyed by
ring_id.  TPU-native: collectives are XLA ops over named mesh axes
(psum/all_gather/ppermute lowered onto ICI).  Eager semantics: a Tensor is a
global array; per-rank views are its shards along the group axis.  all_reduce
on a replicated tensor multiplies by group size (every "rank" contributes its
copy) — identical observable behavior to N NCCL ranks holding equal values.
Inside compiled/shard_map code the same functions map to lax collectives.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map as _shard_map_raw
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map as _shard_map_raw
import inspect as _inspect

_SM_PARAMS = set(_inspect.signature(_shard_map_raw).parameters)
_SM_NOCHECK = (
    {"check_rep": False} if "check_rep" in _SM_PARAMS
    else {"check_vma": False} if "check_vma" in _SM_PARAMS else {}
)


def shard_map(f, mesh=None, in_specs=None, out_specs=None, **kw):
    kw.pop("check_rep", None)
    return _shard_map_raw(f, mesh=mesh, in_specs=in_specs,
                          out_specs=out_specs, **_SM_NOCHECK)

from ..core.tensor import Tensor, _wrap_data
from . import env as _env


class ReduceOp:
    SUM = 0
    MAX = 1
    MIN = 2
    PROD = 3
    AVG = 4


class Group:
    """Parity: collective.py Group — here a named axis over a sub-mesh."""

    def __init__(self, rank, nranks, id=0, ranks=None, mesh=None, axis="data"):
        self.rank = rank
        self.nranks = nranks
        self.id = id
        self.ranks = ranks or list(range(nranks))
        self.mesh = mesh
        self.axis = axis

    @property
    def world_size(self):
        return self.nranks

    def get_group_rank(self, rank):
        return self.ranks.index(rank) if rank in self.ranks else -1

    def __repr__(self):
        return f"Group(rank={self.rank}, nranks={self.nranks}, axis={self.axis})"


_default_group = None
_group_counter = [0]
_GROUPS = {}


def _get_default_group():
    global _default_group
    if _default_group is None:
        mesh = _env.global_mesh()
        axis = mesh.axis_names[0]
        _default_group = Group(
            _env.get_rank(), mesh.shape[axis], id=0, mesh=mesh, axis=axis
        )
    return _default_group


def new_group(ranks=None, backend=None, timeout=None):
    """Parity: collective.py:209.  Groups are modeled as sub-axes; for rank
    subsets we record membership (program-rewrite tests assert on groups, the
    compiled path uses mesh axes directly)."""
    _group_counter[0] += 1
    mesh = _env.global_mesh()
    n = len(ranks) if ranks else _env.get_world_size()
    g = Group(_env.get_rank(), n, id=_group_counter[0], ranks=ranks, mesh=mesh,
              axis=mesh.axis_names[0])
    _GROUPS[g.id] = g
    return g


def _in_trace():
    return isinstance(jnp.zeros(()), jax.core.Tracer)


def _axis_in_scope(axis):
    try:
        jax.lax.axis_index(axis)
        return True
    except BaseException:
        return False


def _group_info(group):
    g = group or _get_default_group()
    return g, g.axis, g.nranks


def _over_mesh(fn, x, group):
    """Run fn (which uses lax collectives over `axis`) via shard_map on the
    group's mesh.  Input treated as a global array sharded on axis 0 when
    divisible, else replicated."""
    g, axis, n = _group_info(group)
    if _axis_in_scope(axis):
        # already inside shard_map/pjit with this axis: direct lax collective
        return fn(x, axis)
    mesh = g.mesh or _env.global_mesh()
    shard0 = x.shape[0] % n == 0 if x.ndim else False
    in_spec = P(axis) if shard0 else P()
    out_spec = in_spec

    def body(v):
        return fn(v, axis)

    return shard_map(
        body, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec,
    )(x)


_REDUCERS = {
    ReduceOp.SUM: lambda v, ax: jax.lax.psum(v, ax),
    ReduceOp.MAX: lambda v, ax: jax.lax.pmax(v, ax),
    ReduceOp.MIN: lambda v, ax: jax.lax.pmin(v, ax),
    ReduceOp.PROD: lambda v, ax: jnp.exp(jax.lax.psum(jnp.log(v), ax)),
    ReduceOp.AVG: lambda v, ax: jax.lax.pmean(v, ax),
}


def all_reduce(tensor, op=ReduceOp.SUM, group=None, sync_op=True):
    """c_allreduce_{sum,max,min,prod} parity -> XLA AllReduce on ICI."""
    red = _REDUCERS[op]
    out = _over_mesh(lambda v, ax: red(v, ax), tensor._data, group)
    tensor._data = out
    return tensor


def reduce(tensor, dst, op=ReduceOp.SUM, group=None, sync_op=True):
    # On a mesh, reduce == allreduce (result materialized everywhere; the dst
    # distinction is meaningless for value-semantic XLA collectives).
    return all_reduce(tensor, op=op, group=group)


def broadcast(tensor, src, group=None, sync_op=True):
    """c_broadcast parity.  Global arrays are already consistent; for sharded
    inputs broadcast selects src's shard for everyone."""
    g, axis, n = _group_info(group)
    x = tensor._data
    if x.ndim and x.shape[0] % n == 0 and n > 1:
        shard = x.shape[0] // n
        src_local = g.get_group_rank(src) if g.ranks else src
        block = jax.lax.dynamic_slice_in_dim(x, src_local * shard, shard, 0)
        tensor._data = jnp.concatenate([block] * n, axis=0)
    return tensor


def all_gather(tensor_list, tensor, group=None, sync_op=True):
    """c_allgather parity: every rank's shard concatenated."""
    g, axis, n = _group_info(group)
    x = tensor._data
    # eager model: the "per-rank tensor" is the same global array on each rank;
    # gather returns n copies (matching N ranks holding equal tensors), or the
    # shards when the array is axis-0 sharded.
    out = _over_mesh(
        lambda v, ax: jax.lax.all_gather(v, ax, axis=0, tiled=True), x, group
    )
    if tensor_list is not None:
        per = out.shape[0] // n
        for i in range(n):
            tensor_list.append(_wrap_data(out[i * per: (i + 1) * per]))
    return _wrap_data(out)


def reduce_scatter(tensor, tensor_list=None, op=ReduceOp.SUM, group=None,
                   sync_op=True):
    """c_reducescatter parity."""
    g, axis, n = _group_info(group)
    x = tensor_list
    if isinstance(x, (list, tuple)):
        data = jnp.concatenate([t._data for t in x], axis=0)
    else:
        data = (x or tensor)._data
    out = _over_mesh(
        lambda v, ax: jax.lax.psum_scatter(v, ax, scatter_dimension=0, tiled=True),
        data, group,
    )
    tensor._data = out
    return tensor


def scatter(tensor, tensor_list=None, src=0, group=None, sync_op=True):
    g, axis, n = _group_info(group)
    if tensor_list:
        data = jnp.stack([t._data for t in tensor_list], axis=0)
        rank = g.rank if g.ranks is None else g.get_group_rank(g.rank)
        tensor._data = data[max(rank, 0)]
    return tensor


def alltoall(in_tensor_list, out_tensor_list=None, group=None, sync_op=True):
    """alltoall parity -> lax.all_to_all on ICI.

    Compiled path (inside shard_map): use `all_to_all_in_mesh`.  Eager
    single-controller view: each "rank" holds the same global list, so rank r
    receives in_list[r] from every peer: out = [in[r]] * n.
    """
    g, axis, n = _group_info(group)
    if isinstance(in_tensor_list, Tensor):
        out = _over_mesh(
            lambda v, ax: jax.lax.all_to_all(v, ax, split_axis=1, concat_axis=0,
                                             tiled=True),
            in_tensor_list._data, group,
        )
        return _wrap_data(out)
    r = max(g.rank if g.ranks is None else g.get_group_rank(g.rank), 0)
    received = [in_tensor_list[r]._data for _ in range(n)]
    if out_tensor_list is not None:
        for v in received:
            out_tensor_list.append(_wrap_data(v))
        return out_tensor_list
    return [_wrap_data(v) for v in received]


def all_to_all_in_mesh(x, axis_name, split_axis=0, concat_axis=0):
    """Sequence-parallel building block (Ulysses-style head<->seq exchange)."""
    return jax.lax.all_to_all(x, axis_name, split_axis=split_axis,
                              concat_axis=concat_axis, tiled=True)


def send(tensor, dst=0, group=None, sync_op=True):
    """send_v2 parity.  Point-to-point on a mesh is collective-permute; in the
    single-controller eager view data is already globally addressable, so send
    records into a mailbox consumed by recv."""
    _mailbox.setdefault(dst, []).append(tensor._data)
    return tensor


def recv(tensor, src=0, group=None, sync_op=True):
    box = _mailbox.get(_env.get_rank()) or _mailbox.get(src)
    if box:
        tensor._data = box.pop(0)
    return tensor


_mailbox = {}


def isend(tensor, dst=0, group=None):
    send(tensor, dst, group)
    return _DummyTask()


def irecv(tensor, src=0, group=None):
    recv(tensor, src, group)
    return _DummyTask()


class _DummyTask:
    def wait(self):
        return True

    def is_completed(self):
        return True


def barrier(group=None):
    """barrier op parity: drain device queue (XLA programs are ordered; the
    host-side barrier just synchronizes dispatch)."""
    jax.block_until_ready(jnp.zeros(()))


class P2POp:
    def __init__(self, op, tensor, peer, group=None):
        self.op = op
        self.tensor = tensor
        self.peer = peer
        self.group = group


def batch_isend_irecv(p2p_op_list):
    tasks = []
    for op in p2p_op_list:
        tasks.append(op.op(op.tensor, op.peer, op.group))
    return tasks


# ---- in-mesh collective forms (used inside shard_map'd compiled code) ----

def psum(x, axis_name):
    return jax.lax.psum(x, axis_name)


def pmean(x, axis_name):
    return jax.lax.pmean(x, axis_name)


def ppermute(x, axis_name, perm):
    return jax.lax.ppermute(x, axis_name, perm)


def axis_index(axis_name):
    return jax.lax.axis_index(axis_name)


def get_group(id=0):
    """collective.py get_group parity: the Group registered under id, the
    default world group for id 0, None for an unknown id (fail fast
    rather than silently widening a subgroup collective to the world)."""
    if id == 0:
        return _get_default_group()
    return _GROUPS.get(id)


def wait(tensor, group=None, use_calc_stream=True):
    """collective.py wait / c_sync_*_stream parity: XLA collectives are
    value-semantic dataflow, so ordering is already guaranteed; a device
    sync is the only observable effect."""
    if hasattr(tensor, "_data"):
        tensor._data.block_until_ready()
    return tensor
