"""EQuARX-style quantized allreduce (arxiv 2506.17615, PAPERS.md).

The sharded generation step has exactly two collectives per layer: the
Megatron allreduces after the row-sharded ``wo`` and ``w2``
contractions, each over a ``[rows, d_model]`` float32 activation block.
On the wire a ring allreduce moves ``2 (N-1)/N`` of the payload per
device — all of it float32 today.  EQuARX's observation: the payload
tolerates int8 with per-hop abs-max scales at negligible quality loss,
cutting the dominant wire bytes ~4x.

XLA's implicit GSPMD allreduce cannot be quantized from the outside, so
``quantized_matmul_allreduce`` makes the collective EXPLICIT: the
row-sharded matmul runs inside a ``shard_map`` block placed exactly
where the implicit allreduce sits today (between the partial-sum matmul
and the residual add), and the reduction is a hand-rolled ring over
``ppermute``:

- reduce-scatter phase: N-1 hops; each hop quantizes the accumulated
  chunk to int8 against its own abs-max scale (one f32 scalar per
  chunk), ships int8 + scale, and the receiver dequantizes and adds
  its local chunk — the quantize -> psum -> dequant block, per hop,
  exactly the EQuARX construction;
- all-gather phase: N-1 hops shipping each finished chunk once (int8 +
  scale); EVERY shard — the owner included — reads the chunk through
  the same dequant, so the output is bit-identical across shards
  (a replicated out_spec demands it).

Wire bytes per device: ``2 (N-1)/N * rows * d_model`` int8 plus
``2 (N-1)`` f32 scale scalars — the ~4x the acceptance gauge
(`generation.collective_bytes_per_step`) is cut by.  Quantization
noise enters the activations once per hop; the quality-gate harness
(generation/quality.py) bounds the resulting logit drift and token
agreement against the fp32 oracle, the same contract as int8 KV.

Pure function of its inputs and the ring order (fixed by axis index),
so the result is deterministic — int8-vs-int8 token identity across
transports and restarts holds exactly like every other engine path.
"""
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..generation.quantized_kv import dequantize_int8, quantize_int8


def _quant(x):
    """(int8, f32 scalar scale) of one chunk — per-shard abs-max,
    rounded by the ONE quantization home (generation/quantized_kv)."""
    s = jnp.max(jnp.abs(x)).astype(jnp.float32)
    return quantize_int8(x, s, jnp), s


def _dequant(q, s):
    return dequantize_int8(q, s, jnp)


def quantized_ring_allreduce(x, axis_name, n):
    """Sum `x` ([rows, d] per-shard partial) over `axis_name` (size
    `n`, static) through the quantized ring.  Must run inside a
    shard_map over that axis.  Returns the full sum, bit-identical on
    every shard."""
    if n == 1:
        return x
    rows, d = x.shape
    pad = (-rows) % n
    xp = jnp.pad(x, ((0, pad), (0, 0))) if pad else x
    chunks = xp.reshape(n, -1, d)                      # [n, rows/n, d]
    idx = jax.lax.axis_index(axis_name)
    perm = [(i, (i + 1) % n) for i in range(n)]

    # ---- reduce-scatter: hop t delivers chunk (idx - t) mod n, whose
    # running sum gains this shard's local copy; after n-1 hops shard i
    # holds the FULL sum of chunk (i - (n-2)) mod n.  Each hop ships
    # int8 + its abs-max scale; the receiver dequantizes and adds.
    acc = jnp.take(chunks, (idx + 1) % n, axis=0)      # hop-0 send
    for t in range(n - 1):
        q, s = _quant(acc)
        q = jax.lax.ppermute(q, axis_name, perm)
        s = jax.lax.ppermute(s, axis_name, perm)
        recv_id = (idx - t) % n
        acc = _dequant(q, s) + jnp.take(chunks, recv_id, axis=0)
    own_id = (idx - (n - 2)) % n                       # acc's chunk id

    # ---- all-gather: quantize each finished chunk ONCE and walk it
    # around the ring; every shard (owner included) dequantizes the
    # same bytes, so all shards assemble the identical result.
    out = jnp.zeros_like(chunks)
    q, s = _quant(acc)
    out = jax.lax.dynamic_update_index_in_dim(
        out, _dequant(q, s), own_id, axis=0)
    for t in range(n - 1):
        q = jax.lax.ppermute(q, axis_name, perm)
        s = jax.lax.ppermute(s, axis_name, perm)
        recv_id = (own_id - 1 - t) % n
        out = jax.lax.dynamic_update_index_in_dim(
            out, _dequant(q, s), recv_id, axis=0)
    full = out.reshape(-1, d)
    return full[:rows] if pad else full


def quantized_matmul_allreduce(mesh, tp_axis):
    """Build ``qmm(a, w) -> a @ w summed over the sharded contraction``
    for a column-sharded activation `a` ``[rows, k]`` (k split over
    `tp_axis`) against a row-sharded weight `w` ``[k, d]`` — the
    drop-in replacement for the two Megatron matmuls whose implicit
    GSPMD allreduce this makes explicit and quantized.  The returned
    callable is used INSIDE the jitted step traces (shard_map under
    jit, the same nesting as the mesh-native Pallas kernels)."""
    from .collective import shard_map

    n = int(mesh.shape[tp_axis])

    def local(a_loc, w_loc):
        part = jnp.matmul(a_loc, w_loc,
                          preferred_element_type=jnp.float32)
        return quantized_ring_allreduce(part, tp_axis, n)

    fn = shard_map(local, mesh=mesh,
                   in_specs=(P(None, tp_axis), P(tp_axis, None)),
                   out_specs=P(None, None))

    def qmm(a, w):
        return fn(a, w)

    return qmm


def quantized_collective_bytes(num_layers, rows, d_model, tp_degree):
    """Estimated on-wire bytes of ONE sharded dispatch's two per-layer
    allreduces under the quantized ring — the quantized counterpart of
    fused._collective_bytes_estimate (int8 payload x the same ring
    factor, plus the per-hop scale scalars)."""
    if tp_degree <= 1:
        return 0
    payload = int(rows) * int(d_model)           # int8: 1 byte/elem
    per_ar = (payload * 2 * (tp_degree - 1) / tp_degree
              + 2 * (tp_degree - 1) * 4)         # scale scalars
    return int(2 * num_layers * per_ar)
