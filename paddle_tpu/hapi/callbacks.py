"""Training callbacks.

Reference parity: python/paddle/hapi/callbacks.py (Callback, ProgBarLogger,
ModelCheckpoint:533, EarlyStopping:688, LRScheduler, VisualDL:841 — VisualDL
itself is intentionally absent; a CSV/JSONL logger stands in for observability).
"""
import json
import os
import time

import numpy as np


class Callback:
    def __init__(self):
        self.model = None
        self.params = {}

    def set_model(self, model):
        self.model = model

    def set_params(self, params):
        self.params = params or {}

    def on_train_begin(self, logs=None):
        pass

    def on_train_end(self, logs=None):
        pass

    def on_epoch_begin(self, epoch, logs=None):
        pass

    def on_epoch_end(self, epoch, logs=None):
        pass

    def on_train_batch_begin(self, step, logs=None):
        pass

    def on_train_batch_end(self, step, logs=None):
        pass

    def on_eval_begin(self, logs=None):
        pass

    def on_eval_end(self, logs=None):
        pass


class CallbackList:
    def __init__(self, callbacks=None):
        self.callbacks = list(callbacks or [])

    def append(self, cb):
        self.callbacks.append(cb)

    def set_model(self, model):
        for c in self.callbacks:
            c.set_model(model)

    def set_params(self, params):
        for c in self.callbacks:
            c.set_params(params)

    def __getattr__(self, name):
        if name.startswith("on_"):
            def call(*args, **kwargs):
                for c in self.callbacks:
                    getattr(c, name)(*args, **kwargs)

            return call
        raise AttributeError(name)


class ProgBarLogger(Callback):
    def __init__(self, log_freq=1, verbose=2):
        super().__init__()
        self.log_freq = log_freq
        self.verbose = verbose

    def on_epoch_begin(self, epoch, logs=None):
        self.epoch = epoch
        self._t0 = time.time()
        if self.verbose:
            print(f"Epoch {epoch + 1}/{self.params.get('epochs', '?')}")

    def on_train_batch_end(self, step, logs=None):
        if self.verbose and step % self.log_freq == 0:
            items = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                for k, v in (logs or {}).items() if k != "step"
            )
            print(f"step {step}: {items}")

    def on_epoch_end(self, epoch, logs=None):
        if self.verbose:
            dt = time.time() - self._t0
            print(f"epoch {epoch + 1} done in {dt:.1f}s: {logs}")


class ModelCheckpoint(Callback):
    """callbacks.py:533 parity."""

    def __init__(self, save_freq=1, save_dir=None):
        super().__init__()
        self.save_freq = save_freq
        self.save_dir = save_dir

    def on_epoch_end(self, epoch, logs=None):
        if self.save_dir and (epoch + 1) % self.save_freq == 0:
            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(os.path.join(self.save_dir, str(epoch)))

    def on_train_end(self, logs=None):
        if self.save_dir:
            os.makedirs(self.save_dir, exist_ok=True)
            self.model.save(os.path.join(self.save_dir, "final"))


class EarlyStopping(Callback):
    """callbacks.py:688 parity."""

    def __init__(self, monitor="loss", mode="auto", patience=0, verbose=1,
                 min_delta=0, baseline=None, save_best_model=True):
        super().__init__()
        self.monitor = monitor
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.baseline = baseline
        self.wait = 0
        self.best = None
        if mode == "max" or (mode == "auto" and "acc" in monitor):
            self.is_better = lambda a, b: a > b + self.min_delta
        else:
            self.is_better = lambda a, b: a < b - self.min_delta

    def on_train_begin(self, logs=None):
        self.wait = 0
        self.best = self.baseline

    def on_epoch_end(self, epoch, logs=None):
        cur = (logs or {}).get(self.monitor)
        if cur is None:
            cur = (logs or {}).get("eval_" + self.monitor)
        if cur is None:
            return
        if self.best is None or self.is_better(cur, self.best):
            self.best = cur
            self.wait = 0
        else:
            self.wait += 1
            if self.wait >= self.patience:
                self.model.stop_training = True


class LRScheduler(Callback):
    def __init__(self, by_step=True, by_epoch=False):
        super().__init__()
        self.by_step = by_step
        self.by_epoch = by_epoch

    def _sched(self):
        opt = self.model._optimizer
        from ..optimizer.lr import LRScheduler as Sched

        return opt._lr if opt and isinstance(opt._lr, Sched) else None

    def on_train_batch_end(self, step, logs=None):
        if self.by_step:
            s = self._sched()
            if s:
                s.step()

    def on_epoch_end(self, epoch, logs=None):
        if self.by_epoch:
            s = self._sched()
            if s:
                s.step()


class JSONLLogger(Callback):
    """Metrics sink (VisualDL-callback stand-in): one JSON line per epoch."""

    def __init__(self, log_path="train_log.jsonl"):
        super().__init__()
        self.log_path = log_path

    def on_epoch_end(self, epoch, logs=None):
        rec = {"epoch": epoch}
        for k, v in (logs or {}).items():
            if isinstance(v, (int, float, str)):
                rec[k] = v
            elif isinstance(v, np.floating):
                rec[k] = float(v)
        with open(self.log_path, "a") as f:
            f.write(json.dumps(rec) + "\n")


VisualDL = JSONLLogger


class ReduceLROnPlateau(Callback):
    """Scale the LR by `factor` after `patience` epochs without improvement
    of `monitor` (hapi/callbacks.py ReduceLROnPlateau parity).  Works with
    both plain-float LRs (set_lr) and LRScheduler-driven optimizers (the
    scheduler's base learning rate is scaled)."""

    def __init__(self, monitor="loss", factor=0.1, patience=10,
                 mode="auto", min_delta=1e-4, cooldown=0, min_lr=0,
                 verbose=1):
        super().__init__()
        self.monitor = monitor
        self.factor = float(factor)
        self.patience = patience
        self.min_delta = abs(min_delta)
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.verbose = verbose
        if mode == "auto":
            mode = "max" if "acc" in monitor else "min"
        self.mode = mode
        self._best = None
        self._wait = 0
        self._cooldown_left = 0

    def _improved(self, cur):
        if self._best is None:
            return True
        if self.mode == "min":
            return cur < self._best - self.min_delta
        return cur > self._best + self.min_delta

    def on_epoch_end(self, epoch, logs=None):
        logs = logs or {}
        # eval metrics publish as 'eval_<name>' (model.py epoch-end logs),
        # same fallback EarlyStopping uses
        cur = logs.get(self.monitor, logs.get("eval_" + self.monitor))
        if cur is None:
            return
        cur = float(np.asarray(cur).reshape(-1)[0])
        if self._improved(cur):
            self._best = cur
            self._wait = 0
            return
        if self._cooldown_left > 0:
            # epochs inside the cooldown window never count toward
            # patience (Keras/reference semantics)
            self._cooldown_left -= 1
            self._wait = 0
            return
        self._wait += 1
        if self._wait < self.patience:
            return
        opt = self.model._optimizer
        if opt is None:
            return
        from ..optimizer.lr import LRScheduler as Sched

        if isinstance(opt._lr, Sched):
            new = max(opt._lr.base_lr * self.factor, self.min_lr)
            opt._lr.base_lr = new
            opt._lr.last_lr = max(opt._lr.last_lr * self.factor,
                                  self.min_lr)
        else:
            new = max(float(opt.get_lr()) * self.factor, self.min_lr)
            opt.set_lr(new)
        if self.verbose:
            print(f"ReduceLROnPlateau: epoch {epoch}: lr -> {new:.3e}")
        self._wait = 0
        self._cooldown_left = self.cooldown
