from .model import Model  # noqa: F401
from . import callbacks  # noqa: F401

from .model_summary import summary, summary_string  # noqa: E402,F401
from .dynamic_flops import flops, static_flops  # noqa: E402,F401
from .. import hub  # noqa: E402,F401  (hapi.hub alias)
from . import callbacks as logger  # noqa: E402,F401  (logger shim: the
# reference hapi.logger backs ProgBarLogger; our callbacks own logging)


class ProgressBar:
    """hapi/progressbar.py: minimal terminal progress meter used by
    ProgBarLogger."""

    def __init__(self, num=None, width=30, verbose=1, file=None):
        self.num = num
        self.width = width
        self._seen = 0

    def update(self, current_num, values=None):
        self._seen = current_num
        if self.num:
            frac = min(current_num / self.num, 1.0)
            bar = "=" * int(frac * self.width)
            metrics = " - ".join(
                f"{k}: {v:.4f}" if isinstance(v, float) else f"{k}: {v}"
                for k, v in (values or []))
            print(f"\r{current_num}/{self.num} [{bar:<{self.width}}] "
                  f"{metrics}", end="", flush=True)

    def start(self):
        pass


progressbar = ProgressBar
