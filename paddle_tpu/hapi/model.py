"""High-level Model API.

Reference parity: python/paddle/hapi/model.py (Model:878, fit:1523,
evaluate, predict, save/load, prepare) — Keras-like training loops over
DataLoader with callbacks.
"""
import numpy as np

from ..core.tensor import Tensor, to_tensor
from ..nn.layer import Layer
from ..io.dataloader import DataLoader
from ..metric import Metric
from . import callbacks as cbks


class Model:
    def __init__(self, network, inputs=None, labels=None):
        self.network = network
        self._inputs = inputs
        self._labels = labels
        self._optimizer = None
        self._loss = None
        self._metrics = []
        self.stop_training = False

    def prepare(self, optimizer=None, loss=None, metrics=None, amp_configs=None):
        self._optimizer = optimizer
        self._loss = loss
        if metrics is None:
            self._metrics = []
        elif isinstance(metrics, (list, tuple)):
            self._metrics = list(metrics)
        else:
            self._metrics = [metrics]

    # ---- single-step primitives (hapi/model.py train_batch parity) ----
    def train_batch(self, inputs, labels=None, update=True):
        self.network.train()
        inputs = self._to_list(inputs)
        labels = self._to_list(labels)
        outputs = self.network(*[self._to_tensor(x) for x in inputs])
        losses = self._compute_loss(outputs, labels)
        total = losses[0]
        for l in losses[1:]:
            from ..ops import math as M

            total = M.add(total, l)
        total.backward()
        if update:
            self._optimizer.step()
            self._optimizer.clear_grad()
        metrics = self._update_metrics(outputs, labels)
        return self._loss_values(losses), metrics

    def eval_batch(self, inputs, labels=None):
        self.network.eval()
        from ..core.autograd import no_grad

        with no_grad():
            inputs = self._to_list(inputs)
            labels = self._to_list(labels)
            outputs = self.network(*[self._to_tensor(x) for x in inputs])
            losses = self._compute_loss(outputs, labels)
            metrics = self._update_metrics(outputs, labels)
        return self._loss_values(losses), metrics

    def predict_batch(self, inputs):
        self.network.eval()
        from ..core.autograd import no_grad

        with no_grad():
            inputs = self._to_list(inputs)
            outputs = self.network(*[self._to_tensor(x) for x in inputs])
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        return [o.numpy() for o in outs]

    def _compute_loss(self, outputs, labels):
        if self._loss is None:
            return []
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        losses = self._loss(*(list(outs) + list(labels)))
        return losses if isinstance(losses, (list, tuple)) else [losses]

    def _update_metrics(self, outputs, labels):
        outs = outputs if isinstance(outputs, (list, tuple)) else [outputs]
        res = {}
        for m in self._metrics:
            stats = m.compute(*(list(outs) + list(labels)))
            if isinstance(stats, (list, tuple)):
                r = m.update(*stats)
            else:
                r = m.update(stats)
            names = m.name()
            names = names if isinstance(names, list) else [names]
            vals = r if isinstance(r, (list, tuple)) else [r]
            for n, v in zip(names, vals):
                res[n] = v
        return res

    @staticmethod
    def _loss_values(losses):
        return [float(np.asarray(l.numpy()).reshape(-1)[0]) for l in losses]

    @staticmethod
    def _to_list(x):
        if x is None:
            return []
        return list(x) if isinstance(x, (list, tuple)) else [x]

    @staticmethod
    def _to_tensor(x):
        return x if isinstance(x, Tensor) else to_tensor(x)

    # ---- loops (fit:1523 parity) ----
    def fit(self, train_data=None, eval_data=None, batch_size=1, epochs=1,
            eval_freq=1, log_freq=10, save_dir=None, save_freq=1, verbose=2,
            drop_last=False, shuffle=True, num_workers=0, callbacks=None,
            accumulate_grad_batches=1, num_iters=None):
        train_loader = self._as_loader(train_data, batch_size, shuffle,
                                       drop_last, num_workers)
        eval_loader = self._as_loader(eval_data, batch_size, False, False,
                                      num_workers) if eval_data is not None else None

        cblist = cbks.CallbackList(callbacks or [])
        cblist.set_model(self)
        cblist.set_params({
            "epochs": epochs, "steps": self._safe_len(train_loader),
            "verbose": verbose,
            "metrics": ["loss"] + self._metric_names(),
        })
        cblist.on_train_begin()
        self.stop_training = False

        for epoch in range(epochs):
            cblist.on_epoch_begin(epoch)
            for m in self._metrics:
                m.reset()
            logs = {}
            for step, data in enumerate(train_loader):
                cblist.on_train_batch_begin(step)
                ins, lbs = self._split_batch(data)
                losses, metrics = self.train_batch(ins, lbs)
                logs = {"loss": losses[0] if losses else 0.0, **metrics,
                        "step": step}
                cblist.on_train_batch_end(step, logs)
                if num_iters is not None and step + 1 >= num_iters:
                    break
            if eval_loader is not None and (epoch + 1) % eval_freq == 0:
                eval_logs = self.evaluate(eval_loader, verbose=0)
                logs.update({f"eval_{k}": v for k, v in eval_logs.items()})
            cblist.on_epoch_end(epoch, logs)
            if save_dir and (epoch + 1) % save_freq == 0:
                self.save(f"{save_dir}/{epoch}")
            if self.stop_training:
                break
        cblist.on_train_end(logs)
        return self

    def evaluate(self, eval_data, batch_size=1, log_freq=10, verbose=2,
                 num_workers=0, callbacks=None, num_iters=None):
        loader = self._as_loader(eval_data, batch_size, False, False,
                                 num_workers)
        for m in self._metrics:
            m.reset()
        total_loss, count = 0.0, 0
        for step, data in enumerate(loader):
            ins, lbs = self._split_batch(data)
            losses, _ = self.eval_batch(ins, lbs)
            if losses:
                total_loss += losses[0]
                count += 1
            if num_iters is not None and step + 1 >= num_iters:
                break
        logs = {"loss": total_loss / max(count, 1)}
        for m in self._metrics:
            names = m.name()
            names = names if isinstance(names, list) else [names]
            vals = m.accumulate()
            vals = vals if isinstance(vals, (list, tuple)) else [vals]
            logs.update(dict(zip(names, vals)))
        return logs

    def predict(self, test_data, batch_size=1, num_workers=0, stack_outputs=False,
                callbacks=None, verbose=1):
        loader = self._as_loader(test_data, batch_size, False, False,
                                 num_workers)
        outputs = []
        for data in loader:
            ins, _ = self._split_batch(data, has_label=False)
            outs = self.predict_batch(ins)
            outputs.append(outs)
        if stack_outputs and outputs:
            n_out = len(outputs[0])
            return [np.concatenate([o[i] for o in outputs]) for i in range(n_out)]
        return outputs

    def _metric_names(self):
        names = []
        for m in self._metrics:
            n = m.name()
            names.extend(n if isinstance(n, list) else [n])
        return names

    def _split_batch(self, data, has_label=True):
        if isinstance(data, (list, tuple)):
            data = list(data)
            if has_label and len(data) >= 2:
                n_in = len(self._inputs) if self._inputs else len(data) - 1
                return data[:n_in], data[n_in:]
            return data, []
        return [data], []

    @staticmethod
    def _safe_len(loader):
        try:
            return len(loader)
        except TypeError:
            return None

    def _as_loader(self, data, batch_size, shuffle, drop_last, num_workers):
        if data is None or isinstance(data, DataLoader):
            return data
        return DataLoader(data, batch_size=batch_size, shuffle=shuffle,
                          drop_last=drop_last, num_workers=num_workers)

    # ---- save / load ----
    def save(self, path, training=True):
        from ..framework import save as fsave

        if training:
            fsave(self.network.state_dict(), path + ".pdparams")
            if self._optimizer is not None:
                fsave(self._optimizer.state_dict(), path + ".pdopt")
        else:
            from ..jit import save as jit_save

            jit_save(self.network, path, input_spec=self._inputs)

    def load(self, path, skip_mismatch=False, reset_optimizer=False):
        from ..framework import load as fload

        self.network.set_state_dict(fload(path + ".pdparams"))
        import os

        if not reset_optimizer and self._optimizer is not None and \
                os.path.exists(path + ".pdopt"):
            self._optimizer.set_state_dict(fload(path + ".pdopt"))

    def parameters(self, *args, **kwargs):
        return self.network.parameters(*args, **kwargs)

    def summary(self, input_size=None, dtype=None):
        from .. import summary as _summary

        return _summary(self.network)
