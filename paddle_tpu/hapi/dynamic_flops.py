"""paddle.flops (hapi/dynamic_flops.py): per-layer FLOPs estimation via
forward hooks over a dry run — conv/linear/norm/pool rules matching the
reference's count_* table; custom_ops extends it per layer type.
"""
import numpy as np

__all__ = ["flops", "static_flops"]


def _count_conv(layer, inputs, output):
    # 2 * Cin/groups * prod(k) * (N * Cout * out_spatial)
    w = layer.weight
    kshape = list(w.shape)
    out = np.prod(output.shape)  # N * Cout * spatial
    groups = int(getattr(layer, "_groups", 1) or 1)
    cin = int(inputs[0].shape[1])
    # weight layout differs between conv ([Cout, Cin/g, k..]) and
    # transpose conv ([Cin, Cout/g, k..]): derive MACs from the INPUT
    # channel count, which is layout-independent
    per_out = 2 * (cin // groups) * int(np.prod(kshape[2:]))
    return int(out * per_out)


def _count_linear(layer, inputs, output):
    w = layer.weight
    return int(2 * np.prod(output.shape) * w.shape[0])


def _count_norm(layer, inputs, output):
    return int(2 * np.prod(inputs[0].shape))


def _count_act(layer, inputs, output):
    return int(np.prod(output.shape))


def _count_pool(layer, inputs, output):
    return int(np.prod(output.shape))


def _default_table():
    from ..nn.layers import conv as C
    from ..nn.layers import common as CM
    from ..nn.layers import norm as N

    table = {}
    for mod, names, fn in [
        (C, ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose",
             "Conv2DTranspose", "Conv3DTranspose"], _count_conv),
        (CM, ["Linear"], _count_linear),
        (N, ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
             "LayerNorm", "GroupNorm", "InstanceNorm1D", "InstanceNorm2D",
             "InstanceNorm3D", "SyncBatchNorm"], _count_norm),
    ]:
        for n in names:
            cls = getattr(mod, n, None)
            if cls is not None:
                table[cls] = fn
    return table


def flops(net, input_size=None, custom_ops=None, print_detail=False,
          inputs=None):
    """Total forward FLOPs of `net` on a zeros dry run (dynamic_flops.py
    contract).  custom_ops: {LayerClass: fn(layer, inputs, output) -> int}.
    """
    from ..core.tensor import to_tensor

    table = _default_table()
    custom = dict(custom_ops or {})

    per_layer = []
    handles = []

    def hook_for(name, layer, fn):
        def hook(lyr, h_inputs, h_output):
            n = int(fn(lyr, h_inputs, h_output))
            per_layer.append((name, type(lyr).__name__, n))

        return hook

    for name, layer in net.named_sublayers(include_self=True):
        # user counters first, by exact type then isinstance, so a
        # custom counter for a Conv2D subclass beats the default rule
        fn = custom.get(type(layer))
        if fn is None:
            for cls, counter in custom.items():
                if isinstance(layer, cls):
                    fn = counter
                    break
        if fn is None:
            for cls, counter in table.items():
                if isinstance(layer, cls):
                    fn = counter
                    break
        if fn is not None:
            handles.append(layer.register_forward_post_hook(
                hook_for(name, layer, fn)))

    try:
        if inputs is not None:
            net(*inputs if isinstance(inputs, (list, tuple)) else (inputs,))
        else:
            if input_size is None:
                raise ValueError(
                    "flops() needs input_size or inputs (FLOPs depend on "
                    "activation shapes, unlike summary())")
            sizes = input_size if isinstance(input_size, list) \
                and isinstance(input_size[0], (list, tuple)) \
                else [input_size]
            args = [to_tensor(np.zeros(
                [1 if d is None or int(d) < 0 else int(d) for d in s],
                np.float32)) for s in sizes]
            net(*args)
    finally:
        for h in handles:
            h.remove()

    total = sum(n for _, _, n in per_layer)
    if print_detail:
        for name, kind, n in per_layer:
            print(f"{name:<40}{kind:<20}{n:>16,}")
        print(f"{'Total FLOPs:':<60}{total:>16,}")
    return total


def static_flops(program, print_detail=False):
    """FLOPs of a static Program: estimated from its matmul/conv ops'
    recorded shapes (the static-graph counterpart)."""
    total = 0
    for block in program.blocks:
        for op in block.ops:
            ins = getattr(op, "in_order", None) or op.input_names()
            outs = getattr(op, "out_order", None) or op.output_names()
            if op.type in ("matmul", "mul", "fc"):
                shapes = [block.var(n).shape for n in ins[:2]] \
                    if len(ins) >= 2 else []
                if len(shapes) == 2 and len(shapes[0]) >= 2 \
                        and len(shapes[1]) >= 2:
                    m = int(np.prod([abs(s) for s in shapes[0][:-1]]))
                    k = abs(shapes[0][-1])
                    n = abs(shapes[1][-1])
                    total += 2 * m * k * n
            elif op.type == "conv2d" and outs and len(ins) >= 2:
                oshape = block.var(outs[0]).shape
                wshape = block.var(ins[1]).shape
                total += int(2 * np.prod([abs(s) for s in oshape])
                             * np.prod([abs(s) for s in wshape[1:]]))
    if print_detail:
        print(f"Total FLOPs: {total:,}")
    return total
