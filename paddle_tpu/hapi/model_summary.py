"""paddle.summary (hapi/model_summary.py): per-layer table of output
shapes and parameter counts, collected with forward hooks on a dry-run
forward pass.
"""
import numpy as np

__all__ = ["summary", "summary_string"]


def _num_params(layer):
    """(total, trainable) over the parameters registered directly on
    this layer (leaves only — sublayers report their own rows)."""
    return sum(int(np.prod(p.shape)) for p in layer._parameters.values()), \
        sum(int(np.prod(p.shape)) for p in layer._parameters.values()
            if not p.stop_gradient)


def _shape_of(out):
    if hasattr(out, "shape"):
        return list(out.shape)
    if isinstance(out, (list, tuple)):
        return [_shape_of(o) for o in out if o is not None][:2]
    return []


def summary_string(net, input_size=None, dtypes=None, input=None):
    """(text, stats) form of summary()."""
    from ..core.tensor import to_tensor

    rows = []
    handles = []

    def hook_for(name, layer):
        def hook(lyr, inputs, outputs):
            total, trainable = _num_params(lyr)
            rows.append((f"{type(lyr).__name__}-{len(rows) + 1}",
                         name, _shape_of(outputs), total))

        return hook

    for name, layer in net.named_sublayers(include_self=True):
        if not layer._sub_layers:  # leaves only, like the reference table
            handles.append(layer.register_forward_post_hook(
                hook_for(name, layer)))

    try:
        if input is not None:
            net(*input if isinstance(input, (list, tuple)) else (input,))
        elif input_size is not None:
            sizes = input_size if isinstance(input_size, list) \
                and isinstance(input_size[0], (list, tuple)) \
                else [input_size]
            dts = dtypes or ["float32"] * len(sizes)
            args = [to_tensor(np.zeros(
                [1 if d is None or int(d) < 0 else int(d) for d in s],
                np.dtype(dt) if dt != "float32" else np.float32))
                for s, dt in zip(sizes, dts)]
            net(*args)
    finally:
        for h in handles:
            h.remove()

    total = sum(int(np.prod(p.shape)) for p in net.parameters())
    trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                    if not p.stop_gradient)

    w_layer = max([len(r[0]) for r in rows] + [12]) + 2
    w_shape = max([len(str(r[2])) for r in rows] + [12]) + 2
    lines = ["-" * (w_layer + w_shape + 14),
             f"{'Layer (type)':<{w_layer}}{'Output Shape':<{w_shape}}"
             f"{'Param #':>12}",
             "=" * (w_layer + w_shape + 14)]
    for tag, _, shape, n in rows:
        lines.append(f"{tag:<{w_layer}}{str(shape):<{w_shape}}{n:>12,}")
    lines += ["=" * (w_layer + w_shape + 14),
              f"Total params: {total:,}",
              f"Trainable params: {trainable:,}",
              f"Non-trainable params: {total - trainable:,}",
              "-" * (w_layer + w_shape + 14)]
    stats = {"total_params": total, "trainable_params": trainable}
    return "\n".join(lines), stats


def summary(net, input_size=None, dtypes=None, input=None):
    """Print the per-layer table; returns {total_params, trainable_params}
    (model_summary.py:28 contract).  Works with either an input_size
    (zeros dry run) or concrete `input` tensors; with neither, prints
    parameter totals only."""
    if input_size is None and input is None:
        total = sum(int(np.prod(p.shape)) for p in net.parameters())
        trainable = sum(int(np.prod(p.shape)) for p in net.parameters()
                        if not p.stop_gradient)
        print(f"Total params: {total:,}")
        print(f"Trainable params: {trainable:,}")
        return {"total_params": total, "trainable_params": trainable}
    text, stats = summary_string(net, input_size, dtypes, input)
    print(text)
    return stats
