"""paddle.inference parity — the standalone inference engine.

Reference: paddle/fluid/inference/api/analysis_predictor.h:82
(`AnalysisPredictor`), paddle_inference_api.h (`Config`/`Predictor`/
`PredictorPool`), api/details zero-copy tensors.

TPU-native design: instead of a ProgramDesc + IR-pass pipeline + NaiveExecutor,
the deployable artifact is a `jax.export` serialized StableHLO module with the
weights folded in as constants (the analysis passes' constant-folding /
fusion role is played by XLA itself at AOT-compile time).  `Predictor.run`
executes the deserialized module; input/output handles give the zero-copy
copy_from_cpu / copy_to_cpu API of the reference.

Artifacts are produced by `paddle_tpu.jit.save(..., input_spec=...)` or
`paddle_tpu.static.save_inference_model(...)`, both of which write
`<prefix>.pdexported` next to the params/meta files.
"""
import os
import pickle

import numpy as np

__all__ = [
    "Config", "Predictor", "PredictorPool", "create_predictor",
    "InferTensor", "PlaceType",
]


class PlaceType:
    """Ref: paddle_inference_api PaddlePlace."""
    kUNK = -1
    kCPU = 0
    kTPU = 4


class Config:
    """AnalysisConfig parity (inference/api/paddle_analysis_config.h).

    REAL knobs: device selection (disable_gpu pins execution to a host
    CPU device — exports carry cpu+tpu platforms) and enable_profile
    (RecordEvent spans around Predictor.run).  Knobs with no TPU meaning
    (MKLDNN, TensorRT, GPU memory pool) are accepted and recorded so
    reference configs run unchanged; XLA owns fusion and memory planning.
    """

    def __init__(self, model_dir=None, params_file=None):
        self._prefix = model_dir[:-len(".pdmodel")] if \
            (model_dir or "").endswith(".pdmodel") else model_dir
        # two-file form: an independent params path (reference allows the
        # params file to live anywhere)
        self._params_path = params_file
        self._device = "tpu"
        self._ir_optim = True
        self._memory_optim = True
        self._cpu_threads = 1
        self._settings = {}

    # --- model location ---
    def set_model(self, model_path, params_path=None):
        self._prefix = model_path[:-len(".pdmodel")] if \
            model_path.endswith(".pdmodel") else model_path
        # single-arg form means the conventional <prefix>.pdiparams pair;
        # never keep a previous model's params path
        self._params_path = params_path

    def model_dir(self):
        return self._prefix

    def set_prog_file(self, path):
        self._prefix = path[:-len(".pdmodel")] if path.endswith(".pdmodel") \
            else path

    def prog_file(self):
        return (self._prefix or "") + ".pdmodel"

    def params_file(self):
        return self._params_path or (self._prefix or "") + ".pdiparams"

    # --- device selection ---
    def enable_tpu(self):
        self._device = "tpu"

    def disable_gpu(self):
        self._device = "cpu"

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        # GPU request maps to the accelerator we actually have
        self._device = "tpu"

    def use_gpu(self):
        return False

    def set_cpu_math_library_num_threads(self, n):
        self._cpu_threads = int(n)

    # --- profiling (EnableProfile, paddle_analysis_config.h) ---
    def enable_profile(self):
        """REAL effect: Predictor.run wraps each execution in a
        RecordEvent span ('inference::run'), so paddle.profiler's summary
        table and chrome trace cover serving calls."""
        self._settings["profile"] = True

    def profile_enabled(self):
        return bool(self._settings.get("profile"))

    # --- optimization toggles (XLA decides; recorded for parity) ---
    def switch_ir_optim(self, flag=True):
        self._ir_optim = bool(flag)

    def ir_optim(self):
        return self._ir_optim

    def enable_memory_optim(self, flag=True):
        self._memory_optim = bool(flag)

    def switch_use_feed_fetch_ops(self, flag=False):
        self._settings["use_feed_fetch_ops"] = bool(flag)

    def switch_specify_input_names(self, flag=True):
        self._settings["specify_input_names"] = bool(flag)

    def enable_mkldnn(self):
        self._settings["mkldnn"] = True

    def enable_tensorrt_engine(self, **kwargs):
        self._settings["tensorrt"] = kwargs

    def summary(self):
        return {
            "model": self._prefix, "device": self._device,
            "ir_optim": self._ir_optim, **self._settings,
        }


def _fix_model_path(config):
    if isinstance(config, str):
        c = Config(config)
        return c
    return config


class InferTensor:
    """Zero-copy input/output handle.

    Ref: paddle_infer::Tensor (inference/api/paddle_tensor.h) —
    copy_from_cpu / copy_to_cpu / reshape / shape / type.
    """

    def __init__(self, name, aval=None):
        self.name = name
        self._aval = aval
        self._value = None

    def reshape(self, shape):
        # API parity only: the exported module's shapes are fixed at export
        # time; actual validation happens against the aval in Predictor.run
        pass

    def copy_from_cpu(self, arr):
        self._value = np.ascontiguousarray(arr)

    def share_external_data(self, arr):
        self._value = arr

    def copy_to_cpu(self):
        return np.asarray(self._value)

    def shape(self):
        if self._value is not None:
            return list(np.asarray(self._value).shape)
        return list(self._aval.shape) if self._aval is not None else []

    def type(self):
        if self._aval is not None:
            return np.dtype(self._aval.dtype).name
        return None


class Predictor:
    """AnalysisPredictor parity: load artifact → AOT module → run.

    Loading order:
      1. `<prefix>.pdexported` — jax.export bytes (weights inlined): the
         deployable path.
      2. `<prefix>.pdiparams` + a Layer class via `layer_cls=` — rebuild
         and jit the forward (development convenience).
    """

    def __init__(self, config, layer_cls=None, layer_args=None):
        config = _fix_model_path(config)
        self._config = config
        prefix = config.model_dir()
        self._exported = None
        self._layer = None
        meta = {}
        if prefix and os.path.exists(prefix + ".pdmodel"):
            with open(prefix + ".pdmodel", "rb") as f:
                meta = pickle.load(f)
        self._meta = meta
        if prefix and os.path.exists(prefix + ".pdexported"):
            from jax import export as jax_export

            with open(prefix + ".pdexported", "rb") as f:
                self._exported = jax_export.deserialize(bytearray(f.read()))
            self._in_names = meta.get(
                "feed_names",
                [f"x{i}" for i in range(len(self._exported.in_avals))])
            self._out_names = meta.get(
                "fetch_names",
                [f"out{i}" for i in range(len(self._exported.out_avals))])
            self._in_avals = list(self._exported.in_avals)
        elif layer_cls is not None:
            import jax

            from ..core.tensor import _wrap_data
            from ..core import autograd

            layer = layer_cls(*(layer_args or ()))
            with open(config.params_file(), "rb") as f:
                state = pickle.load(f)
            from ..quant.qat import dequantize_state

            # a weight-only quantized artifact stores integer weights:
            # every .pdiparams consumer must apply the dequant factors
            state = dequantize_state(state, meta.get("weight_quant"))
            layer.set_state_dict(state)
            layer.eval()
            self._layer = layer
            params = layer.param_arrays()

            def fwd(*xs):
                with autograd.no_grad():
                    out = layer.functional_call(params,
                                                *[_wrap_data(x) for x in xs])
                if isinstance(out, (list, tuple)):
                    return tuple(o._data for o in out)
                return (out._data,)

            self._jitted = jax.jit(fwd)
            n_in = len(meta.get("input_shapes", [1]))
            self._in_names = meta.get("feed_names",
                                      [f"x{i}" for i in range(n_in)])
            self._out_names = meta.get("fetch_names", ["out0"])
            self._in_avals = [None] * len(self._in_names)
        else:
            raise RuntimeError(
                f"no loadable inference artifact at prefix {prefix!r}: "
                f"need {prefix}.pdexported (from jit.save / "
                f"save_inference_model) or a layer_cls to rebuild from params")
        self._inputs = {n: InferTensor(n, a)
                        for n, a in zip(self._in_names, self._in_avals)}
        self._outputs = {n: InferTensor(n) for n in self._out_names}

    # --- reference API ---
    def get_input_names(self):
        return list(self._in_names)

    def get_output_names(self):
        return list(self._out_names)

    def get_input_handle(self, name):
        return self._inputs[name]

    def get_output_handle(self, name):
        return self._outputs[name]

    def run(self, inputs=None):
        """Execute.  With `inputs` (list of arrays) returns outputs directly;
        otherwise uses the copy_from_cpu'd input handles (reference calling
        convention) and fills the output handles."""
        if inputs is not None:
            args = [np.asarray(a) for a in inputs]
        else:
            args = []
            for n in self._in_names:
                v = self._inputs[n]._value
                if v is None:
                    raise RuntimeError(
                        f"input {n!r} not set; call "
                        f"get_input_handle({n!r}).copy_from_cpu(...)")
                args.append(np.asarray(v))
        if self._exported is not None:
            for n, aval, a in zip(self._in_names, self._in_avals, args):
                if aval is None:
                    continue
                want = aval.shape
                got = a.shape
                ok = len(want) == len(got) and all(
                    not isinstance(w, int) or w == g
                    for w, g in zip(want, got))
                if not ok:
                    raise ValueError(
                        f"input {n!r} has shape {got}, but the exported "
                        f"module expects {want} (symbolic dims accept any "
                        f"size; re-save with -1 dims in the InputSpec for "
                        f"batch polymorphism)")
            outs = self._run_module(self._exported.call, args)
        else:
            outs = self._run_module(self._jitted, args)
        if not isinstance(outs, (list, tuple)):
            outs = (outs,)
        res = [np.asarray(o) for o in outs]
        for n, o in zip(self._out_names, res):
            self._outputs[n]._value = o
        return res if inputs is not None else True

    def _run_module(self, fn, args):
        """Execute honoring the REAL config knobs: disable_gpu() pins the
        computation to a host CPU device (exports carry cpu+tpu
        platforms); enable_profile() wraps the call in a RecordEvent span
        for the profiler's summary/chrome-trace output."""
        import contextlib

        import jax

        ctx = contextlib.nullcontext()
        if self._config._device == "cpu":
            try:
                cpus = jax.devices("cpu") if jax.default_backend() != "cpu" \
                    else jax.devices()
            except RuntimeError:
                cpus = []  # cpu platform unavailable (pinned platform list)
            if cpus:
                ctx = jax.default_device(cpus[0])
        if self._config.profile_enabled():
            from ..profiler import RecordEvent

            with ctx, RecordEvent("inference::run"):
                return fn(*args)
        with ctx:
            return fn(*args)

    def clone(self):
        p = Predictor.__new__(Predictor)
        p.__dict__.update(self.__dict__)
        p._inputs = {n: InferTensor(n, a)
                     for n, a in zip(self._in_names, self._in_avals)}
        p._outputs = {n: InferTensor(n) for n in self._out_names}
        return p


def create_predictor(config, **kwargs):
    """Ref: CreatePaddlePredictor analysis_predictor.h:62."""
    return Predictor(config, **kwargs)


class PredictorPool:
    """Pool of cloned predictors (api/paddle_inference_api.h).  As in the
    reference, each slot is owned by one caller thread: retrieve a distinct
    index per thread; the predictors share the loaded module but have
    independent input/output handles."""

    def __init__(self, config, size=1):
        base = Predictor(config)
        self._preds = [base] + [base.clone() for _ in range(size - 1)]

    def retrieve(self, idx):
        return self._preds[idx]

    def size(self):
        return len(self._preds)


class DataType:
    """analysis_config data types (inference/api/paddle_api.h DataType)."""
    FLOAT32 = "float32"
    INT64 = "int64"
    INT32 = "int32"
    UINT8 = "uint8"
    INT8 = "int8"
    FLOAT16 = "float16"
    BFLOAT16 = "bfloat16"


class PrecisionType:
    """inference precision modes (paddle_api.h Precision)."""
    Float32 = "float32"
    Half = "float16"
    Bfloat16 = "bfloat16"
    Int8 = "int8"


_DTYPE_BYTES = {"float32": 4, "int64": 8, "int32": 4, "uint8": 1,
                "int8": 1, "float16": 2, "bfloat16": 2, "float64": 8}


def get_num_bytes_of_data_type(dtype):
    key = getattr(dtype, "lower", lambda: dtype)()
    if key not in _DTYPE_BYTES:
        raise ValueError(f"unknown data type {dtype!r}")
    return _DTYPE_BYTES[key]


def get_version():
    import paddle_tpu

    return f"paddle_tpu inference {getattr(paddle_tpu, '__version__', '0')}"


# handle type exposed by Predictor.get_input_handle (the handles ARE the
# inference Tensors in the reference C API)
Tensor = InferTensor
