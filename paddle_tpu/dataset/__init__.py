"""paddle.dataset legacy namespace (python/paddle/dataset/): reader-creator
API over the modern dataset classes.  Deprecated in the reference in favor
of paddle.io.DataLoader (each reference function carries a @deprecated to
the paddle.vision/text.datasets class); kept for API parity.  Zero-egress:
the underlying datasets fall back to deterministic synthetic data when the
real files are absent.
"""
from . import common  # noqa: F401
from . import mnist  # noqa: F401
from . import cifar  # noqa: F401
from . import uci_housing  # noqa: F401
from . import imdb  # noqa: F401
from . import imikolov  # noqa: F401
from . import movielens  # noqa: F401
from . import conll05  # noqa: F401
from . import flowers  # noqa: F401
from . import voc2012  # noqa: F401
from . import wmt14  # noqa: F401
from . import wmt16  # noqa: F401
