"""dataset.imikolov: n-gram reader creators over
text.datasets.Imikolov."""
from ..text.datasets import Imikolov


def build_dict(min_word_freq=50):
    return {f"w{i}": i for i in range(2074)}


def _creator(mode, n):
    def reader():
        for sample in Imikolov(mode=mode, window_size=n):
            yield tuple(sample)
    return reader


def train(word_idx=None, n=5, data_type="NGRAM"):
    return _creator("train", n)


def test(word_idx=None, n=5, data_type="NGRAM"):
    return _creator("test", n)
