"""dataset.common (dataset/common.py): cache-dir + download helpers."""
import hashlib
import os

DATA_HOME = os.path.expanduser("~/.cache/paddle_tpu/dataset")


def md5file(fname):
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(4096), b""):
            h.update(chunk)
    return h.hexdigest()


def download(url, module_name, md5sum, save_name=None):
    """Return the cached path if the file exists; this build has no network
    egress, so a missing file raises with the synthetic-fallback pointer."""
    dirname = os.path.join(DATA_HOME, module_name)
    filename = os.path.join(
        dirname, save_name or url.split("/")[-1])
    if os.path.exists(filename):
        return filename
    raise RuntimeError(
        f"{filename} not present and downloads are disabled (zero egress); "
        "use the paddle_tpu.vision/text dataset classes, which fall back "
        "to synthetic data")


def split(reader, line_count, suffix="%05d.pickle", dumper=None):
    raise NotImplementedError("cluster dataset splitting is out of scope")
