"""dataset.wmt14: translation reader creators over
text.datasets.WMT14."""
from ..text.datasets import WMT14


def _creator(mode):
    def reader():
        for sample in WMT14(mode=mode):
            yield tuple(sample)
    return reader


def train(dict_size=30000):
    return _creator("train")


def test(dict_size=30000):
    return _creator("test")
