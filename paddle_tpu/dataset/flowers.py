"""dataset.flowers: reader creators over vision.datasets.Flowers."""
from ..vision.datasets import Flowers


def _creator(mode):
    def reader():
        for img, lbl in Flowers(mode=mode):
            yield img.reshape(-1), int(lbl[0])
    return reader


def train(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _creator("train")


def test(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _creator("test")


def valid(mapper=None, buffered_size=1024, use_xmap=True, cycle=False):
    return _creator("valid")
