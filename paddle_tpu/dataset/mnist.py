"""dataset.mnist (dataset/mnist.py): reader creators over
vision.datasets.MNIST (the reference deprecates this module to that
class).  Samples: (flat float32[784] in [-1,1], int label)."""
from ..vision.datasets import MNIST


def _creator(mode):
    def reader():
        ds = MNIST(mode=mode)
        for img, lbl in ds:
            yield img.reshape(-1) * 2.0 - 1.0, int(lbl[0])
    return reader


def train():
    return _creator("train")


def test():
    return _creator("test")
