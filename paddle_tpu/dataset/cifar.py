"""dataset.cifar: reader creators over vision.datasets.Cifar10/100.
Samples: (flat float32[3072] in [0,1], int label)."""
from ..vision.datasets import Cifar10, Cifar100


def _creator(cls, mode):
    def reader():
        for img, lbl in cls(mode=mode):
            yield img.reshape(-1), int(lbl[0])
    return reader


def train10(cycle=False):
    return _creator(Cifar10, "train")


def test10(cycle=False):
    return _creator(Cifar10, "test")


def train100():
    return _creator(Cifar100, "train")


def test100():
    return _creator(Cifar100, "test")
