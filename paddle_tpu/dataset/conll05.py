"""dataset.conll05: SRL reader creators over text.datasets.Conll05st."""
from ..text.datasets import Conll05st

_WORD_DICT_LEN = 44068
_VERB_DICT_LEN = 3162
_LABEL_DICT_LEN = 67


def get_dict():
    """(word_dict, verb_dict, label_dict) — synthetic id-keyed vocabs
    matching the reference dict sizes (conll05.py word/verb/label)."""
    return ({f"w{i}": i for i in range(_WORD_DICT_LEN)},
            {f"v{i}": i for i in range(_VERB_DICT_LEN)},
            {f"l{i}": i for i in range(_LABEL_DICT_LEN)})


def get_embedding():
    raise NotImplementedError(
        "pretrained emb download needs egress; initialize embeddings "
        "with paddle_tpu.nn.initializer instead")


def test():
    def reader():
        for sample in Conll05st():
            yield tuple(sample)
    return reader
