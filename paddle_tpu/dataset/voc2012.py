"""dataset.voc2012: segmentation reader creators over
vision.datasets.VOC2012."""
from ..vision.datasets import VOC2012


def _creator(mode):
    def reader():
        for img, lbl in VOC2012(mode=mode):
            yield img, lbl
    return reader


def train():
    return _creator("train")


def test():
    return _creator("test")


def val():
    return _creator("valid")
