"""dataset.movielens: reader creators over text.datasets.Movielens
(sample = (user id, movie id, rating))."""
from ..text.datasets import Movielens


def _creator():
    def reader():
        for sample in Movielens():
            yield tuple(sample)
    return reader


def train():
    return _creator()


def test():
    return _creator()
