"""dataset.wmt16: translation reader creators over
text.datasets.WMT16."""
from ..text.datasets import WMT16


def _creator(mode):
    def reader():
        for sample in WMT16(mode=mode):
            yield tuple(sample)
    return reader


def train(src_dict_size=30000, trg_dict_size=30000, src_lang="en"):
    return _creator("train")


def test(src_dict_size=30000, trg_dict_size=30000, src_lang="en"):
    return _creator("test")
