"""dataset.uci_housing: reader creators over text.datasets.UCIHousing.
Samples: (float32[13] features, float32[1] price)."""
from ..text.datasets import UCIHousing


def _creator(mode):
    def reader():
        for feat, price in UCIHousing(mode=mode):
            yield feat, price
    return reader


def train():
    return _creator("train")


def test():
    return _creator("test")
