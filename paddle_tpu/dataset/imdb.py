"""dataset.imdb: reader creators over text.datasets.Imdb.
Samples: ([word ids], 0/1 label)."""
from ..text.datasets import Imdb


def word_dict():
    return Imdb(mode="train").word_idx


def _creator(mode):
    def reader():
        for ids, lbl in Imdb(mode=mode):
            yield list(ids), int(lbl)
    return reader


def train(word_idx=None):
    return _creator("train")


def test(word_idx=None):
    return _creator("test")
