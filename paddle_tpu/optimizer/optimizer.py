"""Optimizer zoo.

Reference parity: python/paddle/optimizer/ (Adam/AdamW/SGD/Momentum/Lamb/
RMSProp/Adagrad/Adadelta/Adamax) backed by operators/optimizers/ kernels
(sgd_op, momentum_op, adam_op, lamb_op...).  TPU-native: each optimizer exposes
a pure functional `update(param, grad, state) -> (new_param, new_state)` rule;
eager `step()` applies it per-parameter, and the jit path (`fused_step` /
jit.compile_train_step) folds all updates into the one XLA computation so the
whole training step is a single device program.
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import autograd
from .lr import LRScheduler


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None):
        self._lr = learning_rate
        if parameters is not None:
            parameters = list(parameters)
        self._parameter_list = parameters
        self._param_groups = parameters
        self.regularization = weight_decay
        self._grad_clip = grad_clip
        # per-parameter state: id(param) -> dict of jax arrays
        self._states = {}
        self._global_step = 0

    # ---- lr ----
    def get_lr(self):
        if isinstance(self._lr, LRScheduler):
            return float(self._lr())
        return float(self._lr)

    def set_lr(self, value):
        self._lr = value

    @property
    def _learning_rate(self):
        return self._lr

    # ---- state access ----
    def _state_for(self, p):
        st = self._states.get(id(p))
        if st is None:
            st = self._init_state(p)
            self._states[id(p)] = st
        return st

    def _init_state(self, p):
        return {}

    def _weight_decay_coeff(self):
        wd = self.regularization
        if wd is None:
            return 0.0
        if hasattr(wd, "_coeff"):
            return float(wd._coeff)  # L2Decay
        return float(wd)

    # ---- the update rule (pure; override in subclasses) ----
    def update(self, param, grad, state, lr):
        raise NotImplementedError

    # Optimizers whose update rule needs WHOLE-parameter statistics
    # (e.g. Lamb/Lars trust ratios over ||w||, ||update||) must not see a
    # row subset — they fall back to a dense update in _sparse_step.
    _sparse_safe = True

    # ---- sparse (SelectedRows) fast path ----
    def _sparse_step(self, p, slices, plr):
        """Row-wise update for an IndexedSlices grad (selected_rows.h /
        lazy-mode sparse optimizer parity): only the touched rows of the
        param and its param-shaped state update; scalar state (e.g. Adam's
        beta pows) advances once per step as usual."""
        if not self._sparse_safe:
            from ..core.tensor import _wrap_data

            dense = _wrap_data(slices.to_dense(), stop_gradient=True)
            self._dense_param_step(p, dense, plr)
            return
        ids, rows = slices.coalesce()
        state = self._state_for(p)
        row_state = {
            k: v[ids] if getattr(v, "ndim", 0) and v.shape == p._data.shape
            else v
            for k, v in state.items()
        }
        cur = p._data[ids]
        g = rows.astype(cur.dtype) if rows.dtype != cur.dtype else rows
        # same per-param weight-decay controls as the dense loop
        wd = self._weight_decay_coeff()
        reg = p.__dict__.get("regularizer")
        if reg is not None and hasattr(reg, "_coeff"):
            wd = float(reg._coeff)
        decay_fn = getattr(self, "_apply_decay_param_fun", None)
        if decay_fn is not None and p.name and not decay_fn(p.name):
            wd = 0.0
        self._current_param_name = p.name
        if wd and not self._decoupled_weight_decay:
            g = g + wd * cur
        new_rows, new_row_state = self.update(cur, g, row_state, plr)
        if wd and self._decoupled_weight_decay:
            new_rows = new_rows - plr * wd * cur
        p._data = p._data.at[ids].set(new_rows)
        for k, v in new_row_state.items():
            old = state.get(k)
            if getattr(old, "ndim", 0) and old.shape == p._data.shape:
                state[k] = old.at[ids].set(v)
            else:
                state[k] = v
        self._states[id(p)] = state

    # ---- imperative step ----
    def step(self):
        from ..core.indexed_slices import IndexedSlices

        params = self._parameter_list
        if params is None:
            raise ValueError("Optimizer created without parameters")
        self._global_step += 1
        lr = self.get_lr()
        params_grads = [(p, p.grad) for p in params if p.grad is not None
                        and not p.stop_gradient]
        sparse = [(p, g) for p, g in params_grads
                  if isinstance(g, IndexedSlices)]
        params_grads = [(p, g) for p, g in params_grads
                        if not isinstance(g, IndexedSlices)]
        if self._grad_clip is not None and sparse:
            # global-norm clipping needs every grad: densify (documented
            # trade-off; the reference merges SelectedRows the same way)
            from ..core.tensor import _wrap_data

            params_grads += [(p, _wrap_data(g.to_dense(),
                                            stop_gradient=True))
                             for p, g in sparse]
            sparse = []
        for p, g in sparse:
            plr = lr * p.__dict__.get("optimize_attr", {}).get(
                "learning_rate", 1.0)
            self._sparse_step(p, g, plr)
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        for p, g in params_grads:
            plr = lr * p.__dict__.get("optimize_attr", {}).get(
                "learning_rate", 1.0)
            self._dense_param_step(p, g, plr)

    def _dense_param_step(self, p, g, plr):
        """One parameter's dense update (the body of step()'s loop)."""
        gv = g._data.astype(p._data.dtype) \
            if g._data.dtype != p._data.dtype else g._data
        wd = self._weight_decay_coeff()
        reg = p.__dict__.get("regularizer")
        if reg is not None and hasattr(reg, "_coeff"):
            wd = float(reg._coeff)
        decay_fn = getattr(self, "_apply_decay_param_fun", None)
        if decay_fn is not None and p.name and not decay_fn(p.name):
            wd = 0.0
        if wd and self._decoupled_weight_decay is False:
            gv = gv + wd * p._data
        state = self._state_for(p)
        self._current_param_name = p.name
        new_p, new_state = self.update(p._data, gv, state, plr)
        if wd and self._decoupled_weight_decay:
            new_p = new_p - plr * wd * p._data
        p._data = new_p
        self._states[id(p)] = new_state

    _decoupled_weight_decay = False

    def minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        from ..static.program import Variable as StaticVar

        if isinstance(loss, StaticVar):
            from ..static.optimizer_bridge import static_minimize

            return static_minimize(self, loss, startup_program, parameters,
                                   no_grad_set)
        loss.backward()
        self.step()
        return None, None

    def clear_grad(self):
        if self._parameter_list:
            for p in self._parameter_list:
                p.clear_grad()

    clear_gradients = clear_grad

    # ---- functional/jit path ----
    def fused_update(self, params, grads, states, lr):
        """Pure pytree update: dicts name->array.  Used by jit-compiled steps."""
        new_params, new_states = {}, {}
        for n, p in params.items():
            g = grads.get(n)
            if g is None:
                new_params[n] = p
                new_states[n] = states.get(n, {})
                continue
            wd = self._weight_decay_coeff()
            if wd and not self._decoupled_weight_decay:
                g = g + wd * p
            np_, ns = self.update(p, g, states.get(n, {}), lr)
            if wd and self._decoupled_weight_decay:
                np_ = np_ - lr * wd * p
            new_params[n] = np_
            new_states[n] = ns
        return new_params, new_states

    def init_fused_states(self, params):
        return {
            n: self._init_state_arrays(p) for n, p in params.items()
        }

    def _init_state_arrays(self, p_arr):
        from ..core.tensor import _wrap_data

        fake = _wrap_data(p_arr)
        return self._init_state(fake)

    # ---- checkpoint ----
    def state_dict(self):
        out = {"global_step": self._global_step}
        if isinstance(self._lr, LRScheduler):
            out["LR_Scheduler"] = self._lr.state_dict()
        if self._parameter_list:
            for i, p in enumerate(self._parameter_list):
                st = self._states.get(id(p))
                if st:
                    for k, v in st.items():
                        t = Tensor(np.asarray(v))
                        out[f"{p.name}_{k}"] = t
                        # positional alias (same object — pickle memoization
                        # keeps the checkpoint single-copy): auto-generated
                        # param names don't survive a process restart, the
                        # parameter order does
                        out[f"@pos{i}_{k}"] = t
        return out

    def set_state_dict(self, state):
        gs = state.get("global_step", 0)
        self._global_step = int(np.asarray(
            gs.numpy() if isinstance(gs, Tensor) else gs))
        if isinstance(self._lr, LRScheduler) and "LR_Scheduler" in state:
            self._lr.set_state_dict(state["LR_Scheduler"])
        if self._parameter_list:
            for i, p in enumerate(self._parameter_list):
                st = self._state_for(p)
                for k in list(st.keys()):
                    # exact name key first (the reference's name-keyed
                    # checkpoint format); the positional alias is only a
                    # fallback for auto-generated names that didn't survive
                    # a process restart, and must shape-match the param
                    candidates = [f"{p.name}_{k}", f"@pos{i}_{k}"]
                    want = getattr(st[k], "shape", None)
                    for key in candidates:
                        if key not in state:
                            continue
                        v = state[key]
                        arr = jnp.asarray(
                            v.numpy() if isinstance(v, Tensor) else v
                        )
                        # shape-validate BOTH key kinds: a name collision
                        # (same auto-name, different param) is as wrong as
                        # a stale positional entry
                        if (arr.ndim and want is not None
                                and tuple(arr.shape) != tuple(want)):
                            continue
                        st[k] = arr
                        break


class SGD(Optimizer):
    """Ref: operators/optimizers/sgd_op.cc."""

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)

    def update(self, param, grad, state, lr):
        return param - lr * grad, state


class Momentum(Optimizer):
    """Ref: operators/optimizers/momentum_op.h."""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def _init_state(self, p):
        return {"velocity": jnp.zeros(p._data.shape, p._data.dtype)}

    def update(self, param, grad, state, lr):
        v = state["velocity"] * self._momentum + grad
        if self._use_nesterov:
            new_p = param - lr * (grad + self._momentum * v)
        else:
            new_p = param - lr * v
        return new_p, {"velocity": v}


class Adam(Optimizer):
    """Ref: operators/optimizers/adam_op.h (with bias correction via beta pows)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None,
                 lazy_mode=False, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _init_state(self, p):
        z = jnp.zeros(p._data.shape, jnp.float32)
        return {
            "moment1": z,
            "moment2": z,
            "beta1_pow": jnp.ones((), jnp.float32),
            "beta2_pow": jnp.ones((), jnp.float32),
        }

    def update(self, param, grad, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        g32 = grad.astype(jnp.float32)
        m = b1 * state["moment1"] + (1 - b1) * g32
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g32)
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        mhat = m / (1 - b1p)
        vhat = v / (1 - b2p)
        step = lr * mhat / (jnp.sqrt(vhat) + eps)
        new_p = param - step.astype(param.dtype)
        return new_p, {
            "moment1": m, "moment2": v, "beta1_pow": b1p, "beta2_pow": b2p,
        }


class AdamW(Adam):
    """Ref: operators/optimizers/adamw — decoupled weight decay."""

    _decoupled_weight_decay = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, name=name)
        self._apply_decay_param_fun = apply_decay_param_fun


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_state(self, p):
        return {"moment": jnp.full(p._data.shape, self._init_acc, jnp.float32)}

    def update(self, param, grad, state, lr):
        g32 = grad.astype(jnp.float32)
        acc = state["moment"] + jnp.square(g32)
        new_p = param - (lr * g32 / (jnp.sqrt(acc) + self._epsilon)).astype(param.dtype)
        return new_p, {"moment": acc}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _init_state(self, p):
        z = jnp.zeros(p._data.shape, jnp.float32)
        st = {"mean_square": z, "momentum": z}
        if self._centered:
            st["mean_grad"] = z
        return st

    def update(self, param, grad, state, lr):
        g32 = grad.astype(jnp.float32)
        ms = self._rho * state["mean_square"] + (1 - self._rho) * jnp.square(g32)
        if self._centered:
            mg = self._rho * state["mean_grad"] + (1 - self._rho) * g32
            denom = jnp.sqrt(ms - jnp.square(mg) + self._epsilon)
        else:
            mg = None
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * state["momentum"] + lr * g32 / denom
        new_p = param - mom.astype(param.dtype)
        st = {"mean_square": ms, "momentum": mom}
        if mg is not None:
            st["mean_grad"] = mg
        return new_p, st


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._epsilon = epsilon
        self._rho = rho

    def _init_state(self, p):
        z = jnp.zeros(p._data.shape, jnp.float32)
        return {"avg_squared_grad": z, "avg_squared_update": z}

    def update(self, param, grad, state, lr):
        g32 = grad.astype(jnp.float32)
        rho, eps = self._rho, self._epsilon
        asg = rho * state["avg_squared_grad"] + (1 - rho) * jnp.square(g32)
        upd = (
            jnp.sqrt(state["avg_squared_update"] + eps) / jnp.sqrt(asg + eps) * g32
        )
        asu = rho * state["avg_squared_update"] + (1 - rho) * jnp.square(upd)
        return param - (lr * upd).astype(param.dtype), {
            "avg_squared_grad": asg, "avg_squared_update": asu,
        }


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, name=None, **kw):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def _init_state(self, p):
        z = jnp.zeros(p._data.shape, jnp.float32)
        return {"moment": z, "inf_norm": z, "beta1_pow": jnp.ones((), jnp.float32)}

    def update(self, param, grad, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        g32 = grad.astype(jnp.float32)
        m = b1 * state["moment"] + (1 - b1) * g32
        u = jnp.maximum(b2 * state["inf_norm"], jnp.abs(g32))
        b1p = state["beta1_pow"] * b1
        new_p = param - (lr / (1 - b1p) * m / (u + eps)).astype(param.dtype)
        return new_p, {"moment": m, "inf_norm": u, "beta1_pow": b1p}


class Lamb(Optimizer):
    """Ref: operators/optimizers/lamb_op.h — layerwise adaptive Adam."""

    # trust ratio needs whole-parameter norms: sparse grads densify
    _sparse_safe = False

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None, **kw):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def _init_state(self, p):
        z = jnp.zeros(p._data.shape, jnp.float32)
        return {
            "moment1": z, "moment2": z,
            "beta1_pow": jnp.ones((), jnp.float32),
            "beta2_pow": jnp.ones((), jnp.float32),
        }

    def update(self, param, grad, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        wd = self._lamb_wd
        if self._exclude_fn is not None and self._exclude_fn(
            getattr(self, "_current_param_name", None) or ""
        ):
            wd = 0.0
        g32 = grad.astype(jnp.float32)
        m = b1 * state["moment1"] + (1 - b1) * g32
        v = b2 * state["moment2"] + (1 - b2) * jnp.square(g32)
        b1p = state["beta1_pow"] * b1
        b2p = state["beta2_pow"] * b2
        mhat = m / (1 - b1p)
        vhat = v / (1 - b2p)
        r = mhat / (jnp.sqrt(vhat) + eps) + wd * param.astype(jnp.float32)
        w_norm = jnp.linalg.norm(param.astype(jnp.float32))
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        new_p = param - (lr * trust * r).astype(param.dtype)
        return new_p, {
            "moment1": m, "moment2": v, "beta1_pow": b1p, "beta2_pow": b2p,
        }


class L1Decay:
    def __init__(self, coeff=0.0):
        self._coeff = coeff


class L2Decay:
    def __init__(self, coeff=0.0):
        self._coeff = coeff
