"""paddle.autograd namespace: PyLayer + backward.

Reference parity: python/paddle/autograd/ (PyLayer py_layer.py, backward) over
imperative/py_layer_fwd.h.  PyLayer's custom backward is recorded on the same
tape as ordinary ops.
"""
from .core.autograd import backward as _backward_impl, grad, no_grad  # noqa: F401
from .core.autograd import TapeNode, is_grad_enabled
from .core.tensor import Tensor, _wrap_data


def backward(tensors, grad_tensors=None, retain_graph=False):
    _backward_impl(tensors, grad_tensors, retain_graph)


class PyLayerContext:
    def __init__(self):
        self._saved = []
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = list(tensors)

    def saved_tensor(self):
        return self._saved


class PyLayerMeta(type):
    def __init__(cls, name, bases, attrs):
        super().__init__(name, bases, attrs)


class PyLayer(metaclass=PyLayerMeta):
    """User-defined forward/backward (ref: paddle/autograd/py_layer.py).

    class Exp(PyLayer):
        @staticmethod
        def forward(ctx, x):
            y = paddle.exp(x)
            ctx.save_for_backward(y)
            return y

        @staticmethod
        def backward(ctx, dy):
            y, = ctx.saved_tensor()
            return dy * y
    """

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        tensor_args = [a for a in args if isinstance(a, Tensor)]
        needs_grad = is_grad_enabled() and any(
            not t.stop_gradient for t in tensor_args
        )
        with no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)
        multi = isinstance(outputs, (list, tuple))
        out_list = list(outputs) if multi else [outputs]

        if not needs_grad:
            return outputs

        diff_inputs = [t for t in tensor_args if not t.stop_gradient]

        def vjp_fn(cots):
            cot_list = list(cots) if len(out_list) > 1 else [cots]
            grads = cls.backward(
                ctx, *[_wrap_data(c, stop_gradient=True) for c in cot_list]
            )
            if not isinstance(grads, (list, tuple)):
                grads = (grads,)
            out = []
            gi = 0
            for t in tensor_args:
                if t.stop_gradient:
                    continue
                g = grads[gi] if gi < len(grads) else None
                gi += 1
                out.append(g._data if isinstance(g, Tensor) else g)
            return tuple(out)

        node = TapeNode(
            f"pylayer_{cls.__name__}", vjp_fn, diff_inputs, len(out_list),
            [tuple(o.shape) for o in out_list],
            [o._data.dtype for o in out_list],
        )
        wrapped = []
        for i, o in enumerate(out_list):
            t = _wrap_data(o._data, stop_gradient=False)
            t._node = node
            t._out_index = i
            wrapped.append(t)
        return tuple(wrapped) if multi else wrapped[0]

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError


# reference layout parity: paddle.autograd.backward_mode.backward
import sys as _sys
import types as _types

backward_mode = _types.ModuleType(__name__ + ".backward_mode")
backward_mode.backward = backward
backward_mode.__doc__ = ("autograd/backward_mode.py parity: module "
                         "namespace for the reverse-mode entry point.")
_sys.modules[backward_mode.__name__] = backward_mode
