"""Monitor gauges: named int/float counters.

Reference parity: platform/monitor.{h,cc} — `StatRegistry` (monitor.h:77)
with the `STAT_ADD`/`STAT_SUB` macros (monitor.h:130), used by the PS stack
for push/pull counters.  TPU-native: a process-local thread-safe registry;
readers snapshot via stats()/get.
"""
import threading


class Stat:
    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name, value=0):
        self.name = name
        self._value = value
        self._lock = threading.Lock()

    def increase(self, v=1):
        with self._lock:
            self._value += v
            return self._value

    def decrease(self, v=1):
        return self.increase(-v)

    def reset(self):
        with self._lock:
            self._value = 0

    def set(self, v):
        """Gauge semantics (queue depth, percentiles): overwrite instead of
        accumulate, atomically under the same lock increase() takes."""
        with self._lock:
            self._value = v
            return self._value

    def get(self):
        with self._lock:
            return self._value


class StatRegistry:
    """monitor.h:77 parity (singleton via instance())."""

    _instance = None
    _instance_lock = threading.Lock()

    def __init__(self):
        self._stats = {}
        self._lock = threading.Lock()

    @classmethod
    def instance(cls):
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def get_stat(self, name):
        with self._lock:
            st = self._stats.get(name)
            if st is None:
                st = Stat(name)
                self._stats[name] = st
            return st

    def stats(self):
        """Snapshot: {name: value}."""
        with self._lock:
            items = list(self._stats.items())
        return {n: s.get() for n, s in items}

    def reset_all(self):
        with self._lock:
            items = list(self._stats.values())
        for s in items:
            s.reset()

    def stats_snapshot(self, prefix=None, path=None):
        """BENCH_*-style JSON export of the registry: a sorted
        ``{"ts": unix_seconds, "stats": {name: value}}`` dict, optionally
        filtered to names starting with `prefix` (e.g. "serving." or
        "generation.") and optionally written to `path` as one JSON
        document.  Returns the dict either way."""
        import json
        import time

        stats = self.stats()
        if prefix:
            stats = {k: v for k, v in stats.items() if k.startswith(prefix)}
        snap = {"ts": round(time.time(), 3),
                "stats": dict(sorted(stats.items()))}
        if path:
            with open(path, "w") as f:
                json.dump(snap, f, indent=2, sort_keys=True)
        return snap


def stat_add(name, value=1):
    """STAT_ADD macro parity (monitor.h:130)."""
    return StatRegistry.instance().get_stat(name).increase(value)


def stat_sub(name, value=1):
    return StatRegistry.instance().get_stat(name).decrease(value)


def stat_get(name):
    return StatRegistry.instance().get_stat(name).get()
