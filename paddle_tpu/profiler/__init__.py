"""Profiler.

Reference parity: platform/profiler.{h,cc} (RecordEvent, EnableProfiler:213,
chrome-trace export) + fluid/profiler.py context manager.  TPU-native: host
spans via RecordEvent (summary table like the reference's) and device traces
via jax.profiler (XLA/TPU timelines, Perfetto/TensorBoard viewable) — the CUPTI
role (SURVEY §5.1) is played by the PJRT profiler.
"""
import contextlib
import threading
import time
from collections import defaultdict

import jax

_state = threading.local()
_records = defaultdict(lambda: [0, 0.0])  # name -> [count, total_seconds]
_enabled = [False]
_trace_dir = [None]


class RecordEvent:
    """RAII span (platform/profiler.h RecordEvent parity)."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._t0 = None
        self._jax_ctx = None

    def __enter__(self):
        self.begin()
        return self

    def begin(self):
        if _enabled[0]:
            self._t0 = time.perf_counter()
            self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
            self._jax_ctx.__enter__()

    def end(self):
        if self._t0 is not None:
            dt = time.perf_counter() - self._t0
            rec = _records[self.name]
            rec[0] += 1
            rec[1] += dt
            if self._jax_ctx is not None:
                self._jax_ctx.__exit__(None, None, None)
            self._t0 = None

    def __exit__(self, *exc):
        self.end()
        return False


def start_profiler(state="All", tracer_option="Default", trace_dir=None):
    _enabled[0] = True
    _records.clear()
    if trace_dir:
        _trace_dir[0] = trace_dir
        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key="total", profile_path=None):
    _enabled[0] = False
    if _trace_dir[0]:
        jax.profiler.stop_trace()
        _trace_dir[0] = None
    return summary(sorted_key)


def summary(sorted_key="total"):
    rows = sorted(
        ((name, cnt, tot, tot / cnt if cnt else 0.0)
         for name, (cnt, tot) in _records.items()),
        key=lambda r: -r[2],
    )
    lines = [f"{'Event':<40}{'Calls':>8}{'Total(ms)':>12}{'Avg(ms)':>12}"]
    for name, cnt, tot, avg in rows:
        lines.append(f"{name:<40}{cnt:>8}{tot * 1e3:>12.3f}{avg * 1e3:>12.3f}")
    report = "\n".join(lines)
    print(report)
    return report


@contextlib.contextmanager
def profiler(state="All", sorted_key="total", profile_path=None, trace_dir=None):
    """fluid/profiler.py:314 context-manager parity."""
    start_profiler(state, trace_dir=trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


class Profiler:
    """paddle.profiler.Profiler-style API over jax.profiler."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 trace_dir=None):
        self.trace_dir = trace_dir

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def start(self):
        start_profiler(trace_dir=self.trace_dir)

    def stop(self):
        stop_profiler()

    def step(self):
        pass

    def summary(self, **kw):
        return summary()


from .monitor import (  # noqa: E402,F401  (monitor.h StatRegistry parity)
    Stat, StatRegistry, stat_add, stat_sub, stat_get,
)
