"""Profiler.

Reference parity: platform/profiler.{h,cc} (RecordEvent, EnableProfiler:213,
sorted per-event summary table + chrome-trace export via profiler.proto) +
fluid/profiler.py context manager.  TPU-native: host spans via RecordEvent
(summary table matches the reference's columns: Calls/Total/Min/Max/Ave/
Ratio, sorted_key in {default,calls,total,max,min,ave}), chrome-trace JSON
written to profile_path (the reference serializes a proto; chrome://tracing
and Perfetto load this JSON directly), and device traces via jax.profiler
(XLA/TPU timelines) — the CUPTI role (SURVEY §5.1) is played by the PJRT
profiler.
"""
import contextlib
import json
import os
import threading
import time
from collections import defaultdict

import jax

_state = threading.local()
# name -> [count, total_s, min_s, max_s]
_records = defaultdict(lambda: [0, 0.0, float("inf"), 0.0])
_events = []  # (name, tid, start_s, dur_s) for chrome-trace export
_MAX_EVENTS = 200_000
_enabled = [False]
_trace_dir = [None]
_t_origin = [0.0]


class RecordEvent:
    """RAII span (platform/profiler.h RecordEvent parity)."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._t0 = None
        self._jax_ctx = None

    def __enter__(self):
        self.begin()
        return self

    def begin(self):
        if _enabled[0]:
            self._t0 = time.perf_counter()
            self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
            self._jax_ctx.__enter__()

    def end(self):
        if self._t0 is not None:
            dt = time.perf_counter() - self._t0
            rec = _records[self.name]
            rec[0] += 1
            rec[1] += dt
            rec[2] = min(rec[2], dt)
            rec[3] = max(rec[3], dt)
            if len(_events) < _MAX_EVENTS:
                _events.append((self.name, threading.get_ident(),
                                self._t0 - _t_origin[0], dt))
            if self._jax_ctx is not None:
                self._jax_ctx.__exit__(None, None, None)
            self._t0 = None

    def __exit__(self, *exc):
        self.end()
        return False


def start_profiler(state="All", tracer_option="Default", trace_dir=None):
    _enabled[0] = True
    _records.clear()
    _events.clear()
    _t_origin[0] = time.perf_counter()
    if trace_dir:
        _trace_dir[0] = trace_dir
        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key="default", profile_path=None):
    """EnableProfiler teardown parity (profiler.h:213-216): print the
    sorted summary table and, when profile_path is given, dump the span
    timeline as chrome-trace JSON (chrome://tracing / Perfetto)."""
    _enabled[0] = False
    if _trace_dir[0]:
        jax.profiler.stop_trace()
        _trace_dir[0] = None
    if profile_path:
        export_chrome_trace(profile_path)
    return summary(sorted_key)


_SORT = {
    "default": lambda r: 0,          # insertion order, like the reference
    "calls": lambda r: -r[1],
    "total": lambda r: -r[2],
    "max": lambda r: -r[4],
    "min": lambda r: -r[3],
    "ave": lambda r: -r[5],
}


def summary(sorted_key="default"):
    """Sorted per-event table with the reference's columns
    (platform/profiler.cc PrintProfiler): Calls, Total, Min, Max, Ave,
    Ratio (share of the summed span time)."""
    if sorted_key not in _SORT:
        raise ValueError(
            f"sorted_key must be one of {sorted(_SORT)}, got {sorted_key!r}")
    grand = sum(r[1] for r in _records.values()) or 1.0
    rows = [
        (name, cnt, tot, mn if cnt else 0.0, mx,
         tot / cnt if cnt else 0.0, tot / grand)
        for name, (cnt, tot, mn, mx) in _records.items()
    ]
    rows.sort(key=_SORT[sorted_key])
    head = (f"{'Event':<36}{'Calls':>8}{'Total(ms)':>12}{'Min(ms)':>10}"
            f"{'Max(ms)':>10}{'Ave(ms)':>10}{'Ratio':>8}")
    lines = ["-------------------------  Profiling Report  "
             "-------------------------", head]
    for name, cnt, tot, mn, mx, avg, ratio in rows:
        lines.append(
            f"{name:<36}{cnt:>8}{tot * 1e3:>12.3f}{mn * 1e3:>10.3f}"
            f"{mx * 1e3:>10.3f}{avg * 1e3:>10.3f}{ratio:>8.3f}")
    report = "\n".join(lines)
    print(report)
    return report


def export_chrome_trace(path):
    """Write recorded spans in chrome-trace 'traceEvents' JSON (the role
    of the reference's profiler.proto dump, directly loadable by
    chrome://tracing and Perfetto)."""
    trace = {
        "traceEvents": [
            {"name": name, "ph": "X", "pid": os.getpid(), "tid": tid,
             "ts": round(start * 1e6, 3), "dur": round(dur * 1e6, 3),
             "cat": "host"}
            for name, tid, start, dur in _events
        ],
        "displayTimeUnit": "ms",
    }
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)
    return path


@contextlib.contextmanager
def profiler(state="All", sorted_key="default", profile_path=None,
             trace_dir=None):
    """fluid/profiler.py:314 context-manager parity."""
    start_profiler(state, trace_dir=trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


class Profiler:
    """paddle.profiler.Profiler-style API over jax.profiler."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 trace_dir=None):
        self.trace_dir = trace_dir

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.stop()
        return False

    def start(self):
        start_profiler(trace_dir=self.trace_dir)

    def stop(self):
        stop_profiler()

    def step(self):
        pass

    def summary(self, **kw):
        return summary(**kw)

    def export_chrome_trace(self, path):
        return export_chrome_trace(path)


from .monitor import (  # noqa: E402,F401  (monitor.h StatRegistry parity)
    Stat, StatRegistry, stat_add, stat_sub, stat_get,
)
