"""Dynamic batching: coalesce concurrent requests into one TPU dispatch.

A dedicated worker thread pulls requests off the AdmissionQueue and forms
batches under two limits — `max_batch_size` rows or `max_batch_delay_ms`
since the batch opened, whichever comes first (the classic
latency/throughput knob: delay 0 serves singles, delay ~= p50 step time
roughly doubles throughput at +1 batch-delay of tail latency).  One
bucket per dispatch: only requests whose bucketed trailing shapes match
the batch head coalesce (queue.poll_match), so the padded batch is
rectangular and hits exactly one cached executable.

Each dispatch: concatenate rows → pad to the batch bucket → run the
per-bucket AOT executable → slice per-request rows back out → resolve
futures.  Everything is spanned with RecordEvent, so `enable_profile`
configs see serving internals in the profiler summary/chrome trace.
"""
import threading
import time

import numpy as np

from .admission import DeadlineExceededError
from .metrics import ServingMetrics


class DynamicBatcher:
    """Worker-thread batch former + dispatcher.

    runner: callable(list_of_padded_arrays) -> list of output arrays
        (normally a CompiledModelCache; anything positional works).
    queue: AdmissionQueue feeding it.
    bucketer: ShapeBucketer deciding padded shapes.
    """

    _POLL_S = 0.05  # idle poll granularity; shutdown latency bound

    def __init__(self, runner, queue, bucketer, max_batch_size=None,
                 max_batch_delay_ms=2.0, metrics=None, name="serving"):
        self.runner = runner
        self.queue = queue
        self.bucketer = bucketer
        self.max_batch_size = int(max_batch_size or bucketer.max_batch)
        if self.max_batch_size > bucketer.max_batch:
            raise ValueError(
                f"max_batch_size={self.max_batch_size} exceeds the largest "
                f"batch bucket {bucketer.max_batch}")
        self.max_batch_delay_ms = float(max_batch_delay_ms)
        self.metrics = metrics or ServingMetrics()
        self._stop = threading.Event()
        self._paused = threading.Event()
        self._thread = threading.Thread(
            target=self._worker, name=f"{name}-batcher", daemon=True)
        self._thread.start()

    # --- lifecycle ---
    def pause(self):
        """Stop pulling from the queue (drain/testing hook); in-flight
        dispatches finish."""
        self._paused.set()

    def resume(self):
        self._paused.clear()

    def shutdown(self, timeout=5.0):
        self._stop.set()
        self._thread.join(timeout)

    @property
    def alive(self):
        return self._thread.is_alive()

    # --- worker ---
    def _worker(self):
        while not self._stop.is_set():
            if self._paused.is_set():
                time.sleep(self._POLL_S)
                continue
            head = self.queue.poll(timeout=self._POLL_S)
            if head is None:
                continue
            batch = self._coalesce(head)
            if batch:
                self._dispatch(batch)

    def _coalesce(self, head):
        """Grow [head] until max rows or the batch delay elapses."""
        batch, rows = [head], head.rows
        opened = time.monotonic()
        delay_s = self.max_batch_delay_ms / 1e3
        while rows < self.max_batch_size:
            remaining = (opened + delay_s) - time.monotonic()
            if remaining <= 0:
                break
            nxt = self.queue.poll_match(head.bucket_key,
                                        self.max_batch_size - rows,
                                        timeout=remaining)
            if nxt is None:
                break
            batch.append(nxt)
            rows += nxt.rows
        # a request may have expired while the batch formed
        live = []
        n_dead = 0
        for r in batch:
            if r.expired():
                r.reject_expired()
                n_dead += 1
            else:
                live.append(r)
        if n_dead:
            self.metrics.count_rejected_deadline(n_dead)
        return live

    def _dispatch(self, batch):
        from ..profiler import RecordEvent

        rows = [r.rows for r in batch]
        total = sum(rows)
        try:
            with RecordEvent("serving::batch"):
                with RecordEvent("serving::pad"):
                    args = [
                        np.concatenate(per_input, axis=0)
                        if len(batch) > 1 else batch[0].args[i]
                        for i, per_input in enumerate(zip(
                            *[r.args for r in batch]))
                    ]
                    args, bucket_rows = self.bucketer.pad_batch(args, total)
                with RecordEvent("serving::run"):
                    outs = self.runner(args)
                with RecordEvent("serving::scatter"):
                    sliced = self.bucketer.unpad_outputs(outs, rows)
        except Exception as e:  # noqa: BLE001 — the batch fails as a unit
            for r in batch:
                if not r.future.done():
                    r.future.set_exception(e)
            return
        self.metrics.observe_batch(total, bucket_rows)
        now = time.monotonic()
        n_dead = 0
        for r, outs_r in zip(batch, sliced):
            if r.expired(now):
                # deadline lapsed inside the dispatch (e.g. a cold-bucket
                # compile): the admission contract still holds — typed
                # rejection, and the blown latency stays out of the
                # percentiles the live traffic is judged by
                r.reject_expired()
                n_dead += 1
            elif r.future.set_running_or_notify_cancel():
                r.future.set_result(outs_r)
                self.metrics.observe_latency(now - r.submit_t)
            # a cancelled future just drops its (already computed) slice
        if n_dead:
            self.metrics.count_rejected_deadline(n_dead)


__all__ = ["DynamicBatcher", "DeadlineExceededError"]
