"""Deterministic fault injection for the replica process boundary.

A ``FaultPlan`` wraps the rpc.py frame codec PARENT-SIDE (the
SubprocTransport's sends and its reader thread's receives), so every
chaos scenario — a dropped submit, a duplicated token event, a
corrupted frame, a worker killed mid-export, an engine that wedges
while its heartbeat keeps flowing — is a fast, seeded, reproducible
unit test instead of a flake.  Faults land exactly where real ones
do, on the wire between the router and the replica.

Rules with ``side="child"`` run in the WORKER process instead: the
transport ships them (plus a derived seed) in the build frame, the
worker builds its own plan and wraps ITS half of the codec — so
child→parent frame corruption (a token event the worker mangles
before it ever leaves, a worker that SIGKILLs itself mid-stream) is
covered too, not just the parent's view.  ``arm()``/``disarm()`` on
the parent plan re-sync every attached transport's child half over
the wire.

Fault kinds (``FaultRule.kind``):

================  ========================================================
``drop``          the frame never reaches the peer (a lost datagram in
                  socket clothing: RPC requests time out typed, stream
                  events are healed by sequence numbers / the orphan
                  sweep)
``delay``         the frame is held ``delay_s`` before delivery (send
                  side: the caller thread sleeps; recv side: the reader
                  thread sleeps — everything behind it queues, like a
                  congested link)
``dup``           the frame is delivered twice (stream events carry
                  per-stream sequence numbers so the parent dedups;
                  replies dedup on rid)
``truncate``      a torn write: the length header promises more payload
                  bytes than follow, desyncing the channel — the peer
                  blocks mid-frame and every later RPC times out
``corrupt``       the payload bytes are flipped (seeded positions):
                  send side the worker dies unpickling, recv side the
                  reader declares the channel poisoned — both collapse
                  to the crash path
``kill``          SIGKILL the worker the moment the named point is hit
                  (kill-at-submit, mid-stream, at export/import, at
                  heartbeat) — socket EOF is the detection under test
``stall``         the worker's ENGINE wedges (a thread holds the step
                  lock for ``stall_s``) while its heartbeat thread
                  keeps beating — the alive-but-stalled failure only
                  the wedge watchdog can catch
================  ========================================================

Injection points (``FaultRule.point``): on the send direction the RPC
op name (``"submit"``, ``"stats"``, ``"export_prefix"``,
``"import_seq"``, ``"evacuate"``, ...); on the recv direction the
event kind (``"token"`` — mid-stream, ``"done"``, ``"error"``,
``"hb"`` — heartbeat) or ``"resp"`` (any RPC reply).  ``"any"``
matches every frame in the rule's direction(s).

Determinism: each rule counts its OWN matching frames and fires on
matches ``after .. after+count-1``; a ``prob`` rule draws from the
plan's seeded RNG instead.  Same plan + same traffic order ⇒ same
faults.  ``FaultPlan.fired`` logs every firing for drill reports.

Docs: docs/SERVING.md "Failure model".
"""
import pickle
import random
import threading
import time

from .rpc import _HEADER

KINDS = ("drop", "delay", "dup", "truncate", "corrupt", "kill", "stall")
DIRECTIONS = ("send", "recv")
# kinds that end (or wedge) the replica — a drill keeps at least one
# replica free of these so surviving streams have somewhere to land
FATAL_KINDS = ("kill", "stall", "corrupt", "truncate")


class FaultInjected(ValueError):
    """Raised by recv-side corrupt/truncate rules: the frame codec
    declares the channel poisoned, exactly as a real corrupt frame
    would — the reader thread's dead-channel path is the code under
    test."""


class FaultRule:
    """One scheduled fault: `kind` at `point`, firing on this rule's
    ``after``-th matching frame (then ``count-1`` more).  ``direction``
    restricts matching to "send"/"recv" (None = both — points rarely
    collide across directions anyway).  ``prob`` replaces the
    deterministic window with a seeded coin flip per match.  ``side``
    picks the process that applies the rule: "parent" (the transport's
    codec — default, the historical behavior) or "child" (shipped to
    the worker, which wraps its own sends/recvs; directions are then
    relative to the WORKER, so side="child" direction="send" faults
    the token/done/hb events it emits)."""

    __slots__ = ("point", "kind", "direction", "after", "count",
                 "delay_s", "stall_s", "prob", "side", "_seen")

    def __init__(self, point, kind, direction=None, after=0, count=1,
                 delay_s=0.05, stall_s=30.0, prob=None, side="parent"):
        if kind not in KINDS:
            raise ValueError(f"kind must be one of {KINDS}, got {kind!r}")
        if direction is not None and direction not in DIRECTIONS:
            raise ValueError(
                f"direction must be 'send', 'recv' or None, got "
                f"{direction!r}")
        if side not in ("parent", "child"):
            raise ValueError(
                f"side must be 'parent' or 'child', got {side!r}")
        if int(after) < 0 or int(count) < 1:
            raise ValueError(
                f"need after >= 0 and count >= 1, got after={after} "
                f"count={count}")
        self.point = str(point)
        self.kind = kind
        self.direction = direction
        self.after = int(after)
        self.count = int(count)
        self.delay_s = float(delay_s)
        self.stall_s = float(stall_s)
        self.prob = None if prob is None else float(prob)
        self.side = side
        self._seen = 0

    def _matches(self, direction, point, rng):
        if self.direction is not None and self.direction != direction:
            return False
        if self.point != "any" and self.point != point:
            return False
        n = self._seen
        self._seen += 1
        if self.prob is not None:
            return rng.random() < self.prob
        return self.after <= n < self.after + self.count

    def __repr__(self):
        return (f"FaultRule({self.point!r}, {self.kind!r}, "
                f"after={self.after}, count={self.count})")


class FaultPlan:
    """A seeded schedule of FaultRules applied to one transport's
    frame codec.  Thread-safe (the transport's caller threads and its
    reader thread both consult it); ``fired`` is the audit log drills
    and tests read back."""

    def __init__(self, rules=(), seed=0, armed=True, holder="parent"):
        self.rules = list(rules)
        self.seed = seed
        self.holder = holder   # which process applies this copy:
        # "parent" (the transport) or "child" (the worker's shipped
        # half) — rules tagged for the OTHER side never match here
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.armed = bool(armed)   # a disarmed plan is a pure
        # passthrough and counts nothing: drills build the fleet and
        # pay its compile warmup BEFORE the schedule starts ticking
        self.fired = []   # [{"kind", "point", "direction", "t"}]
        self._hosts = []  # transports whose workers hold this plan's
        # child half — arm()/disarm() re-syncs them over the wire

    def arm(self):
        # children first: the parent half must still be disarmed while
        # the sync frame is in flight, or an armed "any" send rule
        # could fault the sync itself
        for host in list(self._hosts):
            host._sync_child_faults(True)
        self.armed = True

    def disarm(self):
        self.armed = False
        for host in list(self._hosts):
            host._sync_child_faults(False)

    def child_spec(self):
        """The worker-shipped half: ``{"rules", "seed", "armed"}`` for
        this plan's side="child" rules, or None when there are none.
        The seed is derived so parent and child draws never share a
        stream."""
        child = [r for r in self.rules if r.side == "child"]
        if not child:
            return None
        return {"rules": child, "seed": ("child", self.seed),
                "armed": self.armed}

    def _take(self, direction, point):
        """The rules firing on this frame (usually 0 or 1).  Rules
        destined for the other process (side="child" on a parent-held
        plan) never match here — the worker's own copy applies them."""
        with self._lock:
            if not self.armed:
                return []
            hits = [r for r in self.rules if r.side == self.holder
                    and r._matches(direction, point, self._rng)]
            now = time.monotonic()
            for r in hits:
                self.fired.append({"kind": r.kind, "point": point,
                                   "direction": direction, "t": now})
            return hits

    def fired_kinds(self):
        return sorted({f["kind"] for f in self.fired})

    # ---------------------- codec integration -----------------------
    # Both hooks are called by SubprocTransport in place of the plain
    # send_frame/recv_frame; a plan-less transport never enters here.

    def on_send(self, transport, msg):
        """Apply send-direction rules and perform the (possibly
        faulted) write of `msg` on the transport's socket.
        `transport` is any codec host exposing _sock/_wlock/kill()/
        _send_stall()/_send_plain() — the SubprocTransport parent-side,
        the worker's fault host child-side."""
        point = msg.get("op") or msg.get("ev", "?")
        hits = self._take("send", point)
        kinds = {r.kind for r in hits}
        for r in hits:
            if r.kind == "delay":
                time.sleep(r.delay_s)
        if "kill" in kinds:
            # kill-at-named-point: the worker dies the instant the
            # router speaks to it — the frame never leaves
            transport.kill()
            return
        if "stall" in kinds:
            stall_s = max(r.stall_s for r in hits if r.kind == "stall")
            transport._send_stall(stall_s)
        if "drop" in kinds:
            return
        if "corrupt" in kinds or "truncate" in kinds:
            payload = pickle.dumps(msg, protocol=pickle.HIGHEST_PROTOCOL)
            if "corrupt" in kinds:
                # flip the opcode stream from byte 0: deterministic
                # positions from the plan RNG, dense enough that the
                # peer's unpickle cannot survive it
                buf = bytearray(payload)
                buf[0] ^= 0xFF
                for _ in range(max(4, len(buf) // 4)):
                    buf[self._rng.randrange(len(buf))] ^= 0xFF
                payload = bytes(buf)
            else:
                # torn write: promise the full length, deliver half —
                # the peer blocks mid-frame and the channel desyncs
                payload = payload[:max(1, len(payload) // 2)]
                data = _HEADER.pack(len(payload) * 2) + payload
                with transport._wlock:
                    transport._sock.sendall(data)
                return
            data = _HEADER.pack(len(payload)) + payload
            with transport._wlock:
                transport._sock.sendall(data)
            return
        transport._send_plain(msg)
        if "dup" in kinds:
            transport._send_plain(msg)

    def on_recv(self, transport):
        """Read one logical frame off the transport's channel and
        return the list of frames to dispatch (0 = dropped, 2 =
        duplicated).  Raises FaultInjected for corrupt/truncate rules
        — the reader thread's poisoned-channel path."""
        frame = transport._recv_plain()
        point = frame.get("ev") or ("resp" if "resp" in frame
                                    else frame.get("op", "?"))
        hits = self._take("recv", point)
        kinds = {r.kind for r in hits}
        for r in hits:
            if r.kind == "delay":
                time.sleep(r.delay_s)
        if "kill" in kinds:
            # e.g. mid-stream: the worker dies right after this token
            transport.kill()
        if "stall" in kinds:
            stall_s = max(r.stall_s for r in hits if r.kind == "stall")
            transport._send_stall(stall_s)
        if "corrupt" in kinds or "truncate" in kinds:
            raise FaultInjected(
                f"chaos: {sorted(kinds & {'corrupt', 'truncate'})} "
                f"frame at {point!r}")
        if "drop" in kinds:
            return []
        if "dup" in kinds:
            return [frame, frame]
        return [frame]


__all__ = ["FaultPlan", "FaultRule", "FaultInjected", "KINDS",
           "FATAL_KINDS", "DIRECTIONS"]
