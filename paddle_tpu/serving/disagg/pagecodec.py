"""Page-payload codec: the data plane's wire format (ISSUE 20).

The page service's export schema (engine.export_prefix_pages) ships
``{"tokens", "k", "v"[, "k_scale", "v_scale"]}`` with K/V pools shaped
``[L, n, page_size, H, D]`` — raw int8+scales (quantized pools) or raw
bf16/fp32 planes.  On a real network those bytes dominate cross-host
adoption and P/D handoff cost, so this module turns a payload into a
versioned, self-describing wire frame:

- level "raw": byte-exact passthrough (the A/B baseline, and the
  negotiated floor every fleet member supports).
- level "delta": per-page delta filter along the TOKEN axis (uint8
  wraparound subtraction of consecutive token rows — adjacent
  positions' K/V are strongly correlated, so deltas concentrate near
  zero) followed by zlib entropy coding.  Decode inverts with a
  modular cumulative sum: the roundtrip is BITWISE exact for every
  dtype, including ml_dtypes bf16 planes viewed as bytes.

Every encoded array records its own filter/codec, and an array whose
compressed form is not smaller than raw falls back to raw passthrough
per array — "delta" never inflates adversarial (incompressible) pages
beyond the frame overhead.

Version negotiation: the fetch request carries the importer's codec
version and accepted levels; the holder encodes at the best mutually
supported level and stamps the frame with ``pv``.  A frame from the
future (unknown version, filter or codec) decodes to a TYPED
PageCodecError — a heterogeneous fleet mid-upgrade degrades to the
cold-prefill ladder, never to corrupt pages.
"""
import zlib

import numpy as np

from ..admission import ServingError

# wire version this build speaks; decoders accept exactly these
VERSION = 1
SUPPORTED_VERSIONS = (1,)
# codec levels, best-first: negotiation picks the first requested
# level this build supports
LEVEL_DELTA = "delta"
LEVEL_RAW = "raw"
SUPPORTED_LEVELS = (LEVEL_DELTA, LEVEL_RAW)

_ZLIB_LEVEL = 6
_ARRAY_FIELDS = ("k", "v", "k_scale", "v_scale")


class PageCodecError(ServingError):
    """A page frame this build cannot decode (unknown version/level/
    filter) or a level negotiation with no common ground — TYPED so
    adoption degrades to the cold-prefill ladder, never corrupts."""


def negotiate(version, levels):
    """Holder-side handshake: pick the best mutually supported codec
    level for an importer speaking `version` and accepting `levels`
    (best-first).  Raises PageCodecError when there is no common
    ground — the typed refusal heterogeneous fleets degrade on."""
    if version not in SUPPORTED_VERSIONS:
        raise PageCodecError(
            f"pagecodec version {version!r} not supported "
            f"(this build speaks {SUPPORTED_VERSIONS})")
    for lv in levels:
        if lv in SUPPORTED_LEVELS:
            return lv
    raise PageCodecError(
        f"no common codec level: importer accepts {list(levels)!r}, "
        f"this build offers {list(SUPPORTED_LEVELS)}")


def _token_rows(arr):
    """Byte view of `arr` as [pages, rows, row_bytes] with the delta
    axis (axis 1) running along in-page token positions.  Pool planes
    are [L, n, page_size, H, D] (rows = page_size); anything else
    (scales, odd shapes) deltas along its leading axis."""
    shape = arr.shape
    if len(shape) == 5:
        pages, rows = shape[0] * shape[1], shape[2]
    else:
        pages, rows = 1, shape[0] if shape else 1
    b = np.frombuffer(arr.tobytes(), np.uint8)
    return b.reshape(pages, rows, -1) if b.size else b.reshape(0, 1, 1)


def _encode_array(arr, level):
    arr = np.ascontiguousarray(arr)
    blob = {"shape": tuple(arr.shape), "dtype": arr.dtype,
            "filter": "raw", "codec": "raw", "data": arr.tobytes()}
    if level == LEVEL_DELTA and arr.size:
        rows = _token_rows(arr)
        d = np.array(rows)   # writable copy, uint8 wraparound domain
        d[:, 1:, :] -= rows[:, :-1, :]
        packed = zlib.compress(d.tobytes(), _ZLIB_LEVEL)
        if len(packed) < len(blob["data"]):
            blob.update(filter="delta", codec="zlib", data=packed)
    return blob


def _decode_array(blob):
    for field in ("shape", "dtype", "filter", "codec", "data"):
        if field not in blob:
            raise PageCodecError(f"page frame array missing {field!r}")
    if blob["codec"] == "zlib":
        raw = zlib.decompress(blob["data"])
    elif blob["codec"] == "raw":
        raw = blob["data"]
    else:
        raise PageCodecError(
            f"unknown entropy codec {blob['codec']!r}")
    shape, dtype = tuple(blob["shape"]), np.dtype(blob["dtype"])
    expect = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    if len(raw) != expect:
        raise PageCodecError(
            f"page frame length {len(raw)} != expected {expect} for "
            f"shape {shape} dtype {dtype}")
    if blob["filter"] == "delta":
        arr = np.frombuffer(raw, np.uint8).reshape(
            *_token_rows_shape(shape, dtype))
        # inverse filter: modular cumulative sum along the token axis
        arr = (np.cumsum(arr, axis=1, dtype=np.int64)
               & 0xFF).astype(np.uint8)
        raw = arr.tobytes()
    elif blob["filter"] != "raw":
        raise PageCodecError(f"unknown filter {blob['filter']!r}")
    return np.frombuffer(raw, dtype=dtype).reshape(shape)


def _token_rows_shape(shape, dtype):
    if len(shape) == 5:
        return (shape[0] * shape[1], shape[2], -1)
    return (1, shape[0] if shape else 1, -1)


def encode_payload(payload, level=LEVEL_DELTA):
    """Encode one export payload into a versioned wire frame.  `level`
    must be a SUPPORTED_LEVELS member (run negotiate() first)."""
    if level not in SUPPORTED_LEVELS:
        raise PageCodecError(f"unknown codec level {level!r}")
    enc = {"pv": VERSION, "level": level,
           "tokens": [int(t) for t in payload["tokens"]]}
    for field in _ARRAY_FIELDS:
        if field in payload:
            enc[field] = _encode_array(payload[field], level)
    return enc


def decode_payload(enc):
    """Decode a wire frame back into the export payload — bitwise
    identical arrays, dtypes included.  Raises PageCodecError for
    frames from an unknown version (or damaged self-description):
    heterogeneous fleets degrade typed, never silently."""
    if not isinstance(enc, dict) or "pv" not in enc:
        raise PageCodecError("not a page frame (no version tag)")
    if enc["pv"] not in SUPPORTED_VERSIONS:
        raise PageCodecError(
            f"page frame version {enc['pv']!r} not supported "
            f"(this build speaks {SUPPORTED_VERSIONS})")
    payload = {"tokens": [int(t) for t in enc.get("tokens", ())]}
    for field in _ARRAY_FIELDS:
        if field in enc:
            payload[field] = _decode_array(enc[field])
    return payload


def wire_bytes(enc):
    """Page bytes actually on the wire for an encoded frame (array
    data only — framing/tokens overhead is O(1) and excluded so the
    compression-ratio arithmetic stays exact)."""
    return sum(len(enc[f]["data"]) for f in _ARRAY_FIELDS if f in enc)


def raw_bytes(enc):
    """What the same frame would weigh uncompressed (the int8+scales
    baseline the compression ratio is measured against)."""
    total = 0
    for f in _ARRAY_FIELDS:
        if f in enc:
            blob = enc[f]
            total += (int(np.prod(blob["shape"], dtype=np.int64))
                      * np.dtype(blob["dtype"]).itemsize)
    return total


def payload_nbytes(payload):
    """Raw byte weight of an UNENCODED export payload (the relay
    path's wire cost accounting)."""
    return sum(payload[f].nbytes for f in _ARRAY_FIELDS
               if f in payload)


__all__ = [
    "VERSION", "SUPPORTED_VERSIONS", "LEVEL_DELTA", "LEVEL_RAW",
    "SUPPORTED_LEVELS", "PageCodecError", "negotiate",
    "encode_payload", "decode_payload", "wire_bytes", "raw_bytes",
    "payload_nbytes",
]
