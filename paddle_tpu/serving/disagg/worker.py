"""Replica worker process — the child half of SubprocTransport.

Two launch modes, one serve loop:

- ``python -m paddle_tpu.serving.disagg.worker <fd>`` — inherit a
  UNIX socketpair fd from the parent (same-host SubprocTransport).
- ``python -m paddle_tpu.serving.disagg.worker --connect host:port``
  — dial back to the parent's ReplicaListener over TCP
  (TcpTransport, the cross-host path).

Either way the worker builds ONE single-process GenerationEngine from
the pickled build spec (first RPC frame) and serves the transport RPC
contract: submit streams tokens back as events, evacuate ships cold
requests and live sequence snapshots for migration, cancel frees a
stream's slot and pages, a heartbeat thread reports load + prefix
register/evict deltas every ``HEARTBEAT_S``.  A prefill-role worker
additionally parks each sequence at prompt completion and ships the
snapshot up as a ``handoff`` event (P/D disaggregation).  The engine
steps itself on its background worker thread; nothing here touches
jax.distributed — a replica is exactly the single-process engine the
CPU oracle runs, behind a socket.

The build frame may carry the CHILD half of a chaos FaultPlan
(side="child" rules + a derived seed): the worker then wraps its own
sends/recvs so child→parent frame corruption, self-SIGKILL and
self-stall are all seeded, reproducible faults too.

Frame schema: docs/SERVING.md "Disaggregated fleet".
"""
import os
import signal
import socket
import sys
import threading
import time
import traceback


class _StreamHandle:
    """Engine-side handle that RELAYS the stream over the socket: the
    duck-typed surface GenerationEngine drives (_push_token/_finish/
    set_exception/done + the stamp attributes), writing one event
    frame per transition.  The parent-side transport reassembles the
    client's GenerationHandle from these frames."""

    __slots__ = ("sid", "_send_event", "submitted_s", "first_token_s",
                 "prefix_hit_tokens", "_done", "_n")

    def __init__(self, sid, send_event):
        self.sid = sid
        self._send_event = send_event
        self.submitted_s = None
        self.first_token_s = None
        self.prefix_hit_tokens = None
        self._done = False
        self._n = 0   # per-stream event index: the parent dedups
        # duplicated frames and detects holes from dropped ones

    def _send(self, obj):
        try:
            self._send_event(obj)
        except OSError:
            pass   # parent gone; this process is about to die anyway

    def _push_token(self, token):
        if self.first_token_s is None:
            self.first_token_s = time.monotonic()
        n = self._n
        self._n += 1
        self._send({"ev": "token", "sid": self.sid, "t": int(token),
                    "n": n})

    def _finish(self, result):
        if self._done:
            return
        self._done = True
        self._send({"ev": "done", "sid": self.sid,
                    "prefix_hit": self.prefix_hit_tokens,
                    "result": {"token_ids": list(result.token_ids),
                               "finish_reason": result.finish_reason,
                               "prompt_len": result.prompt_len,
                               "preemptions": result.preemptions}})

    def set_exception(self, exc):
        if self._done:
            return
        self._done = True
        self._send({"ev": "error", "sid": self.sid, "exc": exc})

    def done(self):
        return self._done


class _Worker:
    def __init__(self, sock):
        from .rpc import FrameAssembler

        self.sock = sock
        self.wlock = threading.Lock()
        self.engine = None
        self.registry = None
        self.chunk_bytes = None   # set by the build frame
        self.faults = None        # child half of a chaos FaultPlan
        self.data_server = None   # p2p page data plane (ISSUE 20)
        self.handles = {}         # sid -> live _StreamHandle (cancel)
        self._hlock = threading.Lock()
        self._assembler = FrameAssembler()
        self._stop_hb = threading.Event()
        # fault-host aliases: FaultPlan.on_send/on_recv drive a codec
        # host through _sock/_wlock/kill/_send_stall/_send_plain —
        # child-side, that host is the worker itself
        self._sock = sock
        self._wlock = self.wlock

    # ------------------------ codec plumbing ------------------------
    def _send_plain(self, msg):
        from .rpc import send_frame

        send_frame(self.sock, msg, self.wlock,
                   chunk_bytes=self.chunk_bytes)

    def _recv_plain(self):
        return self._assembler.recv(self.sock)

    def send_event(self, obj):
        """Event-frame write (token/done/error/hb/handoff): the path
        child-side send faults wrap."""
        if self.faults is None:
            self._send_plain(obj)
        else:
            self.faults.on_send(self, obj)

    def recv(self):
        if self.faults is None:
            return [self._recv_plain()]
        return self.faults.on_recv(self)

    def kill(self):
        """Child-side 'kill' fault: this worker SIGKILLs ITSELF — the
        parent sees exactly what a real crash looks like (socket EOF,
        no goodbye)."""
        os.kill(os.getpid(), signal.SIGKILL)

    def _send_stall(self, stall_s):
        """Child-side 'stall' fault: wedge our own engine."""
        self.op_chaos_stall({"stall_s": stall_s})

    # --------------------------- ops --------------------------------
    def op_build(self, frame):
        from ...generation.engine import GenerationEngine
        from ...generation.metrics import GenerationMetrics
        from ...profiler.monitor import StatRegistry
        from .transport import HEARTBEAT_S

        self.chunk_bytes = frame.get("chunk_bytes")
        fspec = frame.get("faults")
        if fspec is not None:
            from .faults import FaultPlan

            self.faults = FaultPlan(fspec["rules"], seed=fspec["seed"],
                                    armed=fspec["armed"],
                                    holder="child")
        self.registry = StatRegistry()
        self.engine = GenerationEngine(
            frame["model"], frame["config"],
            metrics=GenerationMetrics(registry=self.registry),
            start=True)
        if self.engine.prefix_cache_enabled:
            self.engine.cache.enable_prefix_deltas()
        if frame.get("role") == "prefill":
            # P/D disaggregation: park each sequence at prompt
            # completion; the engine's step loop notifies us (lock
            # already released) and we ship the snapshots up as
            # handoff events for the router to place on decode
            # replicas
            self.engine.enable_handoff()
            self.engine.on_handoff = self._ship_handoffs
        # the p2p data plane: bind an ephemeral data port siblings
        # dial DIRECTLY for page bytes (advertised in heartbeats and
        # the build reply) — the router's socket stays control-only
        from .data_plane import PageDataServer

        self.data_server = PageDataServer(
            self.engine.export_prefix_pages,
            host=frame.get("data_host") or "127.0.0.1",
            chunk_bytes=self.chunk_bytes)
        threading.Thread(target=self._heartbeat, args=(HEARTBEAT_S,),
                         name="replica-heartbeat", daemon=True).start()
        out = dict(self.engine.describe())
        out["data_address"] = self.data_server.address
        return out

    def _ship_handoffs(self):
        for snap in self.engine.take_handoffs():
            handle = snap.pop("future")
            handle._done = True   # stream continues elsewhere; no
            # late done/error frame may race the handoff
            payload = dict(snap)
            with self._hlock:
                self.handles.pop(handle.sid, None)
            try:
                self.send_event({"ev": "handoff", "sid": handle.sid,
                                 "snap": payload})
            except OSError:
                return   # parent gone; nothing to hand off to

    def _heartbeat(self, interval):
        while not self._stop_hb.wait(interval):
            try:
                deltas = self.engine.cache.take_prefix_deltas()
                # "seq" is the engine's step-progress stamp: this
                # thread deliberately shares NO lock with the step
                # loop, so a wedged engine keeps heartbeating a FROZEN
                # seq while reporting work — exactly the signature the
                # parent's wedge watchdog kills on
                self.send_event(
                    {"ev": "hb", "load": self.engine.load_info(),
                     "seq": self.engine.step_seq,
                     "in_step": self.engine.in_step,
                     "deltas": deltas,
                     # data-port advert: the parent learns (and after
                     # a restart re-learns) where to send siblings
                     # for this replica's page bytes
                     "data": (None if self.data_server is None
                              else self.data_server.address)})
            except OSError:
                return
            except Exception:   # noqa: BLE001 — a heartbeat must never
                pass            # kill the worker; the next beat retries

    def _register(self, sid, handle):
        with self._hlock:
            # opportunistic prune keeps the map at O(live streams)
            for old_sid in [s for s, h in self.handles.items()
                            if h.done()]:
                del self.handles[old_sid]
            self.handles[sid] = handle

    def op_submit(self, frame):
        sid = frame["sid"]
        # the wire is at-least-once (dup faults, RPC redelivery): a
        # sid we already own must NOT start a second stream — the
        # doubled token events would interleave into the parent's one
        # ledger entry as a duplicated client stream
        with self._hlock:
            live = self.handles.get(sid)
            if live is not None and not live.done():
                return True
        handle = _StreamHandle(sid, self.send_event)
        self._register(sid, handle)
        self.engine.submit(frame["prompt"], handle=handle,
                           **frame["kwargs"])
        return True

    def op_cancel(self, frame):
        """Free the stream's queue slot and pages; the engine resolves
        the handle with finish_reason="cancelled", whose done frame
        settles the parent's ledger entry — the client never hangs."""
        with self._hlock:
            handle = self.handles.pop(frame["sid"], None)
        if handle is None or handle.done():
            return False
        return bool(self.engine.cancel(handle))

    def op_load(self, frame):
        return self.engine.load_info()

    def op_stats(self, frame):
        return {
            "generation":
                self.registry.stats_snapshot("generation.")["stats"],
            "cache": self.engine.cache.stats(),
        }

    def op_evacuate(self, frame):
        # the same drain state machine as InprocTransport.drain —
        # engine.drain_work, so the oracle and the process boundary
        # cannot diverge (the child's engine always runs its worker
        # thread, so drain_work's wait loop just sleeps here)
        cold, live_snaps = self.engine.drain_work(
            migrate=frame["migrate"], live=frame["live"],
            timeout=frame["timeout"])
        out = {"cold": [], "live": []}
        for req, emitted in cold:
            out["cold"].append({
                "sid": req.future.sid,
                "prompt": list(req.prompt),
                "max_new_tokens": req.max_new_tokens,
                "sampling": req.params,
                "stop_tokens": tuple(req.stop_tokens),
                "deadline": req.deadline,
                "emitted": int(emitted),
            })
        for snap in live_snaps:
            snap["sid"] = snap.pop("future").sid
            out["live"].append(snap)
        return out

    def op_import_seq(self, frame):
        snap = frame["snap"]
        handle = _StreamHandle(frame["sid"], self.send_event)
        self._register(frame["sid"], handle)
        return bool(self.engine.import_sequence(snap, handle=handle))

    def op_export_prefix(self, frame):
        return self.engine.export_prefix_pages(frame["tokens"])

    def op_import_prefix(self, frame):
        return self.engine.import_prefix_pages(frame["payload"])

    def op_import_prefix_from(self, frame):
        """P2P adoption: dial the HOLDER's data port directly, fetch
        + decode the warm prefix, install it locally — the page bytes
        never touch the router's socket.  The dial runs under this
        worker's own fault plan (point "fetch_prefix" / "resp"), so
        the chaos matrix covers the data socket too; a "kill" rule
        SIGKILLs this worker mid-transfer, exactly like the RPC
        channel's kill faults.  Typed failures ride the reply wire
        back and degrade fleet-side to the cold-prefill ladder."""
        from .data_plane import fetch_prefix_pages

        payload, wire, raw = fetch_prefix_pages(
            tuple(frame["addr"]), frame["tokens"],
            timeout_s=float(frame.get("timeout_s", 15.0)),
            levels=frame.get("levels") or ("raw",),
            chunk_bytes=self.chunk_bytes, faults=self.faults,
            kill_cb=self.kill)
        added = (0 if payload is None
                 else self.engine.import_prefix_pages(payload))
        return {"added": added, "wire_bytes": wire, "raw_bytes": raw}

    def op_flush_prefix(self, frame):
        return self.engine.cache.flush_prefix_cache()

    def op_reset_stats(self, frame):
        self.registry.reset_all()
        return True

    def op_ping(self, frame):
        return True

    def op_chaos_arm(self, frame):
        """Parent plan arm()/disarm() mirrored to our child half."""
        if self.faults is not None:
            if frame.get("armed"):
                self.faults.armed = True
            else:
                self.faults.armed = False
        return True

    def op_chaos_stall(self, frame):
        """Chaos-injection hook (serving/disagg/faults.py "stall"):
        WEDGE the engine — a daemon thread holds the step lock for
        `stall_s` — while this serve loop and the heartbeat thread
        keep running.  The replica looks alive (fresh heartbeats, RPC
        replies) but makes no decode progress: the failure mode only
        the parent's wedge watchdog can catch."""
        stall_s = float(frame.get("stall_s", 30.0))
        lock = self.engine._lock

        def hold():
            with lock:
                time.sleep(stall_s)

        threading.Thread(target=hold, name="chaos-stall",
                         daemon=True).start()
        return True

    def op_shutdown(self, frame):
        self._stop_hb.set()
        if self.data_server is not None:
            self.data_server.stop()
        if self.engine is not None:
            self.engine.shutdown()
        return True

    # --------------------------- loop -------------------------------
    def serve(self):
        from ..admission import ServingError
        from .rpc import ChannelClosed

        while True:
            try:
                frames = self.recv()
            except (ChannelClosed, OSError, Exception):  # noqa: B014
                # parent died, or a poisoned inbound frame (chaos
                # corrupt/truncate, real damage) desynced the channel:
                # either way there is nothing left to serve — shut
                # down cleanly, the parent's EOF detection takes over
                self._stop_hb.set()
                if self.engine is not None:
                    self.engine.shutdown()
                return
            stop = False
            for frame in frames:
                if self._serve_one(frame, ServingError):
                    stop = True
            if stop:
                return

    def _serve_one(self, frame, serving_error):
        """Handle one inbound op frame; True means exit the loop."""
        rid = frame.get("rid")
        op = frame.get("op")
        try:
            handler = getattr(self, f"op_{op}", None)
            if handler is None:
                # a frame that decoded but names no op (garbage
                # that survived unpickling) must answer typed, not
                # crash the worker on an AttributeError
                raise serving_error(f"unknown op {op!r}")
            result = handler(frame)
            reply = {"resp": rid, "ok": result}
        except Exception as e:   # noqa: BLE001 — typed errors ride
            reply = {"resp": rid, "error": e}   # the wire back
        if rid is not None:
            try:
                self._send_plain(reply)
            except OSError:
                return True   # parent gone
            except Exception:   # noqa: BLE001 — unpicklable payload:
                try:            # degrade to a typed, serializable error
                    self._send_plain(
                        {"resp": rid, "error": serving_error(
                            f"op {op!r} reply not serializable: "
                            f"{traceback.format_exc(limit=3)}")})
                except OSError:
                    return True
        return op == "shutdown"


def main(argv):
    if argv and argv[0] == "--connect":
        host, _, port = argv[1].rpartition(":")
        sock = socket.create_connection((host, int(port)), timeout=30)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(None)
    else:
        sock = socket.socket(fileno=int(argv[0]))
    _Worker(sock).serve()


if __name__ == "__main__":
    main(sys.argv[1:])
