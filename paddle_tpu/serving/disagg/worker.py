"""Replica worker process — the subprocess half of SubprocTransport.

``python -m paddle_tpu.serving.disagg.worker <fd>`` builds ONE
single-process GenerationEngine from the pickled build spec (first RPC
frame) and serves the transport RPC contract over the inherited
socketpair fd: submit streams tokens back as events, evacuate ships
cold requests and live sequence snapshots for migration, a heartbeat
thread reports load + prefix register/evict deltas every
``HEARTBEAT_S``.  The engine steps itself on its background worker
thread; nothing here touches jax.distributed — a replica is exactly
the single-process engine the CPU oracle runs, behind a socket.

Frame schema: docs/SERVING.md "Disaggregated fleet".
"""
import socket
import sys
import threading
import time
import traceback


class _StreamHandle:
    """Engine-side handle that RELAYS the stream over the socket: the
    duck-typed surface GenerationEngine drives (_push_token/_finish/
    set_exception/done + the stamp attributes), writing one event
    frame per transition.  The parent-side transport reassembles the
    client's GenerationHandle from these frames."""

    __slots__ = ("sid", "_sock", "_wlock", "submitted_s",
                 "first_token_s", "prefix_hit_tokens", "_done", "_n")

    def __init__(self, sid, sock, wlock):
        self.sid = sid
        self._sock = sock
        self._wlock = wlock
        self.submitted_s = None
        self.first_token_s = None
        self.prefix_hit_tokens = None
        self._done = False
        self._n = 0   # per-stream event index: the parent dedups
        # duplicated frames and detects holes from dropped ones

    def _send(self, obj):
        from .rpc import send_frame

        try:
            send_frame(self._sock, obj, self._wlock)
        except OSError:
            pass   # parent gone; this process is about to die anyway

    def _push_token(self, token):
        if self.first_token_s is None:
            self.first_token_s = time.monotonic()
        n = self._n
        self._n += 1
        self._send({"ev": "token", "sid": self.sid, "t": int(token),
                    "n": n})

    def _finish(self, result):
        if self._done:
            return
        self._done = True
        self._send({"ev": "done", "sid": self.sid,
                    "prefix_hit": self.prefix_hit_tokens,
                    "result": {"token_ids": list(result.token_ids),
                               "finish_reason": result.finish_reason,
                               "prompt_len": result.prompt_len,
                               "preemptions": result.preemptions}})

    def set_exception(self, exc):
        if self._done:
            return
        self._done = True
        self._send({"ev": "error", "sid": self.sid, "exc": exc})

    def done(self):
        return self._done


class _Worker:
    def __init__(self, sock):
        self.sock = sock
        self.wlock = threading.Lock()
        self.engine = None
        self.registry = None
        self._stop_hb = threading.Event()

    # --------------------------- ops --------------------------------
    def op_build(self, frame):
        from ...generation.engine import GenerationEngine
        from ...generation.metrics import GenerationMetrics
        from ...profiler.monitor import StatRegistry
        from .transport import HEARTBEAT_S

        self.registry = StatRegistry()
        self.engine = GenerationEngine(
            frame["model"], frame["config"],
            metrics=GenerationMetrics(registry=self.registry),
            start=True)
        if self.engine.prefix_cache_enabled:
            self.engine.cache.enable_prefix_deltas()
        threading.Thread(target=self._heartbeat, args=(HEARTBEAT_S,),
                         name="replica-heartbeat", daemon=True).start()
        return self.engine.describe()

    def _heartbeat(self, interval):
        from .rpc import send_frame

        while not self._stop_hb.wait(interval):
            try:
                deltas = self.engine.cache.take_prefix_deltas()
                # "seq" is the engine's step-progress stamp: this
                # thread deliberately shares NO lock with the step
                # loop, so a wedged engine keeps heartbeating a FROZEN
                # seq while reporting work — exactly the signature the
                # parent's wedge watchdog kills on
                send_frame(self.sock,
                           {"ev": "hb", "load": self.engine.load_info(),
                            "seq": self.engine.step_seq,
                            "in_step": self.engine.in_step,
                            "deltas": deltas}, self.wlock)
            except OSError:
                return
            except Exception:   # noqa: BLE001 — a heartbeat must never
                pass            # kill the worker; the next beat retries

    def op_submit(self, frame):
        handle = _StreamHandle(frame["sid"], self.sock, self.wlock)
        self.engine.submit(frame["prompt"], handle=handle,
                           **frame["kwargs"])
        return True

    def op_load(self, frame):
        return self.engine.load_info()

    def op_stats(self, frame):
        return {
            "generation":
                self.registry.stats_snapshot("generation.")["stats"],
            "cache": self.engine.cache.stats(),
        }

    def op_evacuate(self, frame):
        # the same drain state machine as InprocTransport.drain —
        # engine.drain_work, so the oracle and the process boundary
        # cannot diverge (the child's engine always runs its worker
        # thread, so drain_work's wait loop just sleeps here)
        cold, live_snaps = self.engine.drain_work(
            migrate=frame["migrate"], live=frame["live"],
            timeout=frame["timeout"])
        out = {"cold": [], "live": []}
        for req, emitted in cold:
            out["cold"].append({
                "sid": req.future.sid,
                "prompt": list(req.prompt),
                "max_new_tokens": req.max_new_tokens,
                "sampling": req.params,
                "stop_tokens": tuple(req.stop_tokens),
                "deadline": req.deadline,
                "emitted": int(emitted),
            })
        for snap in live_snaps:
            snap["sid"] = snap.pop("future").sid
            out["live"].append(snap)
        return out

    def op_import_seq(self, frame):
        snap = frame["snap"]
        handle = _StreamHandle(frame["sid"], self.sock, self.wlock)
        return bool(self.engine.import_sequence(snap, handle=handle))

    def op_export_prefix(self, frame):
        return self.engine.export_prefix_pages(frame["tokens"])

    def op_import_prefix(self, frame):
        return self.engine.import_prefix_pages(frame["payload"])

    def op_flush_prefix(self, frame):
        return self.engine.cache.flush_prefix_cache()

    def op_reset_stats(self, frame):
        self.registry.reset_all()
        return True

    def op_ping(self, frame):
        return True

    def op_chaos_stall(self, frame):
        """Chaos-injection hook (serving/disagg/faults.py "stall"):
        WEDGE the engine — a daemon thread holds the step lock for
        `stall_s` — while this serve loop and the heartbeat thread
        keep running.  The replica looks alive (fresh heartbeats, RPC
        replies) but makes no decode progress: the failure mode only
        the parent's wedge watchdog can catch."""
        stall_s = float(frame.get("stall_s", 30.0))
        lock = self.engine._lock

        def hold():
            with lock:
                time.sleep(stall_s)

        threading.Thread(target=hold, name="chaos-stall",
                         daemon=True).start()
        return True

    def op_shutdown(self, frame):
        self._stop_hb.set()
        if self.engine is not None:
            self.engine.shutdown()
        return True

    # --------------------------- loop -------------------------------
    def serve(self):
        from ..admission import ServingError
        from .rpc import ChannelClosed, recv_frame, send_frame

        while True:
            try:
                frame = recv_frame(self.sock)
            except (ChannelClosed, OSError):
                # parent died: nothing to stream to — exit cleanly
                self._stop_hb.set()
                if self.engine is not None:
                    self.engine.shutdown()
                return
            rid = frame.get("rid")
            op = frame.get("op")
            try:
                handler = getattr(self, f"op_{op}", None)
                if handler is None:
                    # a frame that decoded but names no op (garbage
                    # that survived unpickling) must answer typed, not
                    # crash the worker on an AttributeError
                    raise ServingError(f"unknown op {op!r}")
                result = handler(frame)
                reply = {"resp": rid, "ok": result}
            except Exception as e:   # noqa: BLE001 — typed errors ride
                reply = {"resp": rid, "error": e}   # the wire back
            try:
                send_frame(self.sock, reply, self.wlock)
            except OSError:
                return   # parent gone
            except Exception:   # noqa: BLE001 — unpicklable payload:
                try:            # degrade to a typed, serializable error
                    send_frame(self.sock,
                               {"resp": rid, "error": ServingError(
                                   f"op {op!r} reply not serializable: "
                                   f"{traceback.format_exc(limit=3)}")},
                               self.wlock)
                except OSError:
                    return
            if op == "shutdown":
                return


def main(fd):
    sock = socket.socket(fileno=fd)
    _Worker(sock).serve()


if __name__ == "__main__":
    main(int(sys.argv[1]))
