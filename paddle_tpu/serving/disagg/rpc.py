"""Length-prefixed pickled frames over a UNIX socketpair — the wire
format both halves of the replica process boundary speak.

One frame is ``>I`` payload length + a pickled Python object.  Every
RPC request carries ``{"op": ..., "rid": n}`` and is answered by
exactly one ``{"resp": n, "ok": value}`` or ``{"resp": n, "error":
exc}``; everything else on the wire is an EVENT frame (``{"ev": ...}``:
streamed tokens, completions, heartbeats) that needs no reply.  The
schema table lives in docs/SERVING.md "Disaggregated fleet".

Pickle is safe here because both endpoints are the same trusted
codebase on the same machine talking over an inherited socketpair —
this is a process boundary, not a network protocol.
"""
import pickle
import struct

_HEADER = struct.Struct(">I")
# a frame larger than this is a protocol bug, not a payload (page
# exports are the biggest legitimate frames — tens of MB at most)
MAX_FRAME_BYTES = 1 << 30


class ChannelClosed(EOFError):
    """The peer closed the socket (process exit or crash)."""


def send_frame(sock, obj, lock=None):
    """Pickle `obj` and write one length-prefixed frame.  `lock`
    serializes concurrent writers (engine worker thread streaming
    tokens vs the heartbeat thread vs RPC replies)."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    if len(payload) > MAX_FRAME_BYTES:
        raise ValueError(f"frame of {len(payload)} bytes exceeds "
                         f"MAX_FRAME_BYTES={MAX_FRAME_BYTES}")
    data = _HEADER.pack(len(payload)) + payload
    if lock is not None:
        with lock:
            sock.sendall(data)
    else:
        sock.sendall(data)


def _recv_exact(sock, n):
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ChannelClosed("peer closed the channel")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_frame(sock):
    """Read one frame; raises ChannelClosed on EOF (peer death)."""
    (length,) = _HEADER.unpack(_recv_exact(sock, _HEADER.size))
    if length > MAX_FRAME_BYTES:
        raise ValueError(f"incoming frame claims {length} bytes "
                         f"(> MAX_FRAME_BYTES) — corrupt stream")
    return pickle.loads(_recv_exact(sock, length))
