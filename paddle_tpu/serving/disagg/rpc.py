"""Length-prefixed pickled frames over a byte-stream socket — the wire
format both halves of the replica process boundary speak (UNIX
socketpair for SubprocTransport, a TCP connection for TcpTransport).

One frame is ``>I`` payload length + a pickled Python object.  Every
RPC request carries ``{"op": ..., "rid": n}`` and is answered by
exactly one ``{"resp": n, "ok": value}`` or ``{"resp": n, "error":
exc}``; everything else on the wire is an EVENT frame (``{"ev": ...}``:
streamed tokens, completions, heartbeats) that needs no reply.  The
schema table lives in docs/SERVING.md "Disaggregated fleet".

CHUNKED payloads: a logical frame whose pickled payload exceeds
`chunk_bytes` is fragmented into ``{"frag": fid, "i": k, "of": n,
"data": bytes}`` carrier frames, each a small frame of its own and
each written under the socket lock INDIVIDUALLY — so a multi-MB page
export or migration snapshot never holds the write lock for one giant
sendall, and heartbeats / token events interleave between fragments
instead of queueing behind them.  The receive side reassembles by
fragment id (``FrameAssembler``); fragments from concurrent senders
interleave safely because each carries its own fid.  Per-frame bytes
on the wire are therefore bounded by ``chunk_bytes`` + the carrier
overhead, whatever the logical payload size.

Pickle is safe here because both endpoints are the same trusted
codebase talking over a channel the parent created (an inherited
socketpair, or a TCP connection the parent listened for and handed to
the child it spawned) — this is a process boundary under one
operator, not an open network protocol.
"""
import itertools
import os
import pickle
import struct

_HEADER = struct.Struct(">I")
# a frame larger than this is a protocol bug, not a payload (page
# exports are the biggest legitimate frames — tens of MB at most)
MAX_FRAME_BYTES = 1 << 30
# default fragmentation bound for chunk-capable senders: big enough
# that RPC chatter never fragments, small enough that one fragment's
# sendall cannot stall heartbeats behind a 100k-token page export
DEFAULT_CHUNK_BYTES = 256 << 10

# fragment ids are per-process unique (pid folded in so both halves
# of a channel can fragment concurrently without colliding)
_frag_ids = itertools.count(1)


class ChannelClosed(EOFError):
    """The peer closed the socket (process exit or crash)."""


def _send_one(sock, payload, lock):
    if len(payload) > MAX_FRAME_BYTES:
        raise ValueError(f"frame of {len(payload)} bytes exceeds "
                         f"MAX_FRAME_BYTES={MAX_FRAME_BYTES}")
    data = _HEADER.pack(len(payload)) + payload
    if lock is not None:
        with lock:
            sock.sendall(data)
    else:
        sock.sendall(data)


def send_frame(sock, obj, lock=None, chunk_bytes=None):
    """Pickle `obj` and write one logical frame.  `lock` serializes
    concurrent writers (engine worker thread streaming tokens vs the
    heartbeat thread vs RPC replies).  With `chunk_bytes`, a payload
    past the bound ships as fragment carrier frames instead — each
    written under the lock individually, so other writers interleave
    mid-payload (the receiver must run a FrameAssembler)."""
    payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    if chunk_bytes is None or len(payload) <= int(chunk_bytes):
        _send_one(sock, payload, lock)
        return
    chunk = int(chunk_bytes)
    fid = (os.getpid(), next(_frag_ids))
    parts = range(0, len(payload), chunk)
    total = len(parts)
    for k, off in enumerate(parts):
        _send_one(sock, pickle.dumps(
            {"frag": fid, "i": k, "of": total,
             "data": payload[off:off + chunk]},
            protocol=pickle.HIGHEST_PROTOCOL), lock)


def _recv_exact(sock, n):
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ChannelClosed("peer closed the channel")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_frame(sock):
    """Read one WIRE frame; raises ChannelClosed on EOF (peer death).
    May return a fragment carrier — chunk-capable receivers go through
    FrameAssembler.recv, which reassembles logical frames."""
    (length,) = _HEADER.unpack(_recv_exact(sock, _HEADER.size))
    if length > MAX_FRAME_BYTES:
        raise ValueError(f"incoming frame claims {length} bytes "
                         f"(> MAX_FRAME_BYTES) — corrupt stream")
    return pickle.loads(_recv_exact(sock, length))


class FrameAssembler:
    """Reassembles fragmented logical frames on one channel's receive
    side.  Each channel has exactly ONE reader thread, so no locking;
    fragments of different fids interleave freely (concurrent senders),
    fragments of one fid arrive in order (one sender wrote them FIFO
    to one socket).  A missing or out-of-order fragment within a fid
    is a desynced channel — typed ValueError, the poisoned-channel
    path, exactly like a corrupt length header."""

    def __init__(self):
        self._parts = {}   # fid -> [data, ...]

    def feed(self, frame):
        """One wire frame in; the completed logical frame out, or None
        while a fragmented payload is still accumulating."""
        if not (isinstance(frame, dict) and "frag" in frame):
            return frame
        fid, i, of = frame["frag"], frame["i"], frame["of"]
        parts = self._parts.setdefault(fid, [])
        if i != len(parts) or not (0 < of <= MAX_FRAME_BYTES):
            self._parts.pop(fid, None)
            raise ValueError(
                f"fragment {i}/{of} of {fid!r} arrived out of order "
                f"(have {len(parts)}) — corrupt stream")
        parts.append(frame["data"])
        if len(parts) < of:
            return None
        del self._parts[fid]
        return pickle.loads(b"".join(parts))

    def recv(self, sock):
        """Read wire frames until one LOGICAL frame completes."""
        while True:
            out = self.feed(recv_frame(sock))
            if out is not None:
                return out
