"""The chaos soak drill: a seeded fault schedule over a multi-replica
fleet driving concurrent requests, with the acceptance invariants
checked in one place.

The drill is the library half shared by tests/test_chaos.py,
tools/chaos_drill.py (CLI) and tools/gen_bench.py --chaos (the bench
cell): build a fault-free ORACLE run first (one inproc engine, seeded
sampling — the reference streams), then run the same workload through
a subprocess fleet whose RPC codecs are wrapped by seeded FaultPlans,
and assert:

1. NO HANG: every handle resolves — tokens or a typed ServingError —
   inside the global watchdog budget;
2. TOKEN IDENTITY: every stream that resolved with a result matches
   the fault-free oracle exactly (seeded sampling + the remigration
   ladder make this a hard invariant, not a hope), and the STREAMED
   token sequence equals the result (the ordered stream protocol:
   no dupes, no holes, no reordering);
3. NO LEAKS: after the survivors drain and every prefix cache
   flushes, every replica's pool reads all-free (pages_in_use == 0).

Determinism: the fault schedule is a pure function of (seed, traffic
order).  Which requests ride out a fault via remigration vs resolve
typed can depend on timing, but the three invariants above hold on
every run — that is what "chaos scenario as unit test" means here.

Docs: docs/SERVING.md "Failure model".
"""
import random
import threading
import time

import numpy as np

from ...generation import GenerationConfig, GenerationEngine
from ...generation.sampling import SamplingParams
from ..admission import ServingError
from .faults import FATAL_KINDS, FaultPlan, FaultRule

# the named protocol points a full-matrix schedule covers, per
# direction (docs/SERVING.md "Failure model" fault taxonomy)
SEND_POINTS = ("submit", "stats", "export_prefix", "import_seq")
RECV_POINTS = ("token", "done", "hb", "resp")


def full_matrix_plans(seed, names, kinds=None, spare=None):
    """A seeded schedule exercising every fault kind at every named
    injection point, spread over the fleet — with `spare` (default:
    the first name) kept FREE of fatal kinds (kill/stall/corrupt/
    truncate), so surviving streams always have somewhere to land.
    Returns ``{name: FaultPlan}``."""
    rng = random.Random(seed)
    names = list(names)
    if len(names) < 2:
        raise ValueError("a chaos matrix needs >= 2 replicas "
                         "(one stays fatal-free)")
    spare = names[0] if spare is None else spare
    fatal_hosts = [n for n in names if n != spare]
    rules = {n: [] for n in names}
    kinds = tuple(kinds) if kinds else (
        "drop", "delay", "dup", "corrupt", "truncate", "kill", "stall")
    benign_hosts = list(names)
    for kind in kinds:
        hosts = fatal_hosts if kind in FATAL_KINDS else benign_hosts
        points = ([("send", p) for p in SEND_POINTS]
                  + [("recv", p) for p in RECV_POINTS])
        if kind in FATAL_KINDS:
            # one fatal firing per replica is one death: spreading a
            # fatal kind over every point would just kill the same
            # replica at its first hit — pick ONE point per fatal kind
            points = [points[rng.randrange(len(points))]]
        for direction, point in points:
            host = hosts[rng.randrange(len(hosts))]
            rules[host].append(FaultRule(
                point, kind, direction=direction,
                after=rng.randrange(3), count=1,
                delay_s=0.02 + 0.05 * rng.random(),
                stall_s=30.0))
    return {n: FaultPlan(rs, seed=seed + i)
            for i, (n, rs) in enumerate(rules.items())}


def kill_stall_plans(seed, names):
    """The gen_bench --chaos schedule: one replica killed mid-stream,
    one stalled (wedge-watchdog fodder), the first replica clean."""
    rng = random.Random(seed)
    names = list(names)
    if len(names) < 2:
        raise ValueError("need >= 2 replicas")
    plans = {}
    victims = [n for n in names[1:]]
    kill_host = victims[rng.randrange(len(victims))]
    stall_host = next((n for n in victims if n != kill_host),
                      kill_host)
    plans[kill_host] = FaultPlan(
        [FaultRule("token", "kill", direction="recv",
                   after=2 + rng.randrange(3))], seed=seed)
    if stall_host != kill_host:
        plans[stall_host] = FaultPlan(
            [FaultRule("submit", "stall", direction="send",
                       after=1, stall_s=60.0)], seed=seed + 1)
    return plans


def _oracle_streams(model, cfg_kw, prompts, sampling, new_tokens):
    """The fault-free reference: one inproc engine, same seeded
    workload, batched (batched == sequential is the repo-wide oracle
    contract, so this is THE reference stream set)."""
    eng = GenerationEngine(model, GenerationConfig(**cfg_kw),
                           start=False)
    handles = [eng.submit(p, max_new_tokens=new_tokens, sampling=sp)
               for p, sp in zip(prompts, sampling)]
    eng.run_until_idle()
    out = [h.result(timeout=30).token_ids for h in handles]
    eng.shutdown()
    return out


def chaos_drill(model, *, seed=0, n_replicas=3, n_requests=8,
                prompt_tokens=24, new_tokens=10, vocab=None,
                plans=None, engine_kw=None, fleet_kw=None,
                watchdog_s=120.0, wedge_after_s=2.5,
                orphan_grace_s=2.0, restart_dead=False):
    """Run one seeded chaos soak; returns the report dict (raises
    AssertionError on an invariant breach — a hung stream, a stream
    diverging from the oracle, or leaked pages).

    `plans`: {replica_name: FaultPlan} (default: the full matrix over
    seed).  `engine_kw`: per-replica GenerationConfig overrides (pool
    layout / kv_dtype cells).  `fleet_kw`: FleetConfig overrides (the
    drill defaults to tight chaos-grade deadlines).  `restart_dead`
    additionally restarts every dead replica at the end (exercises
    the respawn-backoff path) before the leak check.

    Two phases: a WARMUP wave (fault plans disarmed, watchdog
    thresholds relaxed) pays every replica's compile wall — a 10 s
    first-step jit on a loaded CPU box must not read as a wedge —
    then the plans arm, the wedge/orphan clocks tighten to
    `wedge_after_s`/`orphan_grace_s`, and the measured chaos wave
    runs against steady-state replicas."""
    from ...profiler.monitor import StatRegistry
    from .. import fleet as fleet_mod
    from ..fleet import FleetConfig, FleetRouter, ReplicaSpec

    reg = StatRegistry.instance()

    def reset_fleet_stats():
        for name in list(reg.stats()):
            if name.startswith(fleet_mod.PREFIX):
                reg.get_stat(name).reset()

    # the report reads the global fleet.* registry: zero it so one
    # drill's counters never smear into the next cell's report
    reset_fleet_stats()
    rng = np.random.default_rng(seed)
    vocab = int(vocab if vocab is not None
                else getattr(model, "vocab_size", 48))
    half = max(2, vocab // 2)
    names = [f"c{i}" for i in range(n_replicas)]
    prompts, sampling = [], []
    for i in range(n_requests):
        # measured prompts draw from the LOWER vocab half; the warmup
        # wave uses the upper half, so nothing it caches can warm them
        prompts.append(rng.integers(
            0, half, int(prompt_tokens)).tolist())
        # mixed batch: half greedy, half seeded stochastic — both must
        # replay identically through every remigration
        sampling.append(SamplingParams() if i % 2 == 0 else
                        SamplingParams(temperature=0.9, top_k=8,
                                       seed=1000 + i))
    cfg_kw = dict(max_decode_slots=4, page_size=4,
                  num_pages=(int(prompt_tokens) + int(new_tokens))
                  * n_requests // 4 + 4 * n_requests,
                  queue_depth=n_requests * 2 + 4, prefix_cache=True)
    cfg_kw.update(engine_kw or {})
    oracle = _oracle_streams(model, cfg_kw, prompts, sampling,
                             new_tokens)

    plans = plans if plans is not None else full_matrix_plans(
        seed, names)
    for plan in plans.values():
        plan.disarm()   # nothing fires until the fleet is warm
    fl_kw = dict(seed=seed, transport="proc", rpc_timeout_s=2.0,
                 rpc_retries=2, rpc_backoff_s=0.02,
                 heartbeat_dead_after=5.0,
                 # relaxed until the warmup wave paid the compiles
                 wedge_after_s=60.0, orphan_grace_s=60.0,
                 breaker_threshold=2,
                 breaker_cooldown_s=0.25, respawn_backoff_s=0.05,
                 fault_plans=plans)
    fl_kw.update(fleet_kw or {})
    specs = [ReplicaSpec(n, model, GenerationConfig(**cfg_kw))
             for n in names]
    fl = FleetRouter(specs, FleetConfig(**fl_kw))
    try:
        # ---- warmup: every replica pays its prefill/decode shape
        # warm-up on upper-half-vocab traffic, at the FULL concurrent
        # batch the chaos wave (and its remigration surges — a crash can
        # dump every stream on one survivor) will drive, so no
        # first-big-batch step lands inside the tightened wedge clock.
        # Session pins force one full wave per replica; then the caches
        # flush and the counters reset — the chaos wave starts
        # steady-state with clean books.
        warm_batch = min(n_requests,
                         int(cfg_kw.get("max_decode_slots", 4)))
        warm = []
        for i, name in enumerate(names):
            for j in range(warm_batch):
                sess = f"__warm{i}_{j}"
                fl._sessions[sess] = name
                # the SAME greedy/stochastic mix as the measured wave:
                # a mixed decode batch is its own shape family on the
                # eager path, and an unwarmed one compiles for seconds —
                # indistinguishable from a wedge to any finite clock
                warm_sp = (SamplingParams() if j % 2 == 0 else
                           SamplingParams(temperature=0.9, top_k=8,
                                          seed=7000 + i * warm_batch + j))
                warm.append((sess, fl.submit(
                    rng.integers(half, vocab, int(prompt_tokens)).tolist(),
                    max_new_tokens=new_tokens, sampling=warm_sp,
                    session=sess)))
        for sess, h in warm:
            h.result(timeout=watchdog_s)
            fl._sessions.pop(sess, None)
        for name, rep in fl._replicas.items():
            rep.transport.flush_prefix()
            rep.transport.take_prefix_deltas()
            fl._page_index.drop_replica(name)
        reset_fleet_stats()
        fl.config.wedge_after_s = float(wedge_after_s)
        fl.config.orphan_grace_s = float(orphan_grace_s)
        for plan in plans.values():
            plan.arm()
        t0 = time.monotonic()
        arrivals = [[] for _ in range(n_requests)]
        streamed = [None] * n_requests
        outcomes = [None] * n_requests   # "ok" | exception | "hung"
        handles = [None] * n_requests

        def consume(i, h):
            toks = []
            try:
                for t in h.tokens(timeout=watchdog_s):
                    arrivals[i].append(time.monotonic())
                    toks.append(t)
                streamed[i] = toks
                outcomes[i] = "ok"
            except ServingError as e:
                outcomes[i] = e
            except Exception as e:   # noqa: BLE001 — anything else is a
                outcomes[i] = e      # drill failure, reported not raised

        threads = []
        for i, (p, sp) in enumerate(zip(prompts, sampling)):
            try:
                h = fl.submit(p, max_new_tokens=new_tokens, sampling=sp)
            except ServingError as e:
                outcomes[i] = e
                continue
            handles[i] = h
            th = threading.Thread(target=consume, args=(i, h), daemon=True)
            th.start()
            threads.append(th)
            time.sleep(0.01)   # deterministic-ish traffic order for the
            # per-rule frame counters without serializing the streams
        deadline = time.monotonic() + watchdog_s
        for th in threads:
            th.join(timeout=max(0.1, deadline - time.monotonic()))
        hung = sum(1 for i, th in enumerate(threads) if th.is_alive())
        recovery_wall = time.monotonic() - t0

        # ---- invariant 1: no hangs (tokens or typed error, nothing else)
        assert hung == 0, f"{hung} streams hung past the {watchdog_s}s " \
                          f"global watchdog"
        # ---- invariant 2: surviving streams token-identical to oracle,
        # and the streamed sequence IS the result (ordered protocol)
        identical = 0
        mismatches = []
        for i, out in enumerate(outcomes):
            if out != "ok":
                continue
            result = handles[i].result(timeout=1).token_ids
            if result != oracle[i]:
                mismatches.append((i, "result", result, oracle[i]))
            elif streamed[i] != result:
                mismatches.append((i, "stream", streamed[i], result))
            else:
                identical += 1
        assert not mismatches, f"streams diverged from the fault-free " \
                               f"oracle: {mismatches[:2]}"
        # ---- invariant 3: drained + flushed == all-free, no page leaks
        if restart_dead:
            for name, rep in fl._replicas.items():
                if rep.state == "dead":
                    try:
                        fl.restart(name, wait=True)
                    except ServingError:
                        pass   # crash-loop cap is a legal outcome
        fl.run_until_idle()
        leaked = 0
        for name, rep in fl._replicas.items():
            if rep.state != "serving" or not rep.transport.alive():
                continue
            try:
                rep.transport.flush_prefix()
                stats = rep.transport.stats()
            except ServingError:
                continue   # died/wedged at the very end: nothing to leak
            leaked += int(stats.get("cache", {}).get("pages_in_use", 0))
        assert leaked == 0, f"{leaked} pages leaked after drain + flush"

        snap = fl.stats_snapshot()["fleet"]
        # per-stream inter-arrival gaps ONLY — diffing a cross-stream
        # concatenation would pollute the percentiles with meaningless
        # (often negative) boundary deltas between unrelated streams
        per_stream = [np.diff(np.asarray(a)) for a in arrivals
                      if len(a) > 1]
        gaps = (np.concatenate(per_stream) if per_stream
                else np.zeros(0))
        fired = {n: p.fired_kinds() for n, p in plans.items()}
        report = {
            "seed": seed,
            "replicas": n_replicas,
            "requests": n_requests,
            "resolved_ok": sum(1 for o in outcomes if o == "ok"),
            "resolved_typed_error":
                sum(1 for o in outcomes
                    if o is not None and o != "ok"),
            "hung": hung,
            "token_identical": identical,
            "leaked_pages": leaked,
            "faults_fired": fired,
            "recovery_wall_s": round(recovery_wall, 3),
            "stream_gap_p50_s": round(float(np.percentile(gaps, 50)), 4)
                if gaps.size else None,
            "stream_gap_p95_s": round(float(np.percentile(gaps, 95)), 4)
                if gaps.size else None,
            "replica_dead_total": snap.get("fleet.replica_dead_total", 0),
            "wedge_kill_total": snap.get("fleet.wedge_kill_total", 0),
            "breaker_open_total": snap.get("fleet.breaker_open_total", 0),
            "replica_timeout_total":
                snap.get("fleet.replica_timeout_total", 0),
            "orphan_remigrated_total":
                snap.get("fleet.orphan_remigrated_total", 0),
            "migrated_total": snap.get("fleet.migrated_total", 0),
            "migrated_replay_tokens":
                snap.get("fleet.migrated_replay_tokens", 0),
            "live_migrated_total":
                snap.get("fleet.live_migrated_total", 0),
        }
        return report
    finally:
        # shutdown is idempotent: an invariant breach or a
        # mid-drill exception must not leak worker processes
        fl.shutdown()


__all__ = ["chaos_drill", "full_matrix_plans", "kill_stall_plans",
           "SEND_POINTS", "RECV_POINTS"]
