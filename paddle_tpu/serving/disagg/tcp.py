"""TcpTransport: the replica process boundary over a real TCP socket.

The cross-host rung of the transport ladder.  Everything above the
socket is ``SubprocTransport`` verbatim — length-prefixed pickled
frames, chunked payloads, the in-flight ledger, heartbeat liveness,
RpcPolicy deadlines/retries, FaultPlan chaos wrapping — only the
channel bring-up differs:

1. ``ReplicaListener`` binds ``(spec.host or 127.0.0.1, spec.port or
   ephemeral)`` and listens (port-in-use raises the typed
   ``TcpConnectError`` immediately, not an EADDRINUSE traceback five
   frames deep).
2. The worker is spawned with ``--connect host:port`` instead of an
   inherited socketpair fd — the ONLY part of the handshake that
   assumes one host is the ``subprocess.Popen`` itself, and that seam
   (``_spawn_worker``) is exactly where a remote launcher (ssh, a
   cluster scheduler) slots in.
3. ``accept()`` waits for the dial-back under a bounded deadline,
   polling the child so a worker that dies pre-connect fails fast and
   typed instead of eating the whole accept window.

The accepted socket gets ``TCP_NODELAY`` — the protocol is many small
latency-sensitive frames (tokens, heartbeats, RPC replies) and
Nagle's algorithm would batch exactly the frames we care about.

Docs: docs/SERVING.md "Cross-host fleet".
"""
import socket
import subprocess
import sys
import time

from ..admission import ServingError
from .transport import SubprocTransport


class TcpConnectError(ServingError):
    """TCP channel bring-up failed: port in use, worker died before
    dialing back, or the accept deadline passed."""


class ReplicaListener:
    """One accept()-once listener for a replica's dial-back.

    Binding is split from accepting so the parent can learn the
    EPHEMERAL port (bind to port 0, read it back) BEFORE spawning the
    worker that must dial it."""

    def __init__(self, host="127.0.0.1", port=0, backlog=1):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        # no SO_REUSEADDR on an explicit port: two replicas told to
        # share a port is a config bug that must fail loud, not a race
        # one of them silently wins
        try:
            self._sock.bind((host, int(port)))
            self._sock.listen(backlog)
        except OSError as e:
            self._sock.close()
            raise TcpConnectError(
                f"cannot listen on {host}:{port} for replica "
                f"dial-back: {e}") from e

    @property
    def address(self):
        """``(host, port)`` actually bound — the ephemeral port the
        worker must dial."""
        return self._sock.getsockname()[:2]

    def accept(self, timeout, proc=None):
        """Wait for the worker's dial-back; returns the connected
        socket.  Bounded by `timeout`, and polls `proc` so a child
        that died before connecting raises typed immediately."""
        deadline = time.monotonic() + float(timeout)
        self._sock.settimeout(0.2)
        while True:
            if proc is not None and proc.poll() is not None:
                raise TcpConnectError(
                    f"worker exited (rc={proc.returncode}) before "
                    f"dialing back to {self.address}")
            try:
                conn, _peer = self._sock.accept()
                return conn
            except socket.timeout:
                if time.monotonic() > deadline:
                    raise TcpConnectError(
                        f"no dial-back on {self.address} within "
                        f"{float(timeout):.1f}s") from None

    def close(self):
        try:
            self._sock.close()
        except OSError:
            pass


class TcpTransport(SubprocTransport):
    """SubprocTransport whose channel is a TCP connection the spawned
    worker dials back to — the cross-host replica path, with the
    socketpair fleet's entire failure model riding along unchanged."""

    kind = "tcp"
    CONNECT_TIMEOUT_S = 60.0

    def _spawn_worker(self, spec, env, host, port):
        """The one genuinely host-local step.  A remote launcher
        overrides this to start the worker on another machine — the
        returned object only needs poll()/kill()/wait()."""
        return subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.serving.disagg.worker",
             "--connect", f"{host}:{port}"], env=env)

    def _open_channel(self, spec, env):
        listener = ReplicaListener(
            getattr(spec, "host", None) or "127.0.0.1",
            int(getattr(spec, "port", None) or 0))
        proc = None
        try:
            host, port = listener.address
            proc = self._spawn_worker(spec, env, host, port)
            sock = listener.accept(self.CONNECT_TIMEOUT_S, proc=proc)
        except BaseException:
            if proc is not None:
                proc.kill()
            raise
        finally:
            listener.close()
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        return sock, proc


__all__ = ["TcpTransport", "ReplicaListener", "TcpConnectError"]
