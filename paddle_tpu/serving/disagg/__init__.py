"""paddle_tpu.serving.disagg — the fleet disaggregation subsystem.

Three parts behind the FleetRouter API (docs/SERVING.md "Disaggregated
fleet"):

- ``transport`` — the replica PROCESS boundary: `InprocTransport`
  (direct-object engine, the deterministic CPU oracle path) and
  `SubprocTransport` (one OS process per replica, length-prefixed
  pickled RPC over a UNIX socketpair, heartbeat liveness, crash
  detection) behind one duck-typed contract; ``tcp`` adds
  `TcpTransport` — the same worker dialing back over a real TCP
  socket, the cross-host rung.
- ``page_service`` — `FleetPrefixIndex`: fleet-level prefix/page
  bookkeeping (chain-hash → holders), fed by register/evict deltas
  piggybacked on stats/heartbeat; page BYTES move point-to-point via
  GenerationEngine.export_prefix_pages / import_prefix_pages.
- ``rpc`` — the framing codec both transport halves speak.

The worker module (``python -m paddle_tpu.serving.disagg.worker``) is
the subprocess half: one single-process GenerationEngine per replica —
no JAX multiprocess collectives anywhere.
"""
from .page_service import FleetPrefixIndex, page_chain_hashes
from .tcp import ReplicaListener, TcpConnectError, TcpTransport
from .transport import (InprocTransport, SubprocTransport,
                        build_transport)

__all__ = [
    "FleetPrefixIndex", "page_chain_hashes",
    "InprocTransport", "SubprocTransport", "build_transport",
    "TcpTransport", "ReplicaListener", "TcpConnectError",
]
