"""ReplicaTransport: the replica process boundary.

The FleetRouter never touches a GenerationEngine directly anymore — it
speaks one duck-typed transport contract with two implementations:

- ``InprocTransport`` — the direct-object path (engine in this
  process).  Zero serialization, stepped-mode capable, and therefore
  the deterministic CPU oracle every cross-boundary behavior is
  measured against.
- ``SubprocTransport`` — ONE OS PROCESS per replica: a worker child
  (``python -m paddle_tpu.serving.disagg.worker``) owns a
  single-process GenerationEngine (no JAX multiprocess collectives
  anywhere), and the parent speaks length-prefixed pickled RPC over an
  inherited UNIX socketpair — submit / stream-token / cancel-by-drain
  / stats / evacuate / restart, with a periodic heartbeat carrying
  load + prefix register/evict deltas.  The parent keeps an IN-FLIGHT
  LEDGER (every submitted-but-unfinished request with its delivered
  token count): crash detection (socket EOF or a stale heartbeat)
  marks the replica dead and hands the ledger to the fleet, which
  remigrates queued work and resolves in-flight streams typed —
  migrated or shed, never hung.

The transport contract (duck-typed; every method the router calls):

    alive() heartbeat_age() describe() load_info() stats()
    submit(prompt, kwargs, handle) drain(migrate, live, timeout)
    import_sequence(snap) export_prefix(tokens) import_prefix(payload)
    take_prefix_deltas() flush_prefix() reset_stats()
    idle() pump() stop() take_inflight()

Docs: docs/SERVING.md "Disaggregated fleet" (contract + RPC schema).
"""
import itertools
import os
import socket
import subprocess
import sys
import threading
import time

from ...generation.engine import (GenerationEngine, GenerationResult)
from ...generation.metrics import GenerationMetrics
from ...generation.scheduler import GenerationRequest
from ...profiler.monitor import StatRegistry
from ..admission import ServingError
from .rpc import ChannelClosed, recv_frame, send_frame

HEARTBEAT_S = 0.25


def build_transport(spec, kind, start=True):
    """Transport factory: ``"inproc"`` or ``"proc"``."""
    if kind == "proc":
        return SubprocTransport(spec)
    if kind == "inproc":
        return InprocTransport(spec, start=start)
    raise ValueError(f"transport must be 'inproc' or 'proc', got {kind!r}")


class InprocTransport:
    """The direct-object replica: today's engine-in-process path,
    behind the transport contract — the deterministic CPU oracle the
    subprocess boundary is proven token-identical against."""

    kind = "inproc"

    def __init__(self, spec, start=True):
        self.name = spec.name
        self.registry = StatRegistry()
        self.engine = GenerationEngine(
            spec.model, spec.config,
            metrics=GenerationMetrics(registry=self.registry),
            start=start)
        if self.engine.prefix_cache_enabled:
            self.engine.cache.enable_prefix_deltas()
        self.on_death = None   # inproc replicas share our fate

    # ------------------------- liveness -----------------------------
    def alive(self):
        return not self.engine._closed

    def heartbeat_age(self):
        """0.0 by definition: an in-process engine's liveness IS this
        process's liveness — the gauge stays schema-complete and
        zeroed, exactly what a dashboard should read for it."""
        return 0.0

    # ----------------------- introspection --------------------------
    def describe(self):
        return self.engine.describe()

    def load_info(self):
        return self.engine.load_info()

    def stats(self):
        return {
            "generation":
                self.registry.stats_snapshot("generation.")["stats"],
            "cache": self.engine.cache.stats(),
        }

    # -------------------------- serving -----------------------------
    def submit(self, prompt, kwargs, handle):
        return self.engine.submit(prompt, handle=handle, **kwargs)

    def take_inflight(self):
        return []   # an inproc replica cannot die out from under us

    # ------------------------ page service --------------------------
    def take_prefix_deltas(self):
        # the cache's delta log carries its own mutex, so the router's
        # submit hot path never waits behind an in-flight engine step
        # just to swap a list
        return self.engine.cache.take_prefix_deltas()

    def export_prefix(self, tokens):
        return self.engine.export_prefix_pages(tokens)

    def import_prefix(self, payload):
        return self.engine.import_prefix_pages(payload)

    def flush_prefix(self):
        return self.engine.cache.flush_prefix_cache()

    def reset_stats(self):
        self.registry.reset_all()

    # ----------------------- drain / migration ----------------------
    def import_sequence(self, snap):
        return self.engine.import_sequence(snap)

    def drain(self, migrate=True, live=True, timeout=60.0):
        """Evacuate this replica's unfinished work and shut the engine
        down.  Returns ``(cold, live_snaps)``: cold resubmits
        ``[(GenerationRequest, emitted)]`` plus live-migration sequence
        snapshots.  One state machine for both transport halves:
        engine.drain_work (migrate=False lets residents finish first,
        stragglers past `timeout` evacuate anyway)."""
        return self.engine.drain_work(migrate=migrate, live=live,
                                      timeout=timeout)

    # ------------------------- lifecycle ----------------------------
    def idle(self):
        sched = self.engine.scheduler
        return not (sched.active() or sched.pending_count())

    def pump(self):
        eng = self.engine
        if eng._thread is not None and eng._thread.is_alive():
            time.sleep(0.002)
        else:
            eng.step()

    def stop(self):
        self.engine.shutdown()


class SubprocTransport:
    """One OS process per replica, length-prefixed pickled RPC over a
    UNIX socketpair (rpc.py), heartbeat liveness, crash detection with
    an in-flight ledger the fleet remigrates from."""

    kind = "proc"
    BUILD_TIMEOUT_S = 180.0
    RPC_TIMEOUT_S = 60.0

    def __init__(self, spec):
        cfg = spec.config
        if cfg is not None and getattr(cfg, "mesh", None) is not None:
            raise ValueError(
                "SubprocTransport replicas are single-process engines: "
                "a jax Mesh cannot cross the process boundary (shard "
                "INSIDE a replica with InprocTransport, or give the "
                "subprocess replica an unsharded config)")
        self.name = spec.name
        self.registry = None       # stats live in the child
        self.engine = None         # no direct-object path
        self.on_death = None       # fleet sets: callback(transport)
        parent, child = socket.socketpair()
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        self._proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.serving.disagg.worker",
             str(child.fileno())],
            pass_fds=(child.fileno(),), env=env)
        child.close()
        self._sock = parent
        self._wlock = threading.Lock()
        self._lock = threading.Lock()   # rpc waits + inflight + deltas
        self._ids = itertools.count(1)  # rids and stream sids alike
        self._rpc_waits = {}            # rid -> (Event, slot dict)
        self._inflight = {}             # sid -> ledger entry
        self._deltas = []
        self._load = {"queue_depth": 0, "active": 0, "pages_in_use": 0,
                      "num_pages": 1, "idle": True}
        self._last_hb = time.monotonic()
        self._dead = threading.Event()
        self._closing = False
        self._death_handled = False
        self._reader = threading.Thread(
            target=self._read_loop, name=f"replica-{spec.name}-rx",
            daemon=True)
        self._reader.start()
        # the build handshake doubles as the readiness barrier: the
        # child pays its jax import + engine build before replying.
        # A failed build must not leak the worker: the reader thread
        # keeps the parent socket referenced, so without an explicit
        # kill the child would outlive this constructor forever
        try:
            self._describe = self._call(
                {"op": "build", "model": spec.model, "config": cfg},
                timeout=self.BUILD_TIMEOUT_S)
        except BaseException:
            self._closing = True
            self._proc.kill()
            try:
                self._sock.close()
            except OSError:
                pass
            raise
        # the liveness clock starts AFTER the handshake: the child's
        # heartbeat thread only exists from here, and a build that took
        # longer than heartbeat_dead_after must not read as a stale
        # replica the reaper kills at the first submit
        self._last_hb = time.monotonic()

    # ------------------------- wire pump ----------------------------
    def _read_loop(self):
        try:
            while True:
                self._dispatch(recv_frame(self._sock))
        except (ChannelClosed, OSError, EOFError, ValueError):
            pass
        except Exception:   # noqa: BLE001 — a poisoned frame is a dead
            pass            # channel, not a crashed router
        self._mark_dead()

    def _dispatch(self, frame):
        rid = frame.get("resp")
        if rid is not None:
            with self._lock:
                wait = self._rpc_waits.pop(rid, None)
            if wait is not None:
                ev, slot = wait
                slot.update(frame)
                ev.set()
            return
        kind = frame.get("ev")
        if kind == "hb":
            self._last_hb = time.monotonic()
            self._load = frame.get("load", self._load)
            deltas = frame.get("deltas")
            if deltas:
                with self._lock:
                    self._deltas.extend(deltas)
            return
        sid = frame.get("sid")
        with self._lock:
            entry = self._inflight.get(sid)
        if entry is None:
            return   # stream already resolved/migrated elsewhere
        handle = entry["handle"]
        if kind == "token":
            entry["emitted"] += 1
            handle._push_token(frame["t"])
        elif kind == "done":
            with self._lock:
                self._inflight.pop(sid, None)
            hit = frame.get("prefix_hit")
            if hit is not None and getattr(handle, "prefix_hit_tokens",
                                           0) is None:
                handle.prefix_hit_tokens = hit
            r = frame["result"]
            handle._finish(GenerationResult(
                r["token_ids"], r["finish_reason"], r["prompt_len"],
                r["preemptions"]))
        elif kind == "error":
            with self._lock:
                self._inflight.pop(sid, None)
            handle.set_exception(frame["exc"])

    def _mark_dead(self):
        with self._lock:
            if self._death_handled:
                return
            self._death_handled = True
            waits = list(self._rpc_waits.values())
            self._rpc_waits.clear()
        self._dead.set()
        err = ServingError(
            f"replica {self.name!r} process died mid-call")
        for ev, slot in waits:
            slot["error"] = err
            ev.set()
        if not self._closing and self.on_death is not None:
            # the fleet remigrates the in-flight ledger; the callback
            # runs on the reader thread AFTER every pending RPC was
            # failed, so a router blocked on this replica unwinds first
            self.on_death(self)

    def _call(self, msg, timeout=None):
        if self._dead.is_set():
            raise ServingError(
                f"replica {self.name!r} process is dead")
        rid = next(self._ids)
        ev = threading.Event()
        slot = {}
        with self._lock:
            self._rpc_waits[rid] = (ev, slot)
        msg = dict(msg)
        msg["rid"] = rid
        try:
            send_frame(self._sock, msg, self._wlock)
        except OSError as e:
            with self._lock:
                self._rpc_waits.pop(rid, None)
            raise ServingError(
                f"replica {self.name!r} channel write failed") from e
        if not ev.wait(self.RPC_TIMEOUT_S if timeout is None
                       else float(timeout)):
            with self._lock:
                self._rpc_waits.pop(rid, None)
            raise ServingError(
                f"RPC {msg.get('op')!r} to replica {self.name!r} "
                f"timed out")
        if "error" in slot:
            raise slot["error"]
        return slot.get("ok")

    # ------------------------- liveness -----------------------------
    def alive(self):
        return not self._dead.is_set()

    def heartbeat_age(self):
        return max(0.0, time.monotonic() - self._last_hb)

    def kill(self):
        """Hard-kill the worker process (crash-injection for tests and
        drills): SIGKILL, no cleanup — the reader thread's EOF is the
        detection path under test."""
        self._proc.kill()

    # ----------------------- introspection --------------------------
    def describe(self):
        return dict(self._describe)

    def load_info(self):
        return dict(self._load)   # heartbeat-cached (no RPC on the
        # routing hot path; staleness is one heartbeat period)

    def stats(self):
        if self._dead.is_set():
            return {}
        return self._call({"op": "stats"})

    # -------------------------- serving -----------------------------
    def submit(self, prompt, kwargs, handle):
        if getattr(handle, "submitted_s", None) is None:
            handle.submitted_s = time.monotonic()
        sid = next(self._ids)
        timeout_ms = kwargs.get("timeout_ms")
        entry = {
            "prompt": list(prompt),
            "kwargs": dict(kwargs),
            "handle": handle,
            "emitted": 0,
            "deadline": (None if timeout_ms is None else
                         time.monotonic() + float(timeout_ms) / 1e3),
        }
        with self._lock:
            self._inflight[sid] = entry
        try:
            self._call({"op": "submit", "sid": sid,
                        "prompt": list(prompt), "kwargs": dict(kwargs)})
        except BaseException:
            with self._lock:
                self._inflight.pop(sid, None)
            raise
        return handle

    def take_inflight(self):
        """Drain the in-flight ledger — every submitted-but-unfinished
        request with its delivered-token count.  The death path: the
        fleet resubmits each entry elsewhere (seeded sampling replays
        identically; a relay skips what the client already has)."""
        with self._lock:
            entries = list(self._inflight.values())
            self._inflight.clear()
        return entries

    # ------------------------ page service --------------------------
    def take_prefix_deltas(self):
        with self._lock:
            out, self._deltas = self._deltas, []
        return out

    def export_prefix(self, tokens):
        return self._call({"op": "export_prefix",
                           "tokens": [int(t) for t in tokens]})

    def import_prefix(self, payload):
        return self._call({"op": "import_prefix", "payload": payload})

    def flush_prefix(self):
        return self._call({"op": "flush_prefix"})

    def reset_stats(self):
        return self._call({"op": "reset_stats"})

    # ----------------------- drain / migration ----------------------
    def import_sequence(self, snap):
        handle = snap.get("future")
        sid = next(self._ids)
        payload = {k: v for k, v in snap.items() if k != "future"}
        entry = {
            "prompt": list(snap["prompt"]),
            "kwargs": {"max_new_tokens": snap["max_new_tokens"],
                       "sampling": snap["sampling"],
                       "stop_tokens": snap["stop_tokens"],
                       "timeout_ms": None},
            "handle": handle,
            "emitted": int(snap["n_generated"]),
            "deadline": snap.get("deadline"),
        }
        with self._lock:
            self._inflight[sid] = entry
        try:
            ok = bool(self._call({"op": "import_seq", "sid": sid,
                                  "snap": payload}))
        except BaseException:
            with self._lock:
                self._inflight.pop(sid, None)
            raise
        if not ok:
            with self._lock:
                self._inflight.pop(sid, None)
        return ok

    def drain(self, migrate=True, live=True, timeout=60.0):
        out = self._call(
            {"op": "evacuate", "migrate": bool(migrate),
             "live": bool(live), "timeout": float(timeout)},
            timeout=float(timeout) + self.RPC_TIMEOUT_S)
        cold, live_snaps = [], []
        with self._lock:
            for item in out["cold"]:
                entry = self._inflight.pop(item["sid"], None)
                if entry is None:
                    continue   # resolved while the drain was in flight
                req = GenerationRequest(
                    item["prompt"], entry["handle"], item["sampling"],
                    max_new_tokens=item["max_new_tokens"],
                    stop_tokens=item["stop_tokens"],
                    deadline=item["deadline"])
                cold.append((req, max(int(item["emitted"]),
                                      entry["emitted"])))
            for snap in out["live"]:
                entry = self._inflight.pop(snap.pop("sid"), None)
                if entry is None:
                    continue
                snap["future"] = entry["handle"]
                live_snaps.append(snap)
        self.stop()
        return cold, live_snaps

    # ------------------------- lifecycle ----------------------------
    def idle(self):
        if self._dead.is_set():
            return True
        try:
            load = self._call({"op": "load"}, timeout=10.0)
        except ServingError:
            return True
        self._load = load
        with self._lock:
            busy = bool(self._inflight)
        return bool(load.get("idle")) and not busy

    def pump(self):
        time.sleep(0.01)   # the child steps itself; just yield

    def stop(self):
        self._closing = True
        if not self._dead.is_set():
            try:
                self._call({"op": "shutdown"}, timeout=10.0)
            except ServingError:
                pass
        try:
            self._proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            self._proc.kill()
            self._proc.wait(timeout=10.0)
        try:
            self._sock.close()
        except OSError:
            pass


__all__ = ["InprocTransport", "SubprocTransport", "build_transport",
           "HEARTBEAT_S"]
