"""ReplicaTransport: the replica process boundary.

The FleetRouter never touches a GenerationEngine directly anymore — it
speaks one duck-typed transport contract with two implementations:

- ``InprocTransport`` — the direct-object path (engine in this
  process).  Zero serialization, stepped-mode capable, and therefore
  the deterministic CPU oracle every cross-boundary behavior is
  measured against.
- ``SubprocTransport`` — ONE OS PROCESS per replica: a worker child
  (``python -m paddle_tpu.serving.disagg.worker``) owns a
  single-process GenerationEngine (no JAX multiprocess collectives
  anywhere), and the parent speaks length-prefixed pickled RPC over an
  inherited UNIX socketpair — submit / stream-token / cancel / stats /
  evacuate / restart, with a periodic heartbeat carrying load + prefix
  register/evict deltas.  The parent keeps an IN-FLIGHT LEDGER (every
  submitted-but-unfinished request with its delivered token count):
  crash detection (socket EOF or a stale heartbeat) marks the replica
  dead and hands the ledger to the fleet, which remigrates queued work
  and resolves in-flight streams typed — migrated or shed, never hung.
- ``TcpTransport`` (serving/disagg/tcp.py) — the SAME parent logic
  over a real TCP connection the spawned worker dials back to
  (``--connect host:port``), the cross-host path.  Only the channel
  bring-up differs (``_open_channel`` below is the override seam);
  frames, ledger, heartbeats, deadlines, faults are all shared.

The transport contract (duck-typed; every method the router calls):

    alive() heartbeat_age() describe() load_info() stats()
    submit(prompt, kwargs, handle) drain(migrate, live, timeout)
    import_sequence(snap) export_prefix(tokens) import_prefix(payload)
    take_prefix_deltas() flush_prefix() reset_stats() ping()
    cancel(handle) take_handoffs() idle() pump() stop() take_inflight()

Docs: docs/SERVING.md "Disaggregated fleet" (contract + RPC schema)
and "Cross-host fleet" (TCP bring-up, P/D handoff, supervisor).
"""
import itertools
import os
import random
import socket
import subprocess
import sys
import threading
import time

from ...generation.engine import (GenerationEngine, GenerationResult)
from ...generation.kv_cache import compact_prefix_deltas
from ...generation.metrics import GenerationMetrics
from ...generation.scheduler import GenerationRequest
from ...profiler.monitor import StatRegistry
from ..admission import ReplicaTimeoutError, ServingError
from .rpc import (ChannelClosed, DEFAULT_CHUNK_BYTES, FrameAssembler,
                  send_frame)

HEARTBEAT_S = 0.25

# ops a timed-out caller may safely re-issue: they read state or
# re-assert idempotent state, so a lost REPLY cannot double-apply
# (cancelling an already-cancelled/finished stream is a no-op, so
# "cancel" qualifies).  submit / import_seq / import_prefix /
# evacuate are NOT here — a lost reply may mean the op landed, and
# re-issuing would double-run it; they fail fast into the fleet's
# remigration ladder instead.
RETRYABLE_OPS = frozenset({"stats", "load", "export_prefix",
                           "flush_prefix", "reset_stats", "ping",
                           "cancel"})


class RpcPolicy:
    """Bounded-RPC knobs for one SubprocTransport: every `_call` gets
    a deadline (`timeout_s` — there is NO unbounded default), and
    idempotent ops retry up to `retries` total attempts with
    exponential backoff + seeded jitter (`backoff_s` base).  The
    FleetRouter builds one from FleetConfig.rpc_* per replica."""

    __slots__ = ("timeout_s", "retries", "backoff_s", "seed")

    def __init__(self, timeout_s=15.0, retries=3, backoff_s=0.05,
                 seed=0):
        if float(timeout_s) <= 0:
            raise ValueError(f"timeout_s must be > 0, got {timeout_s}")
        if int(retries) < 1:
            raise ValueError(f"retries must be >= 1, got {retries}")
        if float(backoff_s) < 0:
            raise ValueError(f"backoff_s must be >= 0, got {backoff_s}")
        self.timeout_s = float(timeout_s)
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.seed = seed


def build_transport(spec, kind, start=True, rpc=None, fault_plan=None):
    """Transport factory: ``"inproc"``, ``"proc"`` or ``"tcp"``.
    `rpc` is an RpcPolicy (proc/tcp only); `fault_plan` a
    serving.disagg.faults FaultPlan wrapping the frame codec — chaos
    tests/drills only, and only meaningful where there IS a wire."""
    if kind == "proc":
        return SubprocTransport(spec, rpc=rpc, fault_plan=fault_plan)
    if kind == "tcp":
        from .tcp import TcpTransport   # late: tcp imports this module

        return TcpTransport(spec, rpc=rpc, fault_plan=fault_plan)
    if kind == "inproc":
        if fault_plan is not None:
            raise ValueError(
                "fault injection wraps the RPC frame codec; an inproc "
                "replica has no wire to fault — use transport='proc'")
        return InprocTransport(spec, start=start)
    raise ValueError(
        f"transport must be 'inproc', 'proc' or 'tcp', got {kind!r}")


class InprocTransport:
    """The direct-object replica: today's engine-in-process path,
    behind the transport contract — the deterministic CPU oracle the
    subprocess boundary is proven token-identical against."""

    kind = "inproc"

    def __init__(self, spec, start=True):
        self.name = spec.name
        self.role = getattr(spec, "role", "mixed")
        self.registry = StatRegistry()
        self.engine = GenerationEngine(
            spec.model, spec.config,
            metrics=GenerationMetrics(registry=self.registry),
            start=start)
        if self.engine.prefix_cache_enabled:
            self.engine.cache.enable_prefix_deltas()
        if self.role == "prefill":
            # P/D disaggregation: a prefill-class replica parks every
            # sequence the moment its prompt is consumed; the router
            # collects the parked snapshots (take_handoffs) and ships
            # them to a decode-class replica
            self.engine.enable_handoff()
        self.on_death = None   # inproc replicas share our fate
        self.timeout_total = 0   # schema parity: no RPC, no timeouts
        self._data_server = None   # lazy p2p data listener (ISSUE 20)

    # ------------------------- liveness -----------------------------
    def alive(self):
        return not self.engine._closed

    def heartbeat_age(self):
        """0.0 by definition: an in-process engine's liveness IS this
        process's liveness — the gauge stays schema-complete and
        zeroed, exactly what a dashboard should read for it."""
        return 0.0

    # ----------------------- introspection --------------------------
    def describe(self):
        return self.engine.describe()

    def load_info(self):
        return self.engine.load_info()

    def stats(self):
        return {
            "generation":
                self.registry.stats_snapshot("generation.")["stats"],
            "cache": self.engine.cache.stats(),
        }

    # -------------------------- serving -----------------------------
    def submit(self, prompt, kwargs, handle):
        return self.engine.submit(prompt, handle=handle, **kwargs)

    def take_inflight(self):
        return []   # an inproc replica cannot die out from under us

    def ping(self):
        """Liveness probe — the breaker's half-open recovery signal on
        an idle fleet.  Raises typed when the engine is gone, exactly
        like the RPC path."""
        if self.engine._closed:
            raise ServingError(
                f"replica {self.name!r} engine is shut down")
        return True

    def cancel(self, handle):
        return self.engine.cancel(handle)

    def take_handoffs(self):
        """Drain prefill-complete sequence snapshots parked by a
        prefill-class engine (P/D disaggregation).  Each item is
        ``{"snap": <import_sequence snapshot with future=handle>,
        "t": parked-at monotonic stamp}``."""
        return [{"snap": snap, "t": time.monotonic()}
                for snap in self.engine.take_handoffs()]

    # ------------------------ page service --------------------------
    def take_prefix_deltas(self):
        # the cache's delta log carries its own mutex, so the router's
        # submit hot path never waits behind an in-flight engine step
        # just to swap a list
        return self.engine.cache.take_prefix_deltas()

    def export_prefix(self, tokens):
        return self.engine.export_prefix_pages(tokens)

    def import_prefix(self, payload):
        return self.engine.import_prefix_pages(payload)

    def data_address(self):
        """The p2p data plane's (host, port) for this replica — a
        LAZY real TCP listener even in-process, so inproc fleets
        exercise the exact wire path (frames, codec, deadlines) the
        cross-host tier ships on."""
        if self._data_server is None:
            from .data_plane import PageDataServer

            self._data_server = PageDataServer(
                self.engine.export_prefix_pages)
        return self._data_server.address

    def import_prefix_from(self, addr, tokens, timeout_s=15.0,
                           levels=("raw",)):
        """P2P adoption: fetch the warm prefix straight off the
        holder's data port and install it — same contract as the
        worker's op, returns {"added", "wire_bytes", "raw_bytes"}."""
        from .data_plane import fetch_prefix_pages

        payload, wire, raw = fetch_prefix_pages(
            tuple(addr), tokens, timeout_s=timeout_s, levels=levels)
        added = (0 if payload is None
                 else self.engine.import_prefix_pages(payload))
        return {"added": added, "wire_bytes": wire, "raw_bytes": raw}

    def flush_prefix(self):
        return self.engine.cache.flush_prefix_cache()

    def reset_stats(self):
        self.registry.reset_all()

    # ----------------------- drain / migration ----------------------
    def import_sequence(self, snap):
        return self.engine.import_sequence(snap)

    def drain(self, migrate=True, live=True, timeout=60.0):
        """Evacuate this replica's unfinished work and shut the engine
        down.  Returns ``(cold, live_snaps)``: cold resubmits
        ``[(GenerationRequest, emitted)]`` plus live-migration sequence
        snapshots.  One state machine for both transport halves:
        engine.drain_work (migrate=False lets residents finish first,
        stragglers past `timeout` evacuate anyway)."""
        return self.engine.drain_work(migrate=migrate, live=live,
                                      timeout=timeout)

    # ------------------------- lifecycle ----------------------------
    def idle(self):
        sched = self.engine.scheduler
        return not (sched.active() or sched.pending_count()
                    or self.engine.handoffs_pending())

    def pump(self):
        eng = self.engine
        if eng._thread is not None and eng._thread.is_alive():
            time.sleep(0.002)
        else:
            eng.step()

    def stop(self):
        if self._data_server is not None:
            self._data_server.stop()
            self._data_server = None
        self.engine.shutdown()


class SubprocTransport:
    """One OS process per replica, length-prefixed pickled RPC over a
    UNIX socketpair (rpc.py), heartbeat liveness, crash detection with
    an in-flight ledger the fleet remigrates from."""

    kind = "proc"
    BUILD_TIMEOUT_S = 180.0
    # class-level fallbacks: chaos tests build bare RPC shells via
    # __new__ (no worker half), and those must stay wire-correct —
    # chunking off, no handoff poke, assembler made lazily on first
    # read
    chunk_bytes = None
    role = "mixed"
    on_handoff = None
    _assembler = None
    _data_addr = None        # p2p data port, learned from heartbeats
    delta_compactions = 0    # prefix-delta log net-op collapses
    # accumulated-but-undrained prefix deltas past this bound collapse
    # to their net op per chain — a router that goes long between
    # pulls (idle fleet, slow snapshot cadence) stays O(live chains),
    # not O(churn), over week-long uptimes
    DELTA_COMPACT_AT = 1024

    def __init__(self, spec, rpc=None, fault_plan=None):
        cfg = spec.config
        if cfg is not None and getattr(cfg, "mesh", None) is not None:
            raise ValueError(
                "SubprocTransport replicas are single-process engines: "
                "a jax Mesh cannot cross the process boundary (shard "
                "INSIDE a replica with InprocTransport, or give the "
                "subprocess replica an unsharded config)")
        self.name = spec.name
        self.role = getattr(spec, "role", "mixed")
        self.registry = None       # stats live in the child
        self.engine = None         # no direct-object path
        self.on_death = None       # fleet sets: callback(transport)
        self.on_handoff = None     # fleet sets: prefill-complete poke
        self.rpc = rpc or RpcPolicy()
        self._faults = fault_plan  # chaos: wraps the codec parent-side
        self._jitter = random.Random((spec.name, self.rpc.seed).__repr__())
        self.timeout_total = 0     # RPC deadline misses (drill report)
        # chunked codec: logical frames past this bound ship as
        # fragment carriers, so a multi-MB page export never blocks
        # heartbeats/tokens behind one giant sendall (spec override:
        # tests pin a tiny bound to force chunking on small payloads)
        self.chunk_bytes = int(getattr(spec, "chunk_bytes", None)
                               or DEFAULT_CHUNK_BYTES)
        self._assembler = FrameAssembler()
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        self._sock, self._proc = self._open_channel(spec, env)
        self._wlock = threading.Lock()
        self._lock = threading.Lock()   # rpc waits + inflight + deltas
        self._ids = itertools.count(1)  # rids and stream sids alike
        self._rpc_waits = {}            # rid -> (Event, slot dict)
        self._inflight = {}             # sid -> ledger entry
        self._handoffs = []             # prefill-complete snaps parked
        self._deltas = []
        self._load = {"queue_depth": 0, "active": 0, "pages_in_use": 0,
                      "num_pages": 1, "idle": True}
        self._last_hb = time.monotonic()
        # wedge-watchdog inputs: the heartbeat's engine-step progress
        # stamp (seq frozen + load busy == alive-but-stalled) and how
        # long the child has reported itself idle (orphan sweep)
        self._progress_seq = None
        self._progress_at = time.monotonic()
        self._in_step = False
        self._idle_since = None
        self._dead = threading.Event()
        self._closing = False
        self._death_handled = False
        self._reader = threading.Thread(
            target=self._read_loop, name=f"replica-{spec.name}-rx",
            daemon=True)
        self._reader.start()
        # the build handshake doubles as the readiness barrier: the
        # child pays its jax import + engine build before replying.
        # A failed build must not leak the worker: the reader thread
        # keeps the parent socket referenced, so without an explicit
        # kill the child would outlive this constructor forever
        child_faults = (None if fault_plan is None
                        else fault_plan.child_spec())
        try:
            self._describe = self._call(
                {"op": "build", "model": spec.model, "config": cfg,
                 "role": self.role, "chunk_bytes": self.chunk_bytes,
                 "faults": child_faults,
                 "data_host": getattr(spec, "host", None)},
                timeout=self.BUILD_TIMEOUT_S)
        except BaseException:
            self._closing = True
            self._proc.kill()
            try:
                self._sock.close()
            except OSError:
                pass
            raise
        # the data-port advert rides the build reply (available before
        # the first heartbeat) and is refreshed by every later beat
        addr = self._describe.pop("data_address", None)
        self._data_addr = None if addr is None else tuple(addr)
        # the liveness clock starts AFTER the handshake: the child's
        # heartbeat thread only exists from here, and a build that took
        # longer than heartbeat_dead_after must not read as a stale
        # replica the reaper kills at the first submit
        self._last_hb = time.monotonic()
        self._progress_at = self._last_hb
        if child_faults is not None:
            # the worker holds its own (seeded) half of the plan;
            # arm()/disarm() on the parent plan re-syncs it over the
            # wire so drills can warm up disarmed, then arm both sides
            fault_plan._hosts.append(self)

    # ------------------------ channel setup -------------------------
    def _open_channel(self, spec, env):
        """Bring up the wire to a freshly spawned worker; returns
        ``(socket, Popen)``.  Base implementation: inherited UNIX
        socketpair.  TcpTransport overrides this with listen /
        spawn-with---connect / accept — everything above the socket
        (frames, ledger, heartbeats, faults) is shared."""
        parent, child = socket.socketpair()
        proc = subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.serving.disagg.worker",
             str(child.fileno())],
            pass_fds=(child.fileno(),), env=env)
        child.close()
        return parent, proc

    # ------------------------- wire pump ----------------------------
    def _send_plain(self, msg):
        """The un-faulted logical-frame write (chunk-capable) — also
        the terminal write the fault plan's passthrough path uses, so
        chunking composes with injected faults."""
        send_frame(self._sock, msg, self._wlock,
                   chunk_bytes=self.chunk_bytes)

    def _recv_plain(self):
        """The un-faulted logical-frame read (fragment-reassembling) —
        single reader thread per channel, so the assembler needs no
        lock."""
        asm = self._assembler
        if asm is None:
            asm = self._assembler = FrameAssembler()
        return asm.recv(self._sock)

    def _send(self, msg):
        """One (possibly fault-injected) frame write."""
        if self._faults is None:
            self._send_plain(msg)
        else:
            self._faults.on_send(self, msg)

    def _sync_child_faults(self, armed):
        """Mirror the parent plan's arm/disarm to the worker's child
        half.  Rid-less fire-and-forget on the PLAIN codec: the frame
        must not itself be subject to the plan, and write order under
        _wlock guarantees it lands before any traffic armed after it."""
        if self._dead.is_set():
            return
        try:
            send_frame(self._sock,
                       {"op": "chaos_arm", "armed": bool(armed)},
                       self._wlock)
        except OSError:
            pass

    def _send_stall(self, stall_s):
        """Chaos: ask the worker to WEDGE its engine (a thread holds
        the step lock for `stall_s`) while its heartbeat thread keeps
        beating — the alive-but-stalled failure the wedge watchdog
        exists for.  Rid-less fire-and-forget, written with the plain
        codec so a stall rule cannot recurse into the fault plan."""
        try:
            send_frame(self._sock,
                       {"op": "chaos_stall", "stall_s": float(stall_s)},
                       self._wlock)
        except OSError:
            pass

    def _read_loop(self):
        try:
            while True:
                if self._faults is None:
                    self._dispatch(self._recv_plain())
                else:
                    for frame in self._faults.on_recv(self):
                        self._dispatch(frame)
        except (ChannelClosed, OSError, EOFError, ValueError):
            pass
        except Exception:   # noqa: BLE001 — a poisoned frame is a dead
            pass            # channel, not a crashed router
        self._mark_dead()

    def _dispatch(self, frame):
        rid = frame.get("resp")
        if rid is not None:
            with self._lock:
                wait = self._rpc_waits.pop(rid, None)
            if wait is not None:
                ev, slot = wait
                slot.update(frame)
                ev.set()
            return
        kind = frame.get("ev")
        if kind == "hb":
            now = time.monotonic()
            self._last_hb = now
            load = frame.get("load", self._load)
            self._load = load
            idle = bool(load.get("idle", True))
            seq = frame.get("seq")
            # the wedge watchdog's progress stamp: the clock re-arms
            # whenever the engine completed a step since the last beat
            # OR the replica is idle (no work ⇒ no progress owed)
            if idle or seq is None or seq != self._progress_seq:
                self._progress_seq = seq
                self._progress_at = now
            self._in_step = bool(frame.get("in_step", False))
            self._idle_since = ((self._idle_since or now) if idle
                                else None)
            deltas = frame.get("deltas")
            if deltas:
                with self._lock:
                    self._deltas.extend(deltas)
                    if len(self._deltas) > self.DELTA_COMPACT_AT:
                        self._deltas = compact_prefix_deltas(
                            self._deltas)
                        self.delta_compactions += 1
            addr = frame.get("data")
            if addr is not None:
                self._data_addr = tuple(addr)
            return
        sid = frame.get("sid")
        with self._lock:
            entry = self._inflight.get(sid)
        if entry is None:
            return   # stream already resolved/migrated elsewhere
        entry["last_event"] = time.monotonic()
        handle = entry["handle"]
        if kind == "token":
            # ordered stream protocol: events carry a per-stream index
            # so a duplicated frame is dropped and a lost frame leaves
            # a HOLE, not a mis-ordered stream — the client only ever
            # sees an exact prefix, backfilled from the authoritative
            # result at completion
            n = frame.get("n")
            if n is None:
                entry["next"] += 1
                entry["emitted"] = entry["base"] + entry["next"]
                handle._push_token(frame["t"])
            elif n == entry["next"]:
                entry["next"] += 1
                handle._push_token(frame["t"])
                ahead = entry["ahead"]
                while entry["next"] in ahead:
                    handle._push_token(ahead.pop(entry["next"]))
                    entry["next"] += 1
                entry["emitted"] = entry["base"] + entry["next"]
            elif n > entry["next"]:
                entry["ahead"][n] = frame["t"]
            # n < next: a duplicated frame — already delivered, drop
        elif kind == "done":
            with self._lock:
                self._inflight.pop(sid, None)
            hit = frame.get("prefix_hit")
            if hit is not None and getattr(handle, "prefix_hit_tokens",
                                           0) is None:
                handle.prefix_hit_tokens = hit
            r = frame["result"]
            # backfill any tokens whose event frames were lost: the
            # result's token_ids are authoritative, and the client has
            # exactly the base+next prefix so far
            for t in r["token_ids"][entry["base"] + entry["next"]:]:
                handle._push_token(t)
            handle._finish(GenerationResult(
                r["token_ids"], r["finish_reason"], r["prompt_len"],
                r["preemptions"]))
        elif kind == "handoff":
            # P/D disaggregation: the prefill replica finished this
            # stream's prompt and shipped the sequence snapshot; the
            # stream continues on a decode replica.  Park the snap for
            # the router (take_handoffs) and heal the client stream to
            # exactly n_generated tokens — the import base — so the
            # decode side never gaps or dupes
            with self._lock:
                self._inflight.pop(sid, None)
            snap = frame["snap"]
            n_gen = int(snap["n_generated"])
            gen = snap["tokens"][len(snap["tokens"]) - n_gen:] \
                if n_gen else []
            for t in gen[entry["base"] + entry["next"]:]:
                handle._push_token(t)
            snap["future"] = handle
            with self._lock:
                self._handoffs.append({"snap": snap,
                                       "t": time.monotonic()})
            if self.on_handoff is not None:
                # poke the router from the reader thread: placement
                # RPCs target SIBLING replicas, never this channel, so
                # the reader cannot deadlock on its own socket
                self.on_handoff()
        elif kind == "error":
            with self._lock:
                self._inflight.pop(sid, None)
            handle.set_exception(frame["exc"])

    def _mark_dead(self):
        with self._lock:
            if self._death_handled:
                return
            self._death_handled = True
            waits = list(self._rpc_waits.values())
            self._rpc_waits.clear()
        self._dead.set()
        err = ServingError(
            f"replica {self.name!r} process died mid-call")
        for ev, slot in waits:
            slot["error"] = err
            ev.set()
        if not self._closing and self.on_death is not None:
            # the fleet remigrates the in-flight ledger; the callback
            # runs on the reader thread AFTER every pending RPC was
            # failed, so a router blocked on this replica unwinds first
            self.on_death(self)

    def _call(self, msg, timeout=None):
        """One RPC round-trip under a BOUNDED deadline — `timeout=None`
        means the transport's RpcPolicy default, never unbounded.  A
        missed deadline raises the typed ReplicaTimeoutError; callers
        that can re-issue safely go through _call_idempotent, everyone
        else fails fast into the fleet's remigration ladder."""
        if self._dead.is_set():
            raise ServingError(
                f"replica {self.name!r} process is dead")
        timeout = (self.rpc.timeout_s if timeout is None
                   else float(timeout))
        rid = next(self._ids)
        ev = threading.Event()
        slot = {}
        with self._lock:
            self._rpc_waits[rid] = (ev, slot)
        msg = dict(msg)
        msg["rid"] = rid
        try:
            self._send(msg)
        except OSError as e:
            with self._lock:
                self._rpc_waits.pop(rid, None)
            raise ServingError(
                f"replica {self.name!r} channel write failed") from e
        if not ev.wait(timeout):
            with self._lock:
                self._rpc_waits.pop(rid, None)
            self.timeout_total += 1
            raise ReplicaTimeoutError(
                f"RPC {msg.get('op')!r} to replica {self.name!r} "
                f"exceeded its {timeout:.1f}s deadline")
        if "error" in slot:
            raise slot["error"]
        return slot.get("ok")

    def _call_idempotent(self, msg, timeout=None):
        """Retry an idempotent op (RETRYABLE_OPS) on deadline misses:
        exponential backoff + seeded jitter under the policy's bounded
        attempt budget.  A dead channel never retries — dead is dead."""
        op = msg.get("op")
        assert op in RETRYABLE_OPS, f"op {op!r} is not idempotent"
        last = None
        for attempt in range(self.rpc.retries):
            try:
                return self._call(msg, timeout)
            except ReplicaTimeoutError as e:
                last = e
                if attempt + 1 < self.rpc.retries \
                        and not self._dead.is_set():
                    time.sleep(self.rpc.backoff_s * (2 ** attempt)
                               * (1.0 + 0.25 * self._jitter.random()))
        raise last

    # ------------------------- liveness -----------------------------
    def alive(self):
        return not self._dead.is_set()

    def heartbeat_age(self):
        return max(0.0, time.monotonic() - self._last_hb)

    def kill(self):
        """Hard-kill the worker process (crash-injection for tests and
        drills, and the watchdog's wedge-kill): SIGKILL, no cleanup —
        the reader thread's EOF is the detection path under test."""
        self._proc.kill()

    def wedged(self, after_s, hard_after_s=None):
        """True when the replica is alive-but-STALLED: it reports work
        (engine not idle) but its heartbeat progress stamp hasn't
        advanced — the heartbeat thread outliving a wedged engine
        loop, the one failure socket EOF and stale heartbeats both
        miss.  Two clocks:

        - SOFT (`after_s`): fires only while the engine is NOT inside
          a step — the step loop cannot even take its own lock (the
          classic stall).  An engine mid-step is doing real work: a
          10 s first-shape jit compile must never read as a wedge.
        - HARD (`hard_after_s`, default 10x soft): fires regardless —
          a step that holds the lock without completing for THIS long
          is hung inside the dispatch, not compiling.

        The router's watchdog kills and remigrates either case
        exactly like a crash."""
        if self._dead.is_set():
            return False
        if bool(self._load.get("idle", True)):
            return False
        frozen = time.monotonic() - self._progress_at
        if hard_after_s is None:
            hard_after_s = 10.0 * float(after_s)
        if frozen > float(hard_after_s):
            return True
        return frozen > float(after_s) and not self._in_step

    def take_orphans(self, grace_s):
        """In-flight ledger entries the child has silently forgotten:
        the worker has reported itself idle (no queue, no live slots)
        for over `grace_s` while these streams still wait — a lost
        completion event (dropped/corrupted `done` frame).  Pops and
        returns them for remigration: seeded sampling replays the
        identical stream and the relay skips the delivered prefix."""
        now = time.monotonic()
        if self._dead.is_set() or self._idle_since is None \
                or now - self._idle_since < float(grace_s):
            return []
        out = []
        with self._lock:
            for sid, entry in list(self._inflight.items()):
                if now - entry["last_event"] > float(grace_s):
                    out.append(self._inflight.pop(sid))
        return out

    # ----------------------- introspection --------------------------
    def describe(self):
        return dict(self._describe)

    def load_info(self):
        return dict(self._load)   # heartbeat-cached (no RPC on the
        # routing hot path; staleness is one heartbeat period)

    def stats(self):
        if self._dead.is_set():
            return {}
        return self._call_idempotent({"op": "stats"})

    # -------------------------- serving -----------------------------
    def submit(self, prompt, kwargs, handle):
        if getattr(handle, "submitted_s", None) is None:
            handle.submitted_s = time.monotonic()
        sid = next(self._ids)
        timeout_ms = kwargs.get("timeout_ms")
        entry = {
            "prompt": list(prompt),
            "kwargs": dict(kwargs),
            "handle": handle,
            "emitted": 0,
            "base": 0, "next": 0, "ahead": {},
            "last_event": time.monotonic(),
            "deadline": (None if timeout_ms is None else
                         time.monotonic() + float(timeout_ms) / 1e3),
        }
        with self._lock:
            self._inflight[sid] = entry
        try:
            self._call({"op": "submit", "sid": sid,
                        "prompt": list(prompt), "kwargs": dict(kwargs)})
        except BaseException:
            with self._lock:
                claimed = self._inflight.pop(sid, None) is None
            if claimed:
                # The entry is already GONE: the death path snapshotted
                # the ledger while our reply was in flight (remigration
                # owns the stream now), or a done/error frame resolved
                # the handle first.  Ownership left this call either
                # way — report PLACED, because raising here would send
                # the router's rung retry after a request the death
                # path is ALSO resubmitting: two live streams feeding
                # one handle, every token delivered twice.
                return handle
            raise
        return handle

    def take_inflight(self):
        """Drain the in-flight ledger — every submitted-but-unfinished
        request with its delivered-token count.  The death path: the
        fleet resubmits each entry elsewhere (seeded sampling replays
        identically; a relay skips what the client already has)."""
        with self._lock:
            entries = list(self._inflight.values())
            self._inflight.clear()
        return entries

    def ping(self, timeout=None):
        """Synthetic liveness probe: one bounded, retried round-trip.
        The watchdog sends these so an idle fleet's half-open breakers
        earn their recovery without waiting for real traffic."""
        if timeout is None:
            timeout = min(5.0, self.rpc.timeout_s)
        return bool(self._call_idempotent({"op": "ping"},
                                          timeout=timeout))

    def cancel(self, handle):
        """Cancel the in-flight stream owned by `handle`: the worker
        frees its queue slot and pages and resolves the stream with a
        ``finish_reason="cancelled"`` done frame (which settles the
        ledger entry through the normal dispatch path — the client
        handle NEVER hangs).  False when the stream is unknown here
        (already finished, migrated away, or replica dead — the death
        path resolves it instead)."""
        if self._dead.is_set():
            return False
        with self._lock:
            sid = next((s for s, e in self._inflight.items()
                        if e["handle"] is handle), None)
        if sid is None:
            return False
        try:
            return bool(self._call_idempotent({"op": "cancel",
                                               "sid": sid}))
        except ServingError:
            return False

    def take_handoffs(self):
        """Drain prefill-complete sequence snapshots this replica
        shipped up (P/D disaggregation).  Each item: ``{"snap": ...,
        "t": parent-received-at}``; snaps carry page BYTES plus the
        client handle — parent-side state, so they survive the worker
        being SIGKILLed right after the handoff frame left."""
        with self._lock:
            out, self._handoffs = self._handoffs, []
        return out

    # ------------------------ page service --------------------------
    def take_prefix_deltas(self):
        with self._lock:
            out, self._deltas = self._deltas, []
        return out

    def export_prefix(self, tokens):
        # idempotent read: a lost reply just re-exports the same run
        return self._call_idempotent({"op": "export_prefix",
                                      "tokens": [int(t) for t in tokens]})

    def import_prefix(self, payload):
        # NOT retried: a lost reply may mean the pages landed; the
        # import is an optimization and a duplicate would only free
        # itself, but re-shipping multi-MB payloads on a timeout is
        # the wrong trade — fail fast, the cold ladder covers it
        return self._call({"op": "import_prefix", "payload": payload})

    def data_address(self):
        """The replica's advertised p2p data port — (host, port), or
        None until the build reply / first heartbeat delivered it."""
        return self._data_addr

    def import_prefix_from(self, addr, tokens, timeout_s=None,
                           levels=("raw",)):
        """P2P adoption: tell THIS replica to dial the holder's data
        port and fetch the warm prefix itself — the payload crosses
        one replica→replica socket and never this control channel.
        NOT retried, same reasoning as import_prefix; the outer RPC
        deadline wraps the child's bounded fetch with headroom so a
        wedged data socket fails typed HERE, not as a parent timeout
        racing the child's."""
        inner = self.rpc.timeout_s if timeout_s is None \
            else float(timeout_s)
        return self._call(
            {"op": "import_prefix_from", "addr": tuple(addr),
             "tokens": [int(t) for t in tokens], "timeout_s": inner,
             "levels": list(levels)},
            timeout=inner + 5.0)

    def flush_prefix(self):
        return self._call_idempotent({"op": "flush_prefix"})

    def reset_stats(self):
        return self._call_idempotent({"op": "reset_stats"})

    # ----------------------- drain / migration ----------------------
    def import_sequence(self, snap):
        handle = snap.get("future")
        sid = next(self._ids)
        payload = {k: v for k, v in snap.items() if k != "future"}
        entry = {
            "prompt": list(snap["prompt"]),
            "kwargs": {"max_new_tokens": snap["max_new_tokens"],
                       "sampling": snap["sampling"],
                       "stop_tokens": snap["stop_tokens"],
                       "timeout_ms": None},
            "handle": handle,
            "emitted": int(snap["n_generated"]),
            "base": int(snap["n_generated"]), "next": 0, "ahead": {},
            "last_event": time.monotonic(),
            "deadline": snap.get("deadline"),
        }
        with self._lock:
            self._inflight[sid] = entry
        try:
            ok = bool(self._call({"op": "import_seq", "sid": sid,
                                  "snap": payload}))
        except BaseException:
            with self._lock:
                self._inflight.pop(sid, None)
            raise
        if not ok:
            with self._lock:
                self._inflight.pop(sid, None)
        return ok

    def drain(self, migrate=True, live=True, timeout=60.0):
        # the ONE op with its own longer budget — the drain may wait
        # `timeout` for residents to finish — still explicit and
        # bounded, never None
        out = self._call(
            {"op": "evacuate", "migrate": bool(migrate),
             "live": bool(live), "timeout": float(timeout)},
            timeout=float(timeout) + self.rpc.timeout_s)
        cold, live_snaps = [], []
        with self._lock:
            for item in out["cold"]:
                entry = self._inflight.pop(item["sid"], None)
                if entry is None:
                    continue   # resolved while the drain was in flight
                req = GenerationRequest(
                    item["prompt"], entry["handle"], item["sampling"],
                    max_new_tokens=item["max_new_tokens"],
                    stop_tokens=item["stop_tokens"],
                    deadline=item["deadline"])
                cold.append((req, max(int(item["emitted"]),
                                      entry["emitted"])))
            for snap in out["live"]:
                entry = self._inflight.pop(snap.pop("sid"), None)
                if entry is None:
                    continue
                snap["future"] = entry["handle"]
                live_snaps.append(snap)
        self.stop()
        return cold, live_snaps

    # ------------------------- lifecycle ----------------------------
    def idle(self):
        if self._dead.is_set():
            return True
        try:
            load = self._call_idempotent(
                {"op": "load"}, timeout=min(10.0, self.rpc.timeout_s))
        except ServingError:
            return True
        self._load = load
        with self._lock:
            busy = bool(self._inflight or self._handoffs)
        return bool(load.get("idle")) and not busy

    def pump(self):
        time.sleep(0.01)   # the child steps itself; just yield

    def stop(self):
        self._closing = True
        clean = False
        if not self._dead.is_set():
            try:
                self._call({"op": "shutdown"},
                           timeout=min(10.0, self.rpc.timeout_s))
                clean = True
            except ServingError:
                pass
        if not clean:
            # dead or unresponsive (wedged engine, poisoned channel):
            # don't wait out a corpse's grace period — reap it now
            self._proc.kill()
        try:
            self._proc.wait(timeout=10.0)
        except subprocess.TimeoutExpired:
            self._proc.kill()
            self._proc.wait(timeout=10.0)
        try:
            self._sock.close()
        except OSError:
            pass


__all__ = ["InprocTransport", "SubprocTransport", "build_transport",
           "RpcPolicy", "RETRYABLE_OPS", "HEARTBEAT_S"]
