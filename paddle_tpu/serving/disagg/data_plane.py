"""Peer-to-peer page data plane (ISSUE 20).

Control/data split: the router's RPC channel keeps carrying small
control frames (submit, heartbeats, index deltas), while page BYTES
move replica→replica over a dedicated data socket — the router's
involvement in adoption drops to index bookkeeping, and its socket
moves ZERO page bytes (counter-asserted in tests/test_data_plane.py).

- ``PageDataServer``: the holder side.  Every replica binds an
  ephemeral loopback/host port at build, advertises ``(host, port)``
  in heartbeats, and serves one-shot ``fetch_prefix`` requests: the
  request names the tokens plus the importer's codec version/levels,
  the reply carries the pagecodec-encoded payload (or a typed error).
  One connection per fetch — no session state to desync, and a torn
  transfer is just a closed socket.

- ``fetch_prefix_pages``: the importer side.  Dials the holder under
  a bounded deadline (the RpcPolicy timeout the caller passes),
  speaks the same chunked-frame codec as the RPC channel
  (rpc.send_frame / FrameAssembler — multi-MB payloads fragment
  instead of head-blocking), and composes with the chaos FaultPlan
  through the standard codec-host surface, so the drill matrix
  (drop/delay/dup/truncate/corrupt/kill) runs unchanged over the
  data socket.  Every failure mode — refused dial, deadline, torn
  frame, codec mismatch — degrades TYPED (PageTransferError /
  PageCodecError), which the fleet maps to the cold-prefill ladder.
"""
import socket
import threading
import time

from ..admission import ServingError
from . import pagecodec
from .rpc import ChannelClosed, FrameAssembler, send_frame


class PageTransferError(ServingError):
    """A p2p page fetch that could not complete (dial refused,
    deadline missed, channel torn mid-frame) — typed, so adoption
    degrades to the cold-prefill ladder instead of hanging a
    request."""


class _DataChannel:
    """One data-socket dial behind the chaos codec-host contract
    (_sock/_wlock/_send_plain/_recv_plain/kill/_send_stall), so a
    FaultPlan wraps the data plane exactly as it wraps the RPC
    channel.  ``kill`` runs the caller's callback (the worker's
    SIGKILL-self child-side; tearing the socket parent-side) and
    ``stall`` holds the dial until the deadline catches it."""

    def __init__(self, sock, faults=None, chunk_bytes=None,
                 kill_cb=None):
        self._sock = sock
        self._wlock = threading.Lock()
        self._faults = faults
        self._chunk = chunk_bytes
        self._assembler = FrameAssembler()
        self._kill_cb = kill_cb

    def _send_plain(self, msg):
        send_frame(self._sock, msg, self._wlock,
                   chunk_bytes=self._chunk)

    def _recv_plain(self):
        return self._assembler.recv(self._sock)

    def send(self, msg):
        if self._faults is None:
            self._send_plain(msg)
        else:
            self._faults.on_send(self, msg)

    def recv(self):
        if self._faults is None:
            return [self._recv_plain()]
        return self._faults.on_recv(self)

    def kill(self):
        if self._kill_cb is not None:
            self._kill_cb()
            return
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass

    def _send_stall(self, stall_s):
        time.sleep(float(stall_s))


class PageDataServer:
    """Holder-side data-plane listener: a daemon accept loop serving
    one ``fetch_prefix`` per connection.  ``export_fn(tokens)`` is
    the engine's export_prefix_pages (thread-safe under the engine
    lock); encoding happens here, per-request, at the negotiated
    level — a mixed-version fleet is refused typed, never garbled."""

    REQUEST_TIMEOUT_S = 30.0

    def __init__(self, export_fn, host="127.0.0.1", port=0,
                 chunk_bytes=None):
        self._export = export_fn
        self._chunk = chunk_bytes
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, int(port)))
        self._sock.listen(8)
        self.address = (host, self._sock.getsockname()[1])
        self.requests_served = 0
        self._closed = False
        self._thread = threading.Thread(
            target=self._accept_loop, name="page-data-server",
            daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while not self._closed:
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return   # listener closed: shutdown
            if self._closed:
                # stop() raced our accept: a dial that sneaked in as
                # the listener died must NOT be served by a stopped
                # holder — drop it so the importer degrades typed
                try:
                    conn.close()
                except OSError:
                    pass
                return
            threading.Thread(target=self._serve_one, args=(conn,),
                             name="page-data-serve", daemon=True).start()

    def _serve_one(self, conn):
        try:
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            conn.settimeout(self.REQUEST_TIMEOUT_S)
            req = FrameAssembler().recv(conn)
            if not isinstance(req, dict) \
                    or req.get("op") != "fetch_prefix":
                raise PageTransferError(
                    f"data socket expects fetch_prefix, got "
                    f"{req.get('op') if isinstance(req, dict) else req!r}")
            level = pagecodec.negotiate(req.get("pv"),
                                        req.get("levels") or ("raw",))
            payload = self._export(list(req.get("tokens", ())))
            enc = (None if payload is None
                   else pagecodec.encode_payload(payload, level))
            reply = {"ok": enc}
        except Exception as e:   # noqa: BLE001 — typed errors ride the
            reply = {"error": e}   # wire back, like the RPC channel
        try:
            send_frame(conn, reply, threading.Lock(),
                       chunk_bytes=self._chunk)
            self.requests_served += 1
        except Exception:   # noqa: BLE001 — importer gone or an
            pass            # unserializable error payload: give up
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def stop(self):
        self._closed = True
        # shutdown() BEFORE close(): the accept thread blocked in
        # accept() holds a kernel reference to the listening socket,
        # so close() alone leaves the port accepting until the next
        # (stale) dial is served — shutdown wakes the accept with an
        # error and releases the port NOW
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass


def fetch_prefix_pages(addr, tokens, timeout_s=15.0,
                       levels=pagecodec.SUPPORTED_LEVELS,
                       chunk_bytes=None, faults=None, kill_cb=None):
    """Importer-side fetch: dial the holder's data port, request the
    warm prefix for `tokens`, decode the reply.  Returns
    ``(payload_or_None, wire_bytes, raw_bytes)``.  Bounded end to end
    by `timeout_s` (dial + both frame directions); every failure is
    typed — PageTransferError for wire trouble, PageCodecError for a
    version/level mismatch, and a holder-side error frame re-raises
    its (Serving-typed) exception here."""
    deadline = time.monotonic() + float(timeout_s)
    try:
        sock = socket.create_connection(tuple(addr),
                                        timeout=float(timeout_s))
    except OSError as e:
        raise PageTransferError(
            f"page data dial to {addr} failed: {e}") from e
    ch = _DataChannel(sock, faults=faults, chunk_bytes=chunk_bytes,
                      kill_cb=kill_cb)
    try:
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(float(timeout_s))
        try:
            ch.send({"op": "fetch_prefix",
                     "tokens": [int(t) for t in tokens],
                     "pv": pagecodec.VERSION, "levels": list(levels)})
            reply = None
            while reply is None:
                if time.monotonic() > deadline:
                    raise PageTransferError(
                        f"page fetch from {addr} missed its "
                        f"{timeout_s}s deadline")
                frames = ch.recv()   # chaos drop returns [] — re-read
                if frames:
                    reply = frames[0]
        except ServingError:
            raise
        except (socket.timeout, ChannelClosed, OSError, EOFError,
                ValueError) as e:
            # deadline, torn/poisoned frame (FaultInjected is a
            # ValueError), or the holder died mid-transfer
            raise PageTransferError(
                f"page fetch from {addr} failed: "
                f"{type(e).__name__}: {e}") from e
        if not isinstance(reply, dict):
            raise PageTransferError(
                f"page fetch from {addr}: malformed reply")
        if "error" in reply:
            exc = reply["error"]
            if isinstance(exc, ServingError):
                raise exc
            raise PageTransferError(
                f"holder {addr} refused page fetch: {exc!r}")
        enc = reply.get("ok")
        if enc is None:
            return None, 0, 0   # evicted since the last delta pull
        return (pagecodec.decode_payload(enc), pagecodec.wire_bytes(enc),
                pagecodec.raw_bytes(enc))
    finally:
        try:
            sock.close()
        except OSError:
            pass


__all__ = ["PageDataServer", "PageTransferError", "fetch_prefix_pages"]
