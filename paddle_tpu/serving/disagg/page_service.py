"""FleetPrefixIndex: fleet-level prefix/page bookkeeping.

The parameter-server split (PAPER.md L5): BOOKKEEPING is centralized —
one small index in the router mapping prefix chain hashes to the
replicas that measurably hold them — while page BYTES move
point-to-point on demand (GenerationEngine.export_prefix_pages →
import_prefix_pages), never through a shared store.

Each replica's cache emits ``("add"|"drop", chain_hash)`` deltas at the
exact trie transitions (register_prefix / _drop_node / flush —
kv_cache.take_prefix_deltas), piggybacked on stats or heartbeat frames,
so the index tracks what each prefix index ACTUALLY holds instead of
guessing from a stable hash.  Routing looks up the deepest chain of a
prompt's leading full pages; when the holder is not the chosen replica,
the router moves the run's bytes so ANY replica adopts pages it never
prefilled (docs/SERVING.md "Disaggregated fleet").

A chain hash collision can at worst misroute or skip one adoption —
adoption and admission both re-verify against literal tokens
(kv_cache.page_chain_hash documents the containment).
"""
from ...generation.kv_cache import (compact_prefix_deltas,
                                    page_chain_hash)

# delta-log net-op collapse, re-exported for transport/heartbeat
# accumulators: an add→drop churn nets to its last op per chain
compact_deltas = compact_prefix_deltas


def page_chain_hashes(tokens, page_size):
    """Chain hashes of every leading FULL page of `tokens`:
    ``out[i]`` identifies the prefix ``tokens[:(i+1) * page_size]``.
    Must mirror register_prefix's incremental hashing exactly — both
    call kv_cache.page_chain_hash page by page."""
    out = []
    h = 0
    for i in range(len(tokens) // page_size):
        h = page_chain_hash(
            h, tokens[i * page_size:(i + 1) * page_size])
        out.append(h)
    return out


class FleetPrefixIndex:
    """chain_hash -> {replica_name: recency} — which replicas hold
    which cached prefix runs, by measurement.  Not thread-safe on its
    own; the FleetRouter mutates it under its routing lock."""

    def __init__(self):
        self._holders = {}
        self._clock = 0
        self.compactions = 0       # compact() sweeps that dropped work
        self.chains_compacted = 0  # dead-holder chains swept, total

    def _tick(self):
        self._clock += 1
        return self._clock

    def apply(self, name, deltas):
        """Ingest one replica's drained register/evict deltas."""
        for op, chain in deltas:
            if op == "add":
                self._holders.setdefault(chain, {})[name] = self._tick()
            elif op == "drop":
                holders = self._holders.get(chain)
                if holders is not None:
                    holders.pop(name, None)
                    if not holders:
                        del self._holders[chain]

    def drop_replica(self, name):
        """Forget everything `name` held — drain, restart, or death
        invalidates its whole index at once."""
        for chain in [c for c, h in self._holders.items() if name in h]:
            holders = self._holders[chain]
            del holders[name]
            if not holders:
                del self._holders[chain]

    def holders_of(self, chain):
        """Replica names holding `chain` right now (a set copy)."""
        return set(self._holders.get(chain, ()))

    def lookup(self, tokens, page_size, names=None):
        """The DEEPEST registered chain matching a prefix of `tokens`,
        held by a replica in `names` (None = any): returns
        ``(holder_name, matched_tokens, chain_hash)`` or None.  Ties
        between holders break to the most recently registered — the
        replica whose copy is warmest."""
        hashes = page_chain_hashes(tokens, page_size)
        for depth in range(len(hashes), 0, -1):
            holders = self._holders.get(hashes[depth - 1])
            if not holders:
                continue
            pool = [n for n in holders if names is None or n in names]
            if pool:
                best = max(pool, key=lambda n: holders[n])
                return best, depth * page_size, hashes[depth - 1]
        return None

    def compact(self, live):
        """Week-long-uptime memory bound: drop every holder entry not
        in `live` (replica names currently serving) and every chain
        left with no live holder.  drop_replica already handles clean
        deaths; this sweep is the belt-and-braces GC the router's
        watchdog runs so renames, missed death paths, and long
        add/drop churn can never grow the index without bound.
        Returns the number of chains dropped."""
        live = set(live)
        dropped = 0
        for chain in list(self._holders):
            holders = self._holders[chain]
            for name in [n for n in holders if n not in live]:
                del holders[name]
            if not holders:
                del self._holders[chain]
                dropped += 1
        if dropped:
            self.compactions += 1
            self.chains_compacted += dropped
        return dropped

    def chains_held(self, name=None):
        """Registered chain count (fleet-wide, or one replica's) — the
        stats_snapshot gauge."""
        if name is None:
            return len(self._holders)
        return sum(1 for h in self._holders.values() if name in h)
