"""ServingEngine: the dynamically-batched TPU serving runtime.

Composes the subsystem end to end::

    client -> submit() -> AdmissionQueue -> DynamicBatcher(worker thread)
           <- Future   <-  scatter      <- CompiledModelCache[bucket] <- pad

The model can be an `inference.Predictor` (the deployable jax.export
artifact — export with ``InputSpec([-1, ...])`` so one module serves
every bucket), or any positional callable over arrays.  Per-request
outputs are sliced back out of the padded batch, so callers see exactly
what an unbatched `Predictor.run` would have returned.

Overload behavior is explicit: a full queue raises ServerBusyError at
submit; a request whose deadline lapses in queue or while its batch
forms resolves with DeadlineExceededError; nothing ever waits unbounded.
"""
import concurrent.futures
import time

import numpy as np

from .admission import AdmissionQueue, Request, ServingError
from .batcher import DynamicBatcher
from .bucketing import CompiledModelCache, ShapeBucketer
from .metrics import ServingMetrics


class ServingConfig:
    """Serving knobs; every default is safe for a small CPU demo and the
    fields map 1:1 to the docs in docs/SERVING.md."""

    def __init__(self, batch_buckets=(1, 2, 4, 8), length_buckets=None,
                 max_batch_size=None, max_batch_delay_ms=2.0,
                 queue_depth=64, default_timeout_ms=None, pad_value=0):
        self.batch_buckets = tuple(batch_buckets)
        self.length_buckets = (None if length_buckets is None
                               else tuple(length_buckets))
        self.max_batch_size = max_batch_size
        self.max_batch_delay_ms = float(max_batch_delay_ms)
        self.queue_depth = int(queue_depth)
        self.default_timeout_ms = default_timeout_ms
        self.pad_value = pad_value


def _model_fn(model):
    """(fn, in_names) from whatever the caller serves.

    inference.Predictor carries either a deserialized jax.export module
    (`_exported.call`) or a rebuilt jitted forward (`_jitted`) — both are
    positional array fns, exactly what the bucket cache AOT-compiles."""
    exported = getattr(model, "_exported", None)
    if exported is not None:
        return exported.call, list(model.get_input_names())
    jitted = getattr(model, "_jitted", None)
    if jitted is not None:
        return jitted, list(model.get_input_names())
    if callable(model):
        return model, None
    raise TypeError(
        f"cannot serve {type(model).__name__}: need an inference.Predictor "
        f"or a positional callable over arrays")


class ServingEngine:
    """Dynamically-batched, shape-bucketed inference server core."""

    def __init__(self, model, config=None, metrics=None):
        self.config = config or ServingConfig()
        self._fn, self._in_names = _model_fn(model)
        self.metrics = metrics or ServingMetrics()
        self.bucketer = ShapeBucketer(self.config.batch_buckets,
                                      self.config.length_buckets,
                                      self.config.pad_value)
        self.cache = CompiledModelCache(self._fn, metrics=self.metrics)
        self.queue = AdmissionQueue(self.config.queue_depth,
                                    metrics=self.metrics)
        self.batcher = DynamicBatcher(
            self.cache, self.queue, self.bucketer,
            max_batch_size=self.config.max_batch_size,
            max_batch_delay_ms=self.config.max_batch_delay_ms,
            metrics=self.metrics)
        self._closed = False

    # --- client API ---
    def _normalize(self, feeds):
        if isinstance(feeds, dict):
            if self._in_names is None:
                raise ValueError(
                    "dict feeds need a Predictor-backed engine (input "
                    "names unknown for a bare callable); pass a list")
            missing = [n for n in self._in_names if n not in feeds]
            if missing:
                raise ValueError(f"missing feeds: {missing}")
            arrays = [np.asarray(feeds[n]) for n in self._in_names]
        else:
            arrays = [np.asarray(a) for a in feeds]
        if not arrays:
            raise ValueError("empty feed")
        rows = int(arrays[0].shape[0]) if arrays[0].ndim else 1
        for a in arrays:
            if a.ndim == 0 or int(a.shape[0]) != rows:
                raise ValueError(
                    "every input needs the same leading batch dim "
                    f"(got {[tuple(np.asarray(x).shape) for x in arrays]})")
        return arrays, rows

    def submit(self, feeds, timeout_ms=None):
        """Enqueue one request; returns a concurrent.futures.Future whose
        result is the list of per-request output arrays.  Raises
        ServerBusyError synchronously when the queue is full and
        RequestTooLargeError when rows exceed the largest bucket."""
        if self._closed:
            raise ServingError("engine is shut down")
        arrays, rows = self._normalize(feeds)
        self.bucketer.batch_bucket(rows)  # RequestTooLargeError past menu
        arrays = self.bucketer.pad_request(arrays)
        timeout_ms = (self.config.default_timeout_ms
                      if timeout_ms is None else timeout_ms)
        deadline = (None if timeout_ms is None
                    else time.monotonic() + float(timeout_ms) / 1e3)
        fut = concurrent.futures.Future()
        req = Request(arrays, rows, fut, deadline=deadline,
                      bucket_key=self.bucketer.bucket_key(arrays))
        self.queue.offer(req)  # ServerBusyError when full
        self.metrics.count_request()
        return fut

    def infer(self, feeds, timeout_ms=None):
        """Blocking convenience: submit + wait.  The engine's deadline
        machinery resolves the future with DeadlineExceededError, so the
        host-side wait below is only a backstop (2x the deadline)."""
        fut = self.submit(feeds, timeout_ms=timeout_ms)
        wait = (None if timeout_ms is None
                else max(0.1, 2.0 * float(timeout_ms) / 1e3))
        return fut.result(timeout=wait)

    def warmup(self, sample_feeds=None):
        """Pre-compile every batch bucket for the given sample request (or
        per-input trailing shapes from the first real request otherwise)."""
        if sample_feeds is None:
            return
        arrays, _ = self._normalize(sample_feeds)
        arrays = self.bucketer.pad_request(arrays)
        for b in self.bucketer.batch_buckets:
            batch = [np.broadcast_to(
                a[:1], (b,) + tuple(a.shape[1:])).copy() for a in arrays]
            self.cache.get(batch)

    def stats(self):
        """Serving metrics snapshot (the StatRegistry serving.* slice)."""
        return self.metrics.snapshot()

    def shutdown(self):
        if self._closed:
            return
        self._closed = True
        self.batcher.shutdown()
        self.queue.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False


def create_serving_engine(model, **kwargs):
    """Convenience factory mirroring inference.create_predictor."""
    return ServingEngine(model, config=ServingConfig(**kwargs))
