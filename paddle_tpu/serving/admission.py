"""Admission control: bounded queue, per-request deadlines, typed overload.

The overload contract (graceful degradation, not unbounded latency):

- a FULL queue rejects the submit synchronously with `ServerBusyError` —
  callers shed load immediately instead of piling onto a queue whose
  wait already exceeds any useful deadline;
- an EXPIRED request is rejected with `DeadlineExceededError` the moment
  any queue scan observes it (admission, coalescing, or dispatch) — a
  request that cannot make its deadline never spends TPU time.

Reference anchors: the Predictor-side counterpart of the reference's
server-side request queues (PredictorPool gives per-thread predictors but
no queueing/overload semantics at all).
"""
import collections
import threading
import time


class ServingError(RuntimeError):
    """Base class for all serving runtime errors."""


class ServerBusyError(ServingError):
    """Admission queue full: the server is overloaded; retry with backoff
    against another replica (the explicit busy error the overload
    contract promises instead of unbounded queueing latency)."""


class DeadlineExceededError(ServingError, TimeoutError):
    """The request's deadline passed before a result could be produced.
    Subclasses TimeoutError so generic timeout handlers catch it."""


class RequestTooLargeError(ServingError):
    """A single request exceeds the largest configured batch bucket; it
    can never be scheduled and is rejected at submit."""


class ReplicaTimeoutError(ServingError, TimeoutError):
    """A cross-replica RPC exceeded its bounded deadline (the peer is
    hung, wedged, or the channel is poisoned — NOT a client deadline,
    which is DeadlineExceededError).  Idempotent ops retry with backoff
    under a bounded attempt budget; non-idempotent ops fail fast into
    the fleet's remigration ladder.  Subclasses TimeoutError so generic
    timeout handlers catch it."""


class Request:
    """One in-flight inference request."""

    __slots__ = ("args", "rows", "future", "deadline", "submit_t",
                 "bucket_key")

    def __init__(self, args, rows, future, deadline=None, bucket_key=None):
        self.args = args            # list of np arrays, leading batch axis
        self.rows = int(rows)       # real (unpadded) batch rows
        self.future = future        # concurrent.futures.Future
        self.deadline = deadline    # absolute time.monotonic() or None
        self.submit_t = time.monotonic()
        self.bucket_key = bucket_key  # trailing-shape key for coalescing

    def expired(self, now=None):
        return self.deadline is not None and \
            (now if now is not None else time.monotonic()) >= self.deadline

    def reject_expired(self):
        if self.future.done():
            return  # client cancelled; nothing to report
        waited_ms = (time.monotonic() - self.submit_t) * 1e3
        try:
            self.future.set_exception(DeadlineExceededError(
                f"request deadline exceeded after {waited_ms:.1f} ms "
                f"in queue"))
        except Exception:
            pass  # lost a cancel race: the future is already resolved


class AdmissionQueue:
    """Bounded FIFO with deadline-aware scans.

    `offer` never blocks: a full queue is an overload signal, surfaced as
    ServerBusyError.  `poll`/`poll_match` hand requests to the batcher
    worker; both drop expired requests on the way (resolving their
    futures with DeadlineExceededError) so a stale head can never delay a
    live request behind it.
    """

    def __init__(self, max_depth=64, metrics=None):
        if max_depth < 1:
            raise ValueError(f"max_depth must be >= 1, got {max_depth}")
        self.max_depth = int(max_depth)
        self._dq = collections.deque()
        self._cond = threading.Condition()
        self._closed = False
        self._metrics = metrics

    def __len__(self):
        with self._cond:
            return len(self._dq)

    def _gauge(self):
        if self._metrics is not None:
            self._metrics.set_queue_depth(len(self._dq))

    def offer(self, req):
        """Enqueue or raise ServerBusyError; never blocks the caller."""
        with self._cond:
            if self._closed:
                raise ServingError("serving queue is shut down")
            if len(self._dq) >= self.max_depth:
                if self._metrics is not None:
                    self._metrics.count_rejected_busy()
                raise ServerBusyError(
                    f"admission queue full ({self.max_depth} requests "
                    f"queued); server overloaded — retry with backoff")
            self._dq.append(req)
            self._gauge()
            self._cond.notify()

    def _reap_expired_locked(self):
        """Drop every expired request currently queued (any position —
        deadlines need not be FIFO-ordered)."""
        if not self._dq:
            return
        now = time.monotonic()
        live, dropped = [], []
        for r in self._dq:
            (dropped if r.expired(now) else live).append(r)
        if dropped:
            self._dq.clear()
            self._dq.extend(live)
            self._gauge()
        for r in dropped:
            r.reject_expired()
        if dropped and self._metrics is not None:
            self._metrics.count_rejected_deadline(len(dropped))

    def poll(self, timeout=None):
        """Next live request, or None on timeout/shutdown."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                self._reap_expired_locked()
                if self._dq:
                    req = self._dq.popleft()
                    self._gauge()
                    return req
                if self._closed:
                    return None
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(remaining)

    def poll_match(self, bucket_key, max_rows, timeout=None):
        """First live request with `bucket_key`-compatible trailing shapes
        and rows <= max_rows, or None on timeout.  Scans past
        non-matching requests without disturbing their order (shape-
        sharded coalescing: one dispatch serves ONE bucket)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._cond:
            while True:
                self._reap_expired_locked()
                for i, r in enumerate(self._dq):
                    if r.bucket_key == bucket_key and r.rows <= max_rows:
                        del self._dq[i]
                        self._gauge()
                        return r
                if self._closed:
                    return None
                remaining = None if deadline is None \
                    else deadline - time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(remaining)

    def remove(self, pred):
        """Pull every queued request matching `pred(req)` WITHOUT
        resolving its future — the cancel path: the caller owns the
        resolution (a typed result or error), this only frees the
        queue slot.  Returns the removed requests in FIFO order."""
        with self._cond:
            taken = [r for r in self._dq if pred(r)]
            if taken:
                kept = [r for r in self._dq if not pred(r)]
                self._dq.clear()
                self._dq.extend(kept)
                self._gauge()
            return taken

    def close(self):
        """Shut down: wake pollers; every queued request is rejected."""
        with self._cond:
            self._closed = True
            pending = list(self._dq)
            self._dq.clear()
            self._gauge()
            self._cond.notify_all()
        for r in pending:
            if r.future.done():
                continue  # client cancelled while queued
            try:
                r.future.set_exception(ServingError(
                    "serving engine shut down with request queued"))
            except Exception:
                pass  # cancel race: never let one future strand the rest
