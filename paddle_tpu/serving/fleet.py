"""Fleet tier: multi-replica generation serving with prefix-affinity
and SLO-aware routing.

Everything below `serving/` and `generation/` batches inside ONE
process: a single `GenerationEngine` owns one KV pool, one prefix
index, one admission queue.  Heavy traffic needs N engine replicas —
possibly heterogeneous (a long-context replica and a low-latency
replica behind one API) — and a front door that makes page-locality
decisions an engine cannot see: which replica already holds a session's
warm pages, which one likely has a prompt's system prefix indexed,
which one has slack.  The FleetRouter is that front door::

    submit(prompt, session=...) ── routing ladder ──> replica engine
         <- GenerationHandle           │                (its own pools,
            (same streaming            │                 prefix index,
             contract)                 │                 AdmissionQueue)
                                       ▼
          1. SESSION AFFINITY   a session id pins follow-up turns to
                                the replica holding their warm pages
          2. PREFIX AFFINITY    hash of the prompt's leading page-
                                aligned tokens prefers the replica
                                whose prefix index LIKELY holds it —
                                measured, not assumed: the router
                                confirms every prefix bet against the
                                handle's prefix_hit_tokens stamp
          3. LEAST LOADED       queue depth + resident pages + measured
                                TTFT EWMA relative to the fastest
                                candidate (a slow replica sheds new
                                traffic under skewed prompt lengths)
          spill                 a full first choice falls through the
                                remaining candidates by load
          shed                  every candidate's admission gate
                                closed -> fleet.shed_total +
                                ServerBusyError (typed, synchronous)

Per-replica admission is the serving AdmissionQueue unchanged (typed
ServerBusyError / DeadlineExceededError); the fleet only ADDS the
cross-replica hop, so a fleet of one behaves exactly like a bare
engine.

The fleet is DISAGGREGATED (serving/disagg): every replica sits behind
a ReplicaTransport — `InprocTransport` (direct-object engine, the
deterministic CPU oracle) or `SubprocTransport` (one OS process per
replica, pickled RPC over a socketpair, heartbeat liveness; a crashed
process is detected and its in-flight ledger remigrates, streams
resolve typed instead of hanging).  The prefix-affinity rung reads a
fleet-level `FleetPrefixIndex` fed by register/evict deltas each
replica's cache reports — MEASURED bookkeeping centralized in the
router, page BYTES moved point-to-point on demand: when the index
says a different replica holds a prompt's warm run, the router ships
the pages so the chosen replica adopts a run it never prefilled.

Drain (`drain(name)`) stops admissions to a replica and moves its
not-yet-finished work to siblings: live decode residents as TRUE LIVE
MIGRATIONS — page bytes + position + sampling RNG ship to a sibling
that RESUMES the stream with zero replayed tokens — and everything
else (plus any resident no sibling can adopt) as COLD RESUBMITS:
sampling is seeded per request, so a resubmit replays the identical
stream, and a relay handle skips the tokens the client already
received (counted in fleet.migrated_replay_tokens — the live-vs-cold
A/B).  migrate=False lets residents finish first, then joins the
worker.  `restart(name)` rebuilds the replica from its spec (fresh
pools, empty prefix index, a fresh process for subprocess replicas);
stale prefix-affinity bets against it are caught by the confirmation
loop AND the fleet index drop, not assumed away.

Token-identity oracle (tests/test_fleet.py): whatever the routing
outcome — affinity hit, prefix spill, shed-and-retry, mid-stream drain
with resubmit — every completed request's tokens are identical to a
single-replica cold run of the same prompt, greedy and seeded
stochastic alike; and `fleet.shed_total` only increments when every
replica's admission gate is closed.

Docs: docs/SERVING.md "Fleet tier".
"""
import math
import threading
import time
import zlib

import numpy as np

from ..generation.engine import GenerationHandle
from ..generation.sampling import SamplingParams
from ..generation.scheduler import GenerationRequest
from ..profiler.monitor import StatRegistry
from .admission import (ReplicaTimeoutError, RequestTooLargeError,
                        ServerBusyError, ServingError)
from .disagg import pagecodec
from .disagg.page_service import FleetPrefixIndex
from .disagg.transport import HEARTBEAT_S, RpcPolicy, build_transport

PREFIX = "fleet."

ROUTED_AFFINITY = PREFIX + "routed_affinity"
ROUTED_PREFIX = PREFIX + "routed_prefix"
ROUTED_BALANCE = PREFIX + "routed_balance"
ROUTED_RANDOM = PREFIX + "routed_random"
ROUTED_SPILL = PREFIX + "routed_spill"
SHED_TOTAL = PREFIX + "shed_total"
MIGRATED_TOTAL = PREFIX + "migrated_total"
PREFIX_ROUTED_CONFIRMED = PREFIX + "prefix_routed_confirmed"
PREFIX_ROUTED_MISSED = PREFIX + "prefix_routed_missed"
REPLICA_QUEUE_DEPTH = PREFIX + "replica_queue_depth"
# disaggregation tier (serving/disagg): heartbeat liveness, live
# migration vs cold-resubmit accounting, page-service adoptions
REPLICA_HEARTBEAT_AGE = PREFIX + "replica_heartbeat_age_s"
REPLICA_DEAD_TOTAL = PREFIX + "replica_dead_total"
LIVE_MIGRATED_TOTAL = PREFIX + "live_migrated_total"
MIGRATED_REPLAY_TOKENS = PREFIX + "migrated_replay_tokens"
PAGE_ADOPTIONS = PREFIX + "page_adoptions"
PAGES_ADOPTED = PREFIX + "pages_adopted"
# chaos-hardening tier (ISSUE 15): per-replica circuit breakers,
# bounded-RPC deadline misses, wedge watchdog kills, orphaned-stream
# remigration, and exponential respawn backoff
BREAKER_OPEN_TOTAL = PREFIX + "breaker_open_total"
BREAKER_STATE = PREFIX + "breaker_state"
REPLICA_TIMEOUT_TOTAL = PREFIX + "replica_timeout_total"
WEDGE_KILL_TOTAL = PREFIX + "wedge_kill_total"
ORPHAN_REMIGRATED_TOTAL = PREFIX + "orphan_remigrated_total"
RESPAWN_BACKOFF_S = PREFIX + "respawn_backoff_s"
# cross-host fleet tier (ISSUE 17): prefill/decode disaggregation as a
# routing policy, supervisor liveness probes, and autoscaling
PD_HANDOFFS = PREFIX + "pd_handoffs"
PD_HANDOFF_TOKENS = PREFIX + "pd_handoff_tokens"
PD_HANDOFF_WALL_S = PREFIX + "pd_handoff_wall_s"
ROUTED_ROLE = PREFIX + "routed_role"
PING_PROBE_TOTAL = PREFIX + "ping_probe_total"
SUPERVISOR_RESTART_TOTAL = PREFIX + "supervisor_restart_total"
AUTOSCALE_SPAWNED = PREFIX + "autoscale_spawned"
AUTOSCALE_DRAINED = PREFIX + "autoscale_drained"
REPLICA_COUNT = PREFIX + "replica_count"
# data-plane tier (ISSUE 20): p2p page transfer, compressed payloads,
# async adoption.  relay_bytes counts page bytes that crossed the
# ROUTER's socket (must stay 0 on the p2p path — counter-asserted);
# p2p wire/raw bytes carry the compression-ratio arithmetic.
PAGE_RELAY_BYTES = PREFIX + "page_relay_bytes"
PAGE_P2P_BYTES = PREFIX + "page_p2p_bytes"
PAGE_RAW_BYTES = PREFIX + "page_raw_bytes"
PAGE_TRANSFERS_FAILED = PREFIX + "page_transfers_failed"
PAGE_TRANSFERS_CANCELLED = PREFIX + "page_transfers_cancelled"
PREFIX_INDEX_COMPACTIONS = PREFIX + "prefix_index_compactions"


class FleetMetrics:
    """fleet.* counters/gauges in the profiler StatRegistry (the
    serving./generation. pattern one tier up).  Routing counters split
    by the rung that actually placed the request; the per-replica
    queue-depth gauges land under ``fleet.replica_queue_depth.<name>``
    with the bare name carrying the fleet-wide MAX (the saturation
    signal load shedding is about)."""

    def __init__(self, registry=None):
        self._reg = registry or StatRegistry.instance()
        # touch every counter so the very first snapshot carries the
        # complete schema (shed_total == 0 is a statement, not a gap)
        for name in (ROUTED_AFFINITY, ROUTED_PREFIX, ROUTED_BALANCE,
                     ROUTED_RANDOM, ROUTED_SPILL, SHED_TOTAL,
                     MIGRATED_TOTAL, PREFIX_ROUTED_CONFIRMED,
                     PREFIX_ROUTED_MISSED, REPLICA_QUEUE_DEPTH,
                     REPLICA_HEARTBEAT_AGE, REPLICA_DEAD_TOTAL,
                     LIVE_MIGRATED_TOTAL, MIGRATED_REPLAY_TOKENS,
                     PAGE_ADOPTIONS, PAGES_ADOPTED,
                     BREAKER_OPEN_TOTAL, BREAKER_STATE,
                     REPLICA_TIMEOUT_TOTAL, WEDGE_KILL_TOTAL,
                     ORPHAN_REMIGRATED_TOTAL, RESPAWN_BACKOFF_S,
                     PD_HANDOFFS, PD_HANDOFF_TOKENS, PD_HANDOFF_WALL_S,
                     ROUTED_ROLE, PING_PROBE_TOTAL,
                     SUPERVISOR_RESTART_TOTAL, AUTOSCALE_SPAWNED,
                     AUTOSCALE_DRAINED, REPLICA_COUNT,
                     PAGE_RELAY_BYTES, PAGE_P2P_BYTES, PAGE_RAW_BYTES,
                     PAGE_TRANSFERS_FAILED, PAGE_TRANSFERS_CANCELLED,
                     PREFIX_INDEX_COMPACTIONS):
            self._reg.get_stat(name)

    def _stat(self, name):
        return self._reg.get_stat(name)

    def count_routed(self, rung):
        self._stat({"affinity": ROUTED_AFFINITY, "prefix": ROUTED_PREFIX,
                    "balance": ROUTED_BALANCE,
                    "random": ROUTED_RANDOM}[rung]).increase()

    def count_spill(self):
        self._stat(ROUTED_SPILL).increase()

    def count_shed(self):
        self._stat(SHED_TOTAL).increase()

    def count_migrated(self, n=1):
        if n:
            self._stat(MIGRATED_TOTAL).increase(n)

    def count_prefix_confirmed(self, hit):
        self._stat(PREFIX_ROUTED_CONFIRMED if hit
                   else PREFIX_ROUTED_MISSED).increase()

    def count_replica_dead(self):
        self._stat(REPLICA_DEAD_TOTAL).increase()

    def count_live_migrated(self, n=1):
        if n:
            self._stat(LIVE_MIGRATED_TOTAL).increase(n)

    def count_replay_tokens(self, n):
        """Stream tokens a COLD resubmit recomputes that the client
        already streamed (the relay swallows them) — live migration's
        structural 0 vs the cold baseline's full replay, per drain."""
        if n:
            self._stat(MIGRATED_REPLAY_TOKENS).increase(int(n))

    def count_page_adoption(self, pages):
        """One page-service transfer that indexed `pages` new pages on
        the adopting replica."""
        self._stat(PAGE_ADOPTIONS).increase()
        if pages:
            self._stat(PAGES_ADOPTED).increase(int(pages))

    def count_page_relay_bytes(self, n):
        """Page bytes that crossed the ROUTER's socket (relay path).
        The p2p zero-relay assertion reads this counter."""
        if n:
            self._stat(PAGE_RELAY_BYTES).increase(int(n))

    def count_page_p2p_bytes(self, wire, raw):
        """Page bytes that moved replica→replica on the data socket:
        `wire` as encoded (post-codec), `raw` what the same transfer
        would have weighed uncompressed — the compression ratio is
        raw/wire."""
        if wire:
            self._stat(PAGE_P2P_BYTES).increase(int(wire))
        if raw:
            self._stat(PAGE_RAW_BYTES).increase(int(raw))

    def count_transfer_failed(self):
        """One adoption transfer degraded typed to the cold-prefill
        ladder (holder/importer trouble, codec mismatch, deadline)."""
        self._stat(PAGE_TRANSFERS_FAILED).increase()

    def count_transfer_cancelled(self):
        """One queued transfer cancelled before moving bytes: the
        index no longer wants it (importer already holds the chain,
        or a party died)."""
        self._stat(PAGE_TRANSFERS_CANCELLED).increase()

    def count_index_compactions(self, chains):
        """One prefix-index GC sweep that dropped `chains` chains with
        no live holder."""
        if chains:
            self._stat(PREFIX_INDEX_COMPACTIONS).increase(int(chains))

    def count_breaker_open(self):
        """A circuit breaker tripped open: `breaker_threshold`
        consecutive transport faults took the replica out of every
        routing gate."""
        self._stat(BREAKER_OPEN_TOTAL).increase()

    def count_replica_timeout(self):
        """One bounded RPC missed its deadline (ReplicaTimeoutError)."""
        self._stat(REPLICA_TIMEOUT_TOTAL).increase()

    def count_wedge_kill(self):
        """The wedge watchdog killed an alive-but-stalled replica."""
        self._stat(WEDGE_KILL_TOTAL).increase()

    def count_orphan_remigrated(self):
        """A stream whose completion event was lost (idle worker,
        lingering ledger entry) was remigrated by the orphan sweep."""
        self._stat(ORPHAN_REMIGRATED_TOTAL).increase()

    def count_pd_handoff(self, tokens, wall_s):
        """One prefill→decode handoff: a finished prefill's page run
        shipped to a decode-class sibling.  `tokens` is the cache
        length that moved; `wall_s` the park-to-placement wall (gauge:
        the latest handoff's wall, the drain-latency signal)."""
        self._stat(PD_HANDOFFS).increase()
        if tokens:
            self._stat(PD_HANDOFF_TOKENS).increase(int(tokens))
        self._stat(PD_HANDOFF_WALL_S).set(round(float(wall_s), 4))

    def count_routed_role(self):
        """A request placed on a replica whose ROLE matched the
        request class (prefill-heavy → prefill replica, interactive →
        decode replica) — the segregation signal of the P/D rung."""
        self._stat(ROUTED_ROLE).increase()

    def count_ping_probe(self):
        """One synthetic watchdog ping probe sent to earn an idle
        replica's breaker its half-open recovery."""
        self._stat(PING_PROBE_TOTAL).increase()

    def count_supervisor_restart(self):
        """The control plane resurrected a dead/stopped replica."""
        self._stat(SUPERVISOR_RESTART_TOTAL).increase()

    def count_autoscale(self, up):
        self._stat(AUTOSCALE_SPAWNED if up
                   else AUTOSCALE_DRAINED).increase()

    def set_replica_count(self, n):
        self._stat(REPLICA_COUNT).set(int(n))

    def set_breaker_state(self, name, score):
        """0 = closed, 1 = half-open, 2 = open; bare gauge = max."""
        self._stat(f"{BREAKER_STATE}.{name}").set(int(score))

    def set_max_breaker_state(self, score):
        self._stat(BREAKER_STATE).set(int(score))

    def set_respawn_backoff(self, name, backoff_s):
        self._stat(f"{RESPAWN_BACKOFF_S}.{name}").set(
            round(float(backoff_s), 3))
        self._stat(RESPAWN_BACKOFF_S).set(round(float(backoff_s), 3))

    def set_heartbeat_age(self, name, age):
        self._stat(f"{REPLICA_HEARTBEAT_AGE}.{name}").set(
            round(float(age), 3))

    def set_max_heartbeat_age(self, age):
        self._stat(REPLICA_HEARTBEAT_AGE).set(round(float(age), 3))

    def set_replica_queue_depth(self, name, depth):
        self._stat(f"{REPLICA_QUEUE_DEPTH}.{name}").set(int(depth))

    def set_max_queue_depth(self, depth):
        self._stat(REPLICA_QUEUE_DEPTH).set(int(depth))

    def snapshot(self):
        return {k: v for k, v in self._reg.stats().items()
                if k.startswith(PREFIX)}


class CircuitBreaker:
    """Per-replica consecutive-failure circuit breaker.

    States::

        closed ──(threshold consecutive transport FAULTS)──> open
        open ──(cooldown elapsed AND a fresh heartbeat)──> half-open
        half-open ──(probe success)──> closed
        half-open ──(probe failure)──> open (cooldown re-arms)

    A FAULT is a transport failure — an RPC deadline miss, a dead
    channel — never an admission-load rejection (`ServerBusyError` is
    back-pressure, not breakage: it feeds the load score, not the
    breaker).  While open the replica leaves EVERY routing gate; the
    half-open probe rides heartbeat recovery (the replica proved it is
    alive again) and admits exactly one request, whose outcome decides
    the state.  Thread-safe: router threads, transport reader threads,
    and the watchdog all touch it."""

    STATE_SCORE = {"closed": 0, "half-open": 1, "open": 2}

    def __init__(self, threshold=3, cooldown_s=1.0, on_open=None):
        if int(threshold) < 1:
            raise ValueError(f"threshold must be >= 1, got {threshold}")
        self.threshold = int(threshold)
        self.cooldown_s = float(cooldown_s)
        self.state = "closed"
        self.failures = 0
        self._opened_at = 0.0
        self._probe = False
        self._on_open = on_open
        self._lock = threading.Lock()

    @property
    def score(self):
        """The gauge encoding: 0 closed, 1 half-open, 2 open."""
        return self.STATE_SCORE[self.state]

    def _half_open_ready(self, hb_age, hb_fresh_s):
        return (time.monotonic() - self._opened_at >= self.cooldown_s
                and float(hb_age) <= float(hb_fresh_s))

    def routable(self, hb_age=0.0, hb_fresh_s=1.0):
        """Read-only gate for candidate filtering: could a request be
        admitted here right now?  Never claims the half-open probe —
        that happens in admit(), at the moment of actual submission."""
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                return self._half_open_ready(hb_age, hb_fresh_s)
            return not self._probe

    def admit(self, hb_age=0.0, hb_fresh_s=1.0):
        """The submission-time gate: like routable(), but an open
        breaker whose cooldown elapsed under a fresh heartbeat
        transitions to half-open HERE, and the caller claims the one
        probe slot — record_success/record_failure/record_busy MUST
        follow, or the probe slot stays taken."""
        with self._lock:
            if self.state == "closed":
                return True
            if self.state == "open":
                if not self._half_open_ready(hb_age, hb_fresh_s):
                    return False
                self.state = "half-open"
                self._probe = False
            if self._probe:
                return False
            self._probe = True
            return True

    def record_success(self):
        with self._lock:
            self.failures = 0
            self._probe = False
            self.state = "closed"

    def record_busy(self):
        """Admission-load rejection: releases a claimed probe without
        counting a fault — a busy replica is healthy."""
        with self._lock:
            self._probe = False

    def record_failure(self):
        with self._lock:
            self.failures += 1
            self._probe = False
            if self.state == "half-open" \
                    or self.failures >= self.threshold:
                reopened = self.state != "open"
                self.state = "open"
                self._opened_at = time.monotonic()
            else:
                return
        if reopened and self._on_open is not None:
            self._on_open()

    def reset(self):
        """Administrative reset (restart() rebuilds the replica — its
        fault history died with the old process)."""
        with self._lock:
            self.state = "closed"
            self.failures = 0
            self._probe = False


class ReplicaSpec:
    """One replica's build recipe: a protocol model plus its OWN
    GenerationConfig — heterogeneous fleets (long-context next to
    low-latency) are just different specs behind one router.  The
    router keeps the spec so `restart(name)` can rebuild the engine
    after a drain.

    transport: "inproc" (direct-object engine, the deterministic CPU
        oracle path), "proc" (one OS process per replica behind the
        SubprocTransport RPC boundary — model and config must pickle,
        mesh configs are rejected; see serving/disagg), or "tcp" (the
        same worker process dialing back over a real TCP socket — the
        cross-host rung; see serving/disagg/tcp.py).  A
        FleetConfig.transport override applies to every spec.
    role: "mixed" (default — prefills and decodes, the classic
        replica), "prefill" (chews prompts; at prefill completion the
        router ships the finished page run to a decode-class sibling
        that streams the rest), or "decode" (preferred target of both
        the interactive-request rung and prefill handoffs).  Role is a
        ROUTING PREFERENCE, never a capability wall: any replica can
        still serve any request when its preferred class is full.
    host / port: the TCP listener's bind address for transport="tcp"
        (default 127.0.0.1 / ephemeral); ignored by other kinds."""

    __slots__ = ("name", "model", "config", "transport", "role",
                 "host", "port")

    def __init__(self, name, model, config=None, transport="inproc",
                 role="mixed", host=None, port=None):
        self.name = str(name)
        self.model = model
        self.config = config
        if transport not in ("inproc", "proc", "tcp"):
            raise ValueError(
                f"transport must be 'inproc', 'proc' or 'tcp', got "
                f"{transport!r}")
        self.transport = transport
        if role not in ("prefill", "decode", "mixed"):
            raise ValueError(
                f"role must be 'prefill', 'decode' or 'mixed', got "
                f"{role!r}")
        self.role = role
        self.host = host
        self.port = None if port is None else int(port)


class _MigrationRelay:
    """Engine-side handle adapter for a drain-migrated request.

    The sibling replica re-runs the prompt COLD; because sampling is
    seeded per request, the resubmitted stream is token-identical to
    the original, so this relay swallows the first `skip` tokens (the
    client already streamed them from the draining replica) and
    forwards the rest into the client's untouched handle — the client
    observes one continuous, gap-free, duplicate-free stream.  TTFT
    probes and the prefix_hit_tokens stamp stay the CLIENT handle's:
    first admission wins, exactly as for preemption re-admission."""

    __slots__ = ("_client", "_skip", "_skip0", "_pushed", "submitted_s",
                 "first_token_s")

    def __init__(self, client, skip):
        self._client = client
        self._skip = int(skip)
        self._skip0 = int(skip)
        self._pushed = 0
        self.submitted_s = None      # own clock; client keeps original
        self.first_token_s = None

    @property
    def prefix_hit_tokens(self):
        return self._client.prefix_hit_tokens

    @prefix_hit_tokens.setter
    def prefix_hit_tokens(self, v):
        self._client.prefix_hit_tokens = v

    def client_and_delivered(self):
        """(client handle, stream tokens the client has received) — the
        skip count a SECOND migration of the same request needs.  The
        client's own n_streamed counter is the FLOOR: whatever the
        relay bookkeeping says, a replay must never re-push a token
        the client already has."""
        return self._client, max(self._skip0, self._pushed,
                                 getattr(self._client, "n_streamed", 0))

    def _push_token(self, token):
        if self.first_token_s is None:
            self.first_token_s = time.monotonic()
        self._pushed += 1
        if self._skip > 0:
            self._skip -= 1
            return
        self._client._push_token(token)

    def _finish(self, result):
        # the replayed result IS the request's result: token_ids cover
        # the whole stream, already delivered + newly forwarded
        self._client._finish(result)

    def set_exception(self, exc):
        self._client.set_exception(exc)

    def done(self):
        return self._client.done()


class _Replica:
    """One live replica BEHIND A TRANSPORT: the router's view is the
    duck-typed transport contract (serving/disagg/transport.py) — an
    in-process engine and a subprocess replica look identical from
    here — plus the admission state the router flips and the measured
    TTFT EWMA the latency-aware load score folds in."""

    _TTFT_EWMA_ALPHA = 0.3   # same smoothing as generation.tokens_per_s
    _TTFT_LOAD_CAP = 4.0     # a slow replica weighs at most like this
    # many queued requests: bounded back-pressure, never starvation

    def __init__(self, spec, start, transport_kind, on_death=None,
                 rpc=None, fault_plan=None, breaker=None):
        self.spec = spec
        self.kind = transport_kind
        self.state = "stopped"
        self.transport = None
        self._describe = None
        self._on_death = on_death
        self._rpc = rpc
        self._fault_plan = fault_plan
        # the chaos-hardening state the router keeps PER replica: a
        # consecutive-failure circuit breaker and the respawn-backoff
        # clocks (consecutive quick deaths ⇒ exponential restart
        # backoff, capped into a crash-loop refusal)
        self.breaker = breaker or CircuitBreaker()
        self.respawns = 0
        self.built_at = 0.0
        self.died_at = None
        # measured time-to-first-token EWMA (seconds; None = no sample
        # yet).  Updated from handle done-callbacks, which fire on
        # engine worker threads — the float swap is a benign last-
        # writer-wins race for a smoothed load signal.
        self.ttft_ewma = None
        self.build(start)

    def observe_ttft(self, handle):
        """Fold one completed request's measured TTFT into the EWMA
        (requests that never produced a first token — typed failures,
        sheds — carry no latency signal and are skipped)."""
        if handle.first_token_s is None or handle.submitted_s is None:
            return
        ttft = handle.first_token_s - handle.submitted_s
        if ttft < 0:
            return
        prev = self.ttft_ewma
        self.ttft_ewma = (ttft if prev is None else
                          self._TTFT_EWMA_ALPHA * ttft
                          + (1 - self._TTFT_EWMA_ALPHA) * prev)

    def build(self, start):
        self.transport = build_transport(self.spec, self.kind,
                                         start=start, rpc=self._rpc,
                                         fault_plan=self._fault_plan)
        self.transport.on_death = self._on_death
        self._describe = self.transport.describe()
        self.state = "serving"
        self.built_at = time.monotonic()
        self.died_at = None
        # a rebuilt replica is a new process in spirit: its latency
        # and fault history died with the old engine
        self.ttft_ewma = None
        self.breaker.reset()

    @property
    def name(self):
        return self.spec.name

    @property
    def role(self):
        return getattr(self.spec, "role", "mixed")

    @property
    def accepting(self):
        return self.state == "serving" and self.transport.alive()

    @property
    def engine(self):
        """The direct engine object — inproc transports only (tests
        and the stepped oracle drive it); None across a process
        boundary."""
        return getattr(self.transport, "engine", None)

    @property
    def registry(self):
        return getattr(self.transport, "registry", None)

    def can_fit(self, prompt_len, max_new):
        """Could this replica EVER hold the request (pool + positions)?
        The capacity pre-filter that makes heterogeneous fleets work:
        a long prompt routes straight to the long-context replica
        instead of bouncing off a small one's typed rejection.
        Answered from the transport's static describe() — no RPC on
        the routing path."""
        d = self._describe
        if math.ceil((prompt_len + 1) / d["page_size"]) > d["num_pages"]:
            return False
        max_pos = d["max_positions"]
        mn = (d["default_max_new_tokens"] if max_new is None
              else int(max_new))
        return max_pos is None or prompt_len + mn <= max_pos

    def load(self, ttft_baseline=None):
        """Queue depth + live slots + resident-page fraction + measured
        latency — what 'least loaded' compares.  Pages enter as a
        FRACTION so queue position dominates and pool residency breaks
        ties (a replica with warm pages but an empty queue still reads
        near-idle).  `ttft_baseline` (the fastest candidate's TTFT
        EWMA) folds LATENCY in as a relative term: a replica measuring
        k-times the baseline TTFT carries k-1 extra load — a 2x-slower
        replica weighs like one extra queued request — CAPPED at
        _TTFT_LOAD_CAP so one pathological sample against a
        microsecond baseline cannot starve the replica forever: once
        the fast sibling queues past the cap, traffic flows back, the
        slow replica completes requests, and its EWMA decays (it only
        updates on completions).  Under skewed prompt lengths new
        traffic therefore drains toward the replica actually answering
        fast, without ever wedging the slow one out of the fleet.
        Replicas with no sample yet (or without a baseline) add
        nothing — cold replicas are worth probing, not penalizing.
        Load reads the transport's load_info: exact for inproc,
        heartbeat-fresh for subprocess replicas."""
        info = self.transport.load_info()
        score = (info["queue_depth"] + info["active"]
                 + info["pages_in_use"] / max(1, info["num_pages"]))
        if ttft_baseline and self.ttft_ewma:
            score += min(self.ttft_ewma / ttft_baseline - 1.0,
                         self._TTFT_LOAD_CAP)
        return score

    def queue_depth(self):
        return self.transport.load_info()["queue_depth"]


class FleetConfig:
    """Router knobs.

    routing: "affinity" (the session → prefix → least-loaded ladder)
        or "random" (uniform choice — the A/B baseline
        tools/gen_bench.py --replicas measures the ladder against).
    affinity_block_tokens: page alignment of the prefix-affinity hash —
        the prompt's leading ``floor((len-1)/block)*block`` tokens are
        hashed (matching match_prefix's full-page, clip-to-len-1
        semantics so the hash covers exactly what a warm hit could
        alias).  None = auto: the smallest page_size in the fleet.
    start: start each replica engine's background worker (tests drive
        steps themselves via run_until_idle and pass False).
    seed: the random-routing RNG seed (reproducible A/B benches).
    transport: override EVERY spec's transport — "inproc", "proc",
        "tcp", or None (each ReplicaSpec keeps its own; the gen_bench
        --fleet-transport A/B flips this one knob).
    pd_prefill_threshold_tokens: the P/D routing split — a prompt at
        least this long prefers prefill-class replicas (whose finished
        runs hand off to decode-class siblings); shorter interactive
        requests prefer decode-class replicas so a prompt wave never
        queues ahead of their first token.  Only matters when the
        fleet has non-mixed roles.
    min_replicas / max_replicas: the autoscaler's bounds
        (serving/control.py FleetSupervisor spawns under sustained
        queue depth / TTFT pressure up to `max_replicas`, drains its
        own spawns at idle down to `min_replicas`; None max = never
        scale up beyond the configured specs).
    live_migration: drain/crash migration ships resident sequence
        state to a sibling that RESUMES mid-decode (True, the
        default — migrated_replay_tokens stays 0); False restores the
        cold-resubmit-only path (seeded replay, the ablation baseline).
    heartbeat_dead_after: seconds without a heartbeat before a
        subprocess replica is declared dead (hung, not crashed — a
        crash is caught instantly by socket EOF) and its in-flight
        ledger remigrates.  Inproc replicas never age.
    page_service: fleet-level prefix index + point-to-point page
        transfer (True, the default under routing="affinity"); False
        keeps the stable-hash prefix guess only.

    Data-plane knobs (ISSUE 20, docs/SERVING.md "Data plane"):

    page_transfer: "p2p" (default — adoption bytes move on a direct
        replica→replica data socket; the router socket carries ZERO
        page bytes) or "relay" (the export-through-the-router
        baseline, also the automatic fallback while a replica's data
        port is not yet advertised).
    page_codec: "compressed" (default — pagecodec delta+zlib with
        per-array raw fallback) or "raw" (passthrough, the A/B
        baseline).  Applies to the p2p wire; the relay baseline
        always ships raw.
    async_adoption: True (default) ships adoption AFTER routing
        returns — the request prefills cold immediately and arriving
        pages warm the NEXT request; False restores the synchronous
        adopt-before-submit path (deterministic tests, ablation).
    max_inflight_transfers: per-importing-replica bound on concurrent
        adoption transfers the async scheduler allows (>= 1).

    Chaos-hardening knobs (docs/SERVING.md "Failure model"):

    rpc_timeout_s / rpc_retries / rpc_backoff_s: the bounded-RPC
        policy every subprocess replica's transport runs — a default
        deadline on EVERY `_call` (never unbounded), with idempotent
        ops retrying up to `rpc_retries` total attempts under
        exponential backoff (+ seeded jitter) from `rpc_backoff_s`.
    breaker_threshold / breaker_cooldown_s: per-replica circuit
        breaker — `threshold` CONSECUTIVE transport faults (timeouts,
        dead channels; never ServerBusyError) open it, taking the
        replica out of every routing gate; after `cooldown_s` a fresh
        heartbeat earns a single half-open probe request.
    wedge_after_s / wedge_hard_after_s: an alive-but-STALLED replica
        (heartbeats flow, the engine's step-progress stamp is frozen
        while it reports work) is killed and remigrated like a crash.
        The soft clock fires after `wedge_after_s` only when the
        engine is NOT inside a step (the step loop cannot take its
        own lock — a true wedge); an engine mid-step (a long jit
        compile is legitimate work) gets the hard ceiling
        `wedge_hard_after_s` (None = 10x the soft clock).
    orphan_grace_s: a stream whose worker reports idle for this long
        while its ledger entry lingers (lost completion event) is
        remigrated by the watchdog's orphan sweep.
    respawn_backoff_s / respawn_backoff_cap_s / max_respawns /
    respawn_reset_s: `restart()` of a replica that died within
        `respawn_reset_s` of its build waits an exponential backoff
        (base * 2^(n-1), capped at the cap); after `max_respawns`
        consecutive quick deaths restart refuses typed (crash loop) —
        `reset_respawn(name)` is the operator override.
    fault_plans: {replica_name: serving.disagg.faults.FaultPlan} —
        deterministic chaos injection on the replica's RPC codec
        (proc transports only; tests/drills, never production).
    watchdog_interval_s: background watchdog sweep period for fleets
        with subprocess replicas (None = auto from the thresholds).
    """

    def __init__(self, routing="affinity", affinity_block_tokens=None,
                 start=True, seed=None, transport=None,
                 live_migration=True, heartbeat_dead_after=10.0,
                 page_service=True, rpc_timeout_s=15.0, rpc_retries=3,
                 rpc_backoff_s=0.05, breaker_threshold=3,
                 breaker_cooldown_s=1.0, wedge_after_s=10.0,
                 wedge_hard_after_s=None,
                 orphan_grace_s=5.0, respawn_backoff_s=0.5,
                 respawn_backoff_cap_s=30.0, max_respawns=5,
                 respawn_reset_s=30.0, fault_plans=None,
                 watchdog_interval_s=None,
                 pd_prefill_threshold_tokens=64,
                 min_replicas=1, max_replicas=None,
                 page_transfer="p2p", page_codec="compressed",
                 async_adoption=True, max_inflight_transfers=2):
        if routing not in ("affinity", "random"):
            raise ValueError(
                f"routing must be 'affinity' or 'random', got {routing!r}")
        self.routing = routing
        if affinity_block_tokens is not None \
                and int(affinity_block_tokens) < 1:
            raise ValueError(
                f"affinity_block_tokens must be >= 1 or None (auto), "
                f"got {affinity_block_tokens}")
        self.affinity_block_tokens = (
            None if affinity_block_tokens is None
            else int(affinity_block_tokens))
        self.start = bool(start)
        self.seed = seed
        if transport not in (None, "inproc", "proc", "tcp"):
            raise ValueError(
                f"transport must be 'inproc', 'proc', 'tcp' or None "
                f"(per-spec), got {transport!r}")
        self.transport = transport
        self.live_migration = bool(live_migration)
        self.heartbeat_dead_after = float(heartbeat_dead_after)
        self.page_service = bool(page_service)
        # RpcPolicy validates timeout/retries/backoff on construction
        # — fail HERE, not at the first replica build
        RpcPolicy(rpc_timeout_s, rpc_retries, rpc_backoff_s)
        self.rpc_timeout_s = float(rpc_timeout_s)
        self.rpc_retries = int(rpc_retries)
        self.rpc_backoff_s = float(rpc_backoff_s)
        if int(breaker_threshold) < 1:
            raise ValueError(f"breaker_threshold must be >= 1, got "
                             f"{breaker_threshold}")
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        for knob, val in (("wedge_after_s", wedge_after_s),
                          ("orphan_grace_s", orphan_grace_s),
                          ("respawn_backoff_cap_s",
                           respawn_backoff_cap_s),
                          ("respawn_reset_s", respawn_reset_s)):
            if float(val) <= 0:
                raise ValueError(f"{knob} must be > 0, got {val}")
        self.wedge_after_s = float(wedge_after_s)
        if wedge_hard_after_s is not None \
                and float(wedge_hard_after_s) <= 0:
            raise ValueError(f"wedge_hard_after_s must be > 0 or None "
                             f"(auto 10x), got {wedge_hard_after_s}")
        self.wedge_hard_after_s = (None if wedge_hard_after_s is None
                                   else float(wedge_hard_after_s))
        self.orphan_grace_s = float(orphan_grace_s)
        if float(respawn_backoff_s) < 0:
            raise ValueError(f"respawn_backoff_s must be >= 0, got "
                             f"{respawn_backoff_s}")
        self.respawn_backoff_s = float(respawn_backoff_s)
        self.respawn_backoff_cap_s = float(respawn_backoff_cap_s)
        if int(max_respawns) < 1:
            raise ValueError(
                f"max_respawns must be >= 1, got {max_respawns}")
        self.max_respawns = int(max_respawns)
        self.respawn_reset_s = float(respawn_reset_s)
        self.fault_plans = dict(fault_plans) if fault_plans else None
        if watchdog_interval_s is not None \
                and float(watchdog_interval_s) <= 0:
            raise ValueError(f"watchdog_interval_s must be > 0 or None, "
                             f"got {watchdog_interval_s}")
        self.watchdog_interval_s = (
            None if watchdog_interval_s is None
            else float(watchdog_interval_s))
        if int(pd_prefill_threshold_tokens) < 1:
            raise ValueError(
                f"pd_prefill_threshold_tokens must be >= 1, got "
                f"{pd_prefill_threshold_tokens}")
        self.pd_prefill_threshold_tokens = int(pd_prefill_threshold_tokens)
        if int(min_replicas) < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {min_replicas}")
        self.min_replicas = int(min_replicas)
        if max_replicas is not None \
                and int(max_replicas) < self.min_replicas:
            raise ValueError(
                f"max_replicas must be >= min_replicas="
                f"{self.min_replicas} or None, got {max_replicas}")
        self.max_replicas = (None if max_replicas is None
                             else int(max_replicas))
        if page_transfer not in ("relay", "p2p"):
            raise ValueError(
                f"page_transfer must be 'relay' or 'p2p', got "
                f"{page_transfer!r}")
        self.page_transfer = page_transfer
        if page_codec not in ("raw", "compressed"):
            raise ValueError(
                f"page_codec must be 'raw' or 'compressed', got "
                f"{page_codec!r}")
        self.page_codec = page_codec
        self.async_adoption = bool(async_adoption)
        if int(max_inflight_transfers) < 1:
            raise ValueError(
                f"max_inflight_transfers must be >= 1, got "
                f"{max_inflight_transfers}")
        self.max_inflight_transfers = int(max_inflight_transfers)


class _TransferScheduler:
    """The async adoption executor (ISSUE 20): a tiny bounded thread
    pool that moves page bytes AFTER routing returned.  Transfers
    dedup per (importer, chain) — back-to-back requests for one warm
    prefix enqueue one transfer — and each importing replica is
    bounded to `max_inflight` concurrent imports so a popular replica
    cannot be flooded with payloads.  Execution re-checks the fleet
    index first and CANCELS transfers nobody wants anymore (the
    importer registered the chain itself while queued, a party died).
    Everything runs off the routing path: a slow holder costs cold
    prefills, never admission latency."""

    WORKERS = 2

    def __init__(self, router, max_inflight=2):
        self._router = router
        self._max = int(max_inflight)
        self._cv = threading.Condition()
        self._queue = []       # pending transfer dicts, FIFO
        self._keys = set()     # (importer, chain) queued or in flight
        self._inflight = {}    # importer name -> live transfer count
        self._stopped = False
        self._threads = [
            threading.Thread(target=self._loop,
                             name=f"fleet-transfer-{i}", daemon=True)
            for i in range(self.WORKERS)]
        for t in self._threads:
            t.start()

    def request(self, prompt, importer, holder, chain):
        """Enqueue one adoption transfer; False = duplicate/stopped."""
        key = (importer, chain)
        with self._cv:
            if self._stopped or key in self._keys:
                return False
            self._keys.add(key)
            self._queue.append({"prompt": list(prompt),
                                "importer": importer,
                                "holder": holder, "chain": chain})
            self._cv.notify()
        return True

    def _next_locked(self):
        for i, t in enumerate(self._queue):
            if self._inflight.get(t["importer"], 0) < self._max:
                return i
        return None

    def _loop(self):
        while True:
            with self._cv:
                while True:
                    if self._stopped:
                        return
                    i = self._next_locked()
                    if i is not None:
                        break
                    self._cv.wait(0.1)
                t = self._queue.pop(i)
                self._inflight[t["importer"]] = \
                    self._inflight.get(t["importer"], 0) + 1
            try:
                self._router._execute_transfer(t)
            except Exception:   # noqa: BLE001 — a transfer is an
                pass            # optimization; failures are counted
            finally:            # typed inside _execute_transfer
                with self._cv:
                    self._inflight[t["importer"]] -= 1
                    self._keys.discard((t["importer"], t["chain"]))
                    self._cv.notify_all()

    def idle(self):
        with self._cv:
            return not self._queue \
                and not any(self._inflight.values())

    def wait_idle(self, timeout=30.0):
        """Block until queue and in-flight transfers drain (tests and
        run_until_idle); False on timeout."""
        deadline = time.monotonic() + timeout
        with self._cv:
            while self._queue or any(self._inflight.values()):
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(min(left, 0.1))
        return True

    def stop(self):
        with self._cv:
            self._stopped = True
            self._queue.clear()
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=2.0)


class FleetRouter:
    """N GenerationEngine replicas behind one `submit()` with the same
    streaming GenerationHandle contract as a single engine."""

    def __init__(self, specs, config=None, metrics=None):
        if not specs:
            raise ValueError("a fleet needs at least one ReplicaSpec")
        names = [s.name for s in specs]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        self.config = config or FleetConfig()
        self.metrics = metrics or FleetMetrics()
        self._page_index = FleetPrefixIndex()
        # handoff runs awaiting a decode slot: [(item, src_name), ...].
        # Guarded by self._lock; drained and re-parked by
        # _collect_handoffs (backpressure instead of cold replay).
        self._pending_handoffs = []
        cfg = self.config
        if cfg.fault_plans:
            unknown = set(cfg.fault_plans) - set(names)
            if unknown:
                raise ValueError(
                    f"fault_plans name unknown replicas: {sorted(unknown)}")
        rpc = RpcPolicy(cfg.rpc_timeout_s, cfg.rpc_retries,
                        cfg.rpc_backoff_s, seed=cfg.seed or 0)
        self._replicas = {
            s.name: _Replica(
                s, cfg.start, cfg.transport or s.transport,
                on_death=self._on_transport_death, rpc=rpc,
                fault_plan=(cfg.fault_plans or {}).get(s.name),
                breaker=CircuitBreaker(
                    cfg.breaker_threshold, cfg.breaker_cooldown_s,
                    on_open=self._on_breaker_open))
            for s in specs}
        block = self.config.affinity_block_tokens
        if block is None:
            block = min(r._describe["page_size"]
                        for r in self._replicas.values())
        self._block = int(block)
        self._sessions = {}          # session id -> replica name
        self._rng = np.random.default_rng(self.config.seed)
        self._lock = threading.Lock()
        self._closed = False
        self._transfers = None   # lazy async-adoption scheduler
        # a heartbeat this recent counts as "recovered" for the
        # breaker's half-open probe (inproc ages are 0 — always fresh)
        self._hb_fresh_s = max(1.0, 4 * HEARTBEAT_S)
        self._watchdog_gate = threading.Lock()   # one sweep at a time
        self._watchdog_stop = threading.Event()
        self._watchdog_thread = None
        for rep in self._replicas.values():
            self._wire_handoff(rep)
        self._ensure_watchdog()

    def _ensure_watchdog(self):
        """Start the background watchdog when the fleet needs one:
        process/TCP replicas (stale-heartbeat reaping, wedge kills,
        orphan sweeps cannot depend on traffic arriving) or started
        prefill replicas (parked handoffs must drain even when nobody
        is calling run_until_idle).  Idempotent — add_replica() calls
        it again when the fleet's composition changes."""
        if self._watchdog_thread is not None:
            return
        cfg = self.config
        reps = self._replicas.values()
        if not (any(r.kind in ("proc", "tcp") for r in reps)
                or (cfg.start and any(r.role == "prefill"
                                      for r in reps))):
            return
        interval = cfg.watchdog_interval_s
        if interval is None:
            interval = max(0.05, min(cfg.heartbeat_dead_after,
                                     cfg.wedge_after_s,
                                     cfg.orphan_grace_s) / 4)
        self._watchdog_interval = float(interval)
        self._watchdog_thread = threading.Thread(
            target=self._watchdog_loop, name="fleet-watchdog",
            daemon=True)
        self._watchdog_thread.start()

    def _wire_handoff(self, rep):
        """Event-driven prefill→decode handoff: a prefill replica's
        transport (or inproc engine) notifies the router the moment a
        parked run is ready, so placement latency is not bound to a
        polling interval.  The watchdog/run_until_idle pulls stay as
        the backstop (a notification raced with shutdown, a replica
        rebuilt by restart())."""
        if rep.role != "prefill":
            return
        if not self.config.start:
            # stepped fleets are single-threaded by contract:
            # run_until_idle's deterministic pull IS the collector, and
            # a poke thread here could take a parked snap while the
            # step loop reads "everything idle" and returns — placing
            # work into a replica nothing will ever step again
            return
        eng = rep.engine
        if eng is not None:
            eng.on_handoff = self._poke_handoffs
        else:
            rep.transport.on_handoff = self._poke_handoffs

    def _poke_handoffs(self):
        """Handoff notification entry point.  Placement runs on its
        own short-lived thread: the notifier is an engine step thread
        or a transport reader thread, and placement may issue RPCs
        (sibling imports, page adoption) that must never block either
        — a reader thread waiting on its OWN channel's RPC reply would
        deadlock until the deadline."""
        threading.Thread(target=self._collect_handoffs,
                         name="fleet-handoff", daemon=True).start()

    # --------------------------- routing ----------------------------
    def _prefix_key(self, prompt):
        """CRC over the prompt's leading page-aligned tokens (clipped
        to len-1, mirroring match_prefix: the last token always
        prefills).  None when no full block fits — nothing a prefix
        index could hold."""
        n = (len(prompt) - 1) // self._block * self._block
        if n <= 0:
            return None
        return zlib.crc32(np.asarray(prompt[:n], np.int64).tobytes())

    def _candidates(self, prompt_len, max_new):
        return [r for r in self._replicas.values()
                if r.accepting and r.can_fit(prompt_len, max_new)]

    def _pull_prefix_deltas(self):
        """Ingest every live replica's register/evict deltas into the
        fleet prefix index — the measured bookkeeping that replaced
        the CRC guess.  Subprocess replicas accumulate deltas from
        heartbeat frames (no RPC here); inproc replicas drain their
        cache log directly."""
        for rep in self._replicas.values():
            if rep.state in ("stopped", "dead"):
                continue
            try:
                deltas = rep.transport.take_prefix_deltas()
            except ServingError:
                continue
            if deltas:
                self._page_index.apply(rep.name, deltas)

    def _index_lookup(self, prompt):
        """Deepest measured chain for `prompt` across the fleet's
        page-size MENU: each replica's cache hashes chains with its
        OWN page_size, so one lookup per distinct size — filtered to
        the replicas that hash that way — keeps a heterogeneous fleet
        (or an affinity_block_tokens override) fully visible to the
        index instead of silently matching only the min-page-size
        replicas.  Deepest matched-token count wins."""
        sizes = {}
        for r in self._replicas.values():
            if r.state in ("stopped", "dead"):
                continue
            sizes.setdefault(r._describe["page_size"],
                             set()).add(r.name)
        best = None
        for ps, names in sizes.items():
            hit = self._page_index.lookup(prompt, ps, names=names)
            if hit is not None and (best is None or hit[1] > best[1]):
                best = hit
        return best

    def _watchdog_loop(self):
        while not self._watchdog_stop.wait(self._watchdog_interval):
            try:
                self._watchdog()
            except Exception:   # noqa: BLE001 — a watchdog sweep must
                pass            # never die; the next tick retries

    def _watchdog(self):
        """One robustness sweep — runs on every submit, every
        stats_snapshot, and the background watchdog thread (fleets
        with process replicas); reentrancy-guarded and called OUTSIDE
        the routing lock.  Three hunts, all ending in the same death/
        remigration path so streams never hang:

        1. STALE HEARTBEAT: no beat for `heartbeat_dead_after` — a
           hung process (a crashed one is caught instantly by socket
           EOF) — kill + remigrate.
        2. WEDGE: heartbeats flow but the engine's step-progress stamp
           is frozen while the replica reports work (`wedge_after_s`)
           — the heartbeat thread outliving a wedged engine loop —
           kill + remigrate, counted in fleet.wedge_kill_total.
        3. ORPHANS: the worker reports idle while ledger entries
           linger past `orphan_grace_s` (a lost completion event) —
           remigrate just those streams (the replica stays up)."""
        if not self._watchdog_gate.acquire(blocking=False):
            return
        try:
            cfg = self.config
            self._collect_handoffs()
            for rep in list(self._replicas.values()):
                if rep.state != "serving":
                    continue
                t = rep.transport
                if not t.alive():
                    continue   # the death path is already running
                if t.heartbeat_age() > cfg.heartbeat_dead_after:
                    self._kill_replica(rep)
                    continue
                wedged = getattr(t, "wedged", None)
                if wedged is not None and wedged(cfg.wedge_after_s,
                                                 cfg.wedge_hard_after_s):
                    self.metrics.count_wedge_kill()
                    self._kill_replica(rep)
                    continue
                orphans = getattr(t, "take_orphans", None)
                if orphans is not None:
                    for entry in orphans(cfg.orphan_grace_s):
                        self.metrics.count_orphan_remigrated()
                        self._remigrate_entry(entry, exclude=None)
                # synthetic PING probe: an IDLE fleet sends no traffic,
                # so a recovered replica's open breaker would never see
                # the half-open probe request that closes it.  The
                # watchdog claims the probe slot itself and spends a
                # ping on it — success closes the breaker, failure
                # re-arms the cooldown.
                if rep.breaker.state != "closed" and rep.breaker.admit(
                        t.heartbeat_age(), self._hb_fresh_s):
                    self.metrics.count_ping_probe()
                    try:
                        t.ping()
                    except ServingError:
                        rep.breaker.record_failure()
                    else:
                        rep.breaker.record_success()
            # prefix-index GC: drop holder entries for replicas no
            # longer serving — belt-and-braces memory bound alongside
            # the death path's eager drop_replica
            with self._lock:
                live = [r.name for r in self._replicas.values()
                        if r.state == "serving"]
                dropped = self._page_index.compact(live)
            if dropped:
                self.metrics.count_index_compactions(dropped)
        finally:
            self._watchdog_gate.release()

    # --------------------- prefill→decode handoff -------------------
    # How long a handed-off run waits parked for a decode slot before
    # the cold-resubmit fallback (which REPLAYS the prefill) is taken.
    # Parking is free — the snap's pages already left the prefill pool
    # and live parent-side — so a saturated decode class exerts plain
    # backpressure instead of burning replayed tokens.
    HANDOFF_PATIENCE_S = 5.0

    def _collect_handoffs(self):
        """Drain every prefill replica's parked handoffs and place each
        finished page run on a decode-class sibling (live import — zero
        replayed tokens).  A run no sibling can seat RIGHT NOW (decode
        slots full) re-parks in the pending queue and is retried on
        every later pass; only past HANDOFF_PATIENCE_S does it fall to
        the cold seeded resubmit.  Called event-driven (transport/
        engine handoff notifications), from every watchdog sweep, and
        from run_until_idle — all paths funnel through the same
        placement so a handoff can never strand.  Returns the number
        of runs moved."""
        if self._closed:
            return 0
        with self._lock:
            pending, self._pending_handoffs = self._pending_handoffs, []
        for rep in list(self._replicas.values()):
            if rep.role != "prefill" or rep.state in ("stopped", "dead"):
                continue
            take = getattr(rep.transport, "take_handoffs", None)
            if take is None:
                continue
            try:
                items = take()
            except ServingError:
                continue
            pending.extend((item, rep.name) for item in items)
        moved = 0
        parked = []
        for item, src in pending:
            if self._place_handoff(item, exclude=src):
                moved += 1
            else:
                parked.append((item, src))
        if parked:
            with self._lock:
                # new arrivals raced in behind us; keep oldest first
                self._pending_handoffs = parked + self._pending_handoffs
        return moved

    def _place_handoff(self, item, exclude):
        """Place ONE handed-off run.  The snap's pages were freed at
        export (the bytes ride the snap), so the prefill replica's
        pool is already clear; placement is exactly the live-migration
        ladder with the decode class preferred.  Returns True when the
        run found a home (live adoption, or — past the patience
        window — the cold ladder), False to re-park and retry."""
        snap = item["snap"]
        now = time.monotonic()
        waited = max(0.0, now - item.get("t", now))
        patient = waited < self.HANDOFF_PATIENCE_S
        adopted = self._migrate_live(snap, exclude=exclude,
                                     prefer_role="decode",
                                     cold_fallback=not patient)
        if not adopted and patient:
            return False
        self.metrics.count_pd_handoff(
            int(snap.get("cache_len") or 0), waited)
        return True

    def _kill_replica(self, rep):
        kill = getattr(rep.transport, "kill", None)
        if kill is not None:
            kill()
        self._handle_death(rep.transport)

    def _on_breaker_open(self):
        self.metrics.count_breaker_open()

    def _ladder(self, session, key, candidates, holder=None):
        """The ordered (rung, replica) preference list.  Position 0 is
        the ROUTE; everything after it is the spill path (remaining
        candidates, least loaded first).  The prefix rung prefers the
        replica the FLEET INDEX measured as holding the prompt's
        deepest cached chain (`holder`); prompts no index entry covers
        fall back to the stable-hash guess, which keeps cold traffic
        converging on one replica so its index warms."""
        if self.config.routing == "random":
            order = list(candidates)
            self._rng.shuffle(order)
            return [("random", r) for r in order]
        # latency-aware least-loaded: the fastest candidate's measured
        # TTFT EWMA is the baseline every other candidate's latency is
        # scored relative to (docs/SERVING.md "Fleet tier")
        ewmas = [r.ttft_ewma for r in candidates if r.ttft_ewma]
        baseline = min(ewmas) if ewmas else None
        by_load = sorted(candidates, key=lambda r: r.load(baseline))
        prefs, seen = [], set()

        def push(rung, rep):
            if rep is not None and rep.name not in seen:
                prefs.append((rung, rep))
                seen.add(rep.name)

        cand_names = {r.name: r for r in candidates}
        if session is not None:
            push("affinity", cand_names.get(self._sessions.get(session)))
        if holder is not None and holder in cand_names:
            # measured: the fleet index says this replica's prefix
            # index actually holds the prompt's leading pages
            push("prefix", cand_names[holder])
        elif key is not None and len(candidates) > 0:
            # stateless hash preference over the STABLE name order, so
            # every request carrying the same leading tokens converges
            # on one replica — whose index then actually holds the
            # prefix.  Walk forward past non-candidates so a drained
            # replica's keys spread deterministically over survivors.
            stable = sorted(self._replicas.values(), key=lambda r: r.name)
            for off in range(len(stable)):
                rep = stable[(key + off) % len(stable)]
                if rep.name in cand_names:
                    push("prefix", rep)
                    break
        for rep in by_load:
            push("balance", rep)
        return prefs

    def _confirm_prefix(self, handle):
        """The measurement half of prefix routing: once the request
        resolves, its first-admission prefix_hit_tokens stamp says
        whether the bet paid.  A first-of-its-prefix request is
        recorded as a MISS — it seeded the cache, the bet didn't pay
        yet — so the confirmed/missed ratio reads as the real warm
        fraction of prefix-routed traffic, not an assumption."""
        hit = handle.prefix_hit_tokens
        if hit is not None:
            self.metrics.count_prefix_confirmed(hit > 0)

    def _route_and_submit(self, prompt, kwargs, handle, session,
                          exclude=None, prefer_role=None):
        """Run the ladder, count the rung that actually placed the
        request, and return (handle, replica).  Raises ServerBusyError
        (shed — every candidate's gate closed, admission OR breaker)
        or RequestTooLargeError (no candidate could EVER hold it)
        synchronously.  The routing LOCK covers only the bookkeeping
        (candidates, index lookup, ladder, session pins); RPCs —
        page-adoption transfers and the submits themselves — run
        OUTSIDE it, so one slow replica can never serialize fleet
        admission.

        P/D RUNG (ahead of the affinity ladder): in a fleet with
        non-mixed roles, a prompt past `pd_prefill_threshold_tokens`
        prefers the prefill class and anything shorter prefers the
        decode class (mixed replicas belong to both) — the full
        session/prefix/load ladder runs WITHIN the preferred class,
        then the remaining candidates follow load-ordered, so role is
        a preference and never a hard failure.  `prefer_role`
        overrides the length split (the handoff fallback pins
        "decode")."""
        prompt = list(prompt)
        self._watchdog()
        with self._lock:
            if self._closed:
                raise ServingError("fleet router is shut down")
            fit = [r for r in self._candidates(
                len(prompt), kwargs.get("max_new_tokens"))
                if exclude is None or r.name != exclude]
            if not fit:
                if any(r.accepting for r in self._replicas.values()
                       if exclude is None or r.name != exclude):
                    raise RequestTooLargeError(
                        f"no replica can hold a {len(prompt)}-token "
                        f"prompt (+{kwargs.get('max_new_tokens')} new)")
                raise ServingError(
                    "no accepting replica (fleet drained or shut down)")
            candidates = [r for r in fit if r.breaker.routable(
                r.transport.heartbeat_age(), self._hb_fresh_s)]
            if not candidates:
                # capacity exists but every breaker is open: typed
                # shed, same as every admission gate closed
                self.metrics.count_shed()
                raise ServerBusyError(
                    f"fleet saturated: every routable replica's "
                    f"circuit breaker is open ({len(fit)} candidates)")
            key = self._prefix_key(prompt)
            lookup = None
            if self.config.routing == "affinity" \
                    and self.config.page_service:
                self._pull_prefix_deltas()
                lookup = self._index_lookup(prompt)
            holder = lookup[0] if lookup else None
            role_pref = prefer_role
            if role_pref is None and any(
                    r.role != "mixed"
                    for r in self._replicas.values()):
                role_pref = (
                    "prefill" if len(prompt) >=
                    self.config.pd_prefill_threshold_tokens
                    else "decode")
            pref_c = ([r for r in candidates
                       if r.role in (role_pref, "mixed")]
                      if role_pref is not None else candidates)
            if role_pref is not None and pref_c:
                prefs = self._ladder(session, key, pref_c,
                                     holder=holder)
                prefs += self._ladder(
                    None, None,
                    [r for r in candidates if r not in pref_c])
            else:
                prefs = self._ladder(session, key, candidates,
                                     holder=holder)
        last_busy = None
        adoption_tried = False
        for i, (rung, rep) in enumerate(prefs):
            # submission-time breaker gate: claims the one half-open
            # probe slot; a breaker that OPENED since the ladder was
            # built skips the replica
            if not rep.breaker.admit(rep.transport.heartbeat_age(),
                                     self._hb_fresh_s):
                continue
            if not adoption_tried and not self.config.async_adoption:
                # synchronous mode (ablation/deterministic tests):
                # hit-elsewhere moves the bytes BEFORE admission so
                # THIS request is served warm — at the cost of the
                # transfer wall on its critical path
                adoption_tried = self._maybe_adopt_pages(
                    prompt, rep, lookup)
            try:
                rep.transport.submit(prompt, kwargs, handle)
            except ServerBusyError as e:
                last_busy = e
                rep.breaker.record_busy()   # load, not breakage
                continue
            except RequestTooLargeError:
                rep.breaker.record_busy()   # capacity edge, not a fault
                continue
            except ReplicaTimeoutError:
                # the submit RPC missed its bounded deadline: fail
                # fast down the ladder (the ledger entry was popped;
                # if the op actually landed child-side, its stream
                # frames find no entry and drop harmlessly)
                self.metrics.count_replica_timeout()
                rep.breaker.record_failure()
                continue
            except ServingError:
                rep.breaker.record_failure()
                continue   # dead channel / transport fault
            except BaseException:
                # an UNTYPED exception out of the transport (a child-
                # side bug rides the reply wire verbatim) is still a
                # breaker fault — without this, a claimed half-open
                # probe slot would leak and unroute the replica
                # forever.  Re-raise: bugs must stay loud.
                rep.breaker.record_failure()
                raise
            rep.breaker.record_success()
            if self.config.async_adoption:
                # async adoption (the default): the request is already
                # admitted and prefills cold RIGHT NOW; the transfer
                # ships behind it and warms the prefix index for the
                # NEXT request — routing latency never waits on bytes
                self._schedule_adoption(prompt, rep, lookup)
            if i == 0:
                self.metrics.count_routed(rung)
            else:
                self.metrics.count_spill()
            if role_pref is not None and rep.role == role_pref:
                self.metrics.count_routed_role()
            if rung == "prefix" and i == 0:
                client = (handle.client_and_delivered()[0]
                          if isinstance(handle, _MigrationRelay)
                          else handle)
                # hook the confirmation ONLY when this submission
                # is the one whose admission will stamp the handle
                # (stamp still None), and at most once per client —
                # a drain-migrated request re-routed by prefix must
                # not fire a second callback against the ORIGINAL
                # replica's stamp and double-count a bet the new
                # replica never won.  (A started worker can admit
                # and stamp between submit and this check; that
                # rare race under-counts one confirmation, never
                # mis-attributes one.)
                if client.prefix_hit_tokens is None and not getattr(
                        client, "_prefix_confirm_hooked", False):
                    client._prefix_confirm_hooked = True
                    client.add_done_callback(self._confirm_prefix)
            if session is not None:
                with self._lock:
                    self._sessions[session] = rep.name
            # latency measurement: every plainly-submitted request
            # feeds the serving replica's TTFT EWMA at completion.
            # Migration relays are skipped — their first_token_s
            # clock spans two replicas and would smear the signal.
            if not isinstance(handle, _MigrationRelay) and \
                    not getattr(handle, "_ttft_hooked", False):
                handle._ttft_hooked = True
                handle.add_done_callback(rep.observe_ttft)
            self.metrics.set_replica_queue_depth(rep.name,
                                                 rep.queue_depth())
            return handle, rep
        # every candidate's admission gate is closed: fleet-level
        # load shed — the ONLY place shed_total increments
        self.metrics.count_shed()
        raise ServerBusyError(
            f"fleet saturated: all {len(prefs)} routable replicas "
            f"rejected admission") from last_busy

    # --------------------------- client API -------------------------
    def submit(self, prompt, max_new_tokens=None, sampling=None,
               stop_tokens=(), timeout_ms=None, session=None):
        """Route one prompt to a replica; returns a GenerationHandle
        with the engine's exact streaming contract.  `session` pins
        this and follow-up submits carrying the same id to one replica
        (whose pools hold the conversation's warm pages); without it,
        routing falls to prefix affinity, then least-loaded."""
        handle = GenerationHandle()
        # materialize default sampling HERE, not in the replica engine:
        # the params' recorded seed is what makes every later migration
        # (drain resubmit, crash remigration, live-migration cold
        # fallback) replay the identical stream
        sampling = sampling if sampling is not None else SamplingParams()
        handle, _ = self._route_and_submit(
            prompt,
            dict(max_new_tokens=max_new_tokens, sampling=sampling,
                 stop_tokens=stop_tokens, timeout_ms=timeout_ms),
            handle, session)
        return handle

    def generate(self, prompt, **kw):
        """Blocking convenience: submit + result."""
        return self.submit(prompt, **kw).result()

    def replica_of(self, handle_or_session):
        """Debug/test introspection: the replica name a session is
        pinned to (None when unpinned)."""
        return self._sessions.get(handle_or_session)

    # ------------------------- drain / restart ----------------------
    def drain(self, name, migrate=True, timeout=60.0, live=None):
        """Take replica `name` out of service: stop admissions, move
        its unfinished work to siblings, join the worker (or reap the
        process).

        Queued (never-admitted) requests ALWAYS migrate — as cold
        resubmits with their original seeded sampling, so their streams
        are untouched.  With `migrate=True` (default) live slot-holders
        move too — as TRUE LIVE MIGRATIONS when `live`
        (FleetConfig.live_migration default): their resident state
        (page bytes, page table, position, sampling RNG, delivered
        count) ships to a sibling that RESUMES the decode mid-stream,
        so a 10k-token stream moves without replaying a single token
        (fleet.migrated_replay_tokens stays 0).  When a sibling cannot
        adopt (no slot, pool pressure, incompatible layout) — or with
        live=False, the ablation baseline — the request falls back to
        the COLD RESUBMIT ladder: seeded sampling replays the
        identical stream and a relay skips the tokens the client
        already received (counted into migrated_replay_tokens).  With
        `migrate=False` residents finish on the draining replica
        first — but a resident that outlives `timeout` is evacuated
        anyway, so a drain always CONVERGES to "stopped" instead of
        wedging the replica in a half-drained state.  A migrated
        request that finds every sibling's gate closed resolves its
        handle with the typed ServerBusyError (counted in
        fleet.shed_total).  Sessions pinned here unpin; the fleet
        prefix index forgets everything this replica held."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:
                raise KeyError(f"unknown replica {name!r}")
            if rep.state != "serving":
                raise ServingError(
                    f"replica {name!r} is {rep.state}, not serving")
            rep.state = "draining"
            for sess in [s for s, n in self._sessions.items()
                         if n == name]:
                del self._sessions[sess]
        if live is None:
            live = self.config.live_migration
        try:
            cold, live_snaps = rep.transport.drain(
                migrate=migrate, live=live, timeout=timeout)
        except ServingError:
            # the replica died mid-drain: its in-flight ledger already
            # remigrated through the death path
            cold, live_snaps = [], []
        for snap in live_snaps:
            self._migrate_live(snap, exclude=name)
        for req, emitted in cold:
            self._migrate(req, emitted, exclude=name)
        self.metrics.count_migrated(len(cold) + len(live_snaps))
        self._page_index.drop_replica(name)
        rep.state = "stopped"
        rep.respawns = 0   # a clean drain is not a crash: restart
        # owes no backoff

    def _migrate_live(self, snap, exclude, prefer_role=None,
                      cold_fallback=True):
        """Place one exported resident on a sibling that RESUMES its
        decode (zero replayed tokens); falls back to the cold-resubmit
        ladder when no sibling can adopt it right now.  `prefer_role`
        (the P/D handoff path passes "decode") stable-partitions the
        candidates so role-matched (+ mixed) siblings are tried first,
        least loaded within each class — a preference, never a wall.
        With `cold_fallback=False` the run is simply reported unplaced
        (False) so the caller can re-park it instead of paying the
        replay.  Returns True when a sibling adopted the run live."""
        handle = snap.get("future")
        remaining = max(1, snap["max_new_tokens"] - snap["n_generated"])
        with self._lock:
            cands = sorted(
                (r for r in self._replicas.values()
                 if r.accepting and r.name != exclude
                 and r.can_fit(len(snap["tokens"]), remaining)
                 and r.breaker.routable(r.transport.heartbeat_age(),
                                        self._hb_fresh_s)),
                key=lambda r: r.load())
        if prefer_role is not None:
            cands.sort(key=lambda r: r.role not in (prefer_role,
                                                    "mixed"))
        for rep in cands:
            try:
                if rep.transport.import_sequence(snap):
                    self.metrics.count_live_migrated()
                    return True
            except ReplicaTimeoutError:
                self.metrics.count_replica_timeout()
                rep.breaker.record_failure()
                continue
            except ServingError:
                continue
        if not cold_fallback:
            return False
        # cold fallback: seeded sampling replays the identical stream,
        # the relay swallows what the client already saw
        req = GenerationRequest(
            snap["prompt"], handle, snap["sampling"],
            max_new_tokens=snap["max_new_tokens"],
            stop_tokens=snap["stop_tokens"],
            deadline=snap.get("deadline"))
        self._migrate(req, snap["n_generated"], exclude=exclude,
                      prefer_role=prefer_role)
        return True

    def _migrate(self, req, emitted, exclude, prefer_role=None):
        """Cold-resubmit one evacuated request on a sibling, preserving
        the client's handle and stream position.  The skipped replay
        is the live-migration A/B's accounting: every token the relay
        swallows lands in fleet.migrated_replay_tokens."""
        handle = req.future
        if isinstance(handle, _MigrationRelay):   # second migration
            client, delivered = handle.client_and_delivered()
        else:
            client, delivered = handle, int(emitted)
        # the client's own delivered counter is the replay-skip FLOOR:
        # no ledger race (a token dispatched while the death path
        # snapshots the entry) can make a resubmit re-stream a token
        # the client already received
        delivered = max(delivered, getattr(client, "n_streamed", 0))
        engine_handle = (_MigrationRelay(client, delivered)
                         if delivered else client)
        self.metrics.count_replay_tokens(delivered)
        timeout_ms = None
        if req.deadline is not None:
            timeout_ms = max(0.0,
                             (req.deadline - time.monotonic()) * 1e3)
        try:
            self._route_and_submit(
                req.prompt,
                dict(max_new_tokens=req.max_new_tokens,
                     sampling=req.params,
                     stop_tokens=req.stop_tokens, timeout_ms=timeout_ms),
                engine_handle, session=None, exclude=exclude,
                prefer_role=prefer_role)
        except ServingError as e:
            # nowhere to go (typed: busy/too-large/drained) — the
            # client holds the handle, so the error lands there
            client.set_exception(e)

    def _adoption_viable_locked(self, rep, holder_name, chain):
        """Preconditions a transfer must (re-)pass under the routing
        lock: a live, layout-compatible holder that is NOT `rep`, for
        a chain `rep` does not already hold.  Returns the holder
        replica or None."""
        if holder_name == rep.name \
                or rep.name in self._page_index.holders_of(chain):
            return None
        src = self._replicas.get(holder_name)
        if src is None or src.state != "serving" \
                or not src.transport.alive():
            return None
        if src._describe["page_size"] != rep._describe["page_size"]:
            # pages only move between layout-compatible pools; the
            # importer would reject the payload anyway, so skip the
            # export round-trip entirely
            return None
        return src

    def _maybe_adopt_pages(self, prompt, rep, lookup):
        """SYNCHRONOUS adoption (async_adoption=False): when the fleet
        index measured a DIFFERENT replica as holding this prompt's
        warm prefix run, move the bytes NOW so `rep` serves this very
        request warm.  Returns True when a transfer was attempted
        (success or not — one attempt per request), False when not
        applicable.  The byte transfer runs OUTSIDE the routing lock:
        bounded RPCs, typed degrade to the cold-prefill ladder — a
        hung holder never stalls fleet admission."""
        if lookup is None:
            return False
        holder_name, _depth, chain = lookup
        with self._lock:
            src = self._adoption_viable_locked(rep, holder_name, chain)
        if src is None:
            return False
        self._adopt_via_wire(prompt, rep, src, chain)
        return True

    def _schedule_adoption(self, prompt, rep, lookup):
        """ASYNC adoption (the default): enqueue the transfer on the
        scheduler and return immediately — the admitted request
        prefills cold, the arriving pages warm the index for the NEXT
        request.  Dedup and in-flight bounding live in the scheduler;
        viability is re-checked at execution time (cancellation)."""
        if lookup is None:
            return False
        holder_name, _depth, chain = lookup
        with self._lock:
            if self._closed:
                return False
            if self._adoption_viable_locked(rep, holder_name,
                                            chain) is None:
                return False
            if self._transfers is None:
                self._transfers = _TransferScheduler(
                    self, self.config.max_inflight_transfers)
        return self._transfers.request(prompt, rep.name, holder_name,
                                       chain)

    def _execute_transfer(self, t):
        """One queued transfer, on a scheduler thread.  Re-checks
        viability first — the index may have stopped wanting this
        transfer while it sat queued (the importer prefilled and
        registered the chain itself, a party died) — and cancels
        instead of moving dead bytes."""
        rep = self._replicas.get(t["importer"])
        with self._lock:
            if self._closed or rep is None or rep.state != "serving" \
                    or not rep.transport.alive():
                self.metrics.count_transfer_cancelled()
                return
            src = self._adoption_viable_locked(rep, t["holder"],
                                               t["chain"])
            if src is None:
                self.metrics.count_transfer_cancelled()
                return
        self._adopt_via_wire(t["prompt"], rep, src, t["chain"])

    def wait_transfers(self, timeout=30.0):
        """Block until every queued/in-flight adoption transfer
        settles (tests, benches, graceful drains).  True when idle."""
        transfers = self._transfers
        if transfers is None:
            return True
        return transfers.wait_idle(timeout)

    def _adopt_via_wire(self, prompt, rep, src, chain):
        """Move one warm prefix run from `src` to `rep` — the byte-
        moving half shared by both adoption modes.  p2p (default):
        `rep` dials `src`'s advertised data port and the payload
        crosses ONE replica→replica socket, compressed at the
        negotiated codec level — zero page bytes on the router
        socket.  relay (ablation, or a data port not yet advertised):
        export through the router, counted into page_relay_bytes.
        Every failure is typed and counted; the request(s) behind it
        just prefill cold."""
        levels = (("delta", "raw")
                  if self.config.page_codec == "compressed"
                  else ("raw",))
        if self.config.page_transfer == "p2p":
            addr_fn = getattr(src.transport, "data_address", None)
            import_from = getattr(rep.transport, "import_prefix_from",
                                  None)
            addr = addr_fn() if addr_fn is not None else None
            if addr is not None and import_from is not None:
                try:
                    res = import_from(addr, prompt,
                                      timeout_s=self.config.rpc_timeout_s,
                                      levels=levels)
                except ReplicaTimeoutError:
                    # the IMPORTER's RPC missed its deadline — its
                    # breaker bookkeeping decides its fate; the
                    # request degrades to the cold-prefill ladder
                    self.metrics.count_replica_timeout()
                    rep.breaker.record_failure()
                    self.metrics.count_transfer_failed()
                    return
                except ServingError:
                    # typed refusal anywhere on the path (dial failed,
                    # deadline, codec mismatch, holder refused): cold
                    # ladder, counted
                    self.metrics.count_transfer_failed()
                    return
                added = res.get("added", 0) if isinstance(res, dict) \
                    else 0
                if added:
                    self.metrics.count_page_adoption(added)
                    self.metrics.count_page_p2p_bytes(
                        res.get("wire_bytes", 0),
                        res.get("raw_bytes", 0))
                    with self._lock:
                        self._page_index.apply(rep.name,
                                               [("add", chain)])
                return
            # no data port advertised yet (heterogeneous fleet member,
            # pre-first-heartbeat): fall through to the relay baseline
        try:
            payload = src.transport.export_prefix(prompt)
        except ReplicaTimeoutError:
            # bounded-deadline miss: the HOLDER is in trouble, the
            # request is not — degrade to the cold-prefill ladder and
            # let the holder's breaker bookkeeping decide its fate
            self.metrics.count_replica_timeout()
            src.breaker.record_failure()
            self.metrics.count_transfer_failed()
            return
        except ServingError:
            self.metrics.count_transfer_failed()
            return
        if not payload:
            return   # evicted since the last delta pull
        self.metrics.count_page_relay_bytes(
            pagecodec.payload_nbytes(payload))
        try:
            added = rep.transport.import_prefix(payload)
        except ReplicaTimeoutError:
            self.metrics.count_replica_timeout()
            rep.breaker.record_failure()
            self.metrics.count_transfer_failed()
            return
        except ServingError:
            self.metrics.count_transfer_failed()
            return
        if added:
            self.metrics.count_page_adoption(added)
            # eager index update (the importer's own delta confirms on
            # the next pull): back-to-back requests must not re-ship
            with self._lock:
                self._page_index.apply(rep.name, [("add", chain)])

    def _handle_death(self, transport):
        """Crash path: mark the replica dead, count it, forget its
        index entries, unpin its sessions, and remigrate its in-flight
        ledger — queued work resubmits on siblings, mid-stream work
        resumes via relay replay; anything with nowhere to go resolves
        with the typed shed.  Streams never hang on a dead process.
        Fired by the transport reader thread on socket EOF and by the
        stale-heartbeat reaper; idempotent per replica generation."""
        rep = next((r for r in self._replicas.values()
                    if r.transport is transport), None)
        if rep is None:
            return
        now = time.monotonic()
        with self._lock:
            if rep.state != "serving":
                return
            rep.state = "dead"
            rep.died_at = now
            # respawn-backoff bookkeeping, counted ONCE per death: a
            # replica dying within respawn_reset_s of its build is
            # crash-looping — the streak drives restart()'s
            # exponential backoff and the crash-loop cap.  A death
            # after a LONG healthy run resets the streak entirely:
            # it owes no backoff (the documented contract).
            quick = now - rep.built_at < self.config.respawn_reset_s
            rep.respawns = rep.respawns + 1 if quick else 0
            for sess in [s for s, n in self._sessions.items()
                         if n == rep.name]:
                del self._sessions[sess]
        self.metrics.count_replica_dead()
        self._page_index.drop_replica(rep.name)
        # handoff snaps live PARENT-side (the worker shipped the bytes
        # before dying), so a prefill replica SIGKILLed mid-handoff
        # loses nothing: place what already arrived, and anything whose
        # handoff frame never made it is still in the in-flight ledger
        # below — cold remigration with replay skip covers it.
        take = getattr(transport, "take_handoffs", None)
        if take is not None:
            for item in take():
                if not self._place_handoff(item, exclude=rep.name):
                    # decode class momentarily full: park it — the
                    # watchdog's collection sweep retries
                    with self._lock:
                        self._pending_handoffs.append((item, rep.name))
        for entry in transport.take_inflight():
            self._remigrate_entry(entry, exclude=rep.name)

    def _on_transport_death(self, transport):
        self._handle_death(transport)

    def _remigrate_entry(self, entry, exclude):
        """Resubmit one in-flight-ledger entry from a dead replica:
        the client handle survives parent-side, seeded sampling
        replays, a relay skips the delivered tokens."""
        handle = entry["handle"]
        if isinstance(handle, _MigrationRelay):
            client, delivered = handle.client_and_delivered()
        else:
            client, delivered = handle, int(entry["emitted"])
        # same floor as _migrate: the client's n_streamed wins over
        # any stale ledger count
        delivered = max(delivered, getattr(client, "n_streamed", 0))
        engine_handle = (_MigrationRelay(client, delivered)
                         if delivered else client)
        self.metrics.count_replay_tokens(delivered)
        kwargs = dict(entry["kwargs"])
        if entry.get("deadline") is not None:
            kwargs["timeout_ms"] = max(
                0.0, (entry["deadline"] - time.monotonic()) * 1e3)
        migrated = False
        try:
            self._route_and_submit(entry["prompt"], kwargs,
                                   engine_handle, session=None,
                                   exclude=exclude)
            migrated = True
        except ServingError as e:
            client.set_exception(e)
        if migrated:
            self.metrics.count_migrated()

    def restart(self, name, wait=True):
        """Bring a drained (or dead) replica back: a FRESH engine from
        its spec — new pools, empty prefix index, empty queue, and for
        subprocess replicas a new OS process.  Prefix-affinity bets
        against the old index self-correct through the confirmation
        loop (first request misses, seeds, re-warms) AND through the
        fleet index, which forgot the old replica at drain/death.

        CRASH-LOOP discipline: a replica that DIED within
        `respawn_reset_s` of its build owes an exponential respawn
        backoff (`respawn_backoff_s * 2^(streak-1)`, capped at
        `respawn_backoff_cap_s`) measured from its death — `wait=True`
        (default) sleeps it off, `wait=False` raises the typed
        ServingError with the remaining seconds so an external
        supervisor can reschedule.  A streak past `max_respawns`
        refuses to respawn at all (typed) until `reset_respawn(name)`:
        a crash-looping replica must not spin the fleet.  Clean drains
        owe nothing."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:
                raise KeyError(f"unknown replica {name!r}")
            if rep.state not in ("stopped", "dead"):
                raise ServingError(
                    f"replica {name!r} is {rep.state}; drain it first")
            backoff = 0.0
            if rep.state == "dead" and rep.respawns:
                if rep.respawns > self.config.max_respawns:
                    self.metrics.set_respawn_backoff(
                        name, self.config.respawn_backoff_cap_s)
                    raise ServingError(
                        f"replica {name!r} is crash-looping "
                        f"({rep.respawns} quick deaths > max_respawns="
                        f"{self.config.max_respawns}); fix the cause "
                        f"and reset_respawn({name!r}) to override")
                backoff = min(
                    self.config.respawn_backoff_cap_s,
                    self.config.respawn_backoff_s
                    * 2 ** (rep.respawns - 1))
            self.metrics.set_respawn_backoff(name, backoff)
            remaining = 0.0
            if backoff and rep.died_at is not None:
                remaining = rep.died_at + backoff - time.monotonic()
            if remaining > 0 and not wait:
                raise ServingError(
                    f"replica {name!r} owes {remaining:.2f}s of "
                    f"respawn backoff (streak {rep.respawns}); retry "
                    f"then, or restart(wait=True)")
        if remaining > 0:
            time.sleep(remaining)
        with self._lock:
            if rep.state not in ("stopped", "dead"):
                raise ServingError(
                    f"replica {name!r} became {rep.state} during the "
                    f"respawn backoff")
            if rep.state == "dead":
                rep.transport.stop()   # reap the corpse
            rep.build(self.config.start)
            self._wire_handoff(rep)

    def reset_respawn(self, name):
        """Operator override: clear `name`'s crash-loop streak (and
        its breaker) so the next restart() owes no backoff."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:
                raise KeyError(f"unknown replica {name!r}")
            rep.respawns = 0
            rep.breaker.reset()
        self.metrics.set_respawn_backoff(name, 0.0)

    # ------------------------- fleet scaling ------------------------
    def add_replica(self, spec, start=None):
        """Register and build ONE new replica at runtime — the
        autoscaler's scale-up primitive (and an operator's).  The
        replica is built OUTSIDE the routing lock (a process spawn
        must never serialize admission) and joins the candidate set
        the moment it registers; the watchdog starts if the fleet's
        composition now needs one.  Returns the replica name."""
        cfg = self.config
        with self._lock:
            if self._closed:
                raise ServingError("fleet router is shut down")
            if spec.name in self._replicas:
                raise ValueError(
                    f"duplicate replica name {spec.name!r}")
        rpc = RpcPolicy(cfg.rpc_timeout_s, cfg.rpc_retries,
                        cfg.rpc_backoff_s, seed=cfg.seed or 0)
        rep = _Replica(
            spec, cfg.start if start is None else start,
            cfg.transport or spec.transport,
            on_death=self._on_transport_death, rpc=rpc,
            breaker=CircuitBreaker(
                cfg.breaker_threshold, cfg.breaker_cooldown_s,
                on_open=self._on_breaker_open))
        self._wire_handoff(rep)
        with self._lock:
            if self._closed or spec.name in self._replicas:
                rep.transport.stop()   # lost the registration race
                raise ServingError(
                    f"cannot register replica {spec.name!r}: fleet "
                    f"closed or name taken during build")
            self._replicas[spec.name] = rep
        self._ensure_watchdog()
        self.metrics.set_replica_count(
            sum(1 for r in self._replicas.values()
                if r.state == "serving"))
        return rep.name

    def remove_replica(self, name, timeout=30.0):
        """Drain `name` (unfinished work migrates to siblings) and
        forget it entirely — the autoscaler's scale-down primitive.
        A dead replica is reaped instead of drained."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None:
                raise KeyError(f"unknown replica {name!r}")
        if rep.state == "serving":
            self.drain(name, migrate=True, timeout=timeout)
        elif rep.state == "dead":
            rep.transport.stop()
            rep.state = "stopped"
        with self._lock:
            self._replicas.pop(name, None)
        self.metrics.set_replica_count(
            sum(1 for r in self._replicas.values()
                if r.state == "serving"))

    # --------------------------- lifecycle --------------------------
    def run_until_idle(self, max_steps=100000):
        """Drive every live replica until queues and slots drain —
        stepped inproc replicas are stepped here (tests/benchmarks);
        replicas with background workers (and subprocess replicas,
        which always step themselves) are simply waited on."""
        steps = 0
        while True:
            busy = (bool(self._collect_handoffs())
                    or bool(self._pending_handoffs)
                    or not (self._transfers is None
                            or self._transfers.idle()))
            for rep in list(self._replicas.values()):
                if rep.state in ("stopped", "dead"):
                    continue
                t = rep.transport
                if not t.idle():
                    busy = True
                    t.pump()
            if not busy:
                return steps
            steps += 1
            if steps > max_steps:
                raise RuntimeError(f"fleet not idle after {max_steps} "
                                   f"steps")

    def stats_snapshot(self):
        """Fleet-level capacity-planning export: every replica's
        generation.* snapshot + live cache stats keyed by replica name,
        plus the fleet.* routing/shed/migration counters, per-replica
        queue-depth gauges, and the heartbeat-age liveness gauges
        (schema-complete from the first snapshot: 0.0 for inproc
        transports, whose liveness is this process's)."""
        self._watchdog()
        with self._lock:
            self._pull_prefix_deltas()
        replicas = {}
        depths = []
        ages = []
        breaker_scores = []
        for name, rep in list(self._replicas.items()):
            if rep.state in ("stopped", "dead"):
                # a stopped replica queues nothing: zero its gauges so
                # a dashboard never shows pre-drain depth on a dead slot
                self.metrics.set_replica_queue_depth(name, 0)
                self.metrics.set_heartbeat_age(name, 0.0)
                self.metrics.set_breaker_state(name, 0)
                replicas[name] = {"state": rep.state}
                continue
            age = rep.transport.heartbeat_age()
            ages.append(age)
            self.metrics.set_heartbeat_age(name, age)
            score = rep.breaker.score
            breaker_scores.append(score)
            self.metrics.set_breaker_state(name, score)
            depth = rep.queue_depth()
            depths.append(depth)
            self.metrics.set_replica_queue_depth(name, depth)
            info = rep.transport.load_info()
            try:
                stats = rep.transport.stats()
            except ServingError:
                stats = {}
            replicas[name] = {
                "state": rep.state,
                "transport": rep.kind,
                "role": rep.role,
                "queue_depth": depth,
                "active": info["active"],
                "load": round(rep.load(), 3),
                "ttft_ewma_s": (None if rep.ttft_ewma is None
                                else round(rep.ttft_ewma, 4)),
                "heartbeat_age_s": round(age, 3),
                "breaker": rep.breaker.state,
                "respawns": rep.respawns,
                "rpc_timeouts": getattr(rep.transport,
                                        "timeout_total", 0),
                "generation": stats.get("generation", {}),
                "cache": stats.get("cache", {}),
            }
        self.metrics.set_max_queue_depth(max(depths, default=0))
        self.metrics.set_max_heartbeat_age(max(ages, default=0.0))
        self.metrics.set_max_breaker_state(max(breaker_scores,
                                               default=0))
        self.metrics.set_replica_count(
            sum(1 for r in self._replicas.values()
                if r.state == "serving"))
        return {"fleet": self.metrics.snapshot(),
                "prefix_index_chains": self._page_index.chains_held(),
                "prefix_index_compactions": self._page_index.compactions,
                "replicas": replicas}

    def shutdown(self):
        """Stop every replica (typed rejection for anything queued)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._watchdog_stop.set()
        if self._watchdog_thread is not None:
            self._watchdog_thread.join(timeout=5.0)
        if self._transfers is not None:
            self._transfers.stop()
        for rep in self._replicas.values():
            if rep.state != "stopped":
                rep.transport.stop()
                rep.state = "stopped"

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False


__all__ = [
    "FleetRouter", "FleetConfig", "FleetMetrics", "ReplicaSpec",
    "CircuitBreaker",
]
