"""paddle_tpu.serving — dynamically-batched TPU serving runtime.

The production layer above `paddle_tpu.inference`: where a Predictor is
one request / one shape / one thread, the serving runtime batches
concurrent requests into shape-bucketed TPU dispatches with AOT compile
reuse, bounded queueing, per-request deadlines and first-class metrics.
See docs/SERVING.md for the full contract.

Quick start::

    from paddle_tpu import inference, serving

    pred = inference.Predictor(inference.Config(prefix))   # -1 batch export
    engine = serving.ServingEngine(
        pred, serving.ServingConfig(batch_buckets=(1, 2, 4, 8),
                                    max_batch_delay_ms=2,
                                    queue_depth=64))
    fut = engine.submit({"x": features}, timeout_ms=50)
    outputs = fut.result()          # or engine.infer(...) to block
    engine.shutdown()
"""
from .admission import (AdmissionQueue, DeadlineExceededError,
                        ReplicaTimeoutError, Request,
                        RequestTooLargeError, ServerBusyError, ServingError)
from .batcher import DynamicBatcher
from .bucketing import CompiledModelCache, ShapeBucketer
from .control import FleetSupervisor, SupervisorConfig
from .engine import ServingConfig, ServingEngine, create_serving_engine
from .fleet import (CircuitBreaker, FleetConfig, FleetMetrics,
                    FleetRouter, ReplicaSpec)
from .metrics import LatencyReservoir, ServingMetrics

__all__ = [
    "ServingEngine", "ServingConfig", "create_serving_engine",
    "DynamicBatcher", "AdmissionQueue", "Request",
    "ShapeBucketer", "CompiledModelCache",
    "ServingMetrics", "LatencyReservoir",
    "FleetRouter", "FleetConfig", "FleetMetrics", "ReplicaSpec",
    "CircuitBreaker", "FleetSupervisor", "SupervisorConfig",
    "ServingError", "ServerBusyError", "DeadlineExceededError",
    "RequestTooLargeError", "ReplicaTimeoutError",
]
