"""Serving metrics: first-class gauges/counters in the profiler registry.

Every number the runtime tracks lands in the existing
`profiler/monitor.py` `StatRegistry` (the platform/monitor.h STAT_* role),
so `paddle_tpu.profiler.monitor.StatRegistry.instance().stats()` — and
anything already scraping it — sees serving internals with no new
plumbing.  Latency percentiles come from a bounded reservoir recomputed on
record (serving batches are the slow path; a sort over <=2048 floats is
noise next to a TPU dispatch).

Metric names (all under the ``serving.`` prefix):

- ``serving.requests_total``        submitted requests (accepted)
- ``serving.rejected_busy``         admission rejections (queue full)
- ``serving.rejected_deadline``     deadline-expired rejections
- ``serving.queue_depth``           gauge: requests waiting right now
- ``serving.batches_total``         TPU dispatches
- ``serving.batch_rows_total``      real rows dispatched
- ``serving.batch_padded_rows_total`` padding rows dispatched
- ``serving.batch_fill_pct``        gauge: last batch's real/bucket %
- ``serving.cache_hits`` / ``serving.cache_misses``  bucket-executable cache
- ``serving.compiles_total``        AOT compiles (== distinct buckets)
- ``serving.latency_p50_us`` / ``serving.latency_p99_us``  gauges
"""
import bisect
import threading

from ..profiler.monitor import StatRegistry

PREFIX = "serving."

REQUESTS_TOTAL = PREFIX + "requests_total"
REJECTED_BUSY = PREFIX + "rejected_busy"
REJECTED_DEADLINE = PREFIX + "rejected_deadline"
QUEUE_DEPTH = PREFIX + "queue_depth"
BATCHES_TOTAL = PREFIX + "batches_total"
BATCH_ROWS_TOTAL = PREFIX + "batch_rows_total"
BATCH_PADDED_ROWS_TOTAL = PREFIX + "batch_padded_rows_total"
BATCH_FILL_PCT = PREFIX + "batch_fill_pct"
CACHE_HITS = PREFIX + "cache_hits"
CACHE_MISSES = PREFIX + "cache_misses"
COMPILES_TOTAL = PREFIX + "compiles_total"
LATENCY_P50_US = PREFIX + "latency_p50_us"
LATENCY_P99_US = PREFIX + "latency_p99_us"


class LatencyReservoir:
    """Bounded sliding window of request latencies with exact percentiles
    over the window (a sorted shadow list keeps the percentile read
    O(1) and the insert O(window) — fine at serving rates)."""

    def __init__(self, window=2048):
        self._window = int(window)
        self._ring = []
        self._sorted = []
        self._next = 0
        self._lock = threading.Lock()

    def record(self, value):
        with self._lock:
            if len(self._ring) < self._window:
                self._ring.append(value)
            else:
                old = self._ring[self._next]
                self._ring[self._next] = value
                self._next = (self._next + 1) % self._window
                del self._sorted[bisect.bisect_left(self._sorted, old)]
            bisect.insort(self._sorted, value)

    def percentile(self, q):
        """Nearest-rank percentile (exact over the window)."""
        import math

        with self._lock:
            if not self._sorted:
                return 0.0
            idx = max(0, min(len(self._sorted) - 1,
                             math.ceil(q / 100.0 * len(self._sorted)) - 1))
            return self._sorted[idx]

    def count(self):
        with self._lock:
            return len(self._ring)


class ServingMetrics:
    """One instance per ServingEngine; all writes go straight to the
    process StatRegistry so concurrent engines aggregate (the reference's
    STAT_ADD counters are process-global too)."""

    def __init__(self, registry=None, window=2048):
        self._reg = registry or StatRegistry.instance()
        self._lat = LatencyReservoir(window)

    def _stat(self, name):
        return self._reg.get_stat(name)

    # --- counters ---
    def count_request(self):
        self._stat(REQUESTS_TOTAL).increase()

    def count_rejected_busy(self):
        self._stat(REJECTED_BUSY).increase()

    def count_rejected_deadline(self, n=1):
        self._stat(REJECTED_DEADLINE).increase(n)

    def count_cache(self, hit):
        self._stat(CACHE_HITS if hit else CACHE_MISSES).increase()

    def count_compile(self):
        self._stat(COMPILES_TOTAL).increase()

    # --- gauges ---
    def set_queue_depth(self, depth):
        self._stat(QUEUE_DEPTH).set(int(depth))

    def observe_batch(self, rows, bucket_rows):
        self._stat(BATCHES_TOTAL).increase()
        self._stat(BATCH_ROWS_TOTAL).increase(int(rows))
        self._stat(BATCH_PADDED_ROWS_TOTAL).increase(
            int(bucket_rows) - int(rows))
        if bucket_rows:
            self._stat(BATCH_FILL_PCT).set(
                round(100.0 * rows / bucket_rows, 1))

    def observe_latency(self, seconds):
        us = seconds * 1e6
        self._lat.record(us)
        self._stat(LATENCY_P50_US).set(round(self._lat.percentile(50), 1))
        self._stat(LATENCY_P99_US).set(round(self._lat.percentile(99), 1))

    # --- reads ---
    def snapshot(self):
        """All serving.* stats currently in the registry."""
        return {k: v for k, v in self._reg.stats().items()
                if k.startswith(PREFIX)}

    def cache_hit_rate(self):
        s = self._reg.stats()
        hits = s.get(CACHE_HITS, 0)
        total = hits + s.get(CACHE_MISSES, 0)
        return (hits / total) if total else 0.0
