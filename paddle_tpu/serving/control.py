"""Self-healing control plane: the FleetSupervisor.

The FleetRouter is deliberately REACTIVE: its watchdog detects death
(stale heartbeats, wedges, socket EOF), remigrates in-flight work, and
sends synthetic ping probes so idle breakers earn their half-open
recovery — but it never *decides* anything.  A dead replica stays dead
until someone calls ``restart()``; a saturated fleet sheds typed
errors until someone adds capacity.  Those decisions are POLICY, and
policy lives here, in a loop an operator can read top to bottom::

    FleetSupervisor.tick()
        1. RESURRECT   every dead replica gets restart(wait=False);
                       respawn backoff and the crash-loop cap are the
                       router's contract and are RESPECTED, not bypassed
                       — a replica owing backoff is retried next tick,
                       a crash-looping one is left for the operator
        2. SCALE UP    sustained pressure spawns a replica from
                       `spec_factory`, up to FleetConfig.max_replicas.
                       Pressure is measured PER CLASS on a role-split
                       fleet: the prefill class reads queue depth and
                       TTFT EWMA (admission latency IS prefill
                       latency), the decode class reads queue depth
                       and decode slot occupancy (active streams /
                       max_decode_slots) — a fleet drowning in long
                       decodes spawns decode capacity, not another
                       prefill replica, and vice versa.  Each class
                       keeps its own sustain counter; `spec_factory`
                       receives the pressured class via a `role`
                       keyword when its signature accepts one.
        3. SCALE DOWN  a sustained idle fleet (every replica idle for
                       `idle_ticks` consecutive ticks) drains ONE
                       supervisor-spawned replica per tick, down to
                       FleetConfig.min_replicas — only its own spawns:
                       the operator's configured fleet is never shrunk

``tick()`` is synchronous and deterministic (tests drive it directly);
``start()`` runs it on a background thread every ``interval_s`` — the
production shape.  All accounting lands in the fleet.* registry:
supervisor_restart_total, autoscale_spawned/drained, replica_count.

Docs: docs/SERVING.md "Cross-host fleet".
"""
import inspect
import threading

from .admission import ServingError

__all__ = ["FleetSupervisor", "SupervisorConfig"]


class SupervisorConfig:
    """Control-plane policy knobs.

    interval_s: background tick period (start()).
    scale_up_queue_depth: mean queued requests per serving replica at
        or above which a tick counts as PRESSURE.
    scale_up_ttft_s: measured TTFT EWMA (worst serving replica) at or
        above which a tick counts as pressure (None = disabled).  A
        prefill-class signal: TTFT is what prefill capacity buys.
    scale_up_slot_occupancy: decode slot occupancy (active streams /
        max_decode_slots, worst serving replica) at or above which a
        tick counts as pressure (None = disabled).  A decode-class
        signal: a replica with every decode slot seated sheds the
        next admission even with an empty queue.
    sustain_ticks: consecutive pressure ticks (per class) before ONE
        replica is spawned for that class (a single burst must not
        double the fleet).
    idle_ticks: consecutive fully-idle ticks before ONE spawned
        replica is drained.
    """

    def __init__(self, interval_s=0.25, scale_up_queue_depth=4.0,
                 scale_up_ttft_s=None, scale_up_slot_occupancy=None,
                 sustain_ticks=3, idle_ticks=8):
        if float(interval_s) <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.interval_s = float(interval_s)
        if float(scale_up_queue_depth) <= 0:
            raise ValueError(f"scale_up_queue_depth must be > 0, got "
                             f"{scale_up_queue_depth}")
        self.scale_up_queue_depth = float(scale_up_queue_depth)
        if scale_up_ttft_s is not None and float(scale_up_ttft_s) <= 0:
            raise ValueError(f"scale_up_ttft_s must be > 0 or None, "
                             f"got {scale_up_ttft_s}")
        self.scale_up_ttft_s = (None if scale_up_ttft_s is None
                                else float(scale_up_ttft_s))
        if scale_up_slot_occupancy is not None and not (
                0.0 < float(scale_up_slot_occupancy) <= 1.0):
            raise ValueError(f"scale_up_slot_occupancy must be in "
                             f"(0, 1] or None, got "
                             f"{scale_up_slot_occupancy}")
        self.scale_up_slot_occupancy = (
            None if scale_up_slot_occupancy is None
            else float(scale_up_slot_occupancy))
        for knob, val in (("sustain_ticks", sustain_ticks),
                          ("idle_ticks", idle_ticks)):
            if int(val) < 1:
                raise ValueError(f"{knob} must be >= 1, got {val}")
        self.sustain_ticks = int(sustain_ticks)
        self.idle_ticks = int(idle_ticks)


class FleetSupervisor:
    """The decision loop over one FleetRouter.

    `spec_factory(index) -> ReplicaSpec` builds the spec for the
    index-th supervisor-spawned replica (None disables autoscaling
    up — the supervisor still resurrects and drains).  The supervisor
    only ever REMOVES replicas it spawned itself."""

    def __init__(self, router, spec_factory=None, config=None):
        self.router = router
        self.spec_factory = spec_factory
        self.config = config or SupervisorConfig()
        self._spawned = []          # names, spawn order (LIFO drain)
        self._spawn_seq = 0
        self._pressure_ticks = {}   # class -> consecutive hits
        self._idle_ticks = 0
        self._lock = threading.Lock()   # one tick at a time
        self._stop = threading.Event()
        self._thread = None

    # ---------------------------- policy ----------------------------
    def _survey(self):
        """One read of the fleet: (serving replica count, dead names,
        per-class signal dict, all idle).  Classes: on a role-split
        fleet, "prefill" and "decode" — a mixed replica contributes to
        BOTH (it does both jobs); on a homogeneous fleet, one "mixed"
        class (the pre-split behavior, one sustain counter).  Each
        class entry carries queue depths, TTFT EWMAs, and decode slot
        occupancies.  Reads cached transport state only — no RPCs on
        the policy path."""
        serving, dead, idle = 0, [], True
        reps = list(self.router._replicas.values())
        split = any(r.role in ("prefill", "decode") for r in reps
                    if r.state == "serving")
        stats = {}
        for rep in reps:
            if rep.state == "dead":
                dead.append(rep.name)
                continue
            if rep.state != "serving":
                continue
            serving += 1
            try:
                info = rep.transport.load_info()
            except ServingError:
                continue
            if not info.get("idle", True) or info["queue_depth"]:
                idle = False
            slots = getattr(rep, "_describe", {}).get("max_decode_slots")
            classes = (("mixed",) if not split
                       else (("prefill", "decode")
                             if rep.role == "mixed" else (rep.role,)))
            for cls in classes:
                s = stats.setdefault(
                    cls, {"depths": [], "ewmas": [], "occ": []})
                s["depths"].append(info["queue_depth"])
                if rep.ttft_ewma is not None:
                    s["ewmas"].append(rep.ttft_ewma)
                if slots:
                    s["occ"].append(info["active"] / slots)
        return serving, dead, stats, idle

    def _resurrect(self, dead):
        """restart(wait=False) every dead replica, respecting the
        router's respawn discipline: backoff still owed → retry next
        tick; crash-loop cap hit → leave it for the operator (the
        typed error names reset_respawn as the override)."""
        healed = 0
        for name in dead:
            try:
                self.router.restart(name, wait=False)
            except (ServingError, KeyError):
                continue   # backoff owed / crash loop / raced a remove
            self.router.metrics.count_supervisor_restart()
            healed += 1
        return healed

    def _class_pressure(self, cls, s):
        """One class's pressure verdict from its survey signals.
        Queue depth presses every class; TTFT EWMA presses prefill
        (and mixed); decode slot occupancy presses decode (and
        mixed)."""
        cfg = self.config
        depths = s["depths"]
        mean_depth = (sum(depths) / len(depths)) if depths else 0.0
        if mean_depth >= cfg.scale_up_queue_depth:
            return True
        if cls != "decode" and cfg.scale_up_ttft_s is not None \
                and max(s["ewmas"], default=0.0) >= cfg.scale_up_ttft_s:
            return True
        return (cls != "prefill"
                and cfg.scale_up_slot_occupancy is not None
                and max(s["occ"], default=0.0)
                >= cfg.scale_up_slot_occupancy)

    def _make_spec(self, seq, role):
        """Build the spec for one spawn, passing the pressured class
        through to factories that accept a `role` keyword — a role-
        split fleet scales the class that is actually starved.  Plain
        `factory(seq)` factories keep working unchanged."""
        if role != "mixed":
            try:
                params = inspect.signature(
                    self.spec_factory).parameters
            except (TypeError, ValueError):
                params = {}
            if "role" in params or any(
                    p.kind is inspect.Parameter.VAR_KEYWORD
                    for p in params.values()):
                return self.spec_factory(seq, role=role)
        return self.spec_factory(seq)

    def _scale_up(self, serving, role="mixed"):
        cap = self.router.config.max_replicas
        if self.spec_factory is None or cap is None or serving >= cap:
            return False
        spec = self._make_spec(self._spawn_seq, role)
        try:
            name = self.router.add_replica(spec)
        except (ServingError, ValueError):
            return False
        self._spawn_seq += 1
        self._spawned.append(name)
        self.router.metrics.count_autoscale(up=True)
        return True

    def _scale_down(self, serving):
        if not self._spawned \
                or serving <= self.router.config.min_replicas:
            return False
        name = self._spawned.pop()   # LIFO: newest spawn drains first
        try:
            self.router.remove_replica(name)
        except (ServingError, KeyError):
            return False
        self.router.metrics.count_autoscale(up=False)
        return True

    def tick(self):
        """One deterministic control-plane pass.  Returns a dict of
        the actions taken — the test/introspection surface."""
        with self._lock:
            serving, dead, stats, idle = self._survey()
            healed = self._resurrect(dead)
            spawned = drained = False
            pressured = {cls: self._class_pressure(cls, s)
                         for cls, s in stats.items()}
            # a class that left the fleet (role split appeared or
            # vanished) forgets its streak
            for cls in [c for c in self._pressure_ticks
                        if c not in pressured]:
                del self._pressure_ticks[cls]
            for cls, hit in pressured.items():
                self._pressure_ticks[cls] = \
                    self._pressure_ticks.get(cls, 0) + 1 if hit else 0
            if any(pressured.values()):
                self._idle_ticks = 0
            elif idle and not dead:
                self._idle_ticks += 1
            else:
                self._idle_ticks = 0
            for cls in ("mixed", "prefill", "decode"):
                if self._pressure_ticks.get(cls, 0) \
                        >= self.config.sustain_ticks:
                    if self._scale_up(serving + (1 if spawned else 0),
                                      role=cls):
                        spawned = True
                        self._pressure_ticks[cls] = 0
            if not spawned \
                    and self._idle_ticks >= self.config.idle_ticks:
                drained = self._scale_down(serving)
                if drained:
                    self._idle_ticks = 0
            depths = [d for s in stats.values() for d in s["depths"]]
            ewmas = [e for s in stats.values() for e in s["ewmas"]]
            return {"healed": healed, "spawned": spawned,
                    "drained": drained, "serving": serving,
                    "mean_queue_depth": round(
                        (sum(depths) / len(depths)) if depths else 0.0,
                        3),
                    "worst_ttft_s": round(max(ewmas, default=0.0), 4),
                    "pressure": pressured,
                    "idle": idle}

    # --------------------------- lifecycle --------------------------
    def start(self):
        """Run tick() on a background thread every interval_s."""
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-supervisor", daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.config.interval_s):
            try:
                self.tick()
            except Exception:   # noqa: BLE001 — the control plane
                pass            # must outlive any single bad tick

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
