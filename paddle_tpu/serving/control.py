"""Self-healing control plane: the FleetSupervisor.

The FleetRouter is deliberately REACTIVE: its watchdog detects death
(stale heartbeats, wedges, socket EOF), remigrates in-flight work, and
sends synthetic ping probes so idle breakers earn their half-open
recovery — but it never *decides* anything.  A dead replica stays dead
until someone calls ``restart()``; a saturated fleet sheds typed
errors until someone adds capacity.  Those decisions are POLICY, and
policy lives here, in a loop an operator can read top to bottom::

    FleetSupervisor.tick()
        1. RESURRECT   every dead replica gets restart(wait=False);
                       respawn backoff and the crash-loop cap are the
                       router's contract and are RESPECTED, not bypassed
                       — a replica owing backoff is retried next tick,
                       a crash-looping one is left for the operator
        2. SCALE UP    sustained pressure (queue depth or TTFT EWMA
                       over thresholds for `sustain_ticks` consecutive
                       ticks) spawns a replica from `spec_factory`,
                       up to FleetConfig.max_replicas
        3. SCALE DOWN  a sustained idle fleet (every replica idle for
                       `idle_ticks` consecutive ticks) drains ONE
                       supervisor-spawned replica per tick, down to
                       FleetConfig.min_replicas — only its own spawns:
                       the operator's configured fleet is never shrunk

``tick()`` is synchronous and deterministic (tests drive it directly);
``start()`` runs it on a background thread every ``interval_s`` — the
production shape.  All accounting lands in the fleet.* registry:
supervisor_restart_total, autoscale_spawned/drained, replica_count.

Docs: docs/SERVING.md "Cross-host fleet".
"""
import threading

from .admission import ServingError

__all__ = ["FleetSupervisor", "SupervisorConfig"]


class SupervisorConfig:
    """Control-plane policy knobs.

    interval_s: background tick period (start()).
    scale_up_queue_depth: mean queued requests per serving replica at
        or above which a tick counts as PRESSURE.
    scale_up_ttft_s: measured TTFT EWMA (worst serving replica) at or
        above which a tick counts as pressure (None = queue depth
        only).
    sustain_ticks: consecutive pressure ticks before ONE replica is
        spawned (a single burst must not double the fleet).
    idle_ticks: consecutive fully-idle ticks before ONE spawned
        replica is drained.
    """

    def __init__(self, interval_s=0.25, scale_up_queue_depth=4.0,
                 scale_up_ttft_s=None, sustain_ticks=3, idle_ticks=8):
        if float(interval_s) <= 0:
            raise ValueError(f"interval_s must be > 0, got {interval_s}")
        self.interval_s = float(interval_s)
        if float(scale_up_queue_depth) <= 0:
            raise ValueError(f"scale_up_queue_depth must be > 0, got "
                             f"{scale_up_queue_depth}")
        self.scale_up_queue_depth = float(scale_up_queue_depth)
        if scale_up_ttft_s is not None and float(scale_up_ttft_s) <= 0:
            raise ValueError(f"scale_up_ttft_s must be > 0 or None, "
                             f"got {scale_up_ttft_s}")
        self.scale_up_ttft_s = (None if scale_up_ttft_s is None
                                else float(scale_up_ttft_s))
        for knob, val in (("sustain_ticks", sustain_ticks),
                          ("idle_ticks", idle_ticks)):
            if int(val) < 1:
                raise ValueError(f"{knob} must be >= 1, got {val}")
        self.sustain_ticks = int(sustain_ticks)
        self.idle_ticks = int(idle_ticks)


class FleetSupervisor:
    """The decision loop over one FleetRouter.

    `spec_factory(index) -> ReplicaSpec` builds the spec for the
    index-th supervisor-spawned replica (None disables autoscaling
    up — the supervisor still resurrects and drains).  The supervisor
    only ever REMOVES replicas it spawned itself."""

    def __init__(self, router, spec_factory=None, config=None):
        self.router = router
        self.spec_factory = spec_factory
        self.config = config or SupervisorConfig()
        self._spawned = []          # names, spawn order (LIFO drain)
        self._spawn_seq = 0
        self._pressure_ticks = 0
        self._idle_ticks = 0
        self._lock = threading.Lock()   # one tick at a time
        self._stop = threading.Event()
        self._thread = None

    # ---------------------------- policy ----------------------------
    def _survey(self):
        """One read of the fleet: (serving replica count, dead names,
        mean queue depth per serving replica, worst TTFT EWMA, all
        idle).  Reads cached transport state only — no RPCs on the
        policy path."""
        serving, dead, depths, ewmas, idle = 0, [], [], [], True
        for rep in list(self.router._replicas.values()):
            if rep.state == "dead":
                dead.append(rep.name)
                continue
            if rep.state != "serving":
                continue
            serving += 1
            try:
                info = rep.transport.load_info()
            except ServingError:
                continue
            depths.append(info["queue_depth"])
            if not info.get("idle", True) or info["queue_depth"]:
                idle = False
            if rep.ttft_ewma is not None:
                ewmas.append(rep.ttft_ewma)
        mean_depth = (sum(depths) / len(depths)) if depths else 0.0
        return serving, dead, mean_depth, max(ewmas, default=0.0), idle

    def _resurrect(self, dead):
        """restart(wait=False) every dead replica, respecting the
        router's respawn discipline: backoff still owed → retry next
        tick; crash-loop cap hit → leave it for the operator (the
        typed error names reset_respawn as the override)."""
        healed = 0
        for name in dead:
            try:
                self.router.restart(name, wait=False)
            except (ServingError, KeyError):
                continue   # backoff owed / crash loop / raced a remove
            self.router.metrics.count_supervisor_restart()
            healed += 1
        return healed

    def _pressure(self, mean_depth, worst_ttft):
        cfg = self.config
        if mean_depth >= cfg.scale_up_queue_depth:
            return True
        return (cfg.scale_up_ttft_s is not None
                and worst_ttft >= cfg.scale_up_ttft_s)

    def _scale_up(self, serving):
        cap = self.router.config.max_replicas
        if self.spec_factory is None or cap is None or serving >= cap:
            return False
        spec = self.spec_factory(self._spawn_seq)
        try:
            name = self.router.add_replica(spec)
        except (ServingError, ValueError):
            return False
        self._spawn_seq += 1
        self._spawned.append(name)
        self.router.metrics.count_autoscale(up=True)
        return True

    def _scale_down(self, serving):
        if not self._spawned \
                or serving <= self.router.config.min_replicas:
            return False
        name = self._spawned.pop()   # LIFO: newest spawn drains first
        try:
            self.router.remove_replica(name)
        except (ServingError, KeyError):
            return False
        self.router.metrics.count_autoscale(up=False)
        return True

    def tick(self):
        """One deterministic control-plane pass.  Returns a dict of
        the actions taken — the test/introspection surface."""
        with self._lock:
            serving, dead, mean_depth, worst_ttft, idle = self._survey()
            healed = self._resurrect(dead)
            spawned = drained = False
            if self._pressure(mean_depth, worst_ttft):
                self._pressure_ticks += 1
                self._idle_ticks = 0
            elif idle and not dead:
                self._idle_ticks += 1
                self._pressure_ticks = 0
            else:
                self._pressure_ticks = 0
                self._idle_ticks = 0
            if self._pressure_ticks >= self.config.sustain_ticks:
                spawned = self._scale_up(serving)
                if spawned:
                    self._pressure_ticks = 0
            elif self._idle_ticks >= self.config.idle_ticks:
                drained = self._scale_down(serving)
                if drained:
                    self._idle_ticks = 0
            return {"healed": healed, "spawned": spawned,
                    "drained": drained, "serving": serving,
                    "mean_queue_depth": round(mean_depth, 3),
                    "worst_ttft_s": round(worst_ttft, 4),
                    "idle": idle}

    # --------------------------- lifecycle --------------------------
    def start(self):
        """Run tick() on a background thread every interval_s."""
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="fleet-supervisor", daemon=True)
        self._thread.start()
        return self

    def _loop(self):
        while not self._stop.wait(self.config.interval_s):
            try:
                self.tick()
            except Exception:   # noqa: BLE001 — the control plane
                pass            # must outlive any single bad tick

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False
