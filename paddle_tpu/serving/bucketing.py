"""Shape bucketing + per-bucket AOT-compiled executable cache.

Production TPU serving lives or dies on compile reuse: XLA compiles one
executable PER SHAPE, so free-form request shapes mean a compile storm.
The fix (Ragged Paged Attention, arxiv 2604.15464; the Gemma-on-TPU
report, arxiv 2605.25645, attributes most serving throughput to batching
+ AOT compile reuse) is a small fixed menu of shapes:

- `ShapeBucketer` rounds every request up to the next (batch, length)
  bucket and pads with a constant; outputs are sliced back to real rows;
- `CompiledModelCache` keeps ONE ahead-of-time compiled executable per
  padded shape signature (jax.jit().lower().compile(), the AOT analogue
  of the reference's warmed AnalysisPredictor), so steady-state serving
  never traces or compiles again.
"""
import threading

import numpy as np

from .admission import RequestTooLargeError
from .metrics import ServingMetrics


def _check_buckets(name, buckets):
    bs = tuple(int(b) for b in buckets)
    if not bs or any(b < 1 for b in bs) or list(bs) != sorted(set(bs)):
        raise ValueError(
            f"{name} must be strictly increasing positive ints, got "
            f"{buckets!r}")
    return bs


class ShapeBucketer:
    """Pads request shapes to a fixed bucket menu.

    batch_buckets: allowed padded batch sizes (axis 0 of every input).
    length_buckets: optional allowed padded lengths for axis 1 of every
        input with ndim >= 2 (token/sequence inputs); None disables
        length bucketing (trailing dims must then match the bucket key
        exactly).
    pad_value: fill for padding rows/positions (0 works for both token
        ids and dense features).
    """

    def __init__(self, batch_buckets=(1, 2, 4, 8), length_buckets=None,
                 pad_value=0):
        self.batch_buckets = _check_buckets("batch_buckets", batch_buckets)
        self.length_buckets = None if length_buckets is None else \
            _check_buckets("length_buckets", length_buckets)
        self.pad_value = pad_value

    @staticmethod
    def geometric_menu(limit, start=8):
        """A power-of-two bucket menu covering [1, limit]: (start,
        2*start, ..., first power >= limit).  log2(limit) buckets bound
        the compile count while wasting at most 2x padding — the
        standard serving trade (docs/SERVING.md)."""
        limit = max(int(limit), 1)
        start = max(int(start), 1)
        menu = [start]
        while menu[-1] < limit:
            menu.append(menu[-1] * 2)
        return tuple(menu)

    @property
    def max_batch(self):
        return self.batch_buckets[-1]

    def batch_bucket(self, rows):
        """Smallest batch bucket >= rows; typed rejection past the menu."""
        for b in self.batch_buckets:
            if rows <= b:
                return b
        raise RequestTooLargeError(
            f"request rows={rows} exceed the largest batch bucket "
            f"{self.batch_buckets[-1]}")

    def length_bucket(self, length):
        if self.length_buckets is None:
            return int(length)
        for b in self.length_buckets:
            if length <= b:
                return b
        raise RequestTooLargeError(
            f"sequence length {length} exceeds the largest length bucket "
            f"{self.length_buckets[-1]}")

    def bucket_key(self, arrays):
        """Coalescing key: per-input (bucketed trailing shape, dtype).
        Two requests coalesce into one dispatch iff their keys match —
        after length padding they then share every non-batch dim."""
        key = []
        for a in arrays:
            a = np.asarray(a)
            trail = list(a.shape[1:])
            if trail and self.length_buckets is not None:
                trail[0] = self.length_bucket(trail[0])
            key.append((tuple(trail), str(a.dtype)))
        return tuple(key)

    def pad_request(self, arrays):
        """Pad axis 1 of each input to its length bucket (axis 0 — batch —
        is padded later, once per coalesced dispatch)."""
        out = []
        for a in arrays:
            a = np.asarray(a)
            if a.ndim >= 2 and self.length_buckets is not None:
                want = self.length_bucket(a.shape[1])
                if want != a.shape[1]:
                    widths = [(0, 0)] * a.ndim
                    widths[1] = (0, want - a.shape[1])
                    a = np.pad(a, widths, constant_values=self.pad_value)
            out.append(a)
        return out

    def pad_token_batch(self, seqs, dtype=np.int32):
        """Pad ragged token-id sequences into one bucketed batch:
        returns ``(tokens [batch_bucket, length_bucket], lengths [B])``
        — the prefill-side entry point (generation's batched prefill
        and any token-in serving model share this menu)."""
        lens = np.asarray([len(s) for s in seqs], np.int32)
        if len(seqs) == 0:
            raise ValueError("pad_token_batch needs at least one sequence")
        bb = self.batch_bucket(len(seqs))
        lb = self.length_bucket(int(lens.max()))
        out = np.full((bb, lb), self.pad_value, dtype)
        for i, s in enumerate(seqs):
            out[i, :len(s)] = s
        return out, lens

    def pad_batch(self, arrays, rows):
        """Pad axis 0 from `rows` to the batch bucket; returns (padded
        arrays, bucket_rows)."""
        bucket = self.batch_bucket(rows)
        if bucket == rows:
            return list(arrays), bucket
        out = []
        for a in arrays:
            widths = [(0, 0)] * a.ndim
            widths[0] = (0, bucket - rows)
            out.append(np.pad(a, widths, constant_values=self.pad_value))
        return out, bucket

    @staticmethod
    def unpad_outputs(outs, row_counts):
        """Scatter a padded batch output back per-request: slices rows
        [offset, offset+rows) for each request in dispatch order."""
        per_request = [[] for _ in row_counts]
        for o in outs:
            o = np.asarray(o)
            off = 0
            for i, rows in enumerate(row_counts):
                per_request[i].append(o[off:off + rows])
                off += rows
        return per_request


class CompiledModelCache:
    """(shapes, dtypes) -> ahead-of-time compiled executable.

    Wraps any positional array function (a Predictor's exported module
    call, a CompiledBlock-style jitted fn, or a plain jax callable).  The
    first request into a bucket pays lower+compile ONCE (counted in
    `serving.compiles_total`); every later request is a cache hit that
    goes straight to the executable — the compile-reuse contract the
    bucket menu exists to enable.

    ``aot=False`` keeps the per-signature cache and its counters but
    skips jax.jit: every signature "compiles" to the raw fn, dispatched
    eagerly.  Callers needing BITWISE parity with an unbatched eager
    path use this — XLA whole-program fusion reassociates float
    reductions at the ulp level, which generation's zero-tolerance
    token-identity oracle cannot absorb (docs/GENERATION.md).
    compile_count then still means "distinct shape signatures
    dispatched" — the number the bucket menu exists to bound.
    """

    def __init__(self, fn, metrics=None, aot=True, donate_argnums=()):
        self._fn = fn
        self._metrics = metrics or ServingMetrics()
        self._aot = bool(aot)
        # buffer-donation plan forwarded to jax.jit: generation's fused
        # decode step donates its KV pool arguments so XLA updates them
        # in place (ignored when aot=False — the raw fn never donates)
        self._donate = tuple(donate_argnums)
        self._cache = {}
        self._lock = threading.Lock()
        self.compile_count = 0

    @staticmethod
    def _key(args):
        return tuple((tuple(a.shape), str(a.dtype)) for a in args)

    def _compile(self, args):
        import jax

        from ..profiler import RecordEvent

        if not self._aot:
            return self._fn

        from jax.sharding import NamedSharding

        def aval(a):
            # mesh-sharded callers (generation's sharded fused decode)
            # hand committed NamedSharding arrays — or prewarm
            # ShapeDtypeStructs carrying the same shardings — and the
            # AOT executable must be lowered against those shardings or
            # it would reject the very arrays it is dispatched with.
            # Plain numpy args (and single-device jax arrays) keep the
            # historical sharding-free aval: placement stays the
            # compiler's choice, exactly as before.
            sh = getattr(a, "sharding", None)
            if isinstance(sh, NamedSharding):
                return jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sh)
            return jax.ShapeDtypeStruct(a.shape, a.dtype)

        avals = [aval(a) for a in args]
        with RecordEvent("serving::compile"):
            try:
                exe = jax.jit(self._fn, donate_argnums=self._donate) \
                    .lower(*avals).compile()
            except Exception:
                # fns that resist lowering (host callbacks, non-jax code)
                # still serve, just without the AOT guarantee
                exe = self._fn
        return exe

    def get(self, args):
        """Executable for this exact shape signature (compiling once)."""
        key = self._key(args)
        with self._lock:
            exe = self._cache.get(key)
            hit = exe is not None
        self._metrics.count_cache(hit)
        if hit:
            return exe
        # compile OUTSIDE the lock: buckets compile concurrently and a
        # 30 s XLA compile must not block cache hits on other buckets
        exe = self._compile(args)
        with self._lock:
            # a racing compile of the same bucket: first one in wins so
            # every caller runs the SAME executable (and the compile
            # counter keeps meaning 'one per cached bucket')
            exist = self._cache.get(key)
            if exist is None:
                self._cache[key] = exe
                self.compile_count += 1
                won = True
            else:
                exe = exist
                won = False
        if won:
            self._metrics.count_compile()
        return exe

    def __call__(self, args):
        outs = self.get(args)(*[np.asarray(a) for a in args])
        if not isinstance(outs, (list, tuple)):
            outs = (outs,)
        return [np.asarray(o) for o in outs]

    def warmup(self, shape_sets, dtype="float32"):
        """Pre-compile buckets before traffic: shape_sets is an iterable
        of per-input shape lists, e.g. [[(8, 16)], [(4, 16)]]."""
        for shapes in shape_sets:
            args = [np.zeros(s, dtype=dtype) for s in shapes]
            self.get(args)

    def cached_buckets(self):
        with self._lock:
            return sorted(self._cache)
