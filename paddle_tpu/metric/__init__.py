"""paddle.metric parity.  Ref: python/paddle/metric/metrics.py:38-593."""
import numpy as np

from ..core.tensor import Tensor, to_tensor


class Metric:
    def __init__(self):
        pass

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    """Ref: metrics.py:38 Accuracy (top-k)."""

    def __init__(self, topk=(1,), name=None):
        super().__init__()
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self.maxk = max(self.topk)
        self._name = name or "acc"
        self.reset()

    def compute(self, pred, label, *args):
        p = pred.numpy() if isinstance(pred, Tensor) else np.asarray(pred)
        l = label.numpy() if isinstance(label, Tensor) else np.asarray(label)
        pred_topk = np.argsort(-p, axis=-1)[..., : self.maxk]
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l.squeeze(-1)
        correct = pred_topk == l[..., None]
        return to_tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = correct.numpy() if isinstance(correct, Tensor) else np.asarray(correct)
        accs = []
        for k in self.topk:
            num = c[..., :k].sum()
            accs.append(float(num) / max(c.shape[0], 1))
            self.total[self.topk.index(k)] += num
            self.count[self.topk.index(k)] += c.shape[0]
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c else 0.0 for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return [self._name]
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    """Ref: metrics.py Precision (binary)."""

    def __init__(self, name="precision"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        l = labels.numpy() if isinstance(labels, Tensor) else np.asarray(labels)
        p = (p.reshape(-1) > 0.5).astype(np.int32)
        l = l.reshape(-1).astype(np.int32)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fp += int(((p == 1) & (l == 0)).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        denom = self.tp + self.fp
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name="recall"):
        super().__init__()
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        l = labels.numpy() if isinstance(labels, Tensor) else np.asarray(labels)
        p = (p.reshape(-1) > 0.5).astype(np.int32)
        l = l.reshape(-1).astype(np.int32)
        self.tp += int(((p == 1) & (l == 1)).sum())
        self.fn += int(((p == 0) & (l == 1)).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        denom = self.tp + self.fn
        return self.tp / denom if denom else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Ref: metrics.py Auc — thresholded histogram AUC."""

    def __init__(self, curve="ROC", num_thresholds=4095, name="auc"):
        super().__init__()
        self.num_thresholds = num_thresholds
        self._name = name
        self.reset()

    def update(self, preds, labels):
        p = preds.numpy() if isinstance(preds, Tensor) else np.asarray(preds)
        l = labels.numpy() if isinstance(labels, Tensor) else np.asarray(labels)
        if p.ndim == 2 and p.shape[1] == 2:
            p = p[:, 1]
        p = p.reshape(-1)
        l = l.reshape(-1)
        bins = np.minimum(
            (p * self.num_thresholds).astype(np.int64), self.num_thresholds - 1
        )
        for b, y in zip(bins, l):
            if y:
                self._stat_pos[b] += 1
            else:
                self._stat_neg[b] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds, np.int64)

    def accumulate(self):
        tot_pos = 0.0
        tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds - 1, -1, -1):
            pos = float(self._stat_pos[i])
            neg = float(self._stat_neg[i])
            auc += neg * (tot_pos + pos / 2.0)
            tot_pos += pos
            tot_neg += neg
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return auc / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None):
    """Functional accuracy (fluid/layers/metric_op.py parity)."""
    p = input.numpy()
    l = label.numpy()
    topk = np.argsort(-p, axis=-1)[..., :k]
    if l.ndim == p.ndim and l.shape[-1] == 1:
        l = l.squeeze(-1)
    acc = np.mean((topk == l[..., None]).any(-1).astype(np.float32))
    return to_tensor(np.array([acc], np.float32))
