"""Attention primitives.

Reference parity: the reference composes attention from matmul/softmax/dropout
(nn/layer/transformer.py:406-420) and ships fused CUDA kernels only for
inference (operators/fused/multihead_matmul_op.cu).  Here the training core is
a single fused dataflow XLA maps to the MXU; a Pallas flash-attention kernel
(ops/pallas/flash_attention.py) is used for long sequences on TPU.
"""
import math

import jax
import jax.numpy as jnp

from ..core.registry import apply_op
from ..core.tensor import Tensor
from ..core import random as _random


def scaled_dot_product_attention(q, k, v, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, return_weights=False,
                                 use_flash=None):
    """q,k,v: [B, H, L, D].  attn_mask: additive float mask broadcastable to
    [B, H, Lq, Lk]."""
    scale = 1.0 / math.sqrt(q.shape[-1])

    if q.shape[-2] == 1:
        # decode fast path (Lq == 1, the KV-cache autoregressive step):
        # a single query row attends to every key — tril(k=Lk-1) over one
        # row is all-True — so the causal-mask build is dead weight, and
        # the flash gate is skipped outright (one [1, Lk] score row is a
        # single small gemm; a Pallas dispatch only adds launch cost, and
        # paged decode has its own kernel in ops/pallas/paged_attention).
        is_causal = False
        use_flash = False

    if use_flash is None:
        from ..framework import get_flags

        use_flash = bool(get_flags("FLAGS_flash_attention")
                         .get("FLAGS_flash_attention"))
    if use_flash and not return_weights and dropout_p == 0.0:
        # import only on the flash path: environments without pallas still
        # run the composite path fine
        from .pallas.flash_attention import (flash_attention,
                                             mask_is_flash_compatible,
                                             shapes_are_flash_compatible)

        if (mask_is_flash_compatible(attn_mask)
                and shapes_are_flash_compatible(q.shape[-2], k.shape[-2],
                                                d=q.shape[-1])):
            return flash_attention(q, k, v, attn_mask=attn_mask,
                                   causal=is_causal), None

    key = _random.next_key() if dropout_p > 0.0 else None

    def fn(qv, kv, vv, *mask):
        logits = jnp.einsum("bhqd,bhkd->bhqk", qv, kv) * scale
        if mask:
            logits = logits + mask[0]
        if is_causal:
            Lq, Lk = logits.shape[-2], logits.shape[-1]
            causal = jnp.tril(jnp.ones((Lq, Lk), bool), k=Lk - Lq)
            logits = jnp.where(causal, logits, -1e9)
        weights = jax.nn.softmax(logits, axis=-1)
        if dropout_p > 0.0:
            keep = jax.random.bernoulli(key, 1.0 - dropout_p, weights.shape)
            weights_d = jnp.where(keep, weights / (1.0 - dropout_p), 0.0)
        else:
            weights_d = weights
        out = jnp.einsum("bhqk,bhkd->bhqd", weights_d, vv)
        return out, weights

    args = (q, k, v) + ((attn_mask,) if attn_mask is not None else ())
    out, weights = apply_op("sdp_attention", fn, args, {}, n_outputs=2)
    return out, (weights if return_weights else None)
