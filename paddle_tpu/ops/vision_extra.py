"""Vision / image-manipulation op family.

Reference: operators/affine_channel_op.cc, shuffle_channel_op.h,
space_to_depth_op.cc, spp_op.h (spatial pyramid pooling), unpool_op.h,
pool_with_index (max_pool2d_with_index kernels in math/pooling.cc),
psroi_pool_op.h, prroi_pool_op.h, deformable_conv_op.h/.cu,
random_crop_op.h, pad_constant_like_op.cc, partial_concat_op.cc,
partial_sum_op.cc, fsp_op.h, data_norm_op.cc, cvm_op.h,
fused/fused_softmax_mask_upper_triangle_op.cu,
bilinear_tensor_product_op.h, unique_with_counts_op.h,
*_batch_size_like ops.

TPU-native design: window/ROI gathers become dense take_along_axis /
one-hot matmuls that XLA tiles onto the VPU/MXU; deformable sampling is
a vectorized bilinear gather (no per-pixel loops); the dynamic-shape
unique ops run eagerly (they are host/boundary ops, same as the
reference's CPU-only kernels).
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import apply_op, register_op
from ..core.tensor import Tensor, to_tensor

__all__ = [
    "affine_channel", "shuffle_channel", "space_to_depth", "spp",
    "max_pool2d_with_index", "max_unpool2d", "psroi_pool", "prroi_pool",
    "deformable_psroi_pooling", "deformable_roi_pooling",
    "deformable_conv", "random_crop", "pad_constant_like",
    "partial_concat", "partial_sum", "fsp_matrix", "data_norm", "cvm",
    "softmax_mask_fuse_upper_triangle", "bilinear_tensor_product",
    "unique_with_counts", "uniform_random_batch_size_like",
    "gaussian_random_batch_size_like",
]


def _affine_channel(x, scale, bias):
    return x * scale.reshape(1, -1, 1, 1) + bias.reshape(1, -1, 1, 1)


register_op("affine_channel", _affine_channel)


def affine_channel(x, scale, bias, data_format="NCHW", name=None):
    """Per-channel scale+shift, the frozen-BN replacement
    (affine_channel_op.cc)."""
    if data_format == "NHWC":
        return apply_op("affine_channel_nhwc",
                        lambda v, s, b: v * s.reshape(1, 1, 1, -1)
                        + b.reshape(1, 1, 1, -1), (x, scale, bias), {})
    return apply_op("affine_channel", _affine_channel, (x, scale, bias), {})


def _shuffle_channel(x, group=1):
    B, C, H, W = x.shape
    return x.reshape(B, group, C // group, H, W).transpose(
        0, 2, 1, 3, 4).reshape(B, C, H, W)


register_op("shuffle_channel", _shuffle_channel)


def shuffle_channel(x, group, name=None):
    """ShuffleNet channel shuffle (shuffle_channel_op.h)."""
    return apply_op("shuffle_channel", _shuffle_channel, (x,),
                    {"group": int(group)})


def _space_to_depth(x, blocksize=2):
    B, C, H, W = x.shape
    bs = blocksize
    y = x.reshape(B, C, H // bs, bs, W // bs, bs)
    return y.transpose(0, 3, 5, 1, 2, 4).reshape(
        B, C * bs * bs, H // bs, W // bs)


register_op("space_to_depth", _space_to_depth)


def space_to_depth(x, blocksize, name=None):
    """Rearrange spatial blocks into channels (space_to_depth_op.cc)."""
    return apply_op("space_to_depth", _space_to_depth, (x,),
                    {"blocksize": int(blocksize)})


def spp(x, pyramid_height=2, pool_type="max", name=None):
    """Spatial pyramid pooling (spp_op.h): concat flattened 2^l x 2^l
    adaptive pools for l in [0, pyramid_height)."""
    from .nn_ops import adaptive_avg_pool2d, adaptive_max_pool2d
    from .manipulation import concat, reshape

    outs = []
    B, C = x.shape[0], x.shape[1]
    for level in range(pyramid_height):
        bins = 2 ** level
        pooled = (adaptive_max_pool2d(x, bins) if pool_type == "max"
                  else adaptive_avg_pool2d(x, bins))
        outs.append(reshape(pooled, [B, C * bins * bins]))
    return concat(outs, axis=1)


def _window_patches(x, kh, kw, sh, sw, ph, pw, pad_val):
    """(B, C, Ho, Wo, kh*kw) patch tensor + matching flat input indices."""
    B, C, H, W = x.shape
    xp = jnp.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                 constant_values=pad_val)
    Ho = (H + 2 * ph - kh) // sh + 1
    Wo = (W + 2 * pw - kw) // sw + 1
    rows = jnp.arange(Ho) * sh
    cols = jnp.arange(Wo) * sw
    patches = []
    for i in range(kh):
        for j in range(kw):
            patches.append(xp[:, :, rows[:, None] + i, cols[None, :] + j])
    return jnp.stack(patches, axis=-1), Ho, Wo


def _max_pool_with_index(x, kernel=(2, 2), stride=(2, 2), padding=(0, 0)):
    kh, kw = kernel
    sh, sw = stride
    ph, pw = padding
    B, C, H, W = x.shape
    neg = jnp.asarray(-3.4e38, x.dtype)
    pat, Ho, Wo = _window_patches(x, kh, kw, sh, sw, ph, pw, neg)
    amax = jnp.argmax(pat, axis=-1)  # (B, C, Ho, Wo) in [0, kh*kw)
    out = jnp.max(pat, axis=-1)
    ki, kj = amax // kw, amax % kw
    rows = (jnp.arange(Ho) * sh).reshape(1, 1, Ho, 1) + ki - ph
    cols = (jnp.arange(Wo) * sw).reshape(1, 1, 1, Wo) + kj - pw
    idx = rows * W + cols  # flat index into the unpadded H*W plane
    return out, idx.astype(jnp.int32)


register_op("max_pool2d_with_index", _max_pool_with_index, n_outputs=2)


def _pair(v):
    return tuple(v) if isinstance(v, (list, tuple)) else (int(v), int(v))


def max_pool2d_with_index(x, kernel_size, stride=None, padding=0, name=None):
    """Max pool returning the reference's flat H*W argmax indices
    (pool_with_index, math/pooling.cc MaxPool2dWithIndex)."""
    k = _pair(kernel_size)
    s = _pair(stride) if stride is not None else k
    p = _pair(padding)
    return apply_op("max_pool2d_with_index", _max_pool_with_index, (x,),
                    {"kernel": k, "stride": s, "padding": p}, n_outputs=2)


def _max_unpool2d(x, indices, out_h, out_w):
    B, C, Ho, Wo = x.shape
    flat = jnp.zeros((B, C, out_h * out_w), x.dtype)
    idx = indices.reshape(B, C, Ho * Wo).astype(jnp.int32)
    vals = x.reshape(B, C, Ho * Wo)
    bi = jnp.arange(B).reshape(B, 1, 1)
    ci = jnp.arange(C).reshape(1, C, 1)
    flat = flat.at[bi, ci, idx].add(vals)
    return flat.reshape(B, C, out_h, out_w)


register_op("unpool", _max_unpool2d)


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, name=None):
    """Scatter pooled values back to their argmax positions (unpool_op.h)."""
    k = _pair(kernel_size)
    s = _pair(stride) if stride is not None else k
    p = _pair(padding)
    if output_size is not None:
        out_h, out_w = output_size[-2], output_size[-1]
    else:
        Ho, Wo = x.shape[2], x.shape[3]
        out_h = (Ho - 1) * s[0] - 2 * p[0] + k[0]
        out_w = (Wo - 1) * s[1] - 2 * p[1] + k[1]
    return apply_op("unpool", _max_unpool2d, (x, indices),
                    {"out_h": int(out_h), "out_w": int(out_w)})


def _bilinear_at(x, ys, xs):
    """Sample x (C, H, W) at float coords ys/xs (...) with zero padding."""
    C, H, W = x.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy = ys - y0
    wx = xs - x0

    def tap(yy, xx):
        ok = (yy >= 0) & (yy <= H - 1) & (xx >= 0) & (xx <= W - 1)
        yc = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
        xc = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
        v = x[:, yc, xc]  # (C, ...)
        return jnp.where(ok[None], v, 0.0)

    return (tap(y0, x0) * ((1 - wy) * (1 - wx))[None]
            + tap(y0, x0 + 1) * ((1 - wy) * wx)[None]
            + tap(y0 + 1, x0) * (wy * (1 - wx))[None]
            + tap(y0 + 1, x0 + 1) * (wy * wx)[None])


def psroi_pool(x, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, rois_num=None, name=None):
    """Position-sensitive ROI average pooling (psroi_pool_op.h): input
    channel (c, ph, pw) feeds output channel c at bin (ph, pw).
    rois: (R, 4) [x1, y1, x2, y2] boxes in image coords; all assigned to
    batch item 0 unless rois_num gives a per-image split."""
    ph, pw = int(pooled_height), int(pooled_width)
    oc = int(output_channels)
    rois_arr = np.asarray(rois._data if isinstance(rois, Tensor) else rois,
                          np.float32)
    splits = (np.asarray(rois_num._data if isinstance(rois_num, Tensor)
                         else rois_num, np.int64).reshape(-1)
              if rois_num is not None else
              np.array([rois_arr.shape[0]], np.int64))
    batch_of = np.repeat(np.arange(len(splits)), splits)

    def fn(xv, rv):
        H, W = xv.shape[2], xv.shape[3]

        def one_roi(roi, b):
            x1, y1, x2, y2 = [r * spatial_scale for r in
                              (roi[0], roi[1], roi[2], roi[3])]
            rh = jnp.maximum(y2 - y1, 0.1)
            rw = jnp.maximum(x2 - x1, 0.1)
            bin_h, bin_w = rh / ph, rw / pw
            # average over a fixed 2x2 sample grid per bin (dense, jit-able)
            sy = (jnp.arange(ph)[:, None] * bin_h + y1
                  + (jnp.arange(2)[None, :] + 0.5) * bin_h / 2)  # (ph, 2)
            sx = (jnp.arange(pw)[:, None] * bin_w + x1
                  + (jnp.arange(2)[None, :] + 0.5) * bin_w / 2)  # (pw, 2)
            gy = jnp.broadcast_to(sy[:, None, :, None], (ph, pw, 2, 2))
            gx = jnp.broadcast_to(sx[None, :, None, :], (ph, pw, 2, 2))
            samp = _bilinear_at(xv[b], gy, gx)  # (C, ph, pw, 2, 2)
            pooled = jnp.mean(samp, axis=(-2, -1))  # (C, ph, pw)
            # position-sensitive: channel block (c*ph*pw + iy*pw + ix)
            ps = pooled.reshape(oc, ph, pw, ph, pw)
            iy = jnp.arange(ph)[:, None]
            ix = jnp.arange(pw)[None, :]
            return ps[:, iy, ix, iy, ix]  # (oc, ph, pw)

        outs = [one_roi(rv[i], int(batch_of[i]))
                for i in range(rv.shape[0])]
        return jnp.stack(outs)

    return apply_op("psroi_pool", fn, (x, rois), {})


def deformable_psroi_pooling(input, rois, trans=None, no_trans=False,
                             spatial_scale=1.0, group_size=(1, 1),
                             pooled_height=1, pooled_width=1,
                             output_dim=None, part_size=None,
                             sample_per_part=1, trans_std=0.1,
                             position_sensitive=False, rois_num=None,
                             name=None):
    """Deformable PS-ROI pooling (deformable_psroi_pooling_op.h, the
    fluid.layers.deformable_roi_pooling surface): each output bin's sample
    window is shifted by a learned per-part offset `trans` (scaled by
    trans_std and the ROI extent) before bilinear-average pooling; with
    position_sensitive=True the input channel feeding output channel c at
    bin (gh, gw) is (c*group_h + gh)*group_w + gw.

    input: (N, C, H, W); rois: (R, 4) [x1, y1, x2, y2] image coords;
    trans: (R, 2*num_classes, part_h, part_w) offsets or None.
    """
    ph, pw = int(pooled_height), int(pooled_width)
    gh, gw = int(group_size[0]), int(group_size[1])
    spp = int(sample_per_part)
    rois_arr = np.asarray(rois._data if isinstance(rois, Tensor) else rois,
                          np.float32)
    splits = (np.asarray(rois_num._data if isinstance(rois_num, Tensor)
                         else rois_num, np.int64).reshape(-1)
              if rois_num is not None else
              np.array([rois_arr.shape[0]], np.int64))
    batch_of = np.repeat(np.arange(len(splits)), splits)
    C = input.shape[1]
    if output_dim is None:
        output_dim = C // (gh * gw) if position_sensitive else C
    oc = int(output_dim)
    if part_size is None:
        part_size = (ph, pw)
    pth, ptw = int(part_size[0]), int(part_size[1])
    use_trans = not no_trans and trans is not None
    n_classes = 1
    if use_trans:
        n_classes = (trans.shape[1] if isinstance(trans, Tensor)
                     else np.asarray(trans).shape[1]) // 2
    ch_per_class = max(oc // n_classes, 1)

    # host-precomputed static index grids (bin -> part cell / group cell)
    part_iy = np.minimum((np.arange(ph) * pth) // ph, pth - 1)
    part_ix = np.minimum((np.arange(pw) * ptw) // pw, ptw - 1)
    grp_iy = np.clip((np.arange(ph) * gh) // ph, 0, gh - 1)
    grp_ix = np.clip((np.arange(pw) * gw) // pw, 0, gw - 1)
    class_of = np.minimum(np.arange(oc) // ch_per_class, n_classes - 1)

    def fn(xv, rv, tv):
        H, W = xv.shape[2], xv.shape[3]

        def one_roi(roi, b, t_roi):
            # reference rounds the box then recenters by half a pixel
            x1 = jnp.round(roi[0]) * spatial_scale - 0.5
            y1 = jnp.round(roi[1]) * spatial_scale - 0.5
            x2 = (jnp.round(roi[2]) + 1.0) * spatial_scale - 0.5
            y2 = (jnp.round(roi[3]) + 1.0) * spatial_scale - 0.5
            rw = jnp.maximum(x2 - x1, 0.1)
            rh = jnp.maximum(y2 - y1, 0.1)
            bin_h, bin_w = rh / ph, rw / pw
            sub_h, sub_w = bin_h / spp, bin_w / spp
            if use_trans:
                # trans channel 0 is the x-offset, channel 1 the y-offset
                # (deformable_psroi_pooling_op.h:101-117 bottom_trans layout)
                t = t_roi.reshape(n_classes, 2, pth, ptw) * trans_std
                off_x = t[:, 0][:, part_iy][:, :, part_ix] * rw
                off_y = t[:, 1][:, part_iy][:, :, part_ix] * rh
            else:
                off_x = jnp.zeros((n_classes, ph, pw))
                off_y = jnp.zeros((n_classes, ph, pw))
            # sample grid per class: (classes, ph, pw, spp, spp)
            base_y = y1 + jnp.arange(ph)[:, None] * bin_h  # (ph, 1)
            base_x = x1 + jnp.arange(pw)[None, :] * bin_w  # (1, pw)
            sy = (base_y[None, :, :, None, None] + off_y[..., None, None]
                  + jnp.arange(spp)[None, None, None, :, None] * sub_h)
            sx = (base_x[None, :, :, None, None] + off_x[..., None, None]
                  + jnp.arange(spp)[None, None, None, None, :] * sub_w)
            # boundary samples (exactly ±0.5 outside) are kept, as the
            # reference does, and clamped into range before interpolation
            ok = ((sy >= -0.5) & (sy <= H - 0.5)
                  & (sx >= -0.5) & (sx <= W - 0.5))
            yc = jnp.clip(sy, 0, H - 1)
            xc = jnp.clip(sx, 0, W - 1)
            samp = _bilinear_at(xv[b], yc, xc)  # (C, cls, ph, pw, s, s)
            samp = jnp.where(ok[None], samp, 0.0)
            n_ok = jnp.maximum(jnp.sum(ok, axis=(-2, -1)), 1)  # (cls,ph,pw)
            pooled = jnp.sum(samp, axis=(-2, -1)) / n_ok[None]
            # pick each output channel's input channel + its class plane
            if position_sensitive:
                cin = ((np.arange(oc)[:, None, None] * gh
                        + grp_iy[None, :, None]) * gw
                       + grp_ix[None, None, :])  # (oc, ph, pw)
            else:
                cin = np.broadcast_to(
                    np.arange(oc)[:, None, None], (oc, ph, pw))
            iy = np.arange(ph)[None, :, None]
            ix = np.arange(pw)[None, None, :]
            return pooled[cin, class_of[:, None, None], iy, ix]

        outs = [one_roi(rv[i], int(batch_of[i]),
                        tv[i] if use_trans else None)
                for i in range(rv.shape[0])]
        return jnp.stack(outs)

    args = (input, rois, trans) if use_trans else (input, rois)
    if not use_trans:
        return apply_op("deformable_psroi_pooling",
                        lambda xv, rv: fn(xv, rv, None), args, {})
    return apply_op("deformable_psroi_pooling", fn, args, {})


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=(1, 1),
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1,
                           position_sensitive=False, name=None):
    """fluid.layers.deformable_roi_pooling parity wrapper."""
    return deformable_psroi_pooling(
        input, rois, trans, no_trans=no_trans, spatial_scale=spatial_scale,
        group_size=group_size, pooled_height=pooled_height,
        pooled_width=pooled_width, part_size=part_size,
        sample_per_part=sample_per_part, trans_std=trans_std,
        position_sensitive=position_sensitive, name=name)


def prroi_pool(x, rois, pooled_height, pooled_width, spatial_scale=1.0,
               rois_num=None, name=None):
    """Precise ROI pooling (prroi_pool_op.h): continuous average over each
    bin.  Approximated by a dense 4x4 bilinear sample grid per bin — the
    integral limit the reference computes analytically."""
    ph, pw = int(pooled_height), int(pooled_width)
    rois_arr = np.asarray(rois._data if isinstance(rois, Tensor) else rois,
                          np.float32)
    splits = (np.asarray(rois_num._data if isinstance(rois_num, Tensor)
                         else rois_num, np.int64).reshape(-1)
              if rois_num is not None else
              np.array([rois_arr.shape[0]], np.int64))
    batch_of = np.repeat(np.arange(len(splits)), splits)
    S = 4

    def fn(xv, rv):
        def one_roi(roi, b):
            x1, y1, x2, y2 = [r * spatial_scale for r in
                              (roi[0], roi[1], roi[2], roi[3])]
            bin_h = (y2 - y1) / ph
            bin_w = (x2 - x1) / pw
            gy = (y1 + jnp.arange(ph)[:, None, None, None] * bin_h
                  + (jnp.arange(S)[None, None, :, None] + 0.5) * bin_h / S)
            gx = (x1 + jnp.arange(pw)[None, :, None, None] * bin_w
                  + (jnp.arange(S)[None, None, None, :] + 0.5) * bin_w / S)
            gy = jnp.broadcast_to(gy, (ph, pw, S, S))
            gx = jnp.broadcast_to(gx, (ph, pw, S, S))
            samp = _bilinear_at(xv[b], gy, gx)
            return jnp.mean(samp, axis=(-2, -1))  # (C, ph, pw)

        return jnp.stack([one_roi(rv[i], int(batch_of[i]))
                          for i in range(rv.shape[0])])

    return apply_op("prroi_pool", fn, (x, rois), {})


def deformable_conv(x, offset, weight, mask=None, stride=1, padding=0,
                    dilation=1, deformable_groups=1, groups=1, im2col_step=1,
                    bias=None, name=None):
    """Deformable convolution v1/v2 (deformable_conv_op.h).

    offset (B, 2*dg*kh*kw, Ho, Wo) shifts each kernel tap's sampling
    point; v2 adds a per-tap modulation mask.  Lowered as: bilinear-gather
    all taps into an im2col tensor, then one MXU matmul — no per-pixel
    scalar loops.
    """
    sh, sw = _pair(stride)
    ph, pw = _pair(padding)
    dh, dw = _pair(dilation)

    has_mask = mask is not None
    has_bias = bias is not None

    def fn(xv, off, wv, *rest):
        mk = rest[0] if has_mask else None
        bv = (rest[1] if has_mask else rest[0]) if has_bias else None
        B, C, H, W = xv.shape
        M, Cg, kh, kw = wv.shape
        Ho = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
        Wo = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
        dg = deformable_groups
        off = off.reshape(B, dg, kh * kw, 2, Ho, Wo)

        base_y = (jnp.arange(Ho) * sh - ph)[:, None]
        base_x = (jnp.arange(Wo) * sw - pw)[None, :]
        cols = []  # per tap: (B, C, Ho, Wo)
        for t in range(kh * kw):
            i, j = divmod(t, kw)
            # offset layout (deformable_conv_op kernels): (..., [dy, dx])
            dy = off[:, :, t, 0]  # (B, dg, Ho, Wo)
            dx = off[:, :, t, 1]
            ys = base_y[None, None] + i * dh + dy
            xs = base_x[None, None] + j * dw + dx

            def samp_b(xb, yb, xbx):
                # xb (C,H,W); yb/xbx (dg,Ho,Wo) -> (C,Ho,Wo) w/ channel
                # groups mapped to their deformable group
                per_g = []
                cpg = C // dg
                for g in range(dg):
                    per_g.append(_bilinear_at(xb[g * cpg:(g + 1) * cpg],
                                              yb[g], xbx[g]))
                return jnp.concatenate(per_g, axis=0)

            tap = jax.vmap(samp_b)(xv, ys, xs)  # (B, C, Ho, Wo)
            if mk is not None:
                m = mk.reshape(B, dg, kh * kw, Ho, Wo)[:, :, t]
                m = jnp.repeat(m, C // dg, axis=1)
                tap = tap * m
            cols.append(tap)
        col = jnp.stack(cols, axis=2)  # (B, C, kh*kw, Ho, Wo)
        col = col.reshape(B, C * kh * kw, Ho * Wo)
        wmat = wv.reshape(M, Cg * kh * kw)
        if groups == 1:
            out = jnp.einsum("mk,bkl->bml", wmat, col)
        else:
            cpg = C // groups
            mpg = M // groups
            col_g = col.reshape(B, groups, cpg * kh * kw, Ho * Wo)
            w_g = wmat.reshape(groups, mpg, Cg * kh * kw)
            out = jnp.einsum("gmk,bgkl->bgml", w_g, col_g).reshape(
                B, M, Ho * Wo)
        out = out.reshape(B, M, Ho, Wo)
        if bv is not None:
            out = out + bv.reshape(1, -1, 1, 1)
        return out

    args = (x, offset, weight)
    if mask is not None:
        args = args + (mask,)
    if bias is not None:
        args = args + (bias,)
    return apply_op("deformable_conv", fn, args, {})


def random_crop(x, shape, seed=0, name=None):
    """Random spatial crop to `shape` (random_crop_op.h); seeded threefry,
    same crop for every sample feature dim left of the cropped dims."""
    from ..core import random as _random

    key0 = jax.random.PRNGKey(seed) if seed else _random.next_key()

    def fn(v):
        key = key0
        starts = []
        nd = len(shape)
        for d in range(nd):
            full = v.shape[v.ndim - nd + d]
            key, sub = jax.random.split(key)
            starts.append(jax.random.randint(sub, (), 0,
                                             max(full - shape[d], 0) + 1))
        out = jax.lax.dynamic_slice(
            v, [0] * (v.ndim - nd) + [s for s in starts],
            list(v.shape[:v.ndim - nd]) + list(shape))
        return out

    return apply_op("random_crop", fn, (x,), {})


def _pad_constant_like(x, y, pad_value=0.0):
    pads = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return jnp.pad(y, pads, constant_values=pad_value)


register_op("pad_constant_like", _pad_constant_like)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """Pad y up to x's shape with pad_value (pad_constant_like_op.cc)."""
    return apply_op("pad_constant_like", _pad_constant_like, (x, y),
                    {"pad_value": float(pad_value)})


def partial_concat(inputs, start_index=0, length=-1, name=None):
    """Concat the [start, start+length) column slice of every input
    (partial_concat_op.cc)."""
    def fn(*vs):
        outs = []
        for v in vs:
            end = v.shape[1] if length < 0 else start_index + length
            outs.append(v[:, start_index:end])
        return jnp.concatenate(outs, axis=1)

    return apply_op("partial_concat", fn, tuple(inputs), {})


def partial_sum(inputs, start_index=0, length=-1, name=None):
    """Sum the [start, start+length) column slice of every input
    (partial_sum_op.cc)."""
    def fn(*vs):
        acc = None
        for v in vs:
            end = v.shape[1] if length < 0 else start_index + length
            s = v[:, start_index:end]
            acc = s if acc is None else acc + s
        return acc

    return apply_op("partial_sum", fn, tuple(inputs), {})


def _fsp(x, y):
    hw = x.shape[2] * x.shape[3]
    return jnp.einsum("bihw,bjhw->bij", x, y) / hw


register_op("fsp", _fsp)


def fsp_matrix(x, y, name=None):
    """Flow-of-solution-procedure matrix for distillation (fsp_op.h)."""
    return apply_op("fsp", _fsp, (x, y), {})


def data_norm(x, batch_size, batch_sum, batch_square_sum, epsilon=1e-4,
              name=None):
    """Stats-table normalization (data_norm_op.cc:303): means =
    batch_sum / batch_size, scales = sqrt(batch_size / batch_square_sum)
    — the reference's exact formula (batch_square_sum accumulates squared
    DEVIATIONS, so no mean^2 subtraction); epsilon only guards the
    division.  Returns (normalized, means, scales)."""
    def fn(v, bs, bsum, bsq):
        means = bsum / bs
        scales = jnp.sqrt(bs / jnp.maximum(bsq, epsilon))
        return (v - means[None, :]) * scales[None, :], means, scales

    return apply_op("data_norm", fn,
                    (x, batch_size, batch_sum, batch_square_sum), {},
                    n_outputs=3)


def cvm(x, use_cvm=True, name=None):
    """Click-value-model feature transform (cvm_op.h): first two columns
    are (show, click); use_cvm log-transforms them in place, else they are
    dropped."""
    def fn(v):
        if use_cvm:
            show = jnp.log(v[:, 0:1] + 1.0)
            click = jnp.log(v[:, 1:2] + 1.0) - show
            return jnp.concatenate([show, click, v[:, 2:]], axis=1)
        return v[:, 2:]

    return apply_op("cvm", fn, (x,), {})


def _softmax_mask_ut(x):
    T1, T2 = x.shape[-2], x.shape[-1]
    mask = jnp.tril(jnp.ones((T1, T2), jnp.bool_))
    neg = jnp.asarray(-1e9, x.dtype)
    return jax.nn.softmax(jnp.where(mask, x, neg), axis=-1)


register_op("fused_softmax_mask_upper_triangle", _softmax_mask_ut)


def softmax_mask_fuse_upper_triangle(x, name=None):
    """Causal (upper-triangle-masked) softmax
    (fused_softmax_mask_upper_triangle_op.cu) — XLA fuses mask+softmax
    into one kernel; the Pallas flash path covers the full attention."""
    return apply_op("fused_softmax_mask_upper_triangle", _softmax_mask_ut,
                    (x,), {})


def _bilinear_tp(x, y, w, *rest):
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    if rest:
        out = out + rest[0]
    return out


register_op("bilinear_tensor_product", _bilinear_tp)


def bilinear_tensor_product(x, y, weight, bias=None, name=None):
    """out_k = x W_k y^T (+ b) (bilinear_tensor_product_op.h)."""
    args = (x, y, weight) + ((bias,) if bias is not None else ())
    return apply_op("bilinear_tensor_product", _bilinear_tp, args, {})


def unique_with_counts(x, dtype="int32", name=None):
    """(unique values, index-of-each-input, counts) — eager/host op like
    the reference's CPU-only kernel (unique_with_counts_op.h)."""
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    vals, inv, counts = np.unique(arr, return_inverse=True,
                                  return_counts=True)
    mk = lambda a: to_tensor(np.asarray(a))
    out, index, cnt = mk(vals), mk(inv.astype(dtype)), mk(
        counts.astype(dtype))
    for t in (out, index, cnt):
        t.stop_gradient = True
    return out, index, cnt


def uniform_random_batch_size_like(input, shape, min=-1.0, max=1.0,
                                   input_dim_idx=0, output_dim_idx=0,
                                   seed=0, dtype="float32", name=None):
    """Uniform sample whose output_dim_idx dim copies input's
    input_dim_idx (uniform_random_batch_size_like op)."""
    from .creation import uniform

    shp = list(shape)
    src = input.shape[input_dim_idx] if isinstance(input, Tensor) \
        else np.asarray(input).shape[input_dim_idx]
    shp[output_dim_idx] = src
    return uniform(shp, min=min, max=max, seed=seed, dtype=dtype)


def gaussian_random_batch_size_like(input, shape, mean=0.0, std=1.0,
                                    input_dim_idx=0, output_dim_idx=0,
                                    seed=0, dtype="float32", name=None):
    from .creation import normal

    shp = list(shape)
    src = input.shape[input_dim_idx] if isinstance(input, Tensor) \
        else np.asarray(input).shape[input_dim_idx]
    shp[output_dim_idx] = src
    return normal(mean=mean, std=std, shape=shp)
