"""Loss ops.

Reference parity: softmax_with_cross_entropy_op.cc, cross_entropy_op.cc,
bce_loss_op.cc, huber_loss, kldiv_loss, margin ops, nll_loss
(paddle/fluid/operators/) and python/paddle/nn/functional/loss.py.
"""
import jax
import jax.numpy as jnp

from ..core.registry import apply_op
from ..core.tensor import Tensor, to_tensor


def _reduce_loss(out, reduction):
    from . import math as M

    if reduction == "mean":
        return M.mean(out)
    if reduction == "sum":
        return M.sum(out)
    return out


def softmax_with_cross_entropy(logits, label, soft_label=False, axis=-1,
                               ignore_index=-100, return_softmax=False):
    """Ref: softmax_with_cross_entropy_op.cc (fused, numerically stable)."""
    lbl = label._data if isinstance(label, Tensor) else jnp.asarray(label)

    def fn(lg):
        logp = jax.nn.log_softmax(lg, axis=axis)
        if soft_label:
            loss = -jnp.sum(lbl * logp, axis=axis, keepdims=True)
        else:
            li = lbl
            if li.ndim == lg.ndim and li.shape[axis] == 1:
                li = jnp.squeeze(li, axis=axis)
            li = li.astype(jnp.int32)
            ignored = jnp.expand_dims(li, axis) == ignore_index
            # clamp BEFORE the gather: an ignore_index like the default
            # -100 must not index the class axis (negative wraps silently)
            safe = jnp.clip(li, 0, lg.shape[axis] - 1)
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe, axis), axis=axis
            )
            loss = jnp.where(ignored, 0.0, -picked)
        return loss

    loss = apply_op("softmax_with_cross_entropy", fn, (logits,), {})
    if return_softmax:
        from .nn_ops import softmax as _sm

        return loss, _sm(logits, axis=axis)
    return loss


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, name=None):
    lbl = label._data if isinstance(label, Tensor) else jnp.asarray(label)

    def fn(lg, *w):
        logp = jax.nn.log_softmax(lg, axis=axis) if use_softmax else jnp.log(
            jnp.maximum(lg, 1e-30)
        )
        if soft_label:
            loss = -jnp.sum(lbl * logp, axis=axis)
        else:
            li = lbl
            if li.ndim == lg.ndim and li.shape[axis] == 1:
                li = jnp.squeeze(li, axis=axis)
            ignored = li == ignore_index
            # ignore_index (default -100) must not index the class axis
            safe = jnp.clip(li.astype(jnp.int32), 0, lg.shape[axis] - 1)
            picked = jnp.take_along_axis(
                logp, jnp.expand_dims(safe, axis), axis=axis
            )
            loss = -jnp.squeeze(picked, axis=axis)
            if w:
                loss = loss * jnp.take(w[0], safe)
            loss = jnp.where(ignored, 0.0, loss)
        return loss

    args = (input,) + ((weight,) if weight is not None else ())
    out = apply_op("cross_entropy", fn, args, {})
    if reduction == "mean" and not soft_label:
        # masked/weighted mean divides by the sum of effective weights.
        # Keep the denominator traced (no float()/host sync): labels are
        # tracers when this runs under jit.to_static / compiled steps.
        from . import math as M
        from ..core.tensor import _wrap_data

        li = lbl
        if weight is not None:
            safe = jnp.clip(li.astype(jnp.int32), 0,
                            weight._data.shape[0] - 1)
            w_per = jnp.where(li == ignore_index, 0.0,
                              jnp.take(weight._data, safe))
            denom = jnp.sum(w_per)
        else:
            denom = jnp.sum(li != ignore_index).astype(out._data.dtype)
        return M.divide(M.sum(out),
                        _wrap_data(jnp.maximum(denom, 1e-12)))
    return _reduce_loss(out, reduction)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    lbl = label._data if isinstance(label, Tensor) else jnp.asarray(label)

    def fn(logp, *w):
        picked = jnp.take_along_axis(
            logp, jnp.expand_dims(lbl, 1).astype(jnp.int32), axis=1
        )
        loss = -jnp.squeeze(picked, axis=1)
        if w:
            loss = loss * jnp.take(w[0], lbl.astype(jnp.int32))
        return loss

    args = (input,) + ((weight,) if weight is not None else ())
    return _reduce_loss(apply_op("nll_loss", fn, args, {}), reduction)


def mse_loss(input, label, reduction="mean", name=None):
    out = apply_op("mse_loss", lambda a, b: jnp.square(a - b), (input, label), {})
    return _reduce_loss(out, reduction)


def l1_loss(input, label, reduction="mean", name=None):
    out = apply_op("l1_loss", lambda a, b: jnp.abs(a - b), (input, label), {})
    return _reduce_loss(out, reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def fn(a, b):
        d = a - b
        ad = jnp.abs(d)
        return jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta) * delta

    return _reduce_loss(apply_op("smooth_l1", fn, (input, label), {}), reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def fn(p, y, *w):
        eps = 1e-12
        out = -(y * jnp.log(jnp.maximum(p, eps)) + (1 - y) * jnp.log(jnp.maximum(1 - p, eps)))
        if w:
            out = out * w[0]
        return out

    args = (input, label) + ((weight,) if weight is not None else ())
    return _reduce_loss(apply_op("bce_loss", fn, args, {}), reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def fn(z, y, *extra):
        i = 0
        w = None
        pw = None
        if weight is not None:
            w = extra[i]; i += 1
        if pos_weight is not None:
            pw = extra[i]; i += 1
        # stable: max(z,0) - z*y + log(1+exp(-|z|)); pos_weight scales the y-term
        logexp = jax.nn.softplus(-jnp.abs(z))
        if pw is None:
            out = jnp.maximum(z, 0) - z * y + logexp
        else:
            lw = y * (pw - 1) + 1
            out = (1 - y) * z + lw * (logexp + jnp.maximum(-z, 0))
        if w is not None:
            out = out * w
        return out

    args = (logit, label) + tuple(t for t in (weight, pos_weight) if t is not None)
    return _reduce_loss(apply_op("bce_with_logits", fn, args, {}), reduction)


def kl_div(input, label, reduction="mean", name=None):
    def fn(lp, y):
        return y * (jnp.log(jnp.maximum(y, 1e-12)) - lp)

    out = apply_op("kldiv_loss", fn, (input, label), {})
    if reduction == "batchmean":
        from . import math as M

        return M.divide(M.sum(out), to_tensor(float(input.shape[0])))
    return _reduce_loss(out, reduction)


def hinge_loss(input, label, name=None):
    def fn(p, y):
        return jnp.maximum(0.0, 1.0 - (2.0 * y - 1.0) * p)

    return apply_op("hinge_loss", fn, (input, label), {})


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def fn(a, b, y):
        return jnp.maximum(0.0, -y * (a - b) + margin)

    return _reduce_loss(apply_op("margin_rank", fn, (input, other, label), {}), reduction)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    def fn(a, b):
        num = jnp.sum(a * b, axis=axis)
        den = jnp.sqrt(jnp.sum(a * a, axis=axis)) * jnp.sqrt(jnp.sum(b * b, axis=axis))
        return num / jnp.maximum(den, eps)

    return apply_op("cosine_similarity", fn, (x1, x2), {})


def square_error_cost(input, label):
    return apply_op("square_error", lambda a, b: jnp.square(a - b), (input, label), {})


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def fn(z, y, *n):
        p = jax.nn.sigmoid(z)
        ce = jnp.maximum(z, 0) - z * y + jax.nn.softplus(-jnp.abs(z))
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        out = a_t * ((1 - p_t) ** gamma) * ce
        if n:
            out = out / n[0]
        return out

    args = (logit, label) + ((normalizer,) if normalizer is not None else ())
    return _reduce_loss(apply_op("sigmoid_focal", fn, args, {}), reduction)
