"""3-D / 1-D conv-pool family + functional long tail.

Reference: operators/conv_op.cc (3D variants), pool_op.cc, affine_grid_op,
grid_sampler_op, bilinear_tensor_product_op, ctc ops, temporal_shift_op,
gather_tree_op — the remaining paddle.nn.functional surface.
All lower to lax primitives (conv_general_dilated / reduce_window handle
any spatial rank on the MXU/VPU).
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import apply_op
from ..core.tensor import Tensor, to_tensor

__all__ = [
    "conv3d", "conv3d_transpose", "conv1d_transpose",
    "max_pool3d", "avg_pool3d", "adaptive_avg_pool3d", "adaptive_max_pool3d",
    "adaptive_avg_pool1d", "adaptive_max_pool1d",
    "affine_grid", "grid_sample", "bilinear", "dice_loss", "log_loss",
    "npair_loss", "temporal_shift", "gather_tree", "ctc_loss",
    "hsigmoid_loss", "dropout3d", "selu", "pairwise_distance", "unfold",
    "spectral_norm_apply",
]


def _triple(v):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * 3


def _ntuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in v)
    return (int(v),) * n


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    s, d = _triple(stride), _triple(dilation)
    if isinstance(padding, str):
        pad = padding.upper()
    else:
        p = _triple(padding)
        pad = [(p[0], p[0]), (p[1], p[1]), (p[2], p[2])]
    dn = ("NCDHW", "OIDHW", "NCDHW")

    def fn(xv, wv):
        return jax.lax.conv_general_dilated(
            xv, wv, s, pad, rhs_dilation=d, dimension_numbers=dn,
            feature_group_count=groups, preferred_element_type=xv.dtype)

    out = apply_op("conv3d", fn, (x, weight), {})
    if bias is not None:
        out = apply_op("conv3d_bias",
                       lambda o, b: o + jnp.reshape(b, (1, -1, 1, 1, 1)),
                       (out, bias), {})
    return out


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCDHW", output_size=None, name=None):
    from .nn_ops import _conv_transpose_nd

    s, d = _triple(stride), _triple(dilation)
    op = _triple(output_padding)
    if isinstance(padding, str):
        pk = padding.upper()
        k3 = weight.shape[2:]
        if pk == "VALID":
            pad = [(0, 0)] * 3
        elif pk == "SAME":
            pad = []
            op = list(op)
            for i in range(3):
                total = d[i] * (k3[i] - 1) + 1 - s[i]
                if total < 0:
                    op[i] = op[i] - total  # deficit -> extra output pad
                    total = 0
                pad.append((total // 2, total - total // 2))
            op = tuple(op)
        else:
            raise ValueError("conv3d_transpose padding string must be "
                             "'SAME' or 'VALID'")
    else:
        p = _triple(padding)
        pad = [(pp, pp) for pp in p]
    if output_size is not None:
        k = weight.shape[2:]
        op = tuple(
            int(output_size[i])
            - ((x.shape[2 + i] - 1) * s[i] - pad[i][0] - pad[i][1]
               + d[i] * (k[i] - 1) + 1)
            for i in range(3))

    def fn(xv, wv):
        return _conv_transpose_nd(xv, wv, s, pad, d, groups, op, 3)

    out = apply_op("conv3d_transpose", fn, (x, weight), {})
    if bias is not None:
        out = apply_op("conv3d_transpose_bias",
                       lambda o, b: o + jnp.reshape(b, (1, -1, 1, 1, 1)),
                       (out, bias), {})
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0,
                     output_padding=0, groups=1, dilation=1,
                     data_format="NCL", output_size=None, name=None):
    from .manipulation import unsqueeze, squeeze
    from .nn_ops import conv2d_transpose

    x4 = unsqueeze(x, [3])
    w4 = unsqueeze(weight, [3])
    st = (stride, 1) if isinstance(stride, int) else tuple(stride) + (1,)
    pd = (padding, 0) if isinstance(padding, int) else tuple(padding) + (0,)
    opd = (output_padding, 0) if isinstance(output_padding, int) \
        else tuple(output_padding) + (0,)
    osz = None if output_size is None else list(output_size) + [1]
    out = conv2d_transpose(x4, w4, bias=bias, stride=st, padding=pd,
                           output_padding=opd, output_size=osz,
                           dilation=(dilation, 1) if isinstance(dilation, int)
                           else tuple(dilation) + (1,), groups=groups)
    return squeeze(out, [3])


def _pool3d(x, kind, kernel_size, stride, padding, exclusive=True,
            ceil_mode=False, divisor_override=None):
    k = _triple(kernel_size)
    s = _triple(stride) if stride is not None else k
    p = _triple(padding)
    window = (1, 1) + k
    strides = (1, 1) + s
    spatial = tuple(int(d) for d in x.shape[2:])
    # ceil_mode: pad the high side so the last partial window is kept
    # (out = ceil((L + 2p - k)/s) + 1); reduce_window pads with the init
    # value, which the exclusive count window correctly ignores
    extra = [0, 0, 0]
    if ceil_mode:
        for i, (L, ki, si, pi) in enumerate(zip(spatial, k, s, p)):
            out_ceil = -(-(L + 2 * pi - ki) // si) + 1
            extra[i] = max((out_ceil - 1) * si + ki - (L + 2 * pi), 0)
    pads = [(0, 0), (0, 0)] + [
        (pp, pp + e) for pp, e in zip(p, extra)]

    if kind == "max":
        def fn(v):
            init = -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) else \
                jnp.iinfo(v.dtype).min
            return jax.lax.reduce_window(v, init, jax.lax.max, window,
                                         strides, pads)
        return apply_op("pool3d_max", fn, (x,), {})

    def fn(v):
        ssum = jax.lax.reduce_window(v, 0.0, jax.lax.add, window, strides,
                                     pads)
        if divisor_override is not None:
            return ssum / float(divisor_override)
        if exclusive and any(pp != (0, 0) for pp in pads[2:]):
            cnt = jax.lax.reduce_window(jnp.ones_like(v), 0.0, jax.lax.add,
                                        window, strides, pads)
            return ssum / cnt
        return ssum / float(np.prod(k))

    return apply_op("pool3d_avg", fn, (x,), {})


def _max_pool3d_index(x, k, s, p, ceil_mode):
    """Flattened-spatial argmax indices per window (pool_with_index
    kernels' mask output).  Value patches are padded with -inf by
    pre-padding (conv_general_dilated_patches pads 0, which would win the
    argmax for all-negative windows), and ceil_mode adds the same
    high-side padding as the value path so out/mask shapes agree."""
    k3, s3, p3 = _triple(k), _triple(s), _triple(p)

    def fn(v):
        N, C, D, H, W = v.shape
        spatial = (D, H, W)
        extra = [0, 0, 0]
        if ceil_mode:
            for i, (L, ki, si, pi) in enumerate(zip(spatial, k3, s3, p3)):
                out_ceil = -(-(L + 2 * pi - ki) // si) + 1
                extra[i] = max((out_ceil - 1) * si + ki - (L + 2 * pi), 0)
        widths = [(0, 0), (0, 0)] + [
            (pp, pp + e) for pp, e in zip(p3, extra)]
        idx_map = jnp.broadcast_to(
            jnp.arange(D * H * W, dtype=jnp.float32).reshape(1, 1, D, H, W),
            v.shape)
        vp = jnp.pad(v, widths, constant_values=-jnp.inf)
        ip = jnp.pad(idx_map, widths, constant_values=-1.0)
        patches = jax.lax.conv_general_dilated_patches(
            vp, k3, s3, [(0, 0)] * 3,
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
        ipatches = jax.lax.conv_general_dilated_patches(
            ip, k3, s3, [(0, 0)] * 3,
            dimension_numbers=("NCDHW", "OIDHW", "NCDHW"))
        KK = int(np.prod(k3))
        od, oh, ow = patches.shape[2:]
        pv = patches.reshape(N, C, KK, od, oh, ow)
        iv = ipatches.reshape(N, C, KK, od, oh, ow)
        arg = jnp.argmax(pv, axis=2, keepdims=True)
        return jnp.take_along_axis(iv, arg, axis=2)[:, :, 0].astype(
            jnp.int32)

    return apply_op("max_pool3d_index", fn, (x,), {})


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW", name=None):
    out = _pool3d(x, "max", kernel_size, stride, padding,
                  ceil_mode=ceil_mode)
    if return_mask:
        mask = _max_pool3d_index(x, kernel_size,
                                 stride if stride is not None
                                 else kernel_size, padding, ceil_mode)
        return out, mask
    return out


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW",
               name=None):
    return _pool3d(x, "avg", kernel_size, stride, padding, exclusive,
                   ceil_mode=ceil_mode, divisor_override=divisor_override)


def _adaptive_nd(x, kind, out_sizes, spatial_offset=2):
    """Adaptive pooling over any spatial rank via variable windows."""
    def fn(v):
        spatial = v.shape[spatial_offset:]
        outs = _ntuple(out_sizes, len(spatial))

        def bounds(n, o):
            # paddle adaptive windows: start=floor(i*n/o), end=ceil((i+1)*n/o)
            # — adjacent windows may OVERLAP for non-divisible sizes
            return [((i * n) // o, -(-((i + 1) * n) // o)) for i in range(o)]

        bss = [bounds(n, o) for n, o in zip(spatial, outs)]

        # result dims [N, C, o1..on]: stack each output dim in place
        def build(dim, index):
            if dim == len(outs):
                sl = (slice(None), slice(None)) + tuple(
                    slice(*bss[d][i])
                    for d, i in enumerate(index))
                win = v[sl]
                axes = tuple(range(spatial_offset,
                                   spatial_offset + len(outs)))
                return (jnp.max(win, axis=axes) if kind == "max"
                        else jnp.mean(win, axis=axes))
            # children carry shape [N, C, outs[dim+1], ...]; stacking at
            # axis=2 at EVERY level yields [N, C, outs[dim], ...] (a fixed
            # 2+dim axis runs out of bounds beyond 1 spatial dim)
            return jnp.stack([build(dim + 1, index + (i,))
                              for i in range(outs[dim])], axis=2)

        return build(0, ())

    return apply_op(f"adaptive_pool_{kind}", fn, (x,), {})


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive_nd(x, "avg", output_size)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive_nd(x, "max", output_size)
    return (out, None) if return_mask else out


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive_nd(x, "avg", output_size)


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive_nd(x, "max", output_size)
    return (out, None) if return_mask else out


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """Ref: affine_grid_op.cc — [N,2,3] thetas -> [N,H,W,2] sample grid."""
    def fn(th):
        N = th.shape[0]
        H, W = int(out_shape[-2]), int(out_shape[-1])
        if align_corners:
            ys = jnp.linspace(-1, 1, H)
            xs = jnp.linspace(-1, 1, W)
        else:
            ys = (jnp.arange(H) + 0.5) / H * 2 - 1
            xs = (jnp.arange(W) + 0.5) / W * 2 - 1
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=-1).reshape(-1, 3)  # [HW, 3]
        grid = jnp.einsum("hk,nok->nho", base, th)  # [N, HW, 2]
        return grid.reshape(N, H, W, 2)

    return apply_op("affine_grid", fn, (theta,), {})


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Ref: grid_sampler_op.cc — bilinear or nearest (round(),
    grid_sampler_op.h:228) sampling of NCHW by [N,H,W,2]."""
    def fn(v, g):
        N, C, H, W = v.shape
        gx, gy = g[..., 0], g[..., 1]
        if align_corners:
            fx = (gx + 1) * (W - 1) / 2
            fy = (gy + 1) * (H - 1) / 2
        else:
            fx = ((gx + 1) * W - 1) / 2
            fy = ((gy + 1) * H - 1) / 2
        x0 = jnp.floor(fx)
        y0 = jnp.floor(fy)
        wx = fx - x0
        wy = fy - y0

        def gather(yy, xx):
            yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
            xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
            idx_n = jnp.arange(N).reshape(N, 1, 1)
            vals = v[idx_n, :, yi, xi]  # [N, Ho, Wo, C]
            if padding_mode == "zeros":
                inb = ((yy >= 0) & (yy <= H - 1) & (xx >= 0)
                       & (xx <= W - 1))[..., None]
                vals = jnp.where(inb, vals, 0.0)
            return vals

        if mode == "nearest":
            # C round() = half away from zero (grid_sampler_op.h:228);
            # jnp.round is half-to-even and picks the other pixel at
            # exact .5 coordinates (e.g. the grid center on even sizes)
            r = lambda f: jnp.sign(f) * jnp.floor(jnp.abs(f) + 0.5)
            out = gather(r(fy), r(fx))
        else:
            out = (gather(y0, x0) * ((1 - wx) * (1 - wy))[..., None]
                   + gather(y0, x0 + 1) * (wx * (1 - wy))[..., None]
                   + gather(y0 + 1, x0) * ((1 - wx) * wy)[..., None]
                   + gather(y0 + 1, x0 + 1) * (wx * wy)[..., None])
        return jnp.transpose(out, (0, 3, 1, 2))

    return apply_op("grid_sample", fn, (x, grid), {})


def bilinear(x1, x2, weight, bias=None, name=None):
    """Ref: bilinear_tensor_product_op.cc: out[n,o] = x1 W_o x2 + b."""
    def fn(a, b, w):
        out = jnp.einsum("ni,oij,nj->no", a, w, b)
        return out

    out = apply_op("bilinear", fn, (x1, x2, weight), {})
    if bias is not None:
        out = apply_op("bilinear_bias", lambda o, bb: o + bb, (out, bias), {})
    return out


def dice_loss(input, label, epsilon=1e-5, name=None):
    def fn(p, y):
        y1 = jax.nn.one_hot(y.squeeze(-1), p.shape[-1], dtype=p.dtype)
        inter = jnp.sum(p * y1, axis=-1)
        union = jnp.sum(p, axis=-1) + jnp.sum(y1, axis=-1)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))

    return apply_op("dice_loss", fn, (input, label), {})


def log_loss(input, label, epsilon=1e-4, name=None):
    def fn(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)

    return apply_op("log_loss", fn, (input, label), {})


def npair_loss(anchor, positive, labels, l2_reg=0.002):
    def fn(a, p, y):
        logits = a @ p.T
        same = (y.reshape(-1, 1) == y.reshape(1, -1)).astype(a.dtype)
        tgt = same / jnp.sum(same, axis=1, keepdims=True)
        xent = jnp.mean(jnp.sum(
            -tgt * jax.nn.log_softmax(logits, axis=1), axis=1))
        reg = l2_reg * (jnp.mean(jnp.sum(a * a, axis=1))
                        + jnp.mean(jnp.sum(p * p, axis=1))) * 0.25
        return xent + reg

    return apply_op("npair_loss", fn, (anchor, positive, labels), {})


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None, data_format="NCHW"):
    """Ref: temporal_shift_op.cc — shift channels across the time axis."""
    def fn(v):
        NT, C, H, W = v.shape
        N = NT // seg_num
        v5 = v.reshape(N, seg_num, C, H, W)
        c1 = int(C * shift_ratio)
        c2 = int(C * 2 * shift_ratio)
        fwd = jnp.concatenate(
            [v5[:, 1:, :c1], jnp.zeros_like(v5[:, :1, :c1])], axis=1)
        bwd = jnp.concatenate(
            [jnp.zeros_like(v5[:, :1, c1:c2]), v5[:, :-1, c1:c2]], axis=1)
        rest = v5[:, :, c2:]
        return jnp.concatenate([fwd, bwd, rest], axis=2).reshape(NT, C, H, W)

    return apply_op("temporal_shift", fn, (x,), {})


def gather_tree(ids, parents):
    """Ref: gather_tree_op.cc — back-trace beam-search parent pointers."""
    ids_v = ids._data if isinstance(ids, Tensor) else jnp.asarray(ids)
    par_v = parents._data if isinstance(parents, Tensor) else jnp.asarray(parents)
    T = ids_v.shape[0]

    def step(carry, t):
        beams = carry  # [batch, beam] current beam index per slot
        tok = jnp.take_along_axis(ids_v[t], beams, axis=1)
        nxt = jnp.take_along_axis(par_v[t], beams, axis=1)
        return nxt, tok

    init = jnp.tile(jnp.arange(ids_v.shape[2])[None, :],
                    (ids_v.shape[1], 1))
    _, toks = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
    return Tensor(jnp.flip(toks, axis=0), stop_gradient=True)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """Ref: warpctc_op.cc.  Forward-algorithm CTC in log space via
    lax.scan over time — runs entirely on device (no warpctc dlopen)."""
    lp_in = log_probs if isinstance(log_probs, Tensor) else \
        to_tensor(log_probs)
    lab = labels._data if isinstance(labels, Tensor) else jnp.asarray(labels)
    ilen = (input_lengths._data if isinstance(input_lengths, Tensor)
            else jnp.asarray(input_lengths)).astype(jnp.int32)
    llen = (label_lengths._data if isinstance(label_lengths, Tensor)
            else jnp.asarray(label_lengths)).astype(jnp.int32)
    lp_shape = tuple(lp_in.shape)
    need_t = len(lp_shape) == 3 and lp_shape[0] != lab.shape[0]
    B, T, C = ((lp_shape[1], lp_shape[0], lp_shape[2]) if need_t
               else lp_shape)
    S = lab.shape[1]
    L = 2 * S + 1
    NEG = -1e30

    # extended label seq: blank, l1, blank, l2, ... blank
    ext = jnp.full((B, L), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(lab.astype(jnp.int32))
    same_as_prevprev = jnp.concatenate(
        [jnp.zeros((B, 2), bool), ext[:, 2:] == ext[:, :-2]], axis=1)

    def fwd_fn(lp_b, ext_b, same_b, Tn, Ln):
        alpha0 = jnp.full((L,), NEG)
        alpha0 = alpha0.at[0].set(lp_b[0, ext_b[0]])
        alpha0 = alpha0.at[1].set(jnp.where(Ln > 0, lp_b[0, ext_b[1]], NEG))

        def step(alpha, t):
            a_shift1 = jnp.concatenate([jnp.array([NEG]), alpha[:-1]])
            a_shift2 = jnp.concatenate([jnp.full((2,), NEG), alpha[:-2]])
            a_shift2 = jnp.where(same_b, NEG, a_shift2)
            merged = jnp.logaddexp(jnp.logaddexp(alpha, a_shift1), a_shift2)
            new = merged + lp_b[t, ext_b]
            return jnp.where(t < Tn, new, alpha), None

        alphaT, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        end = 2 * Ln
        ll = jnp.logaddexp(alphaT[end], alphaT[jnp.maximum(end - 1, 0)])
        return -ll

    def fn(lp_raw):
        # transform INSIDE the op fn so the tape differentiates back to
        # the caller's logits (wrapping a detached to_tensor(lp) here
        # silently severed the gradient)
        if need_t:
            lp_raw = jnp.transpose(lp_raw, (1, 0, 2))
        lp_all = jax.nn.log_softmax(lp_raw, axis=-1)
        losses = jax.vmap(fwd_fn)(lp_all, ext, same_as_prevprev, ilen, llen)
        if norm_by_times:
            # warpctc norm_by_times: scale each sequence by 1/T (the
            # reference normalizes the gradient by the timestep count;
            # scaling the loss is the value-level equivalent)
            losses = losses / jnp.maximum(ilen.astype(losses.dtype), 1)
        if reduction == "mean":
            return jnp.mean(losses / jnp.maximum(llen, 1))
        if reduction == "sum":
            return jnp.sum(losses)
        return losses

    return apply_op("ctc_loss", fn, (lp_in,), {})


def hsigmoid_loss(input, label, num_classes, weight, bias=None,
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Ref: hierarchical_sigmoid_op.cc (default complete-tree mode)."""
    def fn(x, w, y):
        # default tree: logits over (num_classes-1) internal nodes
        logits = x @ w.T  # [B, num_classes-1]
        # complete binary tree code/path for each class
        codes = []
        paths = []
        for c in range(num_classes):
            node = c + num_classes - 1  # leaf index in heap order
            path, code = [], []
            while node > 0:
                parent = (node - 1) // 2
                code.append(1.0 if node == 2 * parent + 2 else 0.0)
                path.append(parent)
                node = parent
            paths.append(path[::-1])
            codes.append(code[::-1])
        maxlen = max(len(p) for p in paths)
        pt = np.zeros((num_classes, maxlen), np.int32)
        ct = np.zeros((num_classes, maxlen), np.float32)
        mask = np.zeros((num_classes, maxlen), np.float32)
        for c in range(num_classes):
            pt[c, :len(paths[c])] = paths[c]
            ct[c, :len(codes[c])] = codes[c]
            mask[c, :len(paths[c])] = 1.0
        ptj, ctj, mj = jnp.asarray(pt), jnp.asarray(ct), jnp.asarray(mask)
        yv = y.reshape(-1).astype(jnp.int32)
        sel_logits = logits[jnp.arange(x.shape[0])[:, None], ptj[yv]]
        code_sel = ctj[yv]
        m = mj[yv]
        # binary cross entropy per node
        per = (jax.nn.softplus(sel_logits) - code_sel * sel_logits) * m
        return jnp.mean(jnp.sum(per, axis=1))

    out = apply_op("hsigmoid_loss", fn, (input, weight, label), {})
    return out


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    from .nn_ops import dropout

    if not training or p == 0.0:
        return x
    # channel-wise mask (whole D,H,W planes), matching Dropout3D semantics
    def fn(v, key_holder=[None]):
        from ..core import random as _random

        key = _random.next_key()
        N, C = v.shape[0], v.shape[1]
        keep = jax.random.bernoulli(key, 1 - p, (N, C, 1, 1, 1))
        return jnp.where(keep, v / (1 - p), 0.0)

    return apply_op("dropout3d", fn, (x,), {})


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    def fn(v):
        return scale * jnp.where(v > 0, v, alpha * (jnp.exp(v) - 1))

    return apply_op("selu", fn, (x,), {})


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    def fn(a, b):
        d = a - b + epsilon
        return jnp.linalg.norm(d, ord=p, axis=-1, keepdims=keepdim)

    return apply_op("pairwise_distance", fn, (x, y), {})


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """Ref: unfold_op.cc (im2col as an op)."""
    k = _ntuple(kernel_sizes, 2)
    s = _ntuple(strides, 2)
    p = _ntuple(paddings, 2)
    d = _ntuple(dilations, 2)

    def fn(v):
        N, C, H, W = v.shape
        vp = jnp.pad(v, ((0, 0), (0, 0), (p[0], p[0]), (p[1], p[1])))
        oh = (H + 2 * p[0] - d[0] * (k[0] - 1) - 1) // s[0] + 1
        ow = (W + 2 * p[1] - d[1] * (k[1] - 1) - 1) // s[1] + 1
        cols = []
        for i in range(k[0]):
            for j in range(k[1]):
                patch = vp[:, :, i * d[0]: i * d[0] + oh * s[0]: s[0],
                           j * d[1]: j * d[1] + ow * s[1]: s[1]]
                cols.append(patch)
        out = jnp.stack(cols, axis=2)  # [N, C, k*k, oh, ow]
        return out.reshape(N, C * k[0] * k[1], oh * ow)

    return apply_op("unfold", fn, (x,), {})


def spectral_norm_apply(weight, n_power_iterations=1, eps=1e-12, dim=0):
    """Power-iteration spectral normalization (spectral_norm_op.cc)."""
    def fn(w):
        wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
        u = jnp.ones((wm.shape[0],), w.dtype) / np.sqrt(wm.shape[0])
        for _ in range(max(n_power_iterations, 1)):
            v = wm.T @ u
            v = v / (jnp.linalg.norm(v) + eps)
            u = wm @ v
            u = u / (jnp.linalg.norm(u) + eps)
        sigma = u @ wm @ v
        return w / sigma

    return apply_op("spectral_norm", fn, (weight,), {})


def celu(x, alpha=1.0, name=None):
    """Ref: activation_op.cc celu."""
    def fn(v):
        return jnp.maximum(v, 0.0) + jnp.minimum(
            0.0, alpha * (jnp.exp(v / alpha) - 1.0))

    return apply_op("celu", fn, (x,), {})


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """col2im (fold): [N, C*kh*kw, L] -> [N, C, H, W] by summing
    overlapping patches — the exact adjoint of unfold (math/im2col.cc)."""
    oh_img, ow_img = _ntuple(output_sizes, 2)
    kh, kw = _ntuple(kernel_sizes, 2)
    sh, sw = _ntuple(strides, 2)
    ph, pw = _ntuple(paddings, 2)
    dh, dw = _ntuple(dilations, 2)

    def fn(v):
        N, CKK, L = v.shape
        C = CKK // (kh * kw)
        OH = (oh_img + 2 * ph - dh * (kh - 1) - 1) // sh + 1
        OW = (ow_img + 2 * pw - dw * (kw - 1) - 1) // sw + 1
        cols = v.reshape(N, C, kh, kw, OH, OW)
        out = jnp.zeros((N, C, oh_img + 2 * ph, ow_img + 2 * pw), v.dtype)
        # static small loops over kernel positions: each scatters a strided
        # block-add; XLA fuses them (col2im adjoint)
        for i in range(kh):
            for j in range(kw):
                hi = i * dh
                wj = j * dw
                out = out.at[:, :, hi: hi + sh * OH: sh,
                             wj: wj + sw * OW: sw].add(cols[:, :, i, j])
        if ph or pw:
            out = out[:, :, ph: ph + oh_img, pw: pw + ow_img]
        return out

    return apply_op("fold", fn, (x,), {})
