"""Loss / similarity / ranking-metric long tail.

Reference: operators/huber_loss_op.h (piecewise quadratic), rank_loss_op.h
(pairwise logistic), bpr_loss_op.h (Bayesian personalized ranking),
modified_huber_loss_op.h, teacher_student_sigmoid_loss_op.h (CTR
distillation, 4-way label encoding), center_loss_op.h (feature-center
pull + running center update), squared_l2_distance_op.h,
squared_l2_norm_op.h, l1_norm_op.h, clip_by_norm_op.h, cos_sim_op.h,
mean_iou_op.h, edit_distance_op.h, ctc_align_op.h,
positive_negative_pair_op.h, chunk_eval_op.h.

TPU-native design: every differentiable loss is a pure jnp expression
(grads via jax.vjp); the sequence metrics (edit_distance, chunk_eval,
positive_negative_pair) are host-side numpy — they are evaluation ops the
reference also runs on CPU, and their ragged/dynamic outputs don't belong
under jit.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import apply_op, eager_op, register_op
from ..core.tensor import Tensor, to_tensor

__all__ = [
    "huber_loss", "rank_loss", "bpr_loss", "modified_huber_loss",
    "teacher_student_sigmoid_loss", "center_loss", "squared_l2_distance",
    "squared_l2_norm", "l1_norm", "clip_by_norm", "cos_sim", "mean_iou",
    "edit_distance", "ctc_align", "positive_negative_pair", "chunk_eval",
]


def _softplus_stable(x):
    # log(1 + exp(x)) = max(x, 0) + log(1 + exp(-|x|))
    return jnp.maximum(x, 0.0) + jnp.log1p(jnp.exp(-jnp.abs(x)))


def _huber(x, y, delta=1.0):
    r = y - x
    a = jnp.abs(r)
    return jnp.where(a <= delta, 0.5 * r * r, delta * (a - 0.5 * delta))


register_op("huber_loss", _huber)


def huber_loss(input, label, delta=1.0, name=None):
    """Piecewise-quadratic robust regression loss (huber_loss_op.h:29)."""
    return apply_op("huber_loss", _huber, (input, label), {"delta": delta})


def _rank_loss(label, left, right):
    o = left - right
    return _softplus_stable(o) - label * o


register_op("rank_loss", _rank_loss)


def rank_loss(label, left, right, name=None):
    """RankNet pairwise loss: log(1+e^(l-r)) - t*(l-r) (rank_loss_op.h)."""
    return apply_op("rank_loss", _rank_loss, (label, left, right), {})


def _bpr_loss(x, label):
    n, c = x.shape
    pos = jnp.take_along_axis(x, label.reshape(n, 1).astype(jnp.int32), axis=1)
    # -sum_{j != y} log(sigmoid(pos - neg)) / (C-1); note log(sigmoid(d))
    # = -log(1 + exp(-d)) with d = pos - x_j
    d = pos - x
    per = _softplus_stable(-d)  # = log(1 + exp(x_j - pos))
    mask = 1.0 - jax.nn.one_hot(label.reshape(-1), c, dtype=x.dtype)
    return jnp.sum(per * mask, axis=1, keepdims=True) / (c - 1)


register_op("bpr_loss", _bpr_loss)


def bpr_loss(input, label, name=None):
    """Bayesian Personalized Ranking loss (bpr_loss_op.h:Compute)."""
    return apply_op("bpr_loss", _bpr_loss, (input, label), {})


def _modified_huber(x, y):
    # y in {0,1} -> s in {-1,1}; z = s*x
    z = (2.0 * y - 1.0) * x
    return jnp.where(z < -1.0, -4.0 * z,
                     jnp.where(z < 1.0, jnp.square(1.0 - z), 0.0))


register_op("modified_huber_loss", _modified_huber)


def modified_huber_loss(input, label, name=None):
    """Classification huber (modified_huber_loss_op.h:ForwardFunctor)."""
    return apply_op("modified_huber_loss", _modified_huber,
                    (input, label), {})


def _ts_sigmoid_loss(x, label, soft_max_up_bound=15.0,
                     soft_max_lower_bound=-15.0):
    xs = jnp.clip(x, soft_max_lower_bound, soft_max_up_bound)
    sp = _softplus_stable(xs)
    # label encoding (teacher_student_sigmoid_loss_op.h:40-60):
    #   < -1: no teacher, clk=0          -> log(1+e^x)
    #   < 0 : no teacher, clk=1          -> log(1+e^x) - x
    #   < 1 : teacher z'=label, clk=0    -> log(1+e^x) + log(1+e^x) - x*z'
    #  >= 1 : teacher z'=label-1, clk=1  -> log(1+e^x) - x + log(1+e^x) - x*z'
    return jnp.where(
        label < -1.0, sp,
        jnp.where(label < 0.0, sp - xs,
                  jnp.where(label < 1.0, 2.0 * sp - xs * label,
                            2.0 * sp - xs - xs * (label - 1.0))))


register_op("teacher_student_sigmoid_loss", _ts_sigmoid_loss)


def teacher_student_sigmoid_loss(input, label, soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0, name=None):
    """CTR distillation loss with 4-way label encoding (op .h:40-60)."""
    return apply_op("teacher_student_sigmoid_loss", _ts_sigmoid_loss,
                    (input, label),
                    {"soft_max_up_bound": float(soft_max_up_bound),
                     "soft_max_lower_bound": float(soft_max_lower_bound)})


def center_loss(input, label, centers, alpha=0.1, update_centers=True,
                name=None):
    """Center loss (center_loss_op.h): pulls features to per-class centers.

    Returns (loss, centers_out).  The center update is the reference's
    running rule: delta_c = sum(c_y - x) / (1 + count(y)), applied only
    when update_centers.  The update itself is non-differentiable state
    (stop_gradient), matching the reference's separate CentersOut output.
    """
    def fn(x, c):
        lbl = label._data.astype(jnp.int32) if isinstance(label, Tensor) \
            else jnp.asarray(label, jnp.int32)
        lbl = lbl.reshape(-1)
        cx = c[lbl]
        diff = x - cx
        loss = 0.5 * jnp.sum(jnp.square(diff), axis=1, keepdims=True)
        return loss

    loss = apply_op("center_loss", fn, (input, centers), {})
    if update_centers:
        x = input._data
        c = centers._data
        lbl = (label._data if isinstance(label, Tensor)
               else jnp.asarray(label)).astype(jnp.int32).reshape(-1)
        diff = c[lbl] - x
        cnt = jnp.zeros((c.shape[0],), x.dtype).at[lbl].add(1.0)
        acc = jnp.zeros_like(c).at[lbl].add(diff)
        c_new = c - alpha * acc / (1.0 + cnt)[:, None]
        centers_out = to_tensor(np.asarray(c_new))
        centers_out.stop_gradient = True
    else:
        centers_out = centers
    return loss, centers_out


def _squared_l2_distance(x, y):
    sub = x - y
    return jnp.sum(jnp.square(sub), axis=tuple(range(1, sub.ndim)))


register_op("squared_l2_distance", _squared_l2_distance)


def squared_l2_distance(x, y, name=None):
    """Row-wise ||x-y||^2 (squared_l2_distance_op.h)."""
    return apply_op("squared_l2_distance", _squared_l2_distance, (x, y), {})


def _squared_l2_norm(x):
    return jnp.sum(jnp.square(x)).reshape((1,))


register_op("squared_l2_norm", _squared_l2_norm)


def squared_l2_norm(x, name=None):
    return apply_op("squared_l2_norm", _squared_l2_norm, (x,), {})


def _l1_norm(x):
    return jnp.sum(jnp.abs(x)).reshape((1,))


register_op("l1_norm", _l1_norm)


def l1_norm(x, name=None):
    return apply_op("l1_norm", _l1_norm, (x,), {})


def _clip_by_norm(x, max_norm=1.0):
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12),
                      1.0)
    return x * scale


register_op("clip_by_norm", _clip_by_norm)


def clip_by_norm(x, max_norm, name=None):
    """Scale x so its L2 norm never exceeds max_norm (clip_by_norm_op.h)."""
    return apply_op("clip_by_norm", _clip_by_norm, (x,),
                    {"max_norm": float(max_norm)})


def _cos_sim(x, y):
    # y may be a single row broadcast against all rows of x (cos_sim_op.h)
    if y.shape[0] == 1 and x.shape[0] != 1:
        y = jnp.broadcast_to(y, x.shape)
    xn = jnp.sqrt(jnp.sum(jnp.square(x), axis=1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(jnp.square(y), axis=1, keepdims=True))
    prod = jnp.sum(x * y, axis=1, keepdims=True)
    return prod / jnp.maximum(xn * yn, 1e-12)


register_op("cos_sim", _cos_sim)


def cos_sim(x, y, name=None):
    """Row-wise cosine similarity with row-broadcast y (cos_sim_op.h)."""
    return apply_op("cos_sim", _cos_sim, (x, y), {})


def _mean_iou(pred, label, num_classes=2):
    p = pred.reshape(-1).astype(jnp.int32)
    l = label.reshape(-1).astype(jnp.int32)
    inter = jnp.zeros((num_classes,), jnp.float32).at[
        jnp.where(p == l, p, num_classes)].add(1.0, mode="drop")
    pred_cnt = jnp.zeros((num_classes,), jnp.float32).at[p].add(1.0)
    lbl_cnt = jnp.zeros((num_classes,), jnp.float32).at[l].add(1.0)
    union = pred_cnt + lbl_cnt - inter
    valid = union > 0
    iou = jnp.where(valid, inter / jnp.maximum(union, 1.0), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
    wrong = (pred_cnt - inter).astype(jnp.int32)
    correct = inter.astype(jnp.int32)
    return miou, wrong, correct


register_op("mean_iou", _mean_iou, n_outputs=3)


def mean_iou(pred, label, num_classes, name=None):
    """Segmentation mean-IoU; returns (miou, out_wrong, out_correct)
    (mean_iou_op.h)."""
    return apply_op("mean_iou", _mean_iou, (pred, label),
                    {"num_classes": int(num_classes)}, n_outputs=3)


def _levenshtein(a, b):
    la, lb = len(a), len(b)
    if la == 0:
        return lb
    if lb == 0:
        return la
    prev = list(range(lb + 1))
    for i in range(1, la + 1):
        cur = [i] + [0] * lb
        for j in range(1, lb + 1):
            cost = 0 if a[i - 1] == b[j - 1] else 1
            cur[j] = min(prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + cost)
        prev = cur
    return prev[lb]


def edit_distance(input, label, input_length=None, label_length=None,
                  normalized=True, name=None):
    """Levenshtein distance per sequence pair (edit_distance_op.h).

    Host-side numpy metric op: inputs are (B, T) id matrices with optional
    per-row lengths; returns (distances (B,1) float32, sequence_num (1,)).
    Ragged dynamic programming has no XLA-friendly fixed shape, and the
    reference also treats this as a CPU metric op.
    """
    inp = np.asarray(input._data if isinstance(input, Tensor) else input)
    lbl = np.asarray(label._data if isinstance(label, Tensor) else label)
    in_len = (np.asarray(input_length._data
                         if isinstance(input_length, Tensor)
                         else input_length).reshape(-1)
              if input_length is not None else
              np.full((inp.shape[0],), inp.shape[1], np.int64))
    lb_len = (np.asarray(label_length._data
                         if isinstance(label_length, Tensor)
                         else label_length).reshape(-1)
              if label_length is not None else
              np.full((lbl.shape[0],), lbl.shape[1], np.int64))
    out = np.zeros((inp.shape[0], 1), np.float32)
    for i in range(inp.shape[0]):
        a = list(inp[i, :int(in_len[i])])
        b = list(lbl[i, :int(lb_len[i])])
        d = float(_levenshtein(a, b))
        if normalized:
            d = d / max(len(b), 1)
        out[i, 0] = d
    dist = to_tensor(out)
    dist.stop_gradient = True
    seq_num = to_tensor(np.array([inp.shape[0]], np.int64))
    seq_num.stop_gradient = True
    return dist, seq_num


def ctc_align(input, blank=0, merge_repeated=True, padding_value=0,
              input_length=None, name=None):
    """CTC best-path decode: merge repeats then drop blanks
    (ctc_align_op.h).  Padded (B, T) in -> padded (B, T) out, right-filled
    with padding_value; also returns output lengths (B, 1)."""
    inp = np.asarray(input._data if isinstance(input, Tensor) else input)
    B, T = inp.shape
    in_len = (np.asarray(input_length._data
                         if isinstance(input_length, Tensor)
                         else input_length).reshape(-1)
              if input_length is not None else np.full((B,), T, np.int64))
    out = np.full((B, T), padding_value, inp.dtype)
    out_len = np.zeros((B, 1), np.int64)
    for i in range(B):
        prev = None
        k = 0
        for t in range(int(in_len[i])):
            tok = inp[i, t]
            if merge_repeated and prev is not None and tok == prev:
                continue
            prev = tok
            if tok != blank:
                out[i, k] = tok
                k += 1
        out_len[i, 0] = k
    res = to_tensor(out)
    res.stop_gradient = True
    lens = to_tensor(out_len)
    lens.stop_gradient = True
    return res, lens


def positive_negative_pair(score, label, query_id, name=None):
    """Ranking metric: within each query, count score-ordered pairs that
    agree/disagree with label order (positive_negative_pair_op.h).
    Returns (positive, negative, neutral) float32 scalars."""
    s = np.asarray(score._data if isinstance(score, Tensor)
                   else score).reshape(-1)
    l = np.asarray(label._data if isinstance(label, Tensor)
                   else label).reshape(-1)
    q = np.asarray(query_id._data if isinstance(query_id, Tensor)
                   else query_id).reshape(-1)
    pos = neg = neu = 0.0
    for qid in np.unique(q):
        idx = np.where(q == qid)[0]
        for a in range(len(idx)):
            for b in range(a + 1, len(idx)):
                i, j = idx[a], idx[b]
                if l[i] == l[j]:
                    continue
                ds = s[i] - s[j]
                dl = l[i] - l[j]
                if ds == 0:
                    neu += 1
                elif (ds > 0) == (dl > 0):
                    pos += 1
                else:
                    neg += 1
    mk = lambda v: to_tensor(np.array([v], np.float32))
    p, n, u = mk(pos), mk(neg), mk(neu)
    for t in (p, n, u):
        t.stop_gradient = True
    return p, n, u


def _extract_chunks(tags, scheme, num_chunk_types, excluded=()):
    """Decode (type, begin, end) chunks from an integer tag sequence.

    Tag layout follows chunk_eval_op.h: for scheme 'IOB' tag = type*2 +
    {0:B,1:I}; 'IOE' type*2 + {0:I,1:E}; 'IOBES' type*4 + {0:B,1:I,2:E,
    3:S}; 'plain' tag = type.  num_chunk_types*tag_num is the 'outside'
    tag.
    """
    chunks = []
    n_tag = {"plain": 1, "IOB": 2, "IOE": 2, "IOBES": 4}[scheme]
    outside = num_chunk_types * n_tag
    start = None
    cur_type = None

    def flush(end):
        nonlocal start, cur_type
        if start is not None and cur_type not in excluded:
            chunks.append((cur_type, start, end))
        start, cur_type = None, None

    for i, t in enumerate(tags):
        t = int(t)
        if t >= outside or t < 0:
            flush(i)
            continue
        ctype, pos = divmod(t, n_tag)
        if scheme == "plain":
            if cur_type != ctype:
                flush(i)
                start, cur_type = i, ctype
        elif scheme == "IOB":
            if pos == 0 or cur_type != ctype:
                flush(i)
                start, cur_type = i, ctype
        elif scheme == "IOE":
            if cur_type != ctype:
                flush(i)
                start, cur_type = i, ctype
            if pos == 1:
                flush(i + 1)
        else:  # IOBES
            if pos == 0:  # B
                flush(i)
                start, cur_type = i, ctype
            elif pos == 1:  # I
                if cur_type != ctype:
                    flush(i)
                    start, cur_type = i, ctype
            elif pos == 2:  # E
                if cur_type != ctype:
                    flush(i)
                    start, cur_type = i, ctype
                flush(i + 1)
            else:  # S
                flush(i)
                if ctype not in excluded:
                    chunks.append((ctype, i, i + 1))
    flush(len(tags))
    return set(chunks)


def chunk_eval(input, label, chunk_scheme="IOB", num_chunk_types=1,
               excluded_chunk_types=None, seq_length=None, name=None):
    """Chunking precision/recall/F1 (NER-style), chunk_eval_op.h.

    Returns (precision, recall, f1, num_infer_chunks, num_label_chunks,
    num_correct_chunks) — host numpy metric op over padded (B, T) tags.
    """
    excluded = tuple(excluded_chunk_types or ())
    inf = np.asarray(input._data if isinstance(input, Tensor) else input)
    lab = np.asarray(label._data if isinstance(label, Tensor) else label)
    if inf.ndim == 1:
        inf, lab = inf[None, :], lab[None, :]
    B, T = inf.shape
    lens = (np.asarray(seq_length._data if isinstance(seq_length, Tensor)
                       else seq_length).reshape(-1)
            if seq_length is not None else np.full((B,), T, np.int64))
    n_inf = n_lab = n_cor = 0
    for i in range(B):
        ci = _extract_chunks(inf[i, :int(lens[i])], chunk_scheme,
                             num_chunk_types, excluded)
        cl = _extract_chunks(lab[i, :int(lens[i])], chunk_scheme,
                             num_chunk_types, excluded)
        n_inf += len(ci)
        n_lab += len(cl)
        n_cor += len(ci & cl)
    prec = n_cor / n_inf if n_inf else 0.0
    rec = n_cor / n_lab if n_lab else 0.0
    f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
    mkf = lambda v: to_tensor(np.array([v], np.float32))
    mki = lambda v: to_tensor(np.array([v], np.int64))
    outs = (mkf(prec), mkf(rec), mkf(f1), mki(n_inf), mki(n_lab), mki(n_cor))
    for t in outs:
        t.stop_gradient = True
    return outs
