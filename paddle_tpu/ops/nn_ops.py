"""Neural-net ops: conv / pool / norm / attention-adjacent primitives.

Reference parity: conv2d (operators/conv_op.cc), pool2d (pool_op.cc), batch_norm
(batch_norm_op.cc), layer_norm (layer_norm_op.cc), softmax_with_cross_entropy
(softmax_with_cross_entropy_op.cc), dropout (dropout_op.cc), lookup_table_v2
(lookup_table_v2_op.cc), activation_op.cc family.  All are XLA-native: convs and
matmuls hit the MXU via lax.conv_general_dilated / dot_general; dropout uses
threefry keys (core/random.py); batch-norm running stats update functionally.
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..core.registry import eager_op, apply_op
from ..core.tensor import Tensor, to_tensor, _wrap_data
from ..core import random as _random


_bn_trace_warned = False


def _pair(x, n=2):
    if isinstance(x, (list, tuple)):
        return tuple(int(v) for v in x) * (1 if len(x) == n else n)
    return (int(x),) * n


def _conv_padding(padding, k, stride, dilation, nd):
    """Normalize paddle padding spec to lax padding list."""
    if isinstance(padding, str):
        return padding.upper()  # SAME / VALID
    if isinstance(padding, int):
        return [(padding, padding)] * nd
    padding = list(padding)
    if len(padding) == nd:
        return [(int(p), int(p)) for p in padding]
    if len(padding) == 2 * nd:
        return [(int(padding[2 * i]), int(padding[2 * i + 1])) for i in range(nd)]
    raise ValueError(f"bad padding {padding}")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    """Maps to one lax.conv_general_dilated → MXU.  Ref: conv_op.cc, conv_cudnn_op.cu."""
    stride = _pair(stride)
    dilation = _pair(dilation)
    pad = _conv_padding(padding, None, stride, dilation, 2)
    dn = ("NCHW", "OIHW", "NCHW") if data_format == "NCHW" else ("NHWC", "HWIO", "NHWC")

    if data_format != "NCHW":
        # weights stored OIHW regardless; convert for NHWC
        def fn(xv, wv):
            wv = jnp.transpose(wv, (2, 3, 1, 0))
            return jax.lax.conv_general_dilated(
                xv, wv, stride, pad, rhs_dilation=dilation,
                dimension_numbers=dn, feature_group_count=groups,
                preferred_element_type=xv.dtype,
            )
    else:
        def fn(xv, wv):
            return jax.lax.conv_general_dilated(
                xv, wv, stride, pad, rhs_dilation=dilation,
                dimension_numbers=dn, feature_group_count=groups,
                preferred_element_type=xv.dtype,
            )

    out = apply_op("conv2d", fn, (x, weight), {})
    if bias is not None:
        shape = (1, -1, 1, 1) if data_format == "NCHW" else (1, 1, 1, -1)
        out = apply_op(
            "conv2d_bias", lambda o, b: o + jnp.reshape(b, shape), (out, bias), {}
        )
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    from .manipulation import unsqueeze, squeeze

    x4 = unsqueeze(x, [3] if data_format == "NCL" else [2])
    w4 = unsqueeze(weight, [3])
    s = _pair(stride, 1) + (1,)
    d = _pair(dilation, 1) + (1,)
    if isinstance(padding, int):
        p = [(padding, padding), (0, 0)]
    elif isinstance(padding, str):
        p = padding.upper()
    else:
        p = [(int(padding[0]), int(padding[-1])), (0, 0)]
    dn = ("NCHW", "OIHW", "NCHW")

    def fn(xv, wv):
        return jax.lax.conv_general_dilated(
            xv, wv, s, p, rhs_dilation=d, dimension_numbers=dn,
            feature_group_count=groups,
        )

    out = apply_op("conv1d", fn, (x4, w4), {})
    if bias is not None:
        out = apply_op(
            "conv1d_bias", lambda o, b: o + jnp.reshape(b, (1, -1, 1, 1)), (out, bias), {}
        )
    return squeeze(out, [3])


def _conv_transpose_nd(xv, wv, stride, pad_lo_hi, dilation, groups,
                       output_padding, nd):
    """Grouped n-d transposed conv as the gradient-of-conv formulation:
    lhs-dilate by stride, convolve with the spatially-flipped, I/O-swapped
    kernel (conv2d_transpose_op.cc semantics; verified against the torch
    conv_transpose oracle incl. groups and output_padding).

    wv: paddle layout (Cin, Cout/groups, *k).  pad_lo_hi: per-dim forward
    pads (lo, hi); output_padding extends the hi side.
    """
    k = wv.shape[2:]
    cin = wv.shape[0]
    cog = wv.shape[1]
    # (Cin, Cout/g, *k) -> (g, Cin/g, Cout/g, *k) -> (g, Cout/g, Cin/g, *k)
    # -> (Cout, Cin/g, *k): OIHW for a grouped forward conv
    wg = wv.reshape((groups, cin // groups, cog) + k)
    wg = jnp.swapaxes(wg, 1, 2).reshape((groups * cog, cin // groups) + k)
    wg = jnp.flip(wg, axis=tuple(range(2, 2 + nd)))
    pads = [
        (dilation[i] * (k[i] - 1) - pad_lo_hi[i][0],
         dilation[i] * (k[i] - 1) - pad_lo_hi[i][1] + output_padding[i])
        for i in range(nd)
    ]
    spec = "NC" + "DHW"[3 - nd:]
    return jax.lax.conv_general_dilated(
        xv, wg, (1,) * nd, pads, lhs_dilation=stride, rhs_dilation=dilation,
        dimension_numbers=(spec, "OI" + "DHW"[3 - nd:], spec),
        feature_group_count=groups)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, output_size=None, data_format="NCHW",
                     name=None):
    """Ref: conv2d_transpose_op.cc.  Gradient-of-conv lowering with full
    groups / output_padding / output_size support."""
    stride = _pair(stride)
    dilation = _pair(dilation)
    opad = _pair(output_padding)
    if isinstance(padding, str):
        p = padding.upper()
        k = weight.shape[2:4]
        if p == "VALID":
            pad = [(0, 0), (0, 0)]
        elif p == "SAME":
            # SAME transpose-conv: out = in * stride; forward-equivalent
            # total pad = dilation*(k-1) + 1 - stride.  A negative total
            # (stride larger than the kernel span) becomes extra
            # output_padding instead of being clipped away.
            pad = []
            opad = list(opad)
            for i in range(2):
                total = dilation[i] * (k[i] - 1) + 1 - stride[i]
                if total < 0:
                    opad[i] = opad[i] - total
                    total = 0
                pad.append((total // 2, total - total // 2))
            opad = tuple(opad)
        else:
            raise ValueError("conv2d_transpose padding string must be "
                             "'SAME' or 'VALID'")
    else:
        pad = _conv_padding(padding, None, stride, dilation, 2)
    if output_size is not None:
        # derive output_padding so the result hits the requested size
        k = weight.shape[2:4]
        opad = tuple(
            int(output_size[i])
            - ((x.shape[2 + i] - 1) * stride[i] - pad[i][0] - pad[i][1]
               + dilation[i] * (k[i] - 1) + 1)
            for i in range(2))

    def fn(xv, wv):
        return _conv_transpose_nd(xv, wv, stride, pad, dilation, groups,
                                  opad, 2)

    out = apply_op("conv2d_transpose", fn, (x, weight), {})
    if bias is not None:
        out = apply_op(
            "conv2d_transpose_bias", lambda o, b: o + jnp.reshape(b, (1, -1, 1, 1)),
            (out, bias), {},
        )
    return out


# ---- pooling (ref: pool_op.cc, operators/math/pooling.cu) ----

def _pool(x, kind, kernel_size, stride, padding, ceil_mode, data_format,
          exclusive=True, adaptive=False):
    k = _pair(kernel_size)
    s = _pair(stride) if stride is not None else k
    nchw = data_format == "NCHW"
    spatial = (2, 3) if nchw else (1, 2)
    if adaptive:
        out_hw = k
        in_hw = (x.shape[spatial[0]], x.shape[spatial[1]])
        if all(in_hw[i] % out_hw[i] == 0 for i in range(2)):
            k = tuple(in_hw[i] // out_hw[i] for i in range(2))
            s = k
            padding = 0
        else:
            return _adaptive_pool_general(x, kind, out_hw, nchw)
    pad = _conv_padding(padding, k, s, (1, 1), 2)
    if isinstance(pad, str):
        pad_seq = pad
    else:
        pad_seq = [(0, 0)] * x.ndim
        for i, ax in enumerate(spatial):
            pad_seq[ax] = pad[i]
    window = [1] * x.ndim
    strides = [1] * x.ndim
    for i, ax in enumerate(spatial):
        window[ax] = k[i]
        strides[ax] = s[i]

    if kind == "max":
        def fn(v):
            return jax.lax.reduce_window(
                v, -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating) else jnp.iinfo(v.dtype).min,
                jax.lax.max, window, strides, pad_seq,
            )
        return apply_op("pool2d_max", fn, (x,), {})

    def fn(v):
        ssum = jax.lax.reduce_window(
            v, 0.0, jax.lax.add, window, strides, pad_seq
        )
        if exclusive and pad_seq != "VALID" and any(
            p != (0, 0) for p in (pad_seq if isinstance(pad_seq, list) else [])
        ):
            ones = jnp.ones_like(v)
            cnt = jax.lax.reduce_window(
                ones, 0.0, jax.lax.add, window, strides, pad_seq
            )
            return ssum / cnt
        return ssum / float(np.prod(k))

    return apply_op("pool2d_avg", fn, (x,), {})


def _adaptive_pool_general(x, kind, out_hw, nchw):
    """Non-divisible adaptive pooling: ONE window-math implementation
    lives in nn_extra._adaptive_nd (floor/ceil bounds, never-empty
    windows — this 2D copy once diverged and NaN'd on output > input);
    here we only wrap the NHWC transpose around it."""
    from .nn_extra import _adaptive_nd
    from .manipulation import transpose as _tr

    if not nchw:
        x = _tr(x, [0, 3, 1, 2])
    out = _adaptive_nd(x, kind, out_hw)
    if not nchw:
        out = _tr(out, [0, 2, 3, 1])
    return out


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    out = _pool(x, "max", kernel_size, stride, padding, ceil_mode, data_format)
    if return_mask:
        return out, None
    return out


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, "avg", kernel_size, stride, padding, ceil_mode, data_format,
                 exclusive=exclusive)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _pool(x, "avg", output_size, None, 0, False, data_format, adaptive=True)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _pool(x, "max", output_size, None, 0, False, "NCHW", adaptive=True)
    return (out, None) if return_mask else out


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False, name=None):
    from .manipulation import unsqueeze, squeeze

    x4 = unsqueeze(x, [3])
    ks = _pair(kernel_size, 1) + (1,)
    st = (_pair(stride, 1) + (1,)) if stride is not None else ks
    pd = [(padding, padding), (0, 0)] if isinstance(padding, int) else padding
    out = _pool(x4, "max", ks, st, pd, ceil_mode, "NCHW")
    return squeeze(out, [3])


def avg_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False, name=None):
    from .manipulation import unsqueeze, squeeze

    x4 = unsqueeze(x, [3])
    ks = _pair(kernel_size, 1) + (1,)
    st = (_pair(stride, 1) + (1,)) if stride is not None else ks
    pd = [(padding, padding), (0, 0)] if isinstance(padding, int) else padding
    out = _pool(x4, "avg", ks, st, pd, ceil_mode, "NCHW")
    return squeeze(out, [3])


# ---- activations (ref: operators/activation_op.cc) ----

def _act(name, fn):
    raw = eager_op(name)(fn)

    def op(x, name=None):
        return raw(x if isinstance(x, Tensor) else to_tensor(x))

    op.__name__ = name
    op.raw_fn = fn
    return op


relu = _act("relu", jax.nn.relu)
relu6 = _act("relu6", lambda x: jnp.clip(x, 0, 6))
sigmoid = _act("sigmoid", jax.nn.sigmoid)
log_sigmoid = _act("logsigmoid", jax.nn.log_sigmoid)
silu = _act("silu", jax.nn.silu)
swish = silu
mish = _act("mish", lambda x: x * jnp.tanh(jax.nn.softplus(x)))
softplus_raw = _act("softplus", jax.nn.softplus)
softsign = _act("softsign", jax.nn.soft_sign)
tanhshrink = _act("tanh_shrink", lambda x: x - jnp.tanh(x))
hardsigmoid = _act("hard_sigmoid", lambda x: jnp.clip(x / 6.0 + 0.5, 0.0, 1.0))
hardswish = _act("hard_swish", lambda x: x * jnp.clip(x + 3, 0, 6) / 6)
hardtanh = _act("hard_tanh", lambda x: jnp.clip(x, -1.0, 1.0))
selu_raw = _act("selu", jax.nn.selu)


def softplus(x, beta=1, threshold=20, name=None):
    if beta == 1:
        return softplus_raw(x)
    return apply_op(
        "softplus_beta",
        lambda v: jnp.where(v * beta > threshold, v, jax.nn.softplus(v * beta) / beta),
        (x,), {},
    )


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return selu_raw(x)


@eager_op("gelu")
def _gelu(x, approximate=False):
    return jax.nn.gelu(x, approximate=approximate)


def gelu(x, approximate=False, name=None):
    return _gelu(x, approximate=approximate)


@eager_op("leaky_relu")
def _leaky_relu(x, negative_slope=0.01):
    return jax.nn.leaky_relu(x, negative_slope)


def leaky_relu(x, negative_slope=0.01, name=None):
    return _leaky_relu(x, negative_slope=negative_slope)


@eager_op("elu")
def _elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha)


def elu(x, alpha=1.0, name=None):
    return _elu(x, alpha=alpha)


def prelu(x, weight, data_format="NCHW", name=None):
    def fn(v, w):
        if w.size == 1:
            return jnp.where(v >= 0, v, w.reshape(()) * v)
        shape = [1] * v.ndim
        ch_ax = 1 if data_format == "NCHW" else v.ndim - 1
        shape[ch_ax] = w.size
        return jnp.where(v >= 0, v, w.reshape(shape) * v)

    return apply_op("prelu", fn, (x, weight), {})


@eager_op("hardshrink")
def _hardshrink(x, threshold=0.5):
    return jnp.where(jnp.abs(x) > threshold, x, 0.0)


def hardshrink(x, threshold=0.5, name=None):
    return _hardshrink(x, threshold=threshold)


@eager_op("softshrink")
def _softshrink(x, threshold=0.5):
    return jnp.where(x > threshold, x - threshold, jnp.where(x < -threshold, x + threshold, 0.0))


def softshrink(x, threshold=0.5, name=None):
    return _softshrink(x, threshold=threshold)


@eager_op("thresholded_relu")
def _thresholded_relu(x, threshold=1.0):
    return jnp.where(x > threshold, x, 0.0)


def thresholded_relu(x, threshold=1.0, name=None):
    return _thresholded_relu(x, threshold=threshold)


@eager_op("softmax")
def _softmax(x, axis=-1):
    return jax.nn.softmax(x, axis=axis)


def softmax(x, axis=-1, dtype=None, name=None):
    if dtype is not None:
        from .manipulation import cast

        x = cast(x, dtype)
    return _softmax(x, axis=int(axis))


@eager_op("log_softmax")
def _log_softmax(x, axis=-1):
    return jax.nn.log_softmax(x, axis=axis)


def log_softmax(x, axis=-1, dtype=None, name=None):
    return _log_softmax(x, axis=int(axis))


def glu(x, axis=-1, name=None):
    from .manipulation import split

    a, b = split(x, 2, axis=axis)
    from .math import multiply

    return multiply(a, sigmoid(b))


def maxout(x, groups, axis=1, name=None):
    def fn(v):
        shape = list(v.shape)
        c = shape[axis]
        shape[axis: axis + 1] = [c // groups, groups]
        return jnp.max(jnp.reshape(v, shape), axis=axis + 1)

    return apply_op("maxout", fn, (x,), {})


# ---- normalization ----

def layer_norm(x, normalized_shape=None, weight=None, bias=None, epsilon=1e-5,
               name=None):
    """Ref: layer_norm_op.cc.  Normalizes over the trailing normalized_shape dims."""
    if isinstance(normalized_shape, int):
        normalized_shape = [normalized_shape]
    n_axes = len(normalized_shape) if normalized_shape else 1
    axes = tuple(range(-n_axes, 0))

    def fn(v, *wb):
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(v - mean), axis=axes, keepdims=True)
        out = (v - mean) * jax.lax.rsqrt(var + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return apply_op("layer_norm", fn, args, {})


def batch_norm(x, running_mean, running_var, weight=None, bias=None, training=False,
               momentum=0.9, epsilon=1e-5, data_format="NCHW", use_global_stats=None,
               name=None):
    """Ref: batch_norm_op.cc.  Functional running-stat update (set_value on the
    running tensors) instead of in-place kernel writes."""
    ch_ax = 1 if data_format in ("NCHW", "NCL", "NCDHW") else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_ax)
    shape = [1] * x.ndim
    shape[ch_ax] = x.shape[ch_ax]

    use_batch_stats = training and not use_global_stats
    if use_batch_stats:
        def fn(v, *wb):
            mean = jnp.mean(v, axis=reduce_axes)
            var = jnp.mean(jnp.square(v), axis=reduce_axes) - jnp.square(mean)
            out = (v - mean.reshape(shape)) * jax.lax.rsqrt(
                var.reshape(shape) + epsilon
            )
            i = 0
            if weight is not None:
                out = out * wb[i].reshape(shape)
                i += 1
            if bias is not None:
                out = out + wb[i].reshape(shape)
            return out, mean, var

        args = (x,) + tuple(t for t in (weight, bias) if t is not None)
        out, bmean, bvar = apply_op("batch_norm", fn, args, {}, n_outputs=3)
        m, v = bmean.detach()._data, bvar.detach()._data
        if not isinstance(m, jax.core.Tracer) and not isinstance(
            running_mean._data, jax.core.Tracer
        ):
            # eager: functional running-stat update
            running_mean._data = (
                momentum * running_mean._data + (1 - momentum) * m
            )
            running_var._data = momentum * running_var._data + (1 - momentum) * v
        else:
            # Under jit tracing a traced value must not escape to host state,
            # so the running stats are NOT updated here.  Compiled BN training
            # must thread stats explicitly (functional_call(buffers=...)) —
            # warn once so eval-time wrong-stats bugs aren't silent.
            global _bn_trace_warned
            if not _bn_trace_warned:
                _bn_trace_warned = True
                import warnings

                warnings.warn(
                    "batch_norm running statistics are not updated inside "
                    "jit-compiled training (trace-time). Thread stats via "
                    "functional_call(buffers=...) or train BN models eagerly.",
                    stacklevel=2,
                )
        return out

    def fn(v, rm, rv, *wb):
        out = (v - rm.reshape(shape)) * jax.lax.rsqrt(rv.reshape(shape) + epsilon)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = (x, running_mean, running_var) + tuple(
        t for t in (weight, bias) if t is not None
    )
    return apply_op("batch_norm_infer", fn, args, {})


def instance_norm(x, weight=None, bias=None, epsilon=1e-5, name=None):
    axes = tuple(range(2, x.ndim))

    def fn(v, *wb):
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(v - mean), axis=axes, keepdims=True)
        out = (v - mean) * jax.lax.rsqrt(var + epsilon)
        shape = [1, v.shape[1]] + [1] * (v.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return apply_op("instance_norm", fn, args, {})


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    def fn(v, *wb):
        N, C = v.shape[0], v.shape[1]
        g = num_groups
        rest = v.shape[2:]
        vg = v.reshape((N, g, C // g) + rest)
        axes = tuple(range(2, vg.ndim))
        mean = jnp.mean(vg, axis=axes, keepdims=True)
        var = jnp.mean(jnp.square(vg - mean), axis=axes, keepdims=True)
        out = ((vg - mean) * jax.lax.rsqrt(var + epsilon)).reshape(v.shape)
        shape = [1, C] + [1] * (v.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return apply_op("group_norm", fn, args, {})


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, name=None):
    def fn(v):
        sq = jnp.square(v)
        half = size // 2
        pad = [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (v.ndim - 2)
        sqp = jnp.pad(sq, pad)
        acc = sum(
            sqp[:, i : i + v.shape[1]] for i in range(size)
        )
        return v / jnp.power(k + alpha * acc, beta)

    return apply_op("lrn", fn, (x,), {})


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def fn(v):
        n = jnp.sum(jnp.abs(v) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(n, epsilon)

    return apply_op("normalize", fn, (x,), {})


# ---- dropout (threefry-keyed; ref: dropout_op.cc) ----

def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        return x if mode == "upscale_in_train" else apply_op(
            "dropout_scale", lambda v: v * (1 - p), (x,), {}
        )
    if p == 1.0:
        return apply_op("dropout_all", lambda v: jnp.zeros_like(v), (x,), {})
    key = _random.next_key()
    shape = tuple(x.shape)
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        shape = tuple(s if i in axes else 1 for i, s in enumerate(x.shape))

    def fn(v):
        keep = jax.random.bernoulli(key, 1.0 - p, shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), 0.0).astype(v.dtype)
        return jnp.where(keep, v, 0.0).astype(v.dtype)

    return apply_op("dropout", fn, (x,), {})


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0:
        return x
    alpha_p = -1.7580993408473766
    q = 1 - p
    a = (q + alpha_p**2 * q * p) ** -0.5
    b = -a * alpha_p * p
    key = _random.next_key()

    def fn(v):
        keep = jax.random.bernoulli(key, q, v.shape)
        return (a * jnp.where(keep, v, alpha_p) + b).astype(v.dtype)

    return apply_op("alpha_dropout", fn, (x,), {})


# ---- embedding (ref: lookup_table_v2_op.cc) ----

def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    idx = x._data if isinstance(x, Tensor) else jnp.asarray(x)

    def fn(w):
        out = jnp.take(w, idx, axis=0)
        if padding_idx is not None and padding_idx >= 0:
            mask = (idx != padding_idx)[..., None]
            out = out * mask.astype(out.dtype)
        return out

    if sparse:
        return _sparse_embedding(idx, weight, padding_idx, fn)
    return apply_op("lookup_table_v2", fn, (weight,), {})


def _sparse_embedding(idx, weight, padding_idx, fn):
    """sparse=True eager path (selected_rows.h parity): the weight
    cotangent is an IndexedSlices of the looked-up rows, never a dense
    vocab-size buffer.  Under jit tracing (compiled steps) the weight grad
    must stay a dense array, so tracing falls back to the dense vjp."""
    from ..core import autograd
    from ..core.tensor import _wrap_data
    from ..core.indexed_slices import IndexedSlices

    needs_grad = (
        autograd.is_grad_enabled()
        and isinstance(weight, Tensor)
        and not weight.stop_gradient
        and not isinstance(weight._data, jax.core.Tracer)
        and not isinstance(idx, jax.core.Tracer)
    )
    if not needs_grad:
        return apply_op("lookup_table_v2", fn, (weight,), {})

    with autograd.no_grad():
        out_val = fn(weight._data)
    dim_shape = weight._data.shape[1:]
    flat_idx = idx.reshape(-1)

    def vjp_fn(cot):
        vals = cot.reshape((flat_idx.shape[0],) + dim_shape)
        if padding_idx is not None and padding_idx >= 0:
            mask = (flat_idx != padding_idx)[..., None]
            vals = vals * mask.astype(vals.dtype)
        return (IndexedSlices(flat_idx, vals, weight._data.shape),)

    node = autograd.TapeNode(
        "lookup_table_v2_sparse", vjp_fn, [weight], 1,
        [out_val.shape], [out_val.dtype], tuple_out=False,
    )
    out = _wrap_data(out_val, stop_gradient=False)
    out._node = node
    out._out_index = 0
    return out


# ---- linear ----

def linear(x, weight, bias=None, name=None):
    """Ref: matmul+elementwise_add fusion (fc op).  weight is [in, out]."""
    if bias is not None:
        return apply_op(
            "linear", lambda v, w, b: jnp.matmul(v, w) + b, (x, weight, bias), {}
        )
    return apply_op("linear_nobias", lambda v, w: jnp.matmul(v, w), (x, weight), {})


# ---- interpolate (subset: nearest + bilinear; ref: interpolate_v2_op) ----

def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, data_format="NCHW", name=None):
    nchw = data_format == "NCHW"
    H, W = (x.shape[2], x.shape[3]) if nchw else (x.shape[1], x.shape[2])
    if size is None:
        if isinstance(scale_factor, (int, float)):
            scale_factor = (scale_factor, scale_factor)
        size = (int(H * scale_factor[0]), int(W * scale_factor[1]))
    if isinstance(size, Tensor):
        size = size.tolist()
    size = tuple(int(s) for s in size)
    method = {"nearest": "nearest", "bilinear": "linear", "bicubic": "cubic"}[mode]

    def _src(n_in, n_out):
        """Float source coordinates per interpolate_v2_op.h: align_corners
        spreads output ends onto input ends (ratio 0 when n_out==1, so
        index 0); otherwise half-pixel centers.  One definition for the
        nearest/bilinear/bicubic branches so the edge cases can't drift."""
        k = jnp.arange(n_out, dtype=jnp.float32)
        if align_corners:
            r = (n_in - 1.0) / (n_out - 1.0) if n_out > 1 else 0.0
            return k * r
        return (k + 0.5) * (n_in / n_out) - 0.5

    if mode == "nearest":
        # interpolate_v2_op.h:98-103: align_corners -> round on the
        # (in-1)/(out-1) grid; else floor on the in/out grid (jax.image's
        # nearest is 'nearest-exact' rounding — NOT the reference's)
        def fn(v):
            if not nchw:
                v = jnp.transpose(v, (0, 3, 1, 2))
            ih, iw = v.shape[2], v.shape[3]
            oh, ow = size

            def idx(n_in, n_out):
                if align_corners:
                    i = jnp.floor(_src(n_in, n_out) + 0.5)
                else:
                    k = jnp.arange(n_out, dtype=jnp.float32)
                    i = jnp.floor(k * (n_in / n_out))
                return jnp.clip(i.astype(jnp.int32), 0, n_in - 1)

            out = v[:, :, idx(ih, oh), :][:, :, :, idx(iw, ow)]
            if not nchw:
                out = jnp.transpose(out, (0, 2, 3, 1))
            return out

        return apply_op("interpolate_nearest", fn, (x,), {})

    if mode == "bicubic":
        # interpolate_v2_op.h:464: cubic convolution with A = -0.75
        # (jax.image's cubic uses A=-0.5 — different pixels); separable
        # 4-tap gather with border-replicated taps
        def fn(v):
            if not nchw:
                v = jnp.transpose(v, (0, 3, 1, 2))

            def axis_resize(u, n_in, n_out, axis):
                s = _src(n_in, n_out)
                x1 = jnp.floor(s)
                t = s - x1
                A = -0.75
                d0, d1, d2, d3 = 1.0 + t, t, 1.0 - t, 2.0 - t
                ws = [
                    A * d0**3 - 5 * A * d0**2 + 8 * A * d0 - 4 * A,
                    (A + 2) * d1**3 - (A + 3) * d1**2 + 1,
                    (A + 2) * d2**3 - (A + 3) * d2**2 + 1,
                    A * d3**3 - 5 * A * d3**2 + 8 * A * d3 - 4 * A,
                ]
                acc = 0.0
                for off, w in zip((-1, 0, 1, 2), ws):
                    ii = jnp.clip(x1.astype(jnp.int32) + off, 0, n_in - 1)
                    tap = jnp.take(u, ii, axis=axis)
                    shape = [1] * u.ndim
                    shape[axis] = n_out
                    acc = acc + tap * w.reshape(shape)
                return acc

            out = axis_resize(v.astype(jnp.float32), v.shape[2], size[0], 2)
            out = axis_resize(out, v.shape[3], size[1], 3).astype(v.dtype)
            if not nchw:
                out = jnp.transpose(out, (0, 2, 3, 1))
            return out

        return apply_op("interpolate_bicubic", fn, (x,), {})

    if align_corners and mode == "bilinear":
        # jax.image.resize is half-pixel only; align_corners maps output grid
        # ends onto input grid ends via _src, then gather + bilinear blend
        # (matches the reference kernel's align_corners branch; n_out==1
        # degenerates to index 0 like the reference's ratio=0).
        def fn(v):
            if not nchw:
                v = jnp.transpose(v, (0, 3, 1, 2))
            H, W = v.shape[2], v.shape[3]
            oh, ow = size
            ys = _src(H, oh)
            xs = _src(W, ow)
            y0 = jnp.floor(ys).astype(jnp.int32)
            x0 = jnp.floor(xs).astype(jnp.int32)
            y1 = jnp.minimum(y0 + 1, H - 1)
            x1 = jnp.minimum(x0 + 1, W - 1)
            wy = (ys - y0)[None, None, :, None]
            wx = (xs - x0)[None, None, None, :]
            g = lambda yi, xi: v[:, :, yi, :][:, :, :, xi]
            out = (
                g(y0, x0) * (1 - wy) * (1 - wx)
                + g(y0, x1) * (1 - wy) * wx
                + g(y1, x0) * wy * (1 - wx)
                + g(y1, x1) * wy * wx
            ).astype(v.dtype)
            if not nchw:
                out = jnp.transpose(out, (0, 2, 3, 1))
            return out

        return apply_op("interpolate_ac", fn, (x,), {})

    def fn(v):
        if nchw:
            shape = (v.shape[0], v.shape[1], size[0], size[1])
        else:
            shape = (v.shape[0], size[0], size[1], v.shape[3])
        return jax.image.resize(v, shape, method=method)

    return apply_op("interpolate", fn, (x,), {})


upsample = interpolate


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor

    def fn(v):
        N, C, H, W = v.shape
        v = v.reshape(N, C // (r * r), r, r, H, W)
        v = jnp.transpose(v, (0, 1, 4, 2, 5, 3))
        return v.reshape(N, C // (r * r), H * r, W * r)

    return apply_op("pixel_shuffle", fn, (x,), {})


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    k = _pair(kernel_sizes)
    s = _pair(strides)
    d = _pair(dilations)
    p = _conv_padding(paddings, k, s, d, 2)

    def fn(v):
        N, C, H, W = v.shape
        patches = jax.lax.conv_general_dilated_patches(
            v, k, s, p, rhs_dilation=d, dimension_numbers=("NCHW", "OIHW", "NCHW"),
        )
        L = patches.shape[2] * patches.shape[3]
        return patches.reshape(N, C * k[0] * k[1], L)

    return apply_op("unfold", fn, (x,), {})
