"""Tensor-API long tail: linalg, statistics, manipulation extras, inplace
variants, and framework compat shims.

Reference: python/paddle/tensor/{linalg,math,stat,manipulation,creation}.py —
the remaining `paddle.*` symbols the main op modules don't cover
(SURVEY §2.2 "Tensor ops API" row).
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, to_tensor
from ..core.registry import eager_op
from .math import _unary

__all__ = [
    "add_n", "broadcast_shape", "cholesky", "conj", "imag", "real",
    "inverse", "histogram", "median", "multiplex", "diagflat", "diagonal",
    "trace", "std", "var", "standard_normal", "reverse", "crop",
    "scatter_nd", "tolist", "is_tensor", "reshape_", "scatter_", "squeeze_",
    "tanh_", "unsqueeze_",
]


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _raws(xs):
    return [x._data if isinstance(x, Tensor) else jnp.asarray(x) for x in xs]


def add_n(inputs, name=None):
    """Ref: sum_op.cc (paddle.add_n)."""
    if isinstance(inputs, Tensor):
        return inputs
    raw = eager_op("add_n")(lambda *xs: jnp.sum(jnp.stack(xs), axis=0))
    return raw(*[_t(x) for x in inputs])


def broadcast_shape(x_shape, y_shape):
    return list(np.broadcast_shapes(tuple(x_shape), tuple(y_shape)))


cholesky_raw = eager_op("cholesky")(
    lambda x, upper=False: (jnp.linalg.cholesky(x).swapaxes(-1, -2)
                            if upper else jnp.linalg.cholesky(x)))


def cholesky(x, upper=False, name=None):
    return cholesky_raw(_t(x), upper=upper)


conj = _unary("conj", jnp.conj)
imag = _unary("imag", jnp.imag)
real = _unary("real", jnp.real)
inverse = _unary("inverse", jnp.linalg.inv)


def histogram(input, bins=100, min=0, max=0, name=None):
    x = _t(input)._data
    lo, hi = (min, max) if (min != 0 or max != 0) else \
        (jnp.min(x), jnp.max(x))
    h, _ = jnp.histogram(x.ravel(), bins=bins, range=(lo, hi))
    return Tensor(h, stop_gradient=True)


def median(x, axis=None, keepdim=False, name=None):
    raw = eager_op("median")(
        lambda v: jnp.median(v, axis=axis, keepdims=keepdim))
    return raw(_t(x))


def multiplex(inputs, index, name=None):
    """Ref: multiplex_op.cc — row i of output = row i of inputs[index[i]]."""
    stacked = jnp.stack(_raws(inputs))  # [K, B, ...]
    idx = _t(index)._data.reshape(-1).astype(jnp.int32)
    rows = jnp.arange(idx.shape[0])
    return Tensor(stacked[idx, rows], stop_gradient=True)


def diagflat(x, offset=0, name=None):
    raw = eager_op("diagflat")(lambda v: jnp.diagflat(v, k=offset))
    return raw(_t(x))


def diagonal(x, offset=0, axis1=0, axis2=1, name=None):
    raw = eager_op("diagonal")(
        lambda v: jnp.diagonal(v, offset=offset, axis1=axis1, axis2=axis2))
    return raw(_t(x))


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    raw = eager_op("trace")(
        lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2))
    return raw(_t(x))


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    raw = eager_op("std")(lambda v: jnp.std(
        v, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim))
    return raw(_t(x))


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    raw = eager_op("var")(lambda v: jnp.var(
        v, axis=axis, ddof=1 if unbiased else 0, keepdims=keepdim))
    return raw(_t(x))


def standard_normal(shape, dtype="float32", name=None):
    from ..core import random as _random

    key = _random.next_key()
    return Tensor(jax.random.normal(key, tuple(shape)).astype(dtype),
                  stop_gradient=True)


def reverse(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    raw = eager_op("reverse")(lambda v: jnp.flip(v, axis=tuple(axes)))
    return raw(_t(x))


def crop(x, shape=None, offsets=None, name=None):
    """Ref: crop_tensor_op.cc."""
    t = _t(x)
    shp = [int(s) for s in (shape or t.shape)]
    offs = [int(o) for o in (offsets or [0] * len(shp))]
    raw = eager_op("crop")(
        lambda v: jax.lax.dynamic_slice(v, offs, shp))
    return raw(t)


def scatter_nd(index, updates, shape, name=None):
    """Ref: scatter_nd_op — zeros of `shape` scatter-added at `index`."""
    idx = _t(index)._data
    upd = _t(updates)._data
    zeros = jnp.zeros(tuple(shape), upd.dtype)
    raw = eager_op("scatter_nd")(
        lambda u: zeros.at[tuple(jnp.moveaxis(idx, -1, 0))].add(u))
    return raw(_t(updates))


def tolist(x):
    return np.asarray(_t(x).numpy()).tolist()


def is_tensor(x):
    return isinstance(x, Tensor)


# ---- inplace variants (reference *_ ops mutate the VarBase buffer) ----

def reshape_(x, shape, name=None):
    x._data = jnp.reshape(x._data, [int(s) for s in shape])
    return x


def scatter_(x, index, updates, overwrite=True, name=None):
    idx = _t(index)._data.astype(jnp.int32)
    upd = _t(updates)._data
    x._data = (x._data.at[idx].set(upd) if overwrite
               else x._data.at[idx].add(upd))
    return x


def squeeze_(x, axis=None, name=None):
    x._data = (jnp.squeeze(x._data) if axis is None
               else jnp.squeeze(x._data, axis=axis))
    return x


def tanh_(x, name=None):
    x._data = jnp.tanh(x._data)
    return x


def unsqueeze_(x, axis, name=None):
    axes = axis if isinstance(axis, (list, tuple)) else [axis]
    d = x._data
    for a in sorted(axes):
        d = jnp.expand_dims(d, a)
    x._data = d
    return x
