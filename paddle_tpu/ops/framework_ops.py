"""Framework-glue ops: identity/copy markers, fused buffers, queues,
sparse-rows conversions, host callbacks.

Reference: operators/assign_value_op.cc, memcpy_op.cc, share_data_op.cc,
nop_op.cc / marker_op.cc, coalesce_tensor_op.cc (fused flat grad buffer),
operators/controlflow/op variants enqueue/dequeue + queue_generator_op.cc,
merge_selected_rows_op.cc, get_tensor_from_selected_rows_op.cc,
py_func_op.cc (python-callback op), size_op.cc.

TPU-native notes: memcpy/share_data are true no-ops under XLA (PJRT owns
placement; the executor's donation plan does buffer reuse), but they are
registered so program rewrites and serialized descs round-trip.  The
queue ops bind the native C++ prefetch queue (native/src/queue.cc).
py_func lowers to jax.pure_callback so it stays usable inside jit.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import apply_op, register_op
from ..core.tensor import Tensor, to_tensor
from ..core.indexed_slices import IndexedSlices

__all__ = [
    "assign_value", "size", "numel_op", "memcpy", "share_data", "nop",
    "marker", "coalesce_tensor", "queue_generator", "enqueue", "dequeue",
    "merge_selected_rows", "get_tensor_from_selected_rows",
    "tensor_array_to_tensor", "py_func",
]


def assign_value(shape, dtype, values, name=None):
    """Materialize a host constant (assign_value_op.cc)."""
    from ..core.dtype import convert_dtype

    arr = np.asarray(values, dtype=convert_dtype(dtype)).reshape(shape)
    out = to_tensor(arr)
    out.stop_gradient = True
    return out


def _size(x):
    return jnp.asarray(int(np.prod(x.shape)) if x.ndim else 1, jnp.int64)


register_op("size", _size)


def size(x, name=None):
    """Element count as a 0-d tensor (size_op.cc)."""
    out = apply_op("size", _size, (x,), {})
    out.stop_gradient = True
    return out


numel_op = size


def _identity(x):
    return x


register_op("memcpy", _identity)
register_op("share_data", _identity)


def memcpy(x, dst_place_type=None, name=None):
    """Placement copy (memcpy_op.cc).  PJRT owns placement on TPU, so the
    dataflow value is returned as-is; the op exists for desc parity."""
    return apply_op("memcpy", _identity, (x,), {})


def share_data(x, name=None):
    """Aliased view (share_data_op.cc); XLA donation handles real aliasing."""
    return apply_op("share_data", _identity, (x,), {})


def nop(*xs):
    """Scheduling placeholder (nop_op.cc): returns inputs untouched."""
    return xs if len(xs) != 1 else xs[0]


def marker(marker_role="forward", marker_pos="B", name=None):
    """Profiler marker (marker_op.cc) -> a host RecordEvent span."""
    from ..profiler import RecordEvent

    ev = RecordEvent(f"marker::{marker_role}::{marker_pos}")
    ev.__enter__()
    ev.__exit__(None, None, None)


def coalesce_tensor(inputs, dtype=None, name=None):
    """Fuse tensors into one flat buffer; returns (views, fused)
    (coalesce_tensor_op.cc — the fused-allreduce grad buffer).  The views
    are slices of the fused value, so a collective over `fused` is a
    collective over every input, which is exactly how the compiled DP path
    fuses its grad psum (parallel/hybrid.py flat pmean)."""
    sizes = [int(np.prod(t.shape)) for t in inputs]
    shapes = [tuple(t.shape) for t in inputs]

    def fn(*vals):
        flat = jnp.concatenate([v.reshape(-1) for v in vals])
        outs = []
        off = 0
        for s, shp in zip(sizes, shapes):
            outs.append(flat[off:off + s].reshape(shp))
            off += s
        return tuple(outs) + (flat,)

    res = apply_op("coalesce_tensor", fn, tuple(inputs), {},
                   n_outputs=len(inputs) + 1)
    return list(res[:-1]), res[-1]


_QUEUES = {}


def queue_generator(names, capacity=2):
    """Create named native byte queues (queue_generator_op.cc ->
    native/src/queue.cc)."""
    from .. import native

    for n in ([names] if isinstance(names, str) else names):
        if n not in _QUEUES:
            _QUEUES[n] = native.PrefetchQueue(capacity=capacity)
    return [_QUEUES[n] for n in
            ([names] if isinstance(names, str) else names)]


def enqueue(x, queue_name, timeout_ms=-1):
    """Push a tensor's host bytes into a named queue (enqueue op)."""
    import pickle

    q = _QUEUES[queue_name]
    arr = np.asarray(x._data if isinstance(x, Tensor) else x)
    payload = pickle.dumps((arr.dtype.str, arr.shape, arr.tobytes()),
                           protocol=4)
    return q.push(payload, timeout_ms=timeout_ms)


def dequeue(queue_name, timeout_ms=-1):
    """Pop a tensor from a named queue (dequeue op)."""
    import pickle

    q = _QUEUES[queue_name]
    payload = q.pop(timeout_ms=timeout_ms)
    if payload is None:
        return None
    dt, shape, raw = pickle.loads(payload)
    out = to_tensor(np.frombuffer(raw, dtype=np.dtype(dt)).reshape(shape)
                    .copy())
    out.stop_gradient = True
    return out


def merge_selected_rows(x, name=None):
    """Coalesce duplicate rows of an IndexedSlices by summation
    (merge_selected_rows_op.cc)."""
    if not isinstance(x, IndexedSlices):
        raise TypeError("merge_selected_rows expects IndexedSlices")
    uniq, summed = x.coalesce()
    return IndexedSlices(uniq, summed, x.dense_shape)


def get_tensor_from_selected_rows(x, name=None):
    """Densify an IndexedSlices (get_tensor_from_selected_rows_op.cc)."""
    if not isinstance(x, IndexedSlices):
        raise TypeError("get_tensor_from_selected_rows expects IndexedSlices")
    return to_tensor(np.asarray(x.to_dense()))


def tensor_array_to_tensor(input, axis=0, use_stack=False, name=None):
    """Fuse a tensor array (Python list of Tensors — the LoDTensorArray
    analogue, see docs/ABSENT.md on LoD) into one tensor
    (tensor_array_to_tensor_op.cc).  Returns (out, out_index) where
    out_index records each element's extent along `axis` (all 1s when
    stacking), matching the reference's OutIndex output."""
    if not isinstance(input, (list, tuple)) or not input:
        raise TypeError("tensor_array_to_tensor expects a non-empty list")
    if use_stack:
        fn = lambda *xs: jnp.stack(xs, axis=axis)
    else:
        fn = lambda *xs: jnp.concatenate(xs, axis=axis)
    # OutIndex records each element's extent along axis in BOTH modes
    # (tensor_array_to_tensor_op.cc:115-119 writes inx_dims[axis]
    # unconditionally)
    index = np.array([(t._data if isinstance(t, Tensor)
                       else np.asarray(t)).shape[axis]
                      for t in input], np.int32)
    out = apply_op("tensor_array_to_tensor",
                   fn, tuple(input), {})
    return out, to_tensor(index)


def make_pyfunc_fn(func, specs, backward_func=None):
    """Shared py_func lowering (py_func_op.cc): a host callback via
    jax.pure_callback, optionally wrapped in custom_vjp when the caller
    supplies backward_func(*inputs, *out_grads) -> input grads.  Used by
    both the eager op below and static.py_func."""
    def host(*vals):
        res = func(*[np.asarray(v) for v in vals])
        res = res if isinstance(res, (list, tuple)) else (res,)
        return tuple(np.asarray(r, dtype=s.dtype).reshape(s.shape)
                     for r, s in zip(res, specs))

    if backward_func is None:
        def fn(*vals):
            out = jax.pure_callback(host, specs, *vals)
            return out if len(specs) != 1 else out[0]

        return fn

    @jax.custom_vjp
    def _core(*vals):
        out = jax.pure_callback(host, specs, *vals)
        return out if len(specs) != 1 else out[0]

    def _fwd(*vals):
        return _core(*vals), vals

    def _bwd(vals, g):
        gs = g if isinstance(g, tuple) else (g,)
        in_specs = tuple(jax.ShapeDtypeStruct(v.shape, v.dtype)
                         for v in vals)

        def bhost(*args):
            res = backward_func(*[np.asarray(a) for a in args])
            res = res if isinstance(res, (list, tuple)) else (res,)
            return tuple(np.asarray(r, dtype=s.dtype).reshape(s.shape)
                         for r, s in zip(res, in_specs))

        return jax.pure_callback(bhost, in_specs, *(vals + gs))

    _core.defvjp(_fwd, _bwd)
    return _core


def py_func(func, x, out_shapes, out_dtypes, backward_func=None, name=None):
    """Call arbitrary Python on tensor values (py_func_op.cc).

    Lowered via jax.pure_callback so the op survives jit tracing; an
    optional backward_func supplies the custom VJP the reference wires
    through its grad-op maker.  out_shapes/out_dtypes describe the
    callback results (single spec or lists).
    """
    from ..core.dtype import convert_dtype

    xs = x if isinstance(x, (list, tuple)) else [x]
    single = not isinstance(out_shapes[0], (list, tuple)) \
        if out_shapes else True
    shapes = [out_shapes] if single else list(out_shapes)
    dtypes = [out_dtypes] if isinstance(out_dtypes, str) else list(out_dtypes)
    specs = tuple(jax.ShapeDtypeStruct(tuple(s), convert_dtype(d))
                  for s, d in zip(shapes, dtypes))
    fn = make_pyfunc_fn(func, specs, backward_func)
    n_out = len(specs)
    return apply_op("py_func", fn, tuple(xs), {},
                    n_outputs=n_out if n_out > 1 else None)
