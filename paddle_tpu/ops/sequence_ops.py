"""Sequence-family + classic-NLP ops over the padded+length representation.

Reference: operators/linear_chain_crf_op.{cc,h} (forward/backward CRF
recursions), crf_decoding_op.h (Viterbi), operators/sequence_ops/ (the
sequence_* family over LoDTensors), nce_op.h, sample_logits_op.h,
sampling_id_op.h, beam_search_op.h, beam_search_decode_op.h,
add_position_encoding_op.h, im2sequence_op.h, row_conv_op.h,
conv_shift_op.h, segment_pool_op.h.

TPU-native design (SURVEY §7.3 "LoD"): ragged sequences are carried as
(padded (B, T, ...) data, per-row int lengths) pairs — LoD offsets exist
only at the Python boundary (sequence_pad/sequence_unpad are exactly that
boundary).  All recursions (CRF alpha/viterbi, beam step) are lax.scan
loops with static shapes, so every op jit-compiles; nothing here does a
per-timestep host round-trip.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import apply_op, register_op
from ..core.tensor import Tensor, to_tensor
from ..core import random as _random


def _op_key(seed):
    """seed=0 means nondeterministic (fresh key from the global threefry
    stream), matching ops/creation.py's convention and the reference's
    seed-attr semantics."""
    return jax.random.PRNGKey(seed) if seed else _random.next_key()

__all__ = [
    "linear_chain_crf", "crf_decoding", "nce", "sample_logits",
    "sampling_id", "beam_search", "beam_search_decode",
    "add_position_encoding", "im2sequence", "row_conv", "conv_shift",
    "segment_pool", "segment_sum", "segment_mean", "segment_max",
    "segment_min", "sequence_pool", "sequence_softmax", "sequence_reverse",
    "sequence_pad", "sequence_unpad", "sequence_expand", "sequence_conv",
    "sequence_first_step", "sequence_last_step", "sequence_concat",
    "sequence_enumerate", "sequence_expand_as", "sequence_reshape",
    "sequence_scatter", "sequence_slice",
]


def _len_mask(length, T, dtype=jnp.float32):
    """(B,) lengths -> (B, T) {1,0} validity mask."""
    return (jnp.arange(T)[None, :] < length[:, None]).astype(dtype)


# ---------------------------------------------------------------------------
# Linear-chain CRF
# ---------------------------------------------------------------------------

def _crf_ll(emission, transition, label, length):
    """Per-sequence log-likelihood (linear_chain_crf_op.h:188-222).

    emission (B, T, N); transition (N+2, N) with rows 0/1 = start/stop;
    label (B, T) int; length (B,) int.  Masked logsumexp forward recursion
    under lax.scan — the XLA-native form of the reference's per-sequence
    alpha loop.
    """
    B, T, N = emission.shape
    start, stop, trans = transition[0], transition[1], transition[2:]
    lab = label.astype(jnp.int32)
    lens = length.astype(jnp.int32)

    alpha0 = start[None, :] + emission[:, 0, :]  # (B, N)

    def step(alpha, t):
        # logsumexp over previous tag
        scores = alpha[:, :, None] + trans[None, :, :]  # (B, N_prev, N)
        new = jax.scipy.special.logsumexp(scores, axis=1) + emission[:, t, :]
        alive = (t < lens)[:, None]
        return jnp.where(alive, new, alpha), None

    alphaT, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T)) if T > 1 \
        else (alpha0, None)
    log_z = jax.scipy.special.logsumexp(alphaT + stop[None, :], axis=1)

    # gold score: start + sum_t emission[t, y_t] + sum_t trans[y_{t-1}, y_t]
    # + stop[y_last]
    t_idx = jnp.arange(T)[None, :]
    valid = (t_idx < lens[:, None])
    em_gold = jnp.take_along_axis(emission, lab[:, :, None], axis=2)[..., 0]
    em_sum = jnp.sum(jnp.where(valid, em_gold, 0.0), axis=1)
    prev_lab = lab[:, :-1]
    next_lab = lab[:, 1:]
    tr_gold = trans[prev_lab, next_lab]  # (B, T-1)
    tr_valid = (t_idx[:, 1:] < lens[:, None])
    tr_sum = jnp.sum(jnp.where(tr_valid, tr_gold, 0.0), axis=1) if T > 1 \
        else jnp.zeros((B,), emission.dtype)
    first_lab = lab[:, 0]
    last_lab = jnp.take_along_axis(lab, (lens - 1)[:, None], axis=1)[:, 0]
    gold = start[first_lab] + em_sum + tr_sum + stop[last_lab]
    return (gold - log_z)[:, None]


register_op("linear_chain_crf", _crf_ll)


def linear_chain_crf(input, transition, label, length, name=None):
    """Log-likelihood (B, 1) of gold tag paths under a linear-chain CRF.
    Negate and mean for a training loss (the reference's book usage)."""
    return apply_op("linear_chain_crf", _crf_ll,
                    (input, transition, label, length), {})


def _viterbi(emission, transition, length):
    B, T, N = emission.shape
    start, stop, trans = transition[0], transition[1], transition[2:]
    lens = length.astype(jnp.int32)
    alpha0 = start[None, :] + emission[:, 0, :]

    def step(alpha, t):
        scores = alpha[:, :, None] + trans[None, :, :]  # (B, prev, cur)
        best_prev = jnp.argmax(scores, axis=1)  # (B, N)
        new = jnp.max(scores, axis=1) + emission[:, t, :]
        alive = (t < lens)[:, None]
        return jnp.where(alive, new, alpha), \
            jnp.where(alive, best_prev, jnp.arange(N)[None, :])

    if T > 1:
        alphaT, back = jax.lax.scan(step, alpha0, jnp.arange(1, T))
        back = jnp.moveaxis(back, 0, 1)  # (B, T-1, N)
    else:
        alphaT = alpha0
        back = jnp.zeros((B, 0, N), jnp.int32)
    final = alphaT + stop[None, :]
    last_tag = jnp.argmax(final, axis=1).astype(jnp.int32)  # (B,)

    # backtrack from position lens-1 down to 0
    def bt_step(tag, t):
        # pointer at time t+1 tells the best tag at time t
        ptr = back[:, t, :]  # (B, N) backpointer for transition t -> t+1
        prev = jnp.take_along_axis(ptr, tag[:, None], axis=1)[:, 0]
        # only meaningful while t+1 < lens; else keep current tag
        keep = (t + 1) < lens
        return jnp.where(keep, prev.astype(jnp.int32), tag), \
            jnp.where(keep, prev.astype(jnp.int32), tag)

    ts = jnp.arange(T - 2, -1, -1) if T > 1 else jnp.zeros((0,), jnp.int32)
    _, path_rev = jax.lax.scan(bt_step, last_tag, ts)
    if T > 1:
        path = jnp.concatenate(
            [jnp.flip(jnp.moveaxis(path_rev, 0, 1), axis=1),
             last_tag[:, None]], axis=1)  # (B, T)
    else:
        path = last_tag[:, None]
    # zero out the padding tail (reference pads decoded LoD at boundary)
    return jnp.where(_len_mask(lens, T, jnp.bool_), path, 0).astype(jnp.int64)


register_op("crf_decoding", _viterbi)


def crf_decoding(input, transition, length, name=None):
    """Viterbi decode (B, T) best tag paths (crf_decoding_op.h)."""
    out = apply_op("crf_decoding", _viterbi, (input, transition, length), {})
    out.stop_gradient = True
    return out


# ---------------------------------------------------------------------------
# Sampled-softmax family
# ---------------------------------------------------------------------------

def _log_uniform_sample(key, num_samples, vocab):
    """Log-uniform (Zipf) class sampler, the reference NCE default."""
    u = jax.random.uniform(key, (num_samples,))
    ids = (jnp.exp(u * jnp.log(vocab + 1.0)) - 1.0).astype(jnp.int32)
    return jnp.clip(ids, 0, vocab - 1)


def _log_uniform_log_prob(ids, vocab):
    """log P(k) under the log-uniform sampler:
    P(k) = (log(k+2) - log(k+1)) / log(V+1) (math/sampler.cc
    LogUniformSampler::Probability)."""
    k = ids.astype(jnp.float32)
    return jnp.log(jnp.log((k + 2.0) / (k + 1.0))) \
        - jnp.log(jnp.log(vocab + 1.0))


def nce(input, weight, label, bias=None, num_total_classes=None,
        num_neg_samples=10, sampler="uniform", seed=0, name=None):
    """Noise-contrastive estimation loss (nce_op.h).

    input (B, D); weight (V, D); label (B,) or (B, L) true classes.
    Returns (B, 1) per-sample NCE cost over shared negative samples.
    """
    V = num_total_classes or weight.shape[0]

    key = _op_key(seed)

    def fn(x, w, lbl, *maybe_bias):
        b = maybe_bias[0] if maybe_bias else None
        if sampler == "log_uniform":
            neg = _log_uniform_sample(key, num_neg_samples, V)
        else:
            neg = jax.random.randint(key, (num_neg_samples,), 0, V)
        lbl2 = lbl.reshape(lbl.shape[0], -1).astype(jnp.int32)  # (B, L)
        pos_w = w[lbl2]  # (B, L, D)
        pos_logit = jnp.einsum("bd,bld->bl", x, pos_w)
        neg_logit = x @ w[neg].T  # (B, S)
        if b is not None:
            pos_logit = pos_logit + b[lbl2]
            neg_logit = neg_logit + b[neg][None, :]
        # NCE prices each class by its own sampler probability
        # (nce_op.h: sampler->Probability per sampled/true class)
        if sampler == "log_uniform":
            log_q_pos = _log_uniform_log_prob(lbl2, V)       # (B, L)
            log_q_neg = _log_uniform_log_prob(neg, V)[None]  # (1, S)
        else:
            log_q = -jnp.log(jnp.asarray(float(V), x.dtype))
            log_q_pos = log_q
            log_q_neg = log_q
        pos_cost = -jax.nn.log_sigmoid(pos_logit - log_q_pos)
        neg_cost = -jax.nn.log_sigmoid(-(neg_logit - log_q_neg))
        return (jnp.sum(pos_cost, axis=1)
                + jnp.sum(neg_cost, axis=1))[:, None]

    args = (input, weight, label) + ((bias,) if bias is not None else ())
    return apply_op("nce", fn, args, {})


def sample_logits(logits, label, num_samples, seed=0, name=None):
    """Sampled-softmax helper (sample_logits_op.h): draws shared negative
    classes, gathers their logits next to the true-label logits.
    Returns (sampled_logits (B, L+S), sampled_label (B, L+S))."""
    key = _op_key(seed)

    def fn(lg, lbl):
        B, V = lg.shape
        lbl2 = lbl.reshape(B, -1).astype(jnp.int32)
        L = lbl2.shape[1]
        neg = _log_uniform_sample(key, num_samples, V)  # (S,)
        ids = jnp.concatenate(
            [lbl2, jnp.broadcast_to(neg[None, :], (B, num_samples))], axis=1)
        picked = jnp.take_along_axis(lg, ids, axis=1)
        return picked, ids.astype(jnp.int64)

    out = apply_op("sample_logits", fn, (logits, label), {}, n_outputs=2)
    out[1].stop_gradient = True
    return out


def sampling_id(x, min=0.0, max=1.0, seed=0, name=None):
    """Sample one column index per row of a probability matrix
    (sampling_id_op.h)."""
    key = _op_key(seed)

    def fn(p):
        return jax.random.categorical(key, jnp.log(
            jnp.maximum(p, 1e-20)), axis=1).astype(jnp.int64)

    out = apply_op("sampling_id", fn, (x,), {})
    out.stop_gradient = True
    return out


# ---------------------------------------------------------------------------
# Beam search (dense (batch, beam) layout; LoD layout stays at the boundary)
# ---------------------------------------------------------------------------

def beam_search(pre_ids, pre_scores, ids, scores, beam_size, end_id,
                level=0, is_accumulated=True, name=None):
    """One beam-search expansion step (beam_search_op.h).

    Dense layout: pre_ids/pre_scores (batch*beam, 1); ids/scores
    (batch*beam, K) candidate tokens and their (accumulated) scores.
    Returns (selected_ids (batch*beam, 1), selected_scores (batch*beam, 1),
    parent_idx (batch*beam,) int — the flat beam row each winner came
    from, feedable to gather_tree).  Finished beams (pre_id == end_id)
    propagate with their score frozen, matching the reference semantics.
    """
    def fn(p_ids, p_scores, cand_ids, cand_scores):
        BB, K = cand_scores.shape
        batch = BB // beam_size
        finished = (p_ids.reshape(-1) == end_id)
        acc = cand_scores if is_accumulated \
            else p_scores.reshape(-1, 1) + jnp.log(
                jnp.maximum(cand_scores, 1e-20))
        neg_inf = jnp.asarray(-1e9, acc.dtype)
        # a finished beam contributes exactly one candidate: itself
        keep_score = jnp.where(
            jnp.arange(K)[None, :] == 0, p_scores.reshape(-1, 1), neg_inf)
        acc = jnp.where(finished[:, None], keep_score, acc)
        keep_ids = jnp.where(
            jnp.arange(K)[None, :] == 0, p_ids.reshape(-1, 1),
            jnp.asarray(end_id, cand_ids.dtype))
        cand = jnp.where(finished[:, None], keep_ids, cand_ids)
        flat = acc.reshape(batch, beam_size * K)
        top_score, top_pos = jax.lax.top_k(flat, beam_size)
        src_beam = top_pos // K  # (batch, beam) beam row within the batch
        parent = (src_beam
                  + jnp.arange(batch)[:, None] * beam_size).reshape(-1)
        sel_ids = cand.reshape(batch, beam_size * K)
        sel_ids = jnp.take_along_axis(sel_ids, top_pos, axis=1).reshape(-1, 1)
        return (sel_ids.astype(jnp.int64), top_score.reshape(-1, 1),
                parent.astype(jnp.int64))

    out = apply_op("beam_search", fn, (pre_ids, pre_scores, ids, scores),
                   {}, n_outputs=3)
    for t in (out[0], out[2]):
        t.stop_gradient = True
    return out


def beam_search_decode(step_ids, step_parents, beam_size, end_id, name=None):
    """Backtrack stacked per-step (batch*beam, 1) selections into full
    sequences (beam_search_decode_op.h) via gather_tree.
    step_ids/step_parents: lists (or (T, batch*beam) arrays)."""
    from .nn_extra import gather_tree

    def stack(xs):
        if isinstance(xs, (list, tuple)):
            arrs = [x._data if isinstance(x, Tensor) else jnp.asarray(x)
                    for x in xs]
            return jnp.stack([a.reshape(-1) for a in arrs])  # (T, BB)
        return (xs._data if isinstance(xs, Tensor)
                else jnp.asarray(xs)).reshape(len(xs), -1)

    ids = stack(step_ids)
    parents = stack(step_parents)
    T, BB = ids.shape
    batch = BB // beam_size
    ids3 = ids.reshape(T, batch, beam_size)
    par3 = parents.reshape(T, batch, beam_size) % beam_size
    out = gather_tree(to_tensor(np.asarray(ids3)),
                      to_tensor(np.asarray(par3)))
    out.stop_gradient = True
    return out


# ---------------------------------------------------------------------------
# Positional / sliding-window ops
# ---------------------------------------------------------------------------

def _add_pos_enc(x, alpha=1.0, beta=1.0):
    B, T, D = x.shape
    # first ceil(D/2) channels sin, remaining floor(D/2) cos (odd D safe)
    half = (D + 1) // 2
    pos = jnp.arange(T, dtype=x.dtype)[:, None]
    div = jnp.power(10000.0, jnp.arange(half, dtype=x.dtype) / half)
    enc = jnp.concatenate(
        [jnp.sin(pos / div), jnp.cos(pos / div)[:, :D - half]], axis=1)
    return alpha * x + beta * enc[None, :, :]


register_op("add_position_encoding", _add_pos_enc)


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    """alpha*x + beta*sinusoid(T, D) (add_position_encoding_op.h)."""
    return apply_op("add_position_encoding", _add_pos_enc, (input,),
                    {"alpha": float(alpha), "beta": float(beta)})


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    """NCHW image -> (B*out_h*out_w, C*kh*kw) patch sequence
    (im2sequence_op.h).  unfold + transpose; the LoD offsets the reference
    attaches become the implicit row grouping."""
    from .nn_extra import unfold

    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    cols = unfold(input, list(k), strides=stride, paddings=padding)

    def fn(c):
        B, CKK, L = c.shape
        return jnp.transpose(c, (0, 2, 1)).reshape(B * L, CKK)

    return apply_op("im2sequence", fn, (cols,), {})


def _row_conv(x, w):
    # x (B, T, D); w (k, D) lookahead filter: y[t] = sum_j w[j] * x[t+j]
    k = w.shape[0]
    T = x.shape[1]
    xp = jnp.pad(x, ((0, 0), (0, k - 1), (0, 0)))
    out = jnp.zeros_like(x)
    for j in range(k):
        out = out + xp[:, j:j + T, :] * w[j][None, None, :]
    return out


register_op("row_conv", _row_conv)


def row_conv(input, weight, name=None):
    """Lookahead row convolution (row_conv_op.h, DeepSpeech2)."""
    return apply_op("row_conv", _row_conv, (input, weight), {})


def _conv_shift(x, y):
    # circular correlation (conv_shift_op.h): out[i,j] =
    # sum_k x[i, (j + k - W//2) mod N] * y[i, k]
    B, N = x.shape
    W = y.shape[1]
    shifts = jnp.arange(W) - W // 2
    idx = (jnp.arange(N)[None, :] + shifts[:, None]) % N  # (W, N)
    gath = x[:, idx]  # (B, W, N)
    return jnp.einsum("bwn,bw->bn", gath, y)


register_op("conv_shift", _conv_shift)


def conv_shift(x, y, name=None):
    """Circular convolution/correlation (conv_shift_op.h, NTM addressing)."""
    return apply_op("conv_shift", _conv_shift, (x, y), {})


# ---------------------------------------------------------------------------
# Segment + sequence pooling family
# ---------------------------------------------------------------------------

def segment_pool(x, segment_ids, pool_type="SUM", name=None):
    """Pool rows of x by contiguous segment ids (segment_pool_op.h).
    num_segments is taken as max(id)+1 at trace time (host-read of the
    eager ids, the boundary where ragged meets XLA)."""
    ids_arr = segment_ids._data if isinstance(segment_ids, Tensor) \
        else jnp.asarray(segment_ids)
    n_seg = int(np.asarray(ids_arr).max()) + 1 if ids_arr.size else 0
    kind = pool_type.upper()

    def fn(v, ids):
        ids = ids.astype(jnp.int32)
        if kind == "SUM":
            return jax.ops.segment_sum(v, ids, num_segments=n_seg)
        if kind == "MEAN":
            s = jax.ops.segment_sum(v, ids, num_segments=n_seg)
            c = jax.ops.segment_sum(jnp.ones((v.shape[0],), v.dtype), ids,
                                    num_segments=n_seg)
            return s / jnp.maximum(c, 1.0).reshape(
                (-1,) + (1,) * (v.ndim - 1))
        if kind == "MAX":
            return jax.ops.segment_max(v, ids, num_segments=n_seg)
        if kind == "MIN":
            return jax.ops.segment_min(v, ids, num_segments=n_seg)
        raise ValueError(f"unknown segment pool {pool_type}")

    return apply_op(f"segment_{kind.lower()}", fn, (x, segment_ids), {})


def segment_sum(x, segment_ids, name=None):
    return segment_pool(x, segment_ids, "SUM")


def segment_mean(x, segment_ids, name=None):
    return segment_pool(x, segment_ids, "MEAN")


def segment_max(x, segment_ids, name=None):
    return segment_pool(x, segment_ids, "MAX")


def segment_min(x, segment_ids, name=None):
    return segment_pool(x, segment_ids, "MIN")


def _seq_pool(x, length, pool_type="average"):
    B, T = x.shape[0], x.shape[1]
    mask = _len_mask(length.astype(jnp.int32), T, x.dtype)
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    kind = pool_type.lower()
    if kind == "sum":
        return jnp.sum(x * mask, axis=1)
    if kind in ("average", "mean"):
        return jnp.sum(x * mask, axis=1) / jnp.maximum(
            jnp.sum(mask, axis=1), 1.0)
    if kind == "sqrt":
        return jnp.sum(x * mask, axis=1) / jnp.sqrt(jnp.maximum(
            jnp.sum(mask, axis=1), 1.0))
    if kind == "max":
        neg = jnp.asarray(-3.4e38, x.dtype)
        return jnp.max(jnp.where(mask > 0, x, neg), axis=1)
    if kind == "last":
        idx = (length.astype(jnp.int32) - 1).reshape(
            (B,) + (1,) * (x.ndim - 1))
        return jnp.take_along_axis(x, idx, axis=1)[:, 0]
    if kind == "first":
        return x[:, 0]
    raise ValueError(f"unknown sequence pool {pool_type}")


register_op("sequence_pool", _seq_pool)


def sequence_pool(input, length, pool_type="average", name=None):
    """Pool each padded row over its valid prefix (sequence_pool_op.h)."""
    return apply_op("sequence_pool", _seq_pool, (input, length),
                    {"pool_type": pool_type})


def sequence_first_step(input, length, name=None):
    return sequence_pool(input, length, "first")


def sequence_last_step(input, length, name=None):
    return sequence_pool(input, length, "last")


def _seq_softmax(x, length):
    mask = _len_mask(length.astype(jnp.int32), x.shape[1], jnp.bool_)
    mask = mask.reshape(mask.shape + (1,) * (x.ndim - 2))
    neg = jnp.asarray(-1e9, x.dtype)
    out = jax.nn.softmax(jnp.where(mask, x, neg), axis=1)
    return jnp.where(mask, out, 0.0)


register_op("sequence_softmax", _seq_softmax)


def sequence_softmax(input, length, name=None):
    """Masked softmax over the time axis (sequence_softmax_op.h)."""
    return apply_op("sequence_softmax", _seq_softmax, (input, length), {})


def _seq_reverse(x, length):
    T = x.shape[1]
    lens = length.astype(jnp.int32)[:, None]
    idx = jnp.arange(T)[None, :]
    src = jnp.where(idx < lens, lens - 1 - idx, idx)  # reverse valid prefix
    src = src.reshape(src.shape + (1,) * (x.ndim - 2))
    return jnp.take_along_axis(x, jnp.broadcast_to(src, x.shape), axis=1)


register_op("sequence_reverse", _seq_reverse)


def sequence_reverse(x, length, name=None):
    """Reverse each row's valid prefix, keep padding in place
    (sequence_reverse_op.h)."""
    return apply_op("sequence_reverse", _seq_reverse, (x, length), {})


def sequence_pad(x, lengths, pad_value=0.0, maxlen=None, name=None):
    """Concatenated (sum_len, D) rows + lengths -> (B, T, D) padded batch
    (sequence_pad_op.h) — the LoD -> dense boundary conversion."""
    lens = np.asarray(lengths._data if isinstance(lengths, Tensor)
                      else lengths).astype(np.int64).reshape(-1)
    offsets = np.concatenate([[0], np.cumsum(lens)])
    T = int(maxlen or (lens.max() if lens.size else 0))

    def fn(v):
        rows = []
        for i in range(len(lens)):
            seg = v[offsets[i]:offsets[i + 1]]
            pad_n = T - int(lens[i])
            pad_width = [(0, pad_n)] + [(0, 0)] * (v.ndim - 1)
            rows.append(jnp.pad(seg, pad_width,
                                constant_values=pad_value))
        return jnp.stack(rows)

    out = apply_op("sequence_pad", fn, (x,), {})
    len_t = to_tensor(lens)
    len_t.stop_gradient = True
    return out, len_t


def sequence_unpad(x, length, name=None):
    """(B, T, D) padded -> concatenated (sum_len, D) valid rows
    (sequence_unpad_op.h), the dense -> LoD boundary."""
    lens = np.asarray(length._data if isinstance(length, Tensor)
                      else length).astype(np.int64).reshape(-1)

    def fn(v):
        return jnp.concatenate([v[i, :int(lens[i])] for i in range(len(lens))])

    return apply_op("sequence_unpad", fn, (x,), {})


def sequence_expand(x, ref_lengths, name=None):
    """Repeat row i of x ref_lengths[i] times (sequence_expand_op.h with
    ref_level row granularity)."""
    lens = np.asarray(ref_lengths._data if isinstance(ref_lengths, Tensor)
                      else ref_lengths).astype(np.int64).reshape(-1)
    idx = np.repeat(np.arange(len(lens)), lens)

    def fn(v):
        return v[jnp.asarray(idx)]

    return apply_op("sequence_expand", fn, (x,), {})


def _seq_conv(x, w, length, context_start):
    # x (B, T, D), w (ctx*D, M): gather the context window per step then
    # one big matmul (MXU-friendly im2col form of sequence_conv_op.h)
    B, T, D = x.shape
    ctx = w.shape[0] // D
    cols = []
    for j in range(ctx):
        off = context_start + j
        if off < 0:
            seg = jnp.pad(x[:, :max(T + off, 0)],
                          ((0, 0), (min(-off, T), 0), (0, 0)))
        else:
            seg = jnp.pad(x[:, off:], ((0, 0), (0, min(off, T)), (0, 0)))
        cols.append(seg)
    stacked = jnp.concatenate(cols, axis=2)  # (B, T, ctx*D)
    out = stacked @ w  # (B, T, M)
    mask = _len_mask(length.astype(jnp.int32), T, x.dtype)[:, :, None]
    return out * mask


register_op("sequence_conv", _seq_conv)


def sequence_conv(input, weight, length, context_length=None,
                  context_start=None, name=None):
    """Context-window sequence convolution (sequence_conv_op.h).
    weight is (context_length*D, M); context_start defaults to
    -(context_length-1)//2 like the reference."""
    D = input.shape[2]
    ctx = context_length or weight.shape[0] // D
    start = context_start if context_start is not None else -(ctx - 1) // 2
    return apply_op("sequence_conv", _seq_conv, (input, weight, length),
                    {"context_start": int(start)})


def _lens_of(length):
    arr = length._data if isinstance(length, Tensor) else jnp.asarray(length)
    return arr.reshape(-1).astype(jnp.int32)


def sequence_concat(inputs, lengths, name=None):
    """Per-row concatenation of padded sequences (sequence_concat_op.h):
    out row b = x0[b,:l0[b]] ++ x1[b,:l1[b]] ++ ...  Returns (out, out_len)
    with out maxlen = sum of input maxlens."""
    lens = [_lens_of(l) for l in lengths]
    Ts = [int(x.shape[1]) for x in inputs]
    T_out = sum(Ts)
    trailing = tuple(int(s) for s in inputs[0].shape[2:])

    def fn(*vals):
        B = vals[0].shape[0]
        out = jnp.zeros((B, T_out) + trailing, vals[0].dtype)

        def write_row(out_b, x_b, off_b):
            start = (off_b,) + (0,) * (out_b.ndim - 1)
            return jax.lax.dynamic_update_slice(out_b, x_b, start)

        offsets = jnp.zeros((B,), jnp.int32)
        # each segment is masked to its valid prefix before writing, so
        # input pad contents never leak into the output's pad region
        for i, v in enumerate(vals):
            T_i = v.shape[1]
            m = (jnp.arange(T_i)[None, :] < lens[i][:, None])
            m = m.reshape(m.shape + (1,) * (v.ndim - 2))
            v = jnp.where(m, v, 0).astype(out.dtype)
            out = jax.vmap(write_row)(out, v, offsets)
            offsets = offsets + lens[i]
        return out

    out = apply_op("sequence_concat", fn, tuple(inputs), {})
    from ..core.tensor import _wrap_data
    total = sum(lens[i] for i in range(len(lens)))
    len_t = _wrap_data(jnp.asarray(total))
    len_t.stop_gradient = True
    return out, len_t


def sequence_enumerate(x, length, win_size, pad_value=0, name=None):
    """Sliding windows of ids (sequence_enumerate_op.h): out[b, t] =
    [x[b,t], ..., x[b,t+win-1]], entries past the row's length filled with
    pad_value."""
    lens = _lens_of(length)
    T = int(x.shape[1])

    def fn(v):
        cols = []
        for k in range(win_size):
            shifted = jnp.pad(v[:, k:], [(0, 0), (0, k)],
                              constant_values=pad_value)
            idx = jnp.arange(T)[None, :] + k
            valid = idx < lens[:, None]
            cols.append(jnp.where(valid, shifted, pad_value))
        return jnp.stack(cols, axis=-1)

    return apply_op("sequence_enumerate", fn, (x,), {})


def sequence_expand_as(x, ref_length, maxlen=None, name=None):
    """Broadcast each single-step row x[b] over its reference sequence
    length (sequence_expand_as_op.h): out[b, t] = x[b] for t <
    ref_length[b], zero-padded beyond.  maxlen fixes the padded width
    (required when ref_length is traced — e.g. the static executor)."""
    lens = _lens_of(ref_length)
    T = int(maxlen) if maxlen is not None else (
        int(jnp.max(lens)) if lens.shape[0] else 0)

    def fn(v):
        out = jnp.broadcast_to(v[:, None], (v.shape[0], T) + v.shape[1:])
        mask = (jnp.arange(T)[None, :] < lens[:, None])
        mask = mask.reshape(mask.shape + (1,) * (v.ndim - 1))
        return jnp.where(mask, out, 0).astype(v.dtype)

    return apply_op("sequence_expand_as", fn, (x,), {})


def sequence_reshape(x, length, new_dim, name=None):
    """Reinterpret each row's valid region with a new trailing width
    (sequence_reshape_op.h).  Valid data is a row prefix, so the padded
    reshape is exact: (B, T, D) -> (B, T*D/new_dim, new_dim); out lengths
    scale by D/new_dim."""
    B, T, D = (int(s) for s in x.shape)
    if (T * D) % new_dim:
        raise ValueError(f"T*D={T * D} not divisible by new_dim={new_dim}")
    lens = _lens_of(length)
    if (D % new_dim) and (new_dim % D):
        raise ValueError("new_dim must divide or be divisible by D")
    try:  # concrete (eager) lengths: reject rows whose valid data would
        # be truncated ((len*D) % new_dim != 0); traced lengths cannot be
        # validated host-side and are the caller's contract
        bad = np.asarray((lens * D) % new_dim)
        if bad.any():
            raise ValueError(
                "sequence_reshape would drop data: per-row valid sizes "
                f"{np.asarray(lens * D).tolist()} not divisible by "
                f"new_dim={new_dim}")
    except jax.errors.TracerArrayConversionError:
        pass

    def fn(v):
        return v.reshape(B, (T * D) // new_dim, new_dim)

    out = apply_op("sequence_reshape", fn, (x,), {})
    from ..core.tensor import _wrap_data
    len_t = _wrap_data((lens * D) // new_dim)
    len_t.stop_gradient = True
    return out, len_t


def sequence_scatter(x, index, updates, length, name=None):
    """Scatter-add sequence updates into a dense tensor
    (sequence_scatter_op.h): for each row b and valid position j,
    out[index[b, j]] += updates[b, j]."""
    lens = _lens_of(length)

    def fn(xv, iv, uv):
        T = iv.shape[1]
        valid = jnp.arange(T)[None, :] < lens[:, None]
        flat_idx = jnp.where(valid, iv, 0).reshape(-1)
        flat_upd = jnp.where(valid, uv, 0).reshape(-1)
        return xv.at[flat_idx].add(flat_upd.astype(xv.dtype))

    return apply_op("sequence_scatter", fn, (x, index, updates), {})


def sequence_slice(x, length, offset, slice_length, name=None):
    """Per-row subsequence (sequence_slice_op.h): out[b] =
    x[b, offset[b] : offset[b]+slice_length[b]], padded to the input
    maxlen.  Returns (out, out_len=slice_length)."""
    offs = _lens_of(offset)
    sl = _lens_of(slice_length)
    T = int(x.shape[1])

    def fn(v):
        def row(v_b, o_b, n_b):
            start = (o_b,) + (0,) * (v_b.ndim - 1)
            shifted = jax.lax.dynamic_slice(
                jnp.pad(v_b, [(0, T)] + [(0, 0)] * (v_b.ndim - 1)),
                start, v_b.shape)
            mask = jnp.arange(T) < n_b
            mask = mask.reshape((T,) + (1,) * (v_b.ndim - 1))
            return jnp.where(mask, shifted, 0).astype(v_b.dtype)

        return jax.vmap(row)(v, offs, sl)

    out = apply_op("sequence_slice", fn, (x,), {})
    from ..core.tensor import _wrap_data
    len_t = _wrap_data(jnp.asarray(sl))
    len_t.stop_gradient = True
    return out, len_t
