"""Shape / indexing / joining ops.

Reference parity: reshape2 / transpose2 / concat / split / slice / gather /
scatter / stack / tile / expand_v2 / squeeze2 / unsqueeze2 / flatten_contiguous_range
op kernels (paddle/fluid/operators/) and python/paddle/tensor/manipulation.py.
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..core.registry import eager_op

# the public paddle.slice below shadows the builtin in this module's
# namespace; keep a handle for index construction
_pyslice = slice
from ..core.tensor import Tensor, to_tensor, _wrap_data
from ..core.dtype import convert_dtype


@eager_op("cast")
def _cast(x, dtype=None):
    return x.astype(dtype)


def cast(x, dtype):
    return _cast(x, dtype=convert_dtype(dtype))


@eager_op("reshape2")
def _reshape(x, shape=None):
    return jnp.reshape(x, shape)


def reshape(x, shape, name=None):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    shape = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape]
    # paddle semantics: 0 means copy the input dim at that position
    shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return _reshape(x, shape=tuple(shape))


@eager_op("transpose2")
def _transpose(x, perm=None):
    return jnp.transpose(x, perm)


def transpose(x, perm, name=None):
    return _transpose(x, perm=tuple(int(p) for p in perm))


@eager_op("squeeze2")
def _squeeze(x, axes=None):
    return jnp.squeeze(x, axis=axes)


def squeeze(x, axis=None, name=None):
    if axis is None:
        return _squeeze(x, axes=None)
    if isinstance(axis, int):
        axis = [axis]
    axis = tuple(a for a in axis if x.shape[a] == 1)
    if not axis:
        return x.clone()
    return _squeeze(x, axes=axis)


@eager_op("unsqueeze2")
def _unsqueeze(x, axes=None):
    return jnp.expand_dims(x, axis=axes)


def unsqueeze(x, axis, name=None):
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, int):
        axis = [axis]
    return _unsqueeze(x, axes=tuple(int(a) for a in axis))


@eager_op("flatten_contiguous_range")
def _flatten(x, start_axis=0, stop_axis=-1):
    shape = x.shape
    n = len(shape)
    sa = start_axis % n if n else 0
    so = stop_axis % n if n else 0
    new_shape = shape[:sa] + (int(np.prod(shape[sa : so + 1]) or 1),) + shape[so + 1 :]
    return jnp.reshape(x, new_shape)


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    return _flatten(x, start_axis=start_axis, stop_axis=stop_axis)


@eager_op("concat")
def _concat(*xs, axis=0):
    return jnp.concatenate(xs, axis=axis)


def concat(x, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    xs = [t if isinstance(t, Tensor) else to_tensor(t) for t in x]
    return _concat(*xs, axis=axis)


@eager_op("stack_op")
def _stack(*xs, axis=0):
    return jnp.stack(xs, axis=axis)


def stack(x, axis=0, name=None):
    xs = [t if isinstance(t, Tensor) else to_tensor(t) for t in x]
    return _stack(*xs, axis=axis)


def split(x, num_or_sections, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    axis = int(axis)
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        if dim % num_or_sections != 0:
            raise ValueError(
                f"split: axis {axis} size {dim} is not divisible by "
                f"{num_or_sections}"
            )
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s) for s in num_or_sections]
        n_neg = builtins_sum(1 for s in sizes if s < 0)
        if n_neg:
            rest = dim - builtins_sum(s for s in sizes if s >= 0)
            sizes = [rest if s < 0 else s for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1]).tolist()

    @eager_op("split_op", n_outputs=len(sizes))
    def _split(v):
        return tuple(
            jax.lax.slice_in_dim(v, o, o + s, axis=axis) for o, s in zip(offsets, sizes)
        )

    return list(_split(x))


def builtins_sum(it):
    import builtins

    return builtins.sum(it)


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis=axis)


def unbind(x, axis=0):
    return [squeeze(s, axis=[axis]) for s in split(x, x.shape[axis], axis=axis)]


@eager_op("slice_op")
def _slice(x, axes=None, starts=None, ends=None):
    idx = [_pyslice(None)] * x.ndim
    for ax, st, en in zip(axes, starts, ends):
        idx[ax] = _pyslice(st, en)
    return x[tuple(idx)]


def slice(x, axes, starts, ends):
    starts = [int(s.item()) if isinstance(s, Tensor) else int(s) for s in starts]
    ends = [int(e.item()) if isinstance(e, Tensor) else int(e) for e in ends]
    return _slice(x, axes=tuple(axes), starts=tuple(starts), ends=tuple(ends))


@eager_op("strided_slice_op")
def _strided_slice(x, axes=None, starts=None, ends=None, strides=None):
    idx = [_pyslice(None)] * x.ndim
    for ax, st, en, sd in zip(axes, starts, ends, strides):
        idx[ax] = _pyslice(st, en, sd)
    return x[tuple(idx)]


def strided_slice(x, axes, starts, ends, strides):
    return _strided_slice(
        x, axes=tuple(axes), starts=tuple(starts), ends=tuple(ends),
        strides=tuple(strides),
    )


def _norm_index(idx):
    """Convert Tensor indices inside fancy-index tuples to arrays."""
    if isinstance(idx, Tensor):
        return idx._data
    if isinstance(idx, tuple):
        return tuple(_norm_index(i) for i in idx)
    if isinstance(idx, list):
        return [_norm_index(i) for i in idx]
    return idx


def getitem(x, idx):
    nidx = _norm_index(idx)

    @eager_op("getitem_op")
    def _get(v):
        return v[nidx]

    return _get(x)


@eager_op("tile_op")
def _tile(x, repeat_times=None):
    return jnp.tile(x, repeat_times)


def tile(x, repeat_times, name=None):
    if isinstance(repeat_times, Tensor):
        repeat_times = repeat_times.tolist()
    return _tile(x, repeat_times=tuple(int(r) for r in repeat_times))


@eager_op("expand_v2")
def _expand(x, shape=None):
    target = list(shape)
    nd = len(target)
    xshape = (1,) * (nd - x.ndim) + x.shape
    target = [xs if t in (-1, None) else t for t, xs in zip(target, xshape)]
    return jnp.broadcast_to(jnp.reshape(x, xshape), tuple(target))


def expand(x, shape, name=None):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    return _expand(x, shape=tuple(int(s.item()) if isinstance(s, Tensor) else int(s) for s in shape))


def expand_as(x, y, name=None):
    return expand(x, y.shape)


def broadcast_to(x, shape, name=None):
    return expand(x, shape)


def broadcast_tensors(inputs):
    shape = jnp.broadcast_shapes(*[tuple(t.shape) for t in inputs])
    return [expand(t, list(shape)) for t in inputs]


@eager_op("flip_op")
def _flip(x, axis=None):
    return jnp.flip(x, axis=axis)


def flip(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    return _flip(x, axis=tuple(axis))


@eager_op("roll_op")
def _roll(x, shifts=None, axis=None):
    return jnp.roll(x, shifts, axis=axis)


def roll(x, shifts, axis=None, name=None):
    if isinstance(shifts, Tensor):
        shifts = shifts.tolist()
    return _roll(
        x,
        shifts=tuple(shifts) if isinstance(shifts, (list, tuple)) else int(shifts),
        axis=tuple(axis) if isinstance(axis, (list, tuple)) else axis,
    )


@eager_op("gather_op")
def _gather(x, index, axis=0):
    return jnp.take(x, index, axis=axis)


def gather(x, index, axis=0, name=None):
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)
    if idx.ndim == 2 and idx.shape[1] == 1:
        idx = idx[:, 0]

    @eager_op("gather_op")
    def _g(v):
        return jnp.take(v, idx, axis=int(axis))

    return _g(x)


def gather_nd(x, index, name=None):
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)

    @eager_op("gather_nd_op")
    def _g(v):
        return v[tuple(jnp.moveaxis(idx, -1, 0))]

    return _g(x)


def index_select(x, index, axis=0, name=None):
    return gather(x, index, axis=axis)


def index_sample(x, index):
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)

    @eager_op("index_sample_op")
    def _g(v):
        return jnp.take_along_axis(v, idx, axis=1)

    return _g(x)


def take_along_axis(x, indices, axis):
    idx = indices._data if isinstance(indices, Tensor) else jnp.asarray(indices)

    @eager_op("take_along_axis_op")
    def _g(v):
        return jnp.take_along_axis(v, idx, axis=axis)

    return _g(x)


def scatter(x, index, updates, overwrite=True, name=None):
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)
    if idx.ndim == 2 and idx.shape[1] == 1:
        idx = idx[:, 0]

    @eager_op("scatter_op")
    def _s(v, u):
        if overwrite:
            return v.at[idx].set(u)
        return v.at[idx].set(jnp.zeros_like(u)).at[idx].add(u)

    return _s(x, updates)


def scatter_nd_add(x, index, updates, name=None):
    idx = index._data if isinstance(index, Tensor) else jnp.asarray(index)

    @eager_op("scatter_nd_add_op")
    def _s(v, u):
        return v.at[tuple(jnp.moveaxis(idx, -1, 0))].add(u)

    return _s(x, updates)


def put_along_axis(x, indices, values, axis, reduce="assign"):
    idx = indices._data if isinstance(indices, Tensor) else jnp.asarray(indices)

    @eager_op("put_along_axis_op")
    def _s(v, u):
        u = jnp.broadcast_to(u, idx.shape) if jnp.ndim(u) else jnp.full(idx.shape, u)
        dims = jnp.meshgrid(*[jnp.arange(s) for s in idx.shape], indexing="ij")
        dims[axis] = idx
        if reduce == "add":
            return v.at[tuple(dims)].add(u)
        return v.at[tuple(dims)].set(u)

    vals = values if isinstance(values, Tensor) else to_tensor(values)
    return _s(x, vals)


@eager_op("pad_op")
def _pad(x, paddings=None, mode="constant", value=0.0):
    if mode == "constant":
        return jnp.pad(x, paddings, mode="constant", constant_values=value)
    jmode = {"reflect": "reflect", "replicate": "edge", "circular": "wrap"}[mode]
    return jnp.pad(x, paddings, mode=jmode)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):
    if isinstance(pad, Tensor):
        pad = pad.tolist()
    pad = [int(p) for p in pad]
    nd = x.ndim
    if len(pad) == 2 * nd:
        paddings = tuple((pad[2 * i], pad[2 * i + 1]) for i in range(nd))
    else:
        # paddle convention: pairs are last-spatial-dim-first — for NCHW,
        # pad=[left,right,top,bottom] applies (left,right) to W then
        # (top,bottom) to H.  Build pairs then reverse onto the spatial dims.
        n_spatial = len(pad) // 2
        pairs = [(pad[2 * i], pad[2 * i + 1]) for i in range(n_spatial)]
        pairs.reverse()  # now ordered outer spatial dim .. inner (H then W)
        if data_format.endswith("C"):  # NHWC / NLC / NDHWC: spatial before channel
            lead = nd - n_spatial - 1
            paddings = [(0, 0)] * lead + pairs + [(0, 0)]
        else:
            lead = nd - n_spatial
            paddings = [(0, 0)] * lead + pairs
        paddings = tuple(paddings)
    return _pad(x, paddings=paddings, mode=mode, value=value)


@eager_op("shard_index_op")
def _shard_index(x, index_num, nshards, shard_id, ignore_value):
    shard_size = (index_num + nshards - 1) // nshards
    in_shard = (x // shard_size) == shard_id
    return jnp.where(in_shard, x % shard_size, ignore_value)


def shard_index(x, index_num, nshards, shard_id, ignore_value=-1):
    return _shard_index(x, index_num, nshards, shard_id, ignore_value)


def one_hot(x, num_classes, name=None):
    idx = x._data if isinstance(x, Tensor) else jnp.asarray(x)
    return _wrap_data(jax.nn.one_hot(idx, num_classes, dtype=jnp.float32))


def unstack(x, axis=0, num=None):
    return unbind(x, axis=axis)


def rot90(x, k=1, axes=(0, 1)):
    @eager_op("rot90_op")
    def _r(v):
        return jnp.rot90(v, k=k, axes=axes)

    return _r(x)


def moveaxis(x, source, destination):
    @eager_op("moveaxis_op")
    def _m(v):
        return jnp.moveaxis(v, source, destination)

    return _m(x)


def swapaxes(x, axis1, axis2):
    perm = list(range(x.ndim))
    perm[axis1], perm[axis2] = perm[axis2], perm[axis1]
    return transpose(x, perm)


def as_complex(x):
    @eager_op("as_complex_op")
    def _c(v):
        return jax.lax.complex(v[..., 0], v[..., 1])

    return _c(x)


def as_real(x):
    @eager_op("as_real_op")
    def _r(v):
        return jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1)

    return _r(x)


def repeat_interleave(x, repeats, axis=None):
    @eager_op("repeat_interleave_op")
    def _r(v):
        return jnp.repeat(v, repeats, axis=axis)

    return _r(x)
