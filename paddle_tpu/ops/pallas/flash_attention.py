"""Flash attention as Pallas TPU kernels (fwd + blockwise bwd, custom_vjp).

Role in the framework: the training-time fused attention path.  The reference
has no training flash kernel (its fused attention, operators/fused/
multihead_matmul_op.cu, is inference-only and materializes the full score
matrix); this kernel is the TPU-native upgrade: O(L) memory via online
softmax, blocks sized to the MXU/VMEM, f32 accumulation over bf16 inputs.

Layout: q,k,v are [B, H, L, D], flattened to [B*H, L, D] for the kernels.
Grid iteration (TPU grids run sequentially, last axis innermost) carries the
online-softmax state (m, l, acc) in VMEM scratch across the K-block axis.

Supported in-kernel: causal masking and a key padding mask [B, Lk] (additive,
0/-inf semantics).  Full [B, H, Lq, Lk] masks fall back to the XLA composite
in ops/attention.py.
"""
import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _interpret():
    return jax.default_backend() == "cpu"


_DEFAULT_BLOCK = 512  # swept on v5e: 512 beats 128 ~2x (fewer grid steps)


def _choose_block(n):
    # Tile-legal by construction: a 128-multiple block, or one block spanning
    # the whole axis (a block equal to the array dim is always legal, even
    # when the dim is not an (8,128) multiple — Mosaic pads it).  Reads
    # _DEFAULT_BLOCK at call time so tests/benches can override it.
    for b in (_DEFAULT_BLOCK, 256, 128):
        if b <= _DEFAULT_BLOCK and n % b == 0:
            return b
    return n


def _check_mosaic_specs(specs, shapes, where):
    """Static Mosaic tiling check, run on EVERY backend (so interpret-mode
    CPU tests cannot mask a violation the real TPU lowering would reject).

    Rule (f32-class dtypes): for rank>=2 blocks, the last block dim must be
    a multiple of 128 or equal to the full array dim, and the second-to-last
    a multiple of 8 or equal to the full array dim.  This is the check that
    round-4's lse out_spec (1, block_q) over (bh, lq) failed on hardware.
    """
    for idx, (spec, shape) in enumerate(zip(specs, shapes)):
        blk = spec.block_shape
        if blk is None or len(blk) < 2:
            continue
        ok_last = blk[-1] % 128 == 0 or blk[-1] == shape[-1]
        ok_sub = blk[-2] % 8 == 0 or blk[-2] == shape[-2]
        if not (ok_last and ok_sub):
            raise ValueError(
                f"flash_attention {where}[{idx}]: block {tuple(blk)} over "
                f"array {tuple(shape)} violates Mosaic (8,128) tiling")


def _causal_mask(s, qb, kb, block_q, block_k, offset):
    # query row i may see key j iff j <= i + offset, offset = Lk - Lq —
    # matching the composite path's tril(k=Lk-Lq) (KV-cache decoding shape)
    rows = qb * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    cols = kb * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    return jnp.where(rows + offset >= cols, s, NEG_INF)


def _causal_block_runs(qb, kb, block_q, block_k, offset):
    # K-block overlaps the allowed region iff its first key index is <= the
    # last query row's limit
    return kb * block_k <= (qb + 1) * block_q - 1 + offset


# ------------------------------ forward ---------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, kmask_ref, o_ref, lse_ref,
                acc_ref, m_ref, l_ref, *, causal, block_q, block_k,
                n_kb, have_mask, offset):
    # m/l scratch are (block_q, 128) with every lane holding the row value
    # (broadcast-write, max-read): full-width vector ops only, no strided
    # single-lane stores, matching the Mosaic-proven layout.
    qb = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # a K-block strictly above the causal diagonal contributes nothing
    run = _causal_block_runs(qb, kb, block_q, block_k, offset) if causal else True

    @pl.when(run)
    def _compute():
        # matmuls run in the NATIVE input dtype with f32 accumulation: the
        # MXU takes bf16 operands at full rate, while pre-casting to f32
        # forces multi-pass f32 matmuls (~3x slower, measured on v5e)
        q = q_ref[0]                               # [block_q, d]
        k = k_ref[0]                               # [block_k, d]
        v = v_ref[0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if have_mask:
            s = s + kmask_ref[0, 0].astype(jnp.float32)[None, :]
        if causal:
            s = _causal_mask(s, qb, kb, block_q, block_k, offset)

        m_prev = jnp.max(m_ref[...], axis=1, keepdims=True)   # [block_q, 1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)            # rescale of old state
        p = jnp.exp(s - m_cur)                     # [block_q, block_k]
        # fully-masked rows saturate at s == m_cur == NEG_INF, where exp(0)
        # would leak weight 1 per key; re-mask so l stays 0 for them
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)
        l_prev = jnp.max(l_ref[...], axis=1, keepdims=True)
        l_cur = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        l_ref[...] = jnp.broadcast_to(l_cur, l_ref.shape)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = jnp.broadcast_to(m_cur, m_ref.shape)

    @pl.when(kb == n_kb - 1)
    def _finalize():
        l = jnp.max(l_ref[...], axis=1, keepdims=True)        # [block_q, 1]
        # fully-masked rows (padding): emit zeros, lse -> NEG_INF
        safe_l = jnp.where(l > 0.0, l, 1.0)
        o_ref[0] = (acc_ref[...] / safe_l).astype(o_ref.dtype)
        m_fin = jnp.max(m_ref[...], axis=1, keepdims=True)
        lse_ref[0] = jnp.where(l > 0.0, m_fin + jnp.log(safe_l), NEG_INF)


def _flash_fwd_call(qs, k, v, km, causal, heads, have_mask):
    # km is [Bm, 1, Lk] (Bm = batch or 1): the middle singleton keeps every
    # 2-D-per-row operand rank-3 so its (1, 1, block) BlockSpec is Mosaic
    # tile-legal regardless of the leading dim (round-4 TPU crash class).
    bh, lq, d = qs.shape
    _, lk, _ = k.shape
    block_q, block_k = _choose_block(lq), _choose_block(lk)
    n_qb, n_kb = lq // block_q, lk // block_k

    km_index = (lambda b, i, j: (b // heads, 0, j)) if have_mask else (
        lambda b, i, j: (0, 0, j))
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, 1, block_k), km_index),
    ]
    out_specs = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
    ]
    out_shape = [
        jax.ShapeDtypeStruct((bh, lq, d), qs.dtype),
        jax.ShapeDtypeStruct((bh, lq, 1), jnp.float32),
    ]
    _check_mosaic_specs(in_specs, [a.shape for a in (qs, k, v, km)], "in")
    _check_mosaic_specs(out_specs, [s.shape for s in out_shape], "out")
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, causal=causal, block_q=block_q,
                          block_k=block_k, n_kb=n_kb, have_mask=have_mask,
                          offset=lk - lq),
        grid=(bh, n_qb, n_kb),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
            pltpu.VMEM((block_q, 128), jnp.float32),
        ],
        interpret=_interpret(),
    )(qs, k, v, km)
    return out, lse


# ------------------------------ backward --------------------------------


def _bwd_dkdv_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                     kmask_ref, dk_ref, dv_ref, dk_acc, dv_acc, *,
                     causal, block_q, block_k, n_qb, have_mask, offset):
    kb = pl.program_id(1)
    qb = pl.program_id(2)

    @pl.when(qb == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    run = _causal_block_runs(qb, kb, block_q, block_k, offset) if causal else True

    @pl.when(run)
    def _compute():
        # native-dtype matmul operands, f32 accumulation (see fwd kernel)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]                           # [block_q, 1]
        delta = delta_ref[0]                       # [block_q, 1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if have_mask:
            s = s + kmask_ref[0, 0].astype(jnp.float32)[None, :]
        if causal:
            s = _causal_mask(s, qb, kb, block_q, block_k, offset)
        p = jnp.exp(s - lse)                       # [block_q, block_k]
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)    # see fwd kernel note
        dv_acc[...] += jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dk_acc[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qb == n_qb - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _bwd_dq_kernel(q_ref, do_ref, lse_ref, delta_ref, k_ref, v_ref,
                   kmask_ref, dq_ref, dq_acc, *, causal, block_q, block_k,
                   n_kb, have_mask, offset):
    qb = pl.program_id(1)
    kb = pl.program_id(2)

    @pl.when(kb == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    run = _causal_block_runs(qb, kb, block_q, block_k, offset) if causal else True

    @pl.when(run)
    def _compute():
        # native-dtype matmul operands, f32 accumulation (see fwd kernel)
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        do = do_ref[0]
        lse = lse_ref[0]                           # [block_q, 1]
        delta = delta_ref[0]                       # [block_q, 1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        if have_mask:
            s = s + kmask_ref[0, 0].astype(jnp.float32)[None, :]
        if causal:
            s = _causal_mask(s, qb, kb, block_q, block_k, offset)
        p = jnp.exp(s - lse)
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)    # see fwd kernel note
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta)
        dq_acc[...] += jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(kb == n_kb - 1)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd_call(qs, k, v, km, out, lse, do, causal, heads, have_mask):
    # lse/delta ride as [bh, Lq, 1] columns and km as [Bm, 1, Lk] rows so
    # every BlockSpec satisfies Mosaic's (8, 128) tiling (see fwd call).
    bh, lq, d = qs.shape
    _, lk, _ = k.shape
    block_q, block_k = _choose_block(lq), _choose_block(lk)
    n_qb, n_kb = lq // block_q, lk // block_k
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1, keepdims=True)

    km_idx_kq = (lambda b, j, i: (b // heads, 0, j)) if have_mask else (
        lambda b, j, i: (0, 0, j))
    in_specs_kq = [
        pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, 1, block_k), km_idx_kq),
    ]
    out_specs_kq = [
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
    ]
    operands = (qs, do, lse, delta, k, v, km)
    _check_mosaic_specs(in_specs_kq, [a.shape for a in operands], "bwd-in")
    _check_mosaic_specs(out_specs_kq, [k.shape, v.shape], "bwd-out")
    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkdv_kernel, causal=causal, block_q=block_q,
                          block_k=block_k, n_qb=n_qb, have_mask=have_mask,
                          offset=lk - lq),
        grid=(bh, n_kb, n_qb),
        in_specs=in_specs_kq,
        out_specs=out_specs_kq,
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=_interpret(),
    )(*operands)

    km_idx_qk = (lambda b, i, j: (b // heads, 0, j)) if have_mask else (
        lambda b, i, j: (0, 0, j))
    in_specs_qk = [
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        pl.BlockSpec((1, 1, block_k), km_idx_qk),
    ]
    _check_mosaic_specs(in_specs_qk, [a.shape for a in operands], "bwd-in")
    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, causal=causal, block_q=block_q,
                          block_k=block_k, n_kb=n_kb, have_mask=have_mask,
                          offset=lk - lq),
        grid=(bh, n_qb, n_kb),
        in_specs=in_specs_qk,
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct(qs.shape, qs.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=_interpret(),
    )(*operands)
    return dq, dk, dv


# --------------------------- custom_vjp glue ----------------------------
# km is always a materialized array (zeros placeholder when no mask) so the
# nondiff argnums stay hashable python values.


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _flash(qs, k, v, km, causal, heads, have_mask):
    out, _ = _flash_fwd_call(qs, k, v, km, causal, heads, have_mask)
    return out


def _flash_fwd_rule(qs, k, v, km, causal, heads, have_mask):
    out, lse = _flash_fwd_call(qs, k, v, km, causal, heads, have_mask)
    return out, (qs, k, v, km, out, lse)


def _flash_bwd_rule(causal, heads, have_mask, res, do):
    qs, k, v, km, out, lse = res
    dq, dk, dv = _flash_bwd_call(qs, k, v, km, out, lse, do, causal, heads,
                                 have_mask)
    return dq, dk, dv, jnp.zeros_like(km)


_flash.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# --------------------------- public entry -------------------------------


def flash_attention(q, k, v, attn_mask=None, causal=False):
    """q,k,v: Tensor or array [B, H, L, D].  attn_mask: None or an additive
    mask whose non-trivial axes are batch and key (shapes [B,1,1,Lk] /
    [B,Lk] / [1,1,1,Lk]); richer masks must use the XLA composite path
    (see mask_is_flash_compatible)."""
    from ...core.registry import apply_op

    def fn(qv, kv, vv, *mask):
        b, h, lq, dh = qv.shape
        lk = kv.shape[2]
        scale = 1.0 / math.sqrt(dh)
        # fold the scale into q: s = (q*scale) @ k^T everywhere, so the vjp
        # of the fold handles dq's scale automatically
        qs = (qv * scale).reshape(b * h, lq, dh)
        kf = kv.reshape(b * h, lk, dh)
        vf = vv.reshape(b * h, lk, dh)
        have_mask = bool(mask)
        if have_mask:
            m = mask[0]
            km = jnp.broadcast_to(
                m, (b,) + tuple(m.shape[1:])).reshape(b, -1)
            km = km[:, -lk:].astype(jnp.float32).reshape(b, 1, lk)
        else:
            km = jnp.zeros((1, 1, lk), jnp.float32)
        out = _flash(qs, kf, vf, km, causal, h, have_mask)
        return out.reshape(b, h, lq, dh)

    args = (q, k, v) + ((attn_mask,) if attn_mask is not None else ())
    return apply_op("flash_attention", fn, args, {})


def shapes_are_flash_compatible(lq, lk, d=None):
    """Shapes the kernel handles within VMEM: non-128-multiple axes run as
    one full-axis block, so bound what the kernel would actually resident —
    the f32 score block (block_q x block_k) plus, when the head dim is
    known, the d-dependent blocks: q/out/acc (block_q x d), k/v and the
    backward's dk/dv scratch (block_k x d), and the online-softmax state
    (block_q x 128 x 2), all f32 and doubled for Mosaic's input
    double-buffering.  The combined budget is half of a v5e core's ~16 MB
    VMEM; large-d shapes that blow it fall back to the composite path
    instead of over-allocating VMEM at compile time."""
    bq, bk = _choose_block(lq), _choose_block(lk)
    score = bq * bk * 4
    if d is None:
        # legacy seq-only bound: 4 MB leaves room for typical (d<=128)
        # q/k/v blocks and scratch
        return score <= 4 * 1024 * 1024
    d_blocks = 4 * (3 * bq * d + 4 * bk * d + 2 * bq * 128) * 2
    return score + d_blocks <= 8 * 1024 * 1024


def mask_is_flash_compatible(attn_mask):
    """True when the mask varies only along batch and key axes: None or
    4-D [B|1, 1, 1, Lk].  2-D masks are ambiguous under the sdp contract
    ([Lq, Lk] broadcast) — those take the composite path."""
    if attn_mask is None:
        return True
    shape = tuple(attn_mask.shape)
    return len(shape) == 4 and shape[1] == 1 and shape[2] == 1
