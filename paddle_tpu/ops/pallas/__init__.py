"""Pallas TPU kernels for the hot ops.

The reference ships fused CUDA kernels under operators/fused/ (e.g.
multihead_matmul_op.cu, fused_attention) — here the fused fast path is
written in Pallas against the TPU memory hierarchy (HBM -> VMEM -> MXU),
with interpret-mode execution on CPU so tests run anywhere.
"""
from . import flash_attention as flash_attention_kernels  # noqa: F401
from .flash_attention import flash_attention  # noqa: F401
from .paged_attention import paged_decode_attention_kernel  # noqa: F401
