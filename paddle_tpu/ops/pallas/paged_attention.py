"""Paged decode attention (Lq == 1) as a Pallas TPU kernel.

The decode-side counterpart of flash_attention.py: one query token per
sequence attends over a paged KV cache (Ragged Paged Attention, arxiv
2604.15464).  The kernel never materializes a per-sequence contiguous KV
copy — the page table rides in as a scalar-prefetch operand and the
BlockSpec index_map DMAs each sequence's pages straight out of the pool:

    grid = (B, H, max_pages)          # pages innermost, sequential
    k block = pool_t[h, page_table[b, i]]       # [1, 1, page_size, D]

Online softmax state (m, l, acc) lives in VMEM scratch across the page
axis exactly like the flash forward kernel.  Pages past a sequence's
length are skipped via @pl.when on the prefetched seq_lens (ragged
sequences pay for the pages they own, not the batch max); the page table
pads unused slots with page 0, which is always a valid DMA target.

Layouts are chosen Mosaic tile-legal by construction: pools transpose to
[H, P, page_size, D] so every block's trailing two dims are full array
dims (page_size, D); q/out ride as [B, H, 1, D] with (1, 1, 1, D) blocks.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import NEG_INF, _interpret

_STATE_ROWS = 8  # scratch rows; every row holds the same value so all
# scratch traffic is full-width vector ops (the Mosaic-proven layout)


def _reject_mesh_sharded_pool(pool):
    """Loud failure over silent corruption: a Pallas kernel is a
    single-device program — handed a pool committed to a multi-device
    NamedSharding (the tensor-parallel generation mesh), pallas_call
    would either fail opaquely or compute over one shard as if it were
    the whole pool.  The sharded engine routes around the kernels (the
    jnp references ARE GSPMD-partitionable; engine.py forces
    use_kernel=False under a mesh); this guard catches direct callers.
    Tracers (pools inside a jit trace) pass through untouched — the
    in-trace caller's own sharding machinery governs there."""
    try:
        sharding = getattr(pool, "sharding", None)
    except Exception:
        return  # tracer without a committed sharding: not our problem
    from jax.sharding import NamedSharding

    if (isinstance(sharding, NamedSharding)
            and len(sharding.device_set) > 1):
        raise NotImplementedError(
            "Pallas paged attention over a mesh-sharded KV pool is not "
            "supported: the kernel is a single-device program (a "
            "shard_map'd variant is the tracked follow-on, ROADMAP).  "
            "Use the jnp reference path (use_kernel=False) — GSPMD "
            "partitions it over the head axis.")


def _decode_kernel(pt_ref, sl_ref, q_ref, k_ref, v_ref, o_ref,
                   acc_ref, m_ref, l_ref, *, page_size, n_pages):
    b = pl.program_id(0)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    seq_len = sl_ref[b]
    # page i covers positions [i*page_size, (i+1)*page_size): it runs iff
    # its first position is live; later positions are masked below
    @pl.when(i * page_size < seq_len)
    def _compute():
        q = q_ref[0, 0]                            # [1, D] (scale folded)
        k = k_ref[0, 0]                            # [page_size, D]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        pos = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        s = jnp.where(pos < seq_len, s, NEG_INF)   # ragged tail of page
        m_prev = jnp.max(m_ref[...])
        m_cur = jnp.maximum(m_prev, jnp.max(s))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)                     # [1, page_size]
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)    # masked rows: exactly 0
        l_cur = jnp.max(l_ref[...]) * alpha + jnp.sum(p)
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha + jnp.broadcast_to(
            pv, acc_ref.shape)
        m_ref[...] = jnp.full_like(m_ref, m_cur)
        l_ref[...] = jnp.full_like(l_ref, l_cur)

    @pl.when(i == n_pages - 1)
    def _finalize():
        l = jnp.max(l_ref[...])
        safe_l = jnp.where(l > 0.0, l, 1.0)        # empty sequence: zeros
        o_ref[0, 0] = (acc_ref[...] / safe_l)[0:1].astype(o_ref.dtype)


def _chunk_kernel(pt_ref, info_ref, q_ref, k_ref, v_ref, o_ref,
                  acc_ref, m_ref, l_ref, *, page_size, n_pages, n_rows):
    """Chunked-prefill attention for ONE sequence: n_rows chunk queries
    (query row r at global position start + r) attend over every key the
    page table holds — the already-written prefix AND the chunk's own
    freshly scattered keys — with a per-row causal mask.  Online-softmax
    state is [n_rows, ...] (the decode kernel's, grown from 1 query row
    to the chunk), accumulated across the page axis."""
    i = pl.program_id(1)
    start = info_ref[0]

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # page i covers positions [i*page_size, (i+1)*page_size): it runs iff
    # its first position is visible to SOME query (the last row sees the
    # most: positions <= start + n_rows - 1)
    @pl.when(i * page_size <= start + n_rows - 1)
    def _compute():
        q = q_ref[0]                               # [n_rows, D]
        k = k_ref[0, 0]                            # [page_size, D]
        v = v_ref[0, 0]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        pos = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (n_rows, page_size), 1)
        qpos = start + jax.lax.broadcasted_iota(
            jnp.int32, (n_rows, page_size), 0)
        s = jnp.where(pos <= qpos, s, NEG_INF)     # causal, per query row
        m_prev = jnp.max(m_ref[...], axis=1, keepdims=True)   # [n, 1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)                     # [n, page_size]
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)    # masked keys: exactly 0
        l_prev = jnp.max(l_ref[...], axis=1, keepdims=True)
        l_cur = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_cur, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_cur, l_ref.shape)

    @pl.when(i == n_pages - 1)
    def _finalize():
        l = jnp.max(l_ref[...], axis=1, keepdims=True)
        safe_l = jnp.where(l > 0.0, l, 1.0)  # fully masked pad rows
        o_ref[0] = (acc_ref[...] / safe_l).astype(o_ref.dtype)


def _ragged_kernel(pt_ref, st_ref, ln_ref, kv_ref, q_ref, k_ref, v_ref,
                   o_ref, acc_ref, m_ref, l_ref, *, page_size, n_pages,
                   n_seqs, n_rows):
    """RAGGED mixed-batch paged attention: `n_rows` packed query rows
    (decode singletons AND prefill-chunk runs in one token axis) attend
    through per-descriptor page tables.  Descriptor s owns packed rows
    [st_ref[s], st_ref[s] + ln_ref[s]); row r of s sits at global
    position kv_ref[s] - ln_ref[s] + (r - st_ref[s]) and sees keys
    [0, position].  The grid walks (head, descriptor, page) with online-
    softmax state [n_rows, ...] persisting across BOTH the page and the
    descriptor axes: a descriptor's pages update only its own rows —
    foreign rows see an all-NEG_INF score block, whose update is the
    exact identity (alpha == exp(0) == 1, sum(p) == 0) — so one state
    accumulation serves the whole ragged batch.  Descriptors with
    ln == 0 (padding) and pages past kv_len are skipped entirely."""
    s = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when((s == 0) & (i == 0))
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    start = st_ref[s]
    ln = ln_ref[s]
    kv_len = kv_ref[s]

    # page i of descriptor s runs iff the descriptor is live and the
    # page holds at least one resident key
    @pl.when((ln > 0) & (i * page_size < kv_len))
    def _compute():
        q = q_ref[0]                               # [n_rows, D]
        k = k_ref[0, 0]                            # [page_size, D]
        v = v_ref[0, 0]
        sc = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        row = jax.lax.broadcasted_iota(jnp.int32, (n_rows, page_size), 0)
        col = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (n_rows, page_size), 1)
        mine = (row >= start) & (row < start + ln)
        qpos = kv_len - ln + (row - start)
        sc = jnp.where(mine & (col <= qpos), sc, NEG_INF)
        m_prev = jnp.max(m_ref[...], axis=1, keepdims=True)   # [n, 1]
        m_cur = jnp.maximum(m_prev, jnp.max(sc, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(sc - m_cur)                    # [n, page_size]
        p = jnp.where(sc <= NEG_INF / 2, 0.0, p)   # masked keys: exactly 0
        l_prev = jnp.max(l_ref[...], axis=1, keepdims=True)
        l_cur = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_cur, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_cur, l_ref.shape)

    @pl.when((s == n_seqs - 1) & (i == n_pages - 1))
    def _finalize():
        l = jnp.max(l_ref[...], axis=1, keepdims=True)
        safe_l = jnp.where(l > 0.0, l, 1.0)  # unclaimed rows: zeros
        o_ref[0] = (acc_ref[...] / safe_l).astype(o_ref.dtype)


def ragged_paged_attention_kernel(q, k_pool, v_pool, page_tables, starts,
                                  lens, kv_lens, scale, interpret=None,
                                  layout="token"):
    """q: [T, H, D] — the step's PACKED query rows (decode rows and the
    prefill chunk in one ragged token axis; rows owned by no descriptor
    come back 0).  k_pool/v_pool: one layer's pool, the chunk's and the
    decode tokens' K/V already scattered — [P, page_size, H, D]
    (layout="token") or [H, P, page_size, D] (layout="kernel").
    page_tables: [S, max_pages] int32 (pad with 0).  starts/lens/
    kv_lens: [S] int32 descriptors (lens == 0 marks padding
    descriptors; all three ride as scalar-prefetch operands so the
    BlockSpec index_map DMAs each descriptor's pages straight out of
    the pool).  Returns [T, H, D].

    Layout handling mirrors the decode kernel: token-layout pools are
    transposed per call, kernel-layout pools are consumed as stored."""
    _reject_mesh_sharded_pool(k_pool)
    t, h, d = q.shape
    qs = jnp.transpose((q * scale).astype(q.dtype), (1, 0, 2))  # [H, T, D]
    if layout == "kernel":
        page_size = k_pool.shape[2]
        kt, vt = k_pool, v_pool          # stored kernel-ready: no copy
    else:
        page_size = k_pool.shape[1]
        kt = jnp.transpose(k_pool, (2, 0, 1, 3))
        vt = jnp.transpose(v_pool, (2, 0, 1, 3))
    n_seqs, n_pages = page_tables.shape

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(h, n_seqs, n_pages),
        in_specs=[
            pl.BlockSpec((1, t, d), lambda h_, s, i, pt, st, ln, kv:
                         (h_, 0, 0)),
            pl.BlockSpec((1, 1, page_size, d),
                         lambda h_, s, i, pt, st, ln, kv:
                         (h_, pt[s, i], 0, 0)),
            pl.BlockSpec((1, 1, page_size, d),
                         lambda h_, s, i, pt, st, ln, kv:
                         (h_, pt[s, i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, t, d), lambda h_, s, i, pt, st, ln, kv:
                               (h_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((t, d), jnp.float32),
            pltpu.VMEM((t, 128), jnp.float32),
            pltpu.VMEM((t, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_ragged_kernel, page_size=page_size,
                          n_pages=n_pages, n_seqs=n_seqs, n_rows=t),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((h, t, d), q.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(jnp.asarray(page_tables, jnp.int32), jnp.asarray(starts, jnp.int32),
      jnp.asarray(lens, jnp.int32), jnp.asarray(kv_lens, jnp.int32),
      qs, kt, vt)
    return jnp.transpose(out, (1, 0, 2))


def chunk_prefill_attention_kernel(q, k_pool, v_pool, page_table, start,
                                   scale, interpret=None, layout="token"):
    """q: [n, H, D] — one sequence's prefill-chunk queries (row r at
    global position start + r; rows past the real chunk length are
    bucket padding whose output the caller discards).  k_pool/v_pool:
    one layer's pool, already holding the chunk's scattered K/V —
    [P, page_size, H, D] (layout="token") or [H, P, page_size, D]
    (layout="kernel").  page_table: [max_pages] int32 (pad with 0).
    start: int32 scalar (traced OK — rides as a scalar-prefetch
    operand).  Returns [n, H, D].

    Same layout reasoning as the decode kernel: token-layout pools are
    transposed per call, kernel-layout pools are consumed as stored."""
    _reject_mesh_sharded_pool(k_pool)
    n, h, d = q.shape
    qs = jnp.transpose((q * scale).astype(q.dtype), (1, 0, 2))  # [H, n, D]
    if layout == "kernel":
        page_size = k_pool.shape[2]
        kt, vt = k_pool, v_pool
    else:
        page_size = k_pool.shape[1]
        kt = jnp.transpose(k_pool, (2, 0, 1, 3))
        vt = jnp.transpose(v_pool, (2, 0, 1, 3))
    n_pages = page_table.shape[0]
    info = jnp.asarray(start, jnp.int32).reshape(1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(h, n_pages),
        in_specs=[
            pl.BlockSpec((1, n, d), lambda h_, i, pt, nfo: (h_, 0, 0)),
            pl.BlockSpec((1, 1, page_size, d), lambda h_, i, pt, nfo:
                         (h_, pt[i], 0, 0)),
            pl.BlockSpec((1, 1, page_size, d), lambda h_, i, pt, nfo:
                         (h_, pt[i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, n, d), lambda h_, i, pt, nfo:
                               (h_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((n, d), jnp.float32),
            pltpu.VMEM((n, 128), jnp.float32),
            pltpu.VMEM((n, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_chunk_kernel, page_size=page_size,
                          n_pages=n_pages, n_rows=n),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((h, n, d), q.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(jnp.asarray(page_table, jnp.int32), info, qs, kt, vt)
    return jnp.transpose(out, (1, 0, 2))


def paged_decode_attention_kernel(q, k_pool, v_pool, page_tables, seq_lens,
                                  scale, interpret=None, layout="token"):
    """q: [B, H, D].  k_pool/v_pool: one layer's pool —
    [P, page_size, H, D] (layout="token") or [H, P, page_size, D]
    (layout="kernel", DeviceKVPool's kernel-layout storage).
    page_tables: [B, max_pages] int32 (pad with 0).  seq_lens: [B] int32.
    Returns [B, H, D] attention output.

    The kernel itself always consumes [H, P, page_size, D].  Token-layout
    pools are transposed here per call — O(pool) HBM traffic per layer
    per step, which is exactly why kernel-layout pools exist: scattering
    into [H, P, page_size, D] on write makes this call transpose-free."""
    _reject_mesh_sharded_pool(k_pool)
    b, h, d = q.shape
    qs = (q * scale).astype(q.dtype).reshape(b, h, 1, d)
    if layout == "kernel":
        page_size = k_pool.shape[2]
        kt, vt = k_pool, v_pool          # stored kernel-ready: no copy
    else:
        page_size = k_pool.shape[1]
        # [P, ps, H, D] -> [H, P, ps, D]: trailing block dims full dims
        kt = jnp.transpose(k_pool, (2, 0, 1, 3))
        vt = jnp.transpose(v_pool, (2, 0, 1, 3))
    n_pages = page_tables.shape[1]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, h, n_pages),
        in_specs=[
            pl.BlockSpec((1, 1, 1, d), lambda b_, h_, i, pt, sl:
                         (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, page_size, d), lambda b_, h_, i, pt, sl:
                         (h_, pt[b_, i], 0, 0)),
            pl.BlockSpec((1, 1, page_size, d), lambda b_, h_, i, pt, sl:
                         (h_, pt[b_, i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, 1, d), lambda b_, h_, i, pt, sl:
                               (b_, h_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((_STATE_ROWS, d), jnp.float32),
            pltpu.VMEM((_STATE_ROWS, 128), jnp.float32),
            pltpu.VMEM((_STATE_ROWS, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, page_size=page_size,
                          n_pages=n_pages),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, 1, d), q.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(jnp.asarray(page_tables, jnp.int32), jnp.asarray(seq_lens, jnp.int32),
      qs, kt, vt)
    return out.reshape(b, h, d)
