"""Paged decode attention (Lq == 1) as a Pallas TPU kernel.

The decode-side counterpart of flash_attention.py: one query token per
sequence attends over a paged KV cache (Ragged Paged Attention, arxiv
2604.15464).  The kernel never materializes a per-sequence contiguous KV
copy — the page table rides in as a scalar-prefetch operand and the
BlockSpec index_map DMAs each sequence's pages straight out of the pool:

    grid = (B, H, max_pages)          # pages innermost, sequential
    k block = pool_t[h, page_table[b, i]]       # [1, 1, page_size, D]

Online softmax state (m, l, acc) lives in VMEM scratch across the page
axis exactly like the flash forward kernel.  Pages past a sequence's
length are skipped via @pl.when on the prefetched seq_lens (ragged
sequences pay for the pages they own, not the batch max); the page table
pads unused slots with page 0, which is always a valid DMA target.

Layouts are chosen Mosaic tile-legal by construction: pools transpose to
[H, P, page_size, D] so every block's trailing two dims are full array
dims (page_size, D); q/out ride as [B, H, 1, D] with (1, 1, 1, D) blocks.

INT8 POOLS: every public kernel takes optional ``k_scale``/``v_scale``
[P, H] per-page per-head abs-max arrays (generation.quantized_kv).
They ride as two more scalar-prefetch operands, and each live grid
cell dequantizes its page block in-kernel — ``int8 * (scale * 1/127)``
with the exact expression the jnp gather references use, so
kernel-vs-reference operands stay bitwise equal — before the score
matmul.  The jnp references dequantize their gathered O(tokens) views;
the kernels dequantize per block; nobody ever materializes a
dequantized pool.

MESH-NATIVE dispatch: every public kernel takes ``mesh`` / ``tp_axis``.
Heads are fully independent in all three grids, so under a head-sharded
tensor-parallel mesh the kernel runs as a ``shard_map`` whose per-shard
program is the SAME single-device kernel on ``num_heads / tp`` heads
over that shard's slice of the pool — q/out split on the head axis,
pools split per ``kv_pool_spec``, page tables and descriptors
replicated.  NO collective enters the kernel: the generation stack's
two per-layer Megatron allreduces stay XLA-placed outside it (exactly
where GSPMD puts them on the jnp reference path), which is the layout
the EQuARX-style quantized-collective follow-on assumes.
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash_attention import NEG_INF, _interpret

# int8 KV dequant factor: MUST stay bit-equal to
# generation.quantized_kv.INV_QMAX — the jnp gather references multiply
# by the same constant, which is what keeps kernel and reference
# operands bitwise identical (kept as a literal here so the kernel
# module never imports the generation package)
INV_QMAX = np.float32(1.0 / 127.0)


def _require_scales(pool, k_scale, v_scale):
    """int8 pools MUST arrive with their [P, H] scale arrays — and only
    int8 pools: raw int8 codes decoded as values, or float values
    multiplied by scale/127, are both finite and plausible-looking
    corruption, so a call site that forgot (or half-threaded, or
    wrongly threaded) the cache's layer_scales() fails loudly here
    instead of mis-attending."""
    if (k_scale is None) != (v_scale is None):
        raise ValueError(
            "k_scale and v_scale must be passed together — got one "
            "without the other (thread BOTH of the cache's "
            "layer_scales() arrays)")
    if k_scale is None and pool.dtype == jnp.int8:
        raise ValueError(
            "int8 KV pool passed to a paged-attention kernel without "
            "k_scale/v_scale — thread the cache's layer_scales() through")
    if k_scale is not None and pool.dtype != jnp.int8:
        raise ValueError(
            f"k_scale/v_scale passed with a {pool.dtype} pool — scales "
            "belong to int8 pools only (float values would be silently "
            "multiplied by scale/127)")


_STATE_ROWS = 8  # scratch rows; every row holds the same value so all
# scratch traffic is full-width vector ops (the Mosaic-proven layout)

# query-axis tile of the RAGGED kernel (RPA-paper waste fix #1): a
# (head, descriptor, page) grid cell computes a [RAGGED_Q_BLOCK,
# page_size] score block for ONE query tile instead of the full packed
# [T, page_size] axis, and tiles outside the descriptor's row span are
# skipped entirely — a 1-token decode descriptor computes 1 tile per
# page, not T/8.  8 is the Mosaic sublane width (the flash kernels'
# proven minor-axis tile).
RAGGED_Q_BLOCK = 8


def _reject_mesh_sharded_pool(pool):
    """Loud failure over silent corruption: the raw kernel is a
    single-device program — handed a pool committed to a multi-device
    NamedSharding (the tensor-parallel generation mesh) WITHOUT the
    matching ``mesh=`` argument, pallas_call would either fail opaquely
    or compute over one shard as if it were the whole pool.  Passing
    ``mesh=``/``tp_axis=`` runs the shard_map'd form instead (the
    supported mesh path); this guard catches direct callers that forgot
    to.  Tracers (pools inside a jit or shard_map trace) pass through
    untouched — the in-trace caller's own sharding machinery governs
    there."""
    try:
        sharding = getattr(pool, "sharding", None)
    except Exception:
        return  # tracer without a committed sharding: not our problem
    from jax.sharding import NamedSharding

    if (isinstance(sharding, NamedSharding)
            and len(sharding.device_set) > 1):
        raise NotImplementedError(
            "Pallas paged attention over a mesh-sharded KV pool needs "
            "the mesh spelled out: pass mesh=/tp_axis= to run the "
            "shard_map'd kernel (per-shard program over num_heads/tp "
            "heads), or use the jnp reference path (use_kernel=False) — "
            "GSPMD partitions it over the head axis.  Calling the raw "
            "single-device kernel on a sharded pool would compute over "
            "one shard as if it were the whole pool.")


def _head_shard_map(body, mesh, tp_axis, layout, q, k_pool, v_pool,
                    *scalars, scales=None):
    """Run `body` (a single-device kernel call) as a shard_map over the
    head-sharded tensor-parallel mesh: q and the output split on their
    head axis (axis 1 in all three kernels), the pools split per
    ``kv_pool_spec``, page tables / descriptors / lengths replicated.
    Heads are fully independent in every grid, so the per-shard program
    is exactly the existing kernel on num_heads/tp heads over that
    shard's slice of the pool — no collective is issued here or inside
    the kernel.

    `scales` (int8 pools): the ``(k_scale, v_scale)`` [P, H] arrays —
    sharded on THEIR head axis (kv_scale_spec), so each shard
    dequantizes its own heads with its own scale slice; body then
    receives ``(q, k_pool, v_pool, k_scale, v_scale, *scalars)``."""
    from jax.sharding import PartitionSpec as P

    from ...parallel.collective import shard_map
    from ...parallel.sharding_annotations import kv_pool_spec

    if tp_axis is None:
        tp_axis = tuple(mesh.axis_names)[0]
    tp = int(mesh.shape[tp_axis])
    h = q.shape[1]
    if h % tp:
        raise ValueError(
            f"num_heads={h} is not divisible by tp_degree={tp} (axis "
            f"{tp_axis!r} of the mesh): the shard_map'd kernel splits "
            f"the head axis, so heads must divide evenly")
    qspec = P(None, tp_axis, None)
    pspec = P(*kv_pool_spec(layout, tp_axis))
    args = (q, k_pool, v_pool)
    specs = (qspec, pspec, pspec)
    if scales is not None:
        args += tuple(scales)
        specs += (P(None, tp_axis),) * len(scales)
    fn = shard_map(body, mesh=mesh,
                   in_specs=specs + (P(),) * len(scalars),
                   out_specs=qspec)
    return fn(*args, *scalars)


def ragged_score_blocks(starts, lens, kv_lens, page_size, n_pages, n_rows,
                        q_block=RAGGED_Q_BLOCK):
    """Host-side mirror of the tiled ragged kernel's skip rule — the
    FLOP-proxy counter `generation.step_score_blocks` is set from.

    Returns ``(tiled, untiled)``: the number of [q_block, page_size]
    score-block computations per head the TILED kernel performs for
    these descriptors, and the number the UNTILED kernel (full packed
    token axis per live (descriptor, page) cell) would have performed,
    expressed in the same tile units so "tiled < untiled" is the
    measured statement that out-of-span work was skipped."""
    import numpy as np

    qb = max(1, min(int(q_block), int(n_rows)))
    n_tiles = -(-int(n_rows) // qb)
    ps = int(page_size)
    starts = np.asarray(starts, np.int64)
    lens = np.asarray(lens, np.int64)
    kv_lens = np.asarray(kv_lens, np.int64)
    live = (lens > 0) & (kv_lens > 0)
    pages_live = np.minimum(-(-kv_lens // ps), int(n_pages))
    untiled = int((n_tiles * pages_live)[live].sum())
    tiled = 0
    # this runs in the engine's hot step loop (once per ragged kernel
    # dispatch): descriptors are few (<= slots + 1), so loop those, but
    # the tile axis — the factor that grows with the packed axis — is
    # closed-form vectorized, never a Python loop
    for start, ln, kv in zip(starts[live], lens[live], kv_lens[live]):
        qt = np.arange(start // qb,
                       min((start + ln - 1) // qb, n_tiles - 1) + 1)
        last = np.minimum((qt + 1) * qb, start + ln) - 1
        qpos_max = kv - ln + (last - start)
        tiled += int((qpos_max // ps + 1).sum())
    return tiled, untiled


def _decode_kernel(pt_ref, sl_ref, *refs, page_size, n_pages,
                   quantized=False):
    """refs: ``[ks_ref, vs_ref]`` (quantized only — [P, H] scale
    arrays in SMEM via scalar prefetch) + q/k/v/o + the three scratch
    buffers.  In-kernel dequant: the int8 page block multiplies by its
    ONE per-(page, head) factor ``scale * (1/127)`` before the score
    matmul — the same elementwise expression the jnp reference applies
    to its gathered view."""
    if quantized:
        ks_ref, vs_ref = refs[0], refs[1]
        refs = refs[2:]
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
    b = pl.program_id(0)
    h = pl.program_id(1)
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    seq_len = sl_ref[b]
    # page i covers positions [i*page_size, (i+1)*page_size): it runs iff
    # its first position is live; later positions are masked below
    @pl.when(i * page_size < seq_len)
    def _compute():
        q = q_ref[0, 0]                            # [1, D] (scale folded)
        k = k_ref[0, 0]                            # [page_size, D]
        v = v_ref[0, 0]
        if quantized:
            page = pt_ref[b, i]
            k = k.astype(jnp.float32) * (ks_ref[page, h] * INV_QMAX)
            v = v.astype(jnp.float32) * (vs_ref[page, h] * INV_QMAX)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        pos = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (1, page_size), 1)
        s = jnp.where(pos < seq_len, s, NEG_INF)   # ragged tail of page
        m_prev = jnp.max(m_ref[...])
        m_cur = jnp.maximum(m_prev, jnp.max(s))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)                     # [1, page_size]
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)    # masked rows: exactly 0
        l_cur = jnp.max(l_ref[...]) * alpha + jnp.sum(p)
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha + jnp.broadcast_to(
            pv, acc_ref.shape)
        m_ref[...] = jnp.full_like(m_ref, m_cur)
        l_ref[...] = jnp.full_like(l_ref, l_cur)

    @pl.when(i == n_pages - 1)
    def _finalize():
        l = jnp.max(l_ref[...])
        safe_l = jnp.where(l > 0.0, l, 1.0)        # empty sequence: zeros
        o_ref[0, 0] = (acc_ref[...] / safe_l)[0:1].astype(o_ref.dtype)


def _chunk_kernel(pt_ref, info_ref, *refs, page_size, n_pages, n_rows,
                  quantized=False):
    """Chunked-prefill attention for ONE sequence: n_rows chunk queries
    (query row r at global position start + r) attend over every key the
    page table holds — the already-written prefix AND the chunk's own
    freshly scattered keys — with a per-row causal mask.  Online-softmax
    state is [n_rows, ...] (the decode kernel's, grown from 1 query row
    to the chunk), accumulated across the page axis.  Quantized pools
    prepend [P, H] scale refs and dequantize each page block in-kernel
    (see _decode_kernel)."""
    if quantized:
        ks_ref, vs_ref = refs[0], refs[1]
        refs = refs[2:]
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
    h = pl.program_id(0)
    i = pl.program_id(1)
    start = info_ref[0]

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    # page i covers positions [i*page_size, (i+1)*page_size): it runs iff
    # its first position is visible to SOME query (the last row sees the
    # most: positions <= start + n_rows - 1)
    @pl.when(i * page_size <= start + n_rows - 1)
    def _compute():
        q = q_ref[0]                               # [n_rows, D]
        k = k_ref[0, 0]                            # [page_size, D]
        v = v_ref[0, 0]
        if quantized:
            page = pt_ref[i]
            k = k.astype(jnp.float32) * (ks_ref[page, h] * INV_QMAX)
            v = v.astype(jnp.float32) * (vs_ref[page, h] * INV_QMAX)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32)
        pos = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (n_rows, page_size), 1)
        qpos = start + jax.lax.broadcasted_iota(
            jnp.int32, (n_rows, page_size), 0)
        s = jnp.where(pos <= qpos, s, NEG_INF)     # causal, per query row
        m_prev = jnp.max(m_ref[...], axis=1, keepdims=True)   # [n, 1]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)                     # [n, page_size]
        p = jnp.where(s <= NEG_INF / 2, 0.0, p)    # masked keys: exactly 0
        l_prev = jnp.max(l_ref[...], axis=1, keepdims=True)
        l_cur = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_cur, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_cur, l_ref.shape)

    @pl.when(i == n_pages - 1)
    def _finalize():
        l = jnp.max(l_ref[...], axis=1, keepdims=True)
        safe_l = jnp.where(l > 0.0, l, 1.0)  # fully masked pad rows
        o_ref[0] = (acc_ref[...] / safe_l).astype(o_ref.dtype)


def _ragged_kernel(pt_ref, st_ref, ln_ref, kv_ref, *refs, page_size,
                   n_pages, n_seqs, q_block, quantized=False):
    """RAGGED mixed-batch paged attention, QUERY-TILED (the RPA paper's
    kernel shape): packed query rows (decode singletons AND
    prefill-chunk runs in one token axis) attend through per-descriptor
    page tables.  Descriptor s owns packed rows [st_ref[s], st_ref[s] +
    ln_ref[s]); row r of s sits at global position kv_ref[s] - ln_ref[s]
    + (r - st_ref[s]) and sees keys [0, position].

    The grid walks (head, descriptor, page, QUERY TILE) — the tile axis
    INNERMOST, so the k/v BlockSpec index (h, pt[s, i]) is constant
    across a page's tile sweep and Pallas elides the repeated page-
    block DMA: the tiled kernel moves exactly the HBM bytes the untiled
    kernel did (q/out ride whole-axis blocks fetched once per head),
    while COMPUTE is per-tile.  A (descriptor, page, tile) cell runs
    ONLY when the tile intersects the descriptor's row span AND the
    page holds a key some in-span row of the tile can see — a 1-token
    decode descriptor computes one [q_block, page_size] block per
    visible page instead of a full [T, page_size] one, and pages past a
    row's causal horizon are skipped too (the tile's last in-span row
    sees the most: qpos_max = kv_len - ln + (last_row - start)).
    Online-softmax state spans the whole (tile-padded) token axis in
    scratch; each live cell updates ITS tile's row slice.  Rows of a
    tile the descriptor doesn't own see an all-NEG_INF score row, whose
    update is the exact identity (alpha == exp(0) == 1, sum(p) == 0),
    so tiles straddling a descriptor boundary stay exact.  Descriptors
    with ln == 0 (padding) never run.  Quantized pools prepend [P, H]
    scale refs and each live cell dequantizes its page block in-kernel
    (see _decode_kernel)."""
    if quantized:
        ks_ref, vs_ref = refs[0], refs[1]
        refs = refs[2:]
    q_ref, k_ref, v_ref, o_ref, acc_ref, m_ref, l_ref = refs
    hh = pl.program_id(0)
    s = pl.program_id(1)
    i = pl.program_id(2)
    qt = pl.program_id(3)

    @pl.when((s == 0) & (i == 0) & (qt == 0))
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    start = st_ref[s]
    ln = ln_ref[s]
    kv_len = kv_ref[s]
    row0 = qt * q_block
    # the tile's last row inside the descriptor's span sees the most
    # keys; pages past its causal horizon hold nothing any tile row can
    # attend (qpos_max < kv_len always, so "page has resident keys" is
    # implied)
    last = jnp.minimum(row0 + q_block, start + ln) - 1
    qpos_max = kv_len - ln + (last - start)
    live = ((ln > 0) & (row0 < start + ln) & (row0 + q_block > start)
            & (i * page_size <= qpos_max))

    @pl.when(live)
    def _compute():
        rows_sl = pl.dslice(row0, q_block)
        q = q_ref[0, rows_sl]                      # [q_block, D]
        k = k_ref[0, 0]                            # [page_size, D]
        v = v_ref[0, 0]
        if quantized:
            page = pt_ref[s, i]
            k = k.astype(jnp.float32) * (ks_ref[page, hh] * INV_QMAX)
            v = v.astype(jnp.float32) * (vs_ref[page, hh] * INV_QMAX)
        sc = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        row = row0 + jax.lax.broadcasted_iota(
            jnp.int32, (q_block, page_size), 0)
        col = i * page_size + jax.lax.broadcasted_iota(
            jnp.int32, (q_block, page_size), 1)
        mine = (row >= start) & (row < start + ln)
        qpos = kv_len - ln + (row - start)
        sc = jnp.where(mine & (col <= qpos), sc, NEG_INF)
        m_prev = jnp.max(m_ref[rows_sl], axis=1, keepdims=True)  # [qb, 1]
        m_cur = jnp.maximum(m_prev, jnp.max(sc, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(sc - m_cur)                    # [qb, page_size]
        p = jnp.where(sc <= NEG_INF / 2, 0.0, p)   # masked keys: exactly 0
        l_prev = jnp.max(l_ref[rows_sl], axis=1, keepdims=True)
        l_cur = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        pv = jax.lax.dot_general(p.astype(v.dtype), v,
                                 (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_ref[rows_sl] = acc_ref[rows_sl] * alpha + pv
        m_ref[rows_sl] = jnp.broadcast_to(m_cur, (q_block,
                                                  m_ref.shape[1]))
        l_ref[rows_sl] = jnp.broadcast_to(l_cur, (q_block,
                                                  l_ref.shape[1]))

    @pl.when((s == n_seqs - 1) & (i == n_pages - 1)
             & (qt == pl.num_programs(3) - 1))
    def _finalize():
        l = jnp.max(l_ref[...], axis=1, keepdims=True)
        safe_l = jnp.where(l > 0.0, l, 1.0)  # unclaimed rows: zeros
        o_ref[0] = (acc_ref[...] / safe_l).astype(o_ref.dtype)


def ragged_paged_attention_kernel(q, k_pool, v_pool, page_tables, starts,
                                  lens, kv_lens, scale, interpret=None,
                                  layout="token", q_block=None,
                                  mesh=None, tp_axis=None, k_scale=None,
                                  v_scale=None):
    """q: [T, H, D] — the step's PACKED query rows (decode rows, the
    prefill chunks, and speculative verify runs — a decode row with
    len = 1 + k drafts is just a chunk-shaped descriptor to this
    kernel — in one ragged token axis; rows owned by no descriptor
    come back 0).  k_pool/v_pool: one layer's pool, the
    chunks' and the decode tokens' K/V already scattered —
    [P, page_size, H, D] (layout="token") or [H, P, page_size, D]
    (layout="kernel").  page_tables: [S, max_pages] int32 (pad with 0).
    starts/lens/kv_lens: [S] int32 descriptors (lens == 0 marks padding
    descriptors; all three ride as scalar-prefetch operands so the
    BlockSpec index_map DMAs each descriptor's pages straight out of
    the pool).  Returns [T, H, D].

    q_block tiles the packed query axis (default RAGGED_Q_BLOCK):
    (tile, descriptor, page) cells whose rows lie outside the
    descriptor's span — or whose page no in-span row can see — are
    skipped (see _ragged_kernel; ragged_score_blocks mirrors the rule
    host-side for the FLOP-proxy counter).

    mesh / tp_axis runs the shard_map'd form: the same kernel per shard
    on num_heads/tp heads over that shard's pool slice (_head_shard_map).

    Layout handling mirrors the decode kernel: token-layout pools are
    transposed per call, kernel-layout pools are consumed as stored."""
    _require_scales(k_pool, k_scale, v_scale)
    quantized = k_scale is not None
    if mesh is not None:
        if quantized:
            def body(q_, kp_, vp_, ks_, vs_, pt_, st_, ln_, kv_):
                return ragged_paged_attention_kernel(
                    q_, kp_, vp_, pt_, st_, ln_, kv_, scale,
                    interpret=interpret, layout=layout, q_block=q_block,
                    k_scale=ks_, v_scale=vs_)
        else:
            def body(q_, kp_, vp_, pt_, st_, ln_, kv_):
                return ragged_paged_attention_kernel(
                    q_, kp_, vp_, pt_, st_, ln_, kv_, scale,
                    interpret=interpret, layout=layout, q_block=q_block)

        return _head_shard_map(
            body, mesh, tp_axis, layout, q, k_pool, v_pool,
            jnp.asarray(page_tables, jnp.int32),
            jnp.asarray(starts, jnp.int32), jnp.asarray(lens, jnp.int32),
            jnp.asarray(kv_lens, jnp.int32),
            scales=((k_scale, v_scale) if quantized else None))
    _reject_mesh_sharded_pool(k_pool)
    t, h, d = q.shape
    qb = max(1, min(int(q_block or RAGGED_Q_BLOCK), t))
    n_tiles = -(-t // qb)
    tpad = n_tiles * qb
    qs = jnp.transpose((q * scale).astype(q.dtype), (1, 0, 2))  # [H, T, D]
    if tpad != t:
        # pad the token axis to whole tiles so the kernel's per-tile
        # row slices stay in bounds; padded rows belong to no
        # descriptor (exact zeros) and are sliced off below
        qs = jnp.pad(qs, ((0, 0), (0, tpad - t), (0, 0)))
    if layout == "kernel":
        page_size = k_pool.shape[2]
        kt, vt = k_pool, v_pool          # stored kernel-ready: no copy
    else:
        page_size = k_pool.shape[1]
        kt = jnp.transpose(k_pool, (2, 0, 1, 3))
        vt = jnp.transpose(v_pool, (2, 0, 1, 3))
    n_seqs, n_pages = page_tables.shape

    # scalar-prefetch operands: page tables + descriptors, plus the
    # [P, H] scale arrays for int8 pools (SMEM scalars the kernel
    # indexes per (page, head) for the in-block dequant).  index_maps
    # take *refs so one lambda serves both operand counts.
    prefetch = [jnp.asarray(page_tables, jnp.int32),
                jnp.asarray(starts, jnp.int32),
                jnp.asarray(lens, jnp.int32),
                jnp.asarray(kv_lens, jnp.int32)]
    if quantized:
        prefetch += [jnp.asarray(k_scale, jnp.float32),
                     jnp.asarray(v_scale, jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(prefetch),
        # query tiles INNERMOST: the k/v block index is constant across
        # a page's tile sweep, so the tiling multiplies COMPUTE cells
        # only — the page-block DMA schedule (and q/out whole-axis
        # blocks, fetched once per head) is exactly the untiled
        # kernel's
        grid=(h, n_seqs, n_pages, n_tiles),
        in_specs=[
            pl.BlockSpec((1, tpad, d),
                         lambda h_, s, i, qt, *refs: (h_, 0, 0)),
            pl.BlockSpec((1, 1, page_size, d),
                         lambda h_, s, i, qt, *refs:
                         (h_, refs[0][s, i], 0, 0)),
            pl.BlockSpec((1, 1, page_size, d),
                         lambda h_, s, i, qt, *refs:
                         (h_, refs[0][s, i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, tpad, d),
                               lambda h_, s, i, qt, *refs: (h_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((tpad, d), jnp.float32),
            pltpu.VMEM((tpad, 128), jnp.float32),
            pltpu.VMEM((tpad, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_ragged_kernel, page_size=page_size,
                          n_pages=n_pages, n_seqs=n_seqs, q_block=qb,
                          quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((h, tpad, d), q.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(*prefetch, qs, kt, vt)
    return jnp.transpose(out[:, :t], (1, 0, 2))


def chunk_prefill_attention_kernel(q, k_pool, v_pool, page_table, start,
                                   scale, interpret=None, layout="token",
                                   mesh=None, tp_axis=None, k_scale=None,
                                   v_scale=None):
    """q: [n, H, D] — one sequence's prefill-chunk queries (row r at
    global position start + r; rows past the real chunk length are
    bucket padding whose output the caller discards).  k_pool/v_pool:
    one layer's pool, already holding the chunk's scattered K/V —
    [P, page_size, H, D] (layout="token") or [H, P, page_size, D]
    (layout="kernel").  page_table: [max_pages] int32 (pad with 0).
    start: int32 scalar (traced OK — rides as a scalar-prefetch
    operand).  Returns [n, H, D].

    mesh / tp_axis runs the shard_map'd form (heads independent, page
    table and start replicated — _head_shard_map).

    Same layout reasoning as the decode kernel: token-layout pools are
    transposed per call, kernel-layout pools are consumed as stored."""
    _require_scales(k_pool, k_scale, v_scale)
    quantized = k_scale is not None
    if mesh is not None:
        if quantized:
            def body(q_, kp_, vp_, ks_, vs_, pt_, st_):
                return chunk_prefill_attention_kernel(
                    q_, kp_, vp_, pt_, st_, scale, interpret=interpret,
                    layout=layout, k_scale=ks_, v_scale=vs_)
        else:
            def body(q_, kp_, vp_, pt_, st_):
                return chunk_prefill_attention_kernel(
                    q_, kp_, vp_, pt_, st_, scale, interpret=interpret,
                    layout=layout)

        return _head_shard_map(
            body, mesh, tp_axis, layout, q, k_pool, v_pool,
            jnp.asarray(page_table, jnp.int32),
            jnp.asarray(start, jnp.int32),
            scales=((k_scale, v_scale) if quantized else None))
    _reject_mesh_sharded_pool(k_pool)
    n, h, d = q.shape
    qs = jnp.transpose((q * scale).astype(q.dtype), (1, 0, 2))  # [H, n, D]
    if layout == "kernel":
        page_size = k_pool.shape[2]
        kt, vt = k_pool, v_pool
    else:
        page_size = k_pool.shape[1]
        kt = jnp.transpose(k_pool, (2, 0, 1, 3))
        vt = jnp.transpose(v_pool, (2, 0, 1, 3))
    n_pages = page_table.shape[0]
    info = jnp.asarray(start, jnp.int32).reshape(1)

    prefetch = [jnp.asarray(page_table, jnp.int32), info]
    if quantized:
        prefetch += [jnp.asarray(k_scale, jnp.float32),
                     jnp.asarray(v_scale, jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(prefetch),
        grid=(h, n_pages),
        in_specs=[
            pl.BlockSpec((1, n, d), lambda h_, i, *refs: (h_, 0, 0)),
            pl.BlockSpec((1, 1, page_size, d), lambda h_, i, *refs:
                         (h_, refs[0][i], 0, 0)),
            pl.BlockSpec((1, 1, page_size, d), lambda h_, i, *refs:
                         (h_, refs[0][i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, n, d), lambda h_, i, *refs:
                               (h_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((n, d), jnp.float32),
            pltpu.VMEM((n, 128), jnp.float32),
            pltpu.VMEM((n, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_chunk_kernel, page_size=page_size,
                          n_pages=n_pages, n_rows=n,
                          quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((h, n, d), q.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(*prefetch, qs, kt, vt)
    return jnp.transpose(out, (1, 0, 2))


def paged_decode_attention_kernel(q, k_pool, v_pool, page_tables, seq_lens,
                                  scale, interpret=None, layout="token",
                                  mesh=None, tp_axis=None, k_scale=None,
                                  v_scale=None):
    """q: [B, H, D].  k_pool/v_pool: one layer's pool —
    [P, page_size, H, D] (layout="token") or [H, P, page_size, D]
    (layout="kernel", DeviceKVPool's kernel-layout storage).
    page_tables: [B, max_pages] int32 (pad with 0).  seq_lens: [B] int32.
    Returns [B, H, D] attention output.

    mesh / tp_axis runs the shard_map'd form (heads independent, page
    tables and seq_lens replicated — _head_shard_map).

    The kernel itself always consumes [H, P, page_size, D].  Token-layout
    pools are transposed here per call — O(pool) HBM traffic per layer
    per step, which is exactly why kernel-layout pools exist: scattering
    into [H, P, page_size, D] on write makes this call transpose-free."""
    _require_scales(k_pool, k_scale, v_scale)
    quantized = k_scale is not None
    if mesh is not None:
        if quantized:
            def body(q_, kp_, vp_, ks_, vs_, pt_, sl_):
                return paged_decode_attention_kernel(
                    q_, kp_, vp_, pt_, sl_, scale, interpret=interpret,
                    layout=layout, k_scale=ks_, v_scale=vs_)
        else:
            def body(q_, kp_, vp_, pt_, sl_):
                return paged_decode_attention_kernel(
                    q_, kp_, vp_, pt_, sl_, scale, interpret=interpret,
                    layout=layout)

        return _head_shard_map(
            body, mesh, tp_axis, layout, q, k_pool, v_pool,
            jnp.asarray(page_tables, jnp.int32),
            jnp.asarray(seq_lens, jnp.int32),
            scales=((k_scale, v_scale) if quantized else None))
    _reject_mesh_sharded_pool(k_pool)
    b, h, d = q.shape
    qs = (q * scale).astype(q.dtype).reshape(b, h, 1, d)
    if layout == "kernel":
        page_size = k_pool.shape[2]
        kt, vt = k_pool, v_pool          # stored kernel-ready: no copy
    else:
        page_size = k_pool.shape[1]
        # [P, ps, H, D] -> [H, P, ps, D]: trailing block dims full dims
        kt = jnp.transpose(k_pool, (2, 0, 1, 3))
        vt = jnp.transpose(v_pool, (2, 0, 1, 3))
    n_pages = page_tables.shape[1]

    prefetch = [jnp.asarray(page_tables, jnp.int32),
                jnp.asarray(seq_lens, jnp.int32)]
    if quantized:
        prefetch += [jnp.asarray(k_scale, jnp.float32),
                     jnp.asarray(v_scale, jnp.float32)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(prefetch),
        grid=(b, h, n_pages),
        in_specs=[
            pl.BlockSpec((1, 1, 1, d), lambda b_, h_, i, *refs:
                         (b_, h_, 0, 0)),
            pl.BlockSpec((1, 1, page_size, d), lambda b_, h_, i, *refs:
                         (h_, refs[0][b_, i], 0, 0)),
            pl.BlockSpec((1, 1, page_size, d), lambda b_, h_, i, *refs:
                         (h_, refs[0][b_, i], 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, 1, d), lambda b_, h_, i, *refs:
                               (b_, h_, 0, 0)),
        scratch_shapes=[
            pltpu.VMEM((_STATE_ROWS, d), jnp.float32),
            pltpu.VMEM((_STATE_ROWS, 128), jnp.float32),
            pltpu.VMEM((_STATE_ROWS, 128), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_decode_kernel, page_size=page_size,
                          n_pages=n_pages, quantized=quantized),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b, h, 1, d), q.dtype),
        interpret=_interpret() if interpret is None else interpret,
    )(*prefetch, qs, kt, vt)
    return out.reshape(b, h, d)
