"""Elementwise / reduction / linalg ops.

Reference parity: paddle/fluid/operators/elementwise/, reduce_ops/, matmul_v2_op,
activation_op kernels and python/paddle/tensor/math.py.  Each op is a pure jax
function registered for both eager dispatch and static lowering; grads are
derived by jax.vjp (core/registry.py), replacing per-op GradOpMaker kernels.
"""
import numpy as np

import jax
import jax.numpy as jnp

from ..core.registry import apply_op, eager_op
from ..core.tensor import Tensor, to_tensor, _wrap_data
from ..core.dtype import convert_dtype


def _coerce(x, like=None):
    """Promote python scalars to Tensors matching `like`'s dtype."""
    if isinstance(x, Tensor):
        return x
    if like is not None and isinstance(like, Tensor) and np.isscalar(x):
        dt = like._data.dtype
        if isinstance(x, (float, np.floating)) and jnp.issubdtype(dt, jnp.integer):
            dt = jnp.float32  # float scalar promotes an int tensor op to float
        return _wrap_data(jnp.asarray(x, dtype=dt))
    return to_tensor(x)


def _binary(name, fn):
    raw = eager_op(name)(fn)

    def op(x, y, name=None):
        if not isinstance(x, Tensor):
            x = _coerce(x, y)
        if not isinstance(y, Tensor):
            y = _coerce(y, x)
        return raw(x, y)

    op.__name__ = name
    op.raw_fn = fn
    return op


add = _binary("elementwise_add", lambda x, y: x + y)
subtract = _binary("elementwise_sub", lambda x, y: x - y)
multiply = _binary("elementwise_mul", lambda x, y: x * y)
divide = _binary("elementwise_div", lambda x, y: x / y)
floor_divide = _binary("elementwise_floordiv", lambda x, y: jnp.floor_divide(x, y))
remainder = _binary("elementwise_mod", lambda x, y: jnp.remainder(x, y))
mod = remainder
floor_mod = remainder
pow = _binary("elementwise_pow", lambda x, y: jnp.power(x, y))
maximum = _binary("elementwise_max", jnp.maximum)
minimum = _binary("elementwise_min", jnp.minimum)
fmax = _binary("elementwise_fmax", jnp.fmax)
fmin = _binary("elementwise_fmin", jnp.fmin)
atan2 = _binary("atan2", jnp.arctan2)

elementwise_add = add
elementwise_sub = subtract
elementwise_mul = multiply
elementwise_div = divide


def _unary(name, fn):
    raw = eager_op(name)(fn)

    def op(x, name=None):
        if not isinstance(x, Tensor):
            x = to_tensor(x)
        return raw(x)

    op.__name__ = name
    op.raw_fn = fn
    return op


abs = _unary("abs", jnp.abs)
exp = _unary("exp", jnp.exp)
expm1 = _unary("expm1", jnp.expm1)
log = _unary("log", jnp.log)
log2 = _unary("log2", jnp.log2)
log10 = _unary("log10", jnp.log10)
log1p = _unary("log1p", jnp.log1p)
sqrt = _unary("sqrt", jnp.sqrt)
rsqrt = _unary("rsqrt", jax.lax.rsqrt)
square = _unary("square", jnp.square)
sin = _unary("sin", jnp.sin)
cos = _unary("cos", jnp.cos)
tan = _unary("tan", jnp.tan)
asin = _unary("asin", jnp.arcsin)
acos = _unary("acos", jnp.arccos)
atan = _unary("atan", jnp.arctan)
sinh = _unary("sinh", jnp.sinh)
cosh = _unary("cosh", jnp.cosh)
tanh = _unary("tanh", jnp.tanh)
asinh = _unary("asinh", jnp.arcsinh)
acosh = _unary("acosh", jnp.arccosh)
atanh = _unary("atanh", jnp.arctanh)
floor = _unary("floor", jnp.floor)
ceil = _unary("ceil", jnp.ceil)
round = _unary("round", jnp.round)
trunc = _unary("trunc", jnp.trunc)
sign = _unary("sign", jnp.sign)
reciprocal = _unary("reciprocal", lambda x: 1.0 / x)
neg = _unary("neg", jnp.negative)
erf = _unary("erf", jax.scipy.special.erf)
erfinv = _unary("erfinv", jax.scipy.special.erfinv)
lgamma = _unary("lgamma", jax.scipy.special.gammaln)
digamma = _unary("digamma", jax.scipy.special.digamma)
isnan_raw = _unary("isnan", jnp.isnan)
isinf_raw = _unary("isinf", jnp.isinf)
isfinite_raw = _unary("isfinite", jnp.isfinite)


def isnan(x):
    return isnan_raw(x)


def isinf(x):
    return isinf_raw(x)


def isfinite(x):
    return isfinite_raw(x)


@eager_op("scale")
def _scale(x, scale=1.0, bias=0.0, bias_after_scale=True):
    if bias_after_scale:
        return x * scale + bias
    return (x + bias) * scale


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s = scale
    if isinstance(s, Tensor):
        s = s.item()
    return _scale(x, scale=float(s), bias=float(bias),
                  bias_after_scale=bias_after_scale)


@eager_op("clip")
def _clip(x, min=None, max=None):
    return jnp.clip(x, min, max)


def clip(x, min=None, max=None, name=None):
    if isinstance(min, Tensor):
        min = min.item()
    if isinstance(max, Tensor):
        max = max.item()
    return _clip(x, min=min, max=max)


@eager_op("lerp")
def lerp(x, y, weight):
    return x + weight * (y - x)


@eager_op("stanh")
def stanh(x, scale_a=0.67, scale_b=1.7159):
    return scale_b * jnp.tanh(scale_a * x)


# ---- comparisons / logic (non-differentiable) ----

def _cmp(name, fn):
    raw = eager_op(name)(fn)

    def op(x, y, name=None):
        if not isinstance(x, Tensor):
            x = _coerce(x, y)
        if not isinstance(y, Tensor):
            y = _coerce(y, x)
        return raw(x.detach(), y.detach())

    op.__name__ = name
    return op


equal = _cmp("equal", lambda x, y: x == y)
not_equal = _cmp("not_equal", lambda x, y: x != y)
less_than = _cmp("less_than", lambda x, y: x < y)
less_equal = _cmp("less_equal", lambda x, y: x <= y)
greater_than = _cmp("greater_than", lambda x, y: x > y)
greater_equal = _cmp("greater_equal", lambda x, y: x >= y)
logical_and = _cmp("logical_and", jnp.logical_and)
logical_or = _cmp("logical_or", jnp.logical_or)
logical_xor = _cmp("logical_xor", jnp.logical_xor)
bitwise_and = _cmp("bitwise_and", jnp.bitwise_and)
bitwise_or = _cmp("bitwise_or", jnp.bitwise_or)
bitwise_xor = _cmp("bitwise_xor", jnp.bitwise_xor)


def logical_not(x, name=None):
    return _wrap_data(jnp.logical_not(x._data))


def bitwise_not(x, name=None):
    return _wrap_data(jnp.bitwise_not(x._data))


def equal_all(x, y):
    return _wrap_data(jnp.array_equal(x._data, y._data))


def allclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    return _wrap_data(
        jnp.allclose(x._data, y._data, rtol=rtol, atol=atol, equal_nan=equal_nan)
    )


def isclose(x, y, rtol=1e-5, atol=1e-8, equal_nan=False, name=None):
    return _wrap_data(
        jnp.isclose(x._data, y._data, rtol=rtol, atol=atol, equal_nan=equal_nan)
    )


# ---- reductions ----

def _axis_arg(axis):
    if axis is None:
        return None
    if isinstance(axis, Tensor):
        axis = axis.tolist()
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def _reduce(name, fn):
    raw = eager_op(name)(fn)

    def op(x, axis=None, keepdim=False, name=None, dtype=None):
        out = raw(x, axis=_axis_arg(axis), keepdims=keepdim)
        if dtype is not None:
            out = out.astype(convert_dtype(dtype))
        return out

    op.__name__ = name
    return op


def _sum_fn(x, axis=None, keepdims=False):
    if jnp.issubdtype(x.dtype, jnp.bool_):
        x = x.astype(jnp.int64)
    return jnp.sum(x, axis=axis, keepdims=keepdims)


sum = _reduce("reduce_sum", _sum_fn)
mean = _reduce("reduce_mean", jnp.mean)
max = _reduce("reduce_max", jnp.max)
min = _reduce("reduce_min", jnp.min)
prod = _reduce("reduce_prod", jnp.prod)
amax = max
amin = min


def all(x, axis=None, keepdim=False, name=None):
    return _wrap_data(jnp.all(x._data, axis=_axis_arg(axis), keepdims=keepdim))


def any(x, axis=None, keepdim=False, name=None):
    return _wrap_data(jnp.any(x._data, axis=_axis_arg(axis), keepdims=keepdim))


@eager_op("logsumexp_op")
def _logsumexp(x, axis=None, keepdims=False):
    return jax.scipy.special.logsumexp(x, axis=axis, keepdims=keepdims)


def logsumexp(x, axis=None, keepdim=False, name=None):
    return _logsumexp(x, axis=_axis_arg(axis), keepdims=keepdim)


def argmax(x, axis=None, keepdim=False, dtype="int64", name=None):
    return _wrap_data(jnp.argmax(x._data, axis=axis, keepdims=keepdim))


def argmin(x, axis=None, keepdim=False, dtype="int64", name=None):
    return _wrap_data(jnp.argmin(x._data, axis=axis, keepdims=keepdim))


@eager_op("cumsum_op")
def _cumsum(x, axis=None):
    return jnp.cumsum(x, axis=axis)


def cumsum(x, axis=None, dtype=None, name=None):
    if axis is None:
        x = x.reshape([-1]) if isinstance(x, Tensor) else x
        axis = 0
    out = _cumsum(x, axis=int(axis))
    return out.astype(convert_dtype(dtype)) if dtype else out


@eager_op("cumprod_op")
def _cumprod(x, axis):
    return jnp.cumprod(x, axis=axis)


def cumprod(x, dim=None, dtype=None, name=None):
    out = _cumprod(x, axis=int(dim))
    return out.astype(convert_dtype(dtype)) if dtype else out


# ---- linalg ----

@eager_op("matmul_v2")
def _matmul(x, y, transpose_x=False, transpose_y=False):
    if transpose_x:
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if transpose_y:
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return jnp.matmul(x, y)


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    return _matmul(x, y, transpose_x=transpose_x, transpose_y=transpose_y)


def mm(x, y, name=None):
    return matmul(x, y)


@eager_op("bmm")
def bmm(x, y):
    return jnp.matmul(x, y)


@eager_op("dot_op")
def _dot(x, y):
    return jnp.sum(x * y, axis=-1)


def dot(x, y, name=None):
    return _dot(x, y)


@eager_op("addmm_op")
def _addmm(input, x, y, beta=1.0, alpha=1.0):
    return beta * input + alpha * (x @ y)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return _addmm(input, x, y, beta=beta, alpha=alpha)


@eager_op("t_op")
def t(x):
    return x.T if x.ndim >= 2 else x


@eager_op("outer_op")
def outer(x, y):
    return jnp.outer(x, y)


@eager_op("inner_op")
def inner(x, y):
    return jnp.inner(x, y)


@eager_op("kron_op")
def kron(x, y):
    return jnp.kron(x, y)


@eager_op("mv_op")
def mv(x, vec):
    return x @ vec


@eager_op("p_norm")
def _norm(x, p=2.0, axis=None, keepdims=False):
    if p == float("inf"):
        return jnp.max(jnp.abs(x), axis=axis, keepdims=keepdims)
    if p == float("-inf"):
        return jnp.min(jnp.abs(x), axis=axis, keepdims=keepdims)
    if axis is None:
        x = x.reshape(-1)
        axis = 0
    return jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keepdims) ** (1.0 / p)


def norm(x, p=2.0, axis=None, keepdim=False, name=None):
    if p == "fro":
        p = 2.0
    return _norm(x, p=float(p), axis=_axis_arg(axis), keepdims=keepdim)


def dist(x, y, p=2.0):
    return norm(subtract(x, y), p=p)


# ---- misc math ----

@eager_op("where_op")
def _where(cond, x, y):
    return jnp.where(cond, x, y)


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=True)
    if not isinstance(x, Tensor):
        x = _coerce(x, y)
    if not isinstance(y, Tensor):
        y = _coerce(y, x)
    return _where(condition.detach() if isinstance(condition, Tensor) else condition, x, y)


where_m = where


def nonzero(x, as_tuple=False):
    idx = np.nonzero(x.numpy())
    if as_tuple:
        return tuple(to_tensor(i) for i in idx)
    return to_tensor(np.stack(idx, axis=1))


def masked_select(x, mask, name=None):
    """Output size is data-dependent: resolve the mask host-side (eager
    boundary op, like the reference's CPU-side shape infer) but keep the
    gather on-tape so gradients scatter back into x."""
    m = np.asarray(mask._data if isinstance(mask, Tensor) else mask, bool)
    xshape = tuple(int(s) for s in x._data.shape)
    try:
        m = np.broadcast_to(m, xshape)  # mask must broadcast to x's shape
    except ValueError:
        raise ValueError(
            f"masked_select: mask shape {m.shape} is not broadcastable "
            f"to x shape {xshape}")
    idx = jnp.asarray(np.nonzero(m.reshape(-1))[0])

    return apply_op("masked_select",
                    lambda v: v.reshape(-1)[idx], (x,), {})


@eager_op("topk_v2", n_outputs=2)
def _topk(x, k, largest=True):
    if largest:
        vals, idx = jax.lax.top_k(x, k)
    else:
        vals, idx = jax.lax.top_k(-x, k)
        vals = -vals
    return vals, idx


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())
    if axis is not None and axis not in (-1, x.ndim - 1):
        xm = transpose_to_last(x, axis)
        v, i = _topk(xm, k=k, largest=largest)
        return transpose_from_last(v, axis), transpose_from_last(i, axis)
    return _topk(x, k=k, largest=largest)


def transpose_to_last(x, axis):
    perm = list(range(x.ndim))
    perm[axis], perm[-1] = perm[-1], perm[axis]
    from .manipulation import transpose

    return transpose(x, perm)


transpose_from_last = transpose_to_last


@eager_op("argsort_op")
def _argsort_val(x, axis=-1, descending=False):
    return jnp.argsort(-x if descending else x, axis=axis)


def argsort(x, axis=-1, descending=False, name=None):
    return _argsort_val(x, axis=axis, descending=descending)


@eager_op("sort_op")
def _sort(x, axis=-1, descending=False):
    s = jnp.sort(x, axis=axis)
    return jnp.flip(s, axis=axis) if descending else s


def sort(x, axis=-1, descending=False, name=None):
    return _sort(x, axis=axis, descending=descending)


def unique(x, return_index=False, return_inverse=False, return_counts=False, axis=None):
    res = np.unique(
        x.numpy(),
        return_index=return_index,
        return_inverse=return_inverse,
        return_counts=return_counts,
        axis=axis,
    )
    if isinstance(res, tuple):
        return tuple(to_tensor(r) for r in res)
    return to_tensor(res)


@eager_op("increment_op")
def _increment(x, value=1.0):
    return x + value


def increment(x, value=1.0, name=None):
    out = _increment(x, value=float(value))
    x.set_value(out.detach())
    return x


@eager_op("cross_op")
def _cross(x, y, axis=-1):
    return jnp.cross(x, y, axis=axis)


def cross(x, y, axis=9, name=None):
    if axis == 9:
        axis = next((i for i, s in enumerate(x.shape) if s == 3), -1)
    return _cross(x, y, axis=axis)


def numel_t(x):
    return to_tensor(np.array(x.size, dtype=np.int64))
