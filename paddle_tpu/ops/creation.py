"""Creation ops.

Reference parity: fill_constant / gaussian_random / uniform_random / range /
linspace / eye / tril / triu op kernels (paddle/fluid/operators/*_op.cc) and
python/paddle/tensor/creation.py.
"""
import numpy as np

import jax.numpy as jnp
import jax

from ..core.registry import apply_op
from ..core.tensor import Tensor, to_tensor, _wrap_data
from ..core.dtype import convert_dtype
from ..core import random as _random


def _dt(dtype, default="float32"):
    d = convert_dtype(dtype)
    return d if d is not None else convert_dtype(default)


def full(shape, fill_value, dtype=None):
    if isinstance(shape, Tensor):
        shape = shape.tolist()
    if isinstance(shape, int):
        shape = [shape]
    if dtype is None:
        dtype = "int64" if isinstance(fill_value, (int, np.integer)) and not isinstance(
            fill_value, bool
        ) else "float32"
        if isinstance(fill_value, bool):
            dtype = "bool"
    return _wrap_data(jnp.full(tuple(shape), fill_value, _dt(dtype)))


fill_constant = full


def zeros(shape, dtype="float32"):
    return full(shape, 0, dtype or "float32")


def ones(shape, dtype="float32"):
    return full(shape, 1, dtype or "float32")


def zeros_like(x, dtype=None):
    return _wrap_data(jnp.zeros_like(x._data, dtype=convert_dtype(dtype)))


def ones_like(x, dtype=None):
    return _wrap_data(jnp.ones_like(x._data, dtype=convert_dtype(dtype)))


def full_like(x, fill_value, dtype=None):
    return _wrap_data(jnp.full_like(x._data, fill_value, dtype=convert_dtype(dtype)))


def empty(shape, dtype="float32"):
    return zeros(shape, dtype)


def empty_like(x, dtype=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None):
    if end is None:
        start, end = 0, start
    for v in (start, end, step):
        if isinstance(v, Tensor):
            raise TypeError("arange bounds must be python scalars")
    if dtype is None:
        dtype = (
            "int64"
            if all(isinstance(v, (int, np.integer)) for v in (start, end, step))
            else "float32"
        )
    return _wrap_data(jnp.arange(start, end, step, _dt(dtype)))


def linspace(start, stop, num, dtype="float32"):
    return _wrap_data(jnp.linspace(start, stop, int(num), dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype="float32"):
    return _wrap_data(jnp.eye(num_rows, num_columns, dtype=_dt(dtype)))


def diag(x, offset=0, padding_value=0):

    def fn(v):
        d = jnp.diag(v, k=offset)
        if v.ndim == 1 and padding_value != 0:
            mask = jnp.diag(jnp.ones_like(v, dtype=bool), k=offset)
            return jnp.where(mask, d, padding_value)
        return d

    return apply_op("diag_v2", fn, (x,), {})


def tril(x, diagonal=0):

    return apply_op("tril_triu", lambda v: jnp.tril(v, k=diagonal), (x,), {})


def triu(x, diagonal=0):

    return apply_op("tril_triu", lambda v: jnp.triu(v, k=diagonal), (x,), {})


def meshgrid(*args):
    arrs = [a._data for a in args]
    return [_wrap_data(m) for m in jnp.meshgrid(*arrs, indexing="ij")]


def assign(x, output=None):

    if not isinstance(x, Tensor):
        x = to_tensor(x)
    out = apply_op("assign", lambda v: v + 0, (x,), {})
    if output is not None:
        output.set_value(out)
        return output
    return out


def clone(x):
    return assign(x)


# ---- random creation (threefry-keyed; cf. gaussian_random_op.cc) ----

def uniform(shape, dtype="float32", min=-1.0, max=1.0, seed=0):
    key = jax.random.PRNGKey(seed) if seed else _random.next_key()
    return _wrap_data(
        jax.random.uniform(key, tuple(shape), _dt(dtype), minval=min, maxval=max)
    )


uniform_random = uniform


def rand(shape, dtype="float32"):
    return uniform(shape, dtype, min=0.0, max=1.0)


def randn(shape, dtype="float32"):
    return _wrap_data(jax.random.normal(_random.next_key(), tuple(shape), _dt(dtype)))


def normal(mean=0.0, std=1.0, shape=None):
    out = jax.random.normal(_random.next_key(), tuple(shape or []), jnp.float32)
    return _wrap_data(out * std + mean)


gaussian = normal


def randint(low=0, high=None, shape=(1,), dtype="int64"):
    if high is None:
        low, high = 0, low
    return _wrap_data(
        jax.random.randint(_random.next_key(), tuple(shape), low, high).astype(
            _dt(dtype, "int64")
        )
    )


def randperm(n, dtype="int64"):
    return _wrap_data(
        jax.random.permutation(_random.next_key(), n).astype(_dt(dtype, "int64"))
    )


def bernoulli(x):
    return _wrap_data(
        jax.random.bernoulli(_random.next_key(), x._data).astype(x._data.dtype)
    )


def multinomial(x, num_samples=1, replacement=False):
    probs = x._data
    logits = jnp.log(jnp.maximum(probs, 1e-30))
    key = _random.next_key()
    if replacement:
        out = jax.random.categorical(key, logits, axis=-1, shape=(
            *(logits.shape[:-1]), num_samples))
    else:
        # Gumbel top-k trick for sampling without replacement.
        g = jax.random.gumbel(key, logits.shape)
        _, out = jax.lax.top_k(logits + g, num_samples)
    return _wrap_data(out.astype(jnp.int64) if False else out)
