"""paddle_tpu.ops — the operator library (SURVEY §2.1 "Operator library" row).

Every op is a pure jax function registered in core.registry; eager calls record
jax.vjp tape nodes, static Programs lower whole blocks through the same
registry.  Reference: paddle/fluid/operators/ (286 top-level op defs); grads
come from jax.vjp instead of GradOpMaker kernels.
"""
from .creation import *  # noqa: F401,F403
from .math import *  # noqa: F401,F403
from .manipulation import *  # noqa: F401,F403
from .nn_ops import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .loss_extra import *  # noqa: F401,F403
from .sequence_ops import *  # noqa: F401,F403
from .vision_extra import *  # noqa: F401,F403
from .framework_ops import *  # noqa: F401,F403

from .creation import assign, full, zeros, ones, arange  # noqa: F401
from .math import (  # noqa: F401
    add, subtract, multiply, divide, matmul, scale, clip, pow, abs, sum, mean,
    max, min, equal, not_equal, less_than, less_equal, greater_than,
    greater_equal,
)
from .manipulation import cast, reshape, transpose, concat, split, getitem  # noqa: F401
