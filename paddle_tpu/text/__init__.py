from .datasets import Imdb, UCIHousing, WMT14  # noqa: F401
