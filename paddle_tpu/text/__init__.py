from .datasets import (  # noqa: F401
    Imdb, UCIHousing, WMT14, WMT16, Conll05st, Imikolov, Movielens,
)
