"""Text datasets.

Reference parity: python/paddle/text/datasets/ (Imdb, UCIHousing, WMT14...).
No egress: local files when present, deterministic synthetic fallbacks with
real shapes/vocab sizes otherwise.
"""
import os

import numpy as np

from ..io.dataset import Dataset

DATA_HOME = os.environ.get("PADDLE_TPU_DATA_HOME",
                           os.path.expanduser("~/.cache/paddle_tpu/datasets"))


class Imdb(Dataset):
    """Sentiment classification; sample = (int64 token ids [seq], int64 label)."""

    VOCAB_SIZE = 5147

    def __init__(self, data_file=None, mode="train", cutoff=150,
                 synthetic_size=2000, seq_len=128):
        rng = np.random.RandomState(0 if mode == "train" else 1)
        self.docs = rng.randint(1, self.VOCAB_SIZE,
                                size=(synthetic_size, seq_len)).astype(np.int64)
        self.labels = rng.randint(0, 2, size=(synthetic_size,)).astype(np.int64)
        self.word_idx = {f"w{i}": i for i in range(self.VOCAB_SIZE)}

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class UCIHousing(Dataset):
    """13 float features -> 1 float target."""

    def __init__(self, data_file=None, mode="train"):
        path = data_file or os.path.join(DATA_HOME, "uci_housing",
                                         "housing.data")
        if os.path.exists(path):
            data = np.loadtxt(path).astype(np.float32)
        else:
            rng = np.random.RandomState(42)
            X = rng.rand(506, 13).astype(np.float32)
            w = rng.rand(13, 1).astype(np.float32)
            y = X @ w + 0.1 * rng.randn(506, 1).astype(np.float32)
            data = np.concatenate([X, y], axis=1)
        # normalize features (reference preprocessing parity)
        mx, mn = data[:, :-1].max(0), data[:, :-1].min(0)
        data[:, :-1] = (data[:, :-1] - mn) / np.maximum(mx - mn, 1e-6)
        split = int(len(data) * 0.8)
        self.data = data[:split] if mode == "train" else data[split:]

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:-1], row[-1:]

    def __len__(self):
        return len(self.data)


class WMT14(Dataset):
    """Machine translation; sample = (src ids, trg ids, trg_next ids)."""

    DICT_SIZE = 30000

    def __init__(self, data_file=None, mode="train", dict_size=30000,
                 synthetic_size=1000, seq_len=32):
        rng = np.random.RandomState(7)
        self.src = rng.randint(1, dict_size, (synthetic_size, seq_len)).astype(
            np.int64)
        self.trg = rng.randint(1, dict_size, (synthetic_size, seq_len)).astype(
            np.int64)

    def __getitem__(self, idx):
        trg = self.trg[idx]
        return self.src[idx], trg[:-1], trg[1:]

    def __len__(self):
        return len(self.src)


class Conll05st(Dataset):
    def __init__(self, synthetic_size=500, seq_len=40):
        rng = np.random.RandomState(11)
        self.words = rng.randint(0, 44068, (synthetic_size, seq_len)).astype(
            np.int64)
        self.labels = rng.randint(0, 67, (synthetic_size, seq_len)).astype(
            np.int64)

    def __getitem__(self, idx):
        return self.words[idx], self.labels[idx]

    def __len__(self):
        return len(self.words)


class Movielens(Dataset):
    def __init__(self, synthetic_size=2000):
        rng = np.random.RandomState(13)
        self.users = rng.randint(0, 6040, (synthetic_size,)).astype(np.int64)
        self.movies = rng.randint(0, 3706, (synthetic_size,)).astype(np.int64)
        self.ratings = rng.randint(1, 6, (synthetic_size,)).astype(np.float32)

    def __getitem__(self, idx):
        return self.users[idx], self.movies[idx], self.ratings[idx]

    def __len__(self):
        return len(self.users)


class Imikolov(Dataset):
    """PTB-style n-gram LM dataset (ref: text/datasets/imikolov.py);
    sample = n-gram id window.  Synthetic fallback (no egress)."""

    def __init__(self, data_file=None, data_type="NGRAM", window_size=5,
                 mode="train", min_word_freq=50, synthetic_size=2000):
        rng = np.random.RandomState(13)
        self.window = int(window_size)
        self.data = rng.randint(0, 2074, (synthetic_size, self.window)) \
            .astype(np.int64)

    def __getitem__(self, idx):
        return tuple(self.data[idx])

    def __len__(self):
        return len(self.data)


class WMT16(WMT14):
    """WMT16 en-de (ref: text/datasets/wmt16.py); same sample layout as
    WMT14 with a bpe-sized vocab."""

    DICT_SIZE = 10000

    def __init__(self, data_file=None, mode="train", src_dict_size=10000,
                 trg_dict_size=10000, lang="en", synthetic_size=1000,
                 seq_len=32):
        super().__init__(mode=mode, dict_size=min(src_dict_size,
                                                  trg_dict_size),
                         synthetic_size=synthetic_size, seq_len=seq_len)
