"""paddle.vision.ops — detection op family.

Reference parity: operators/detection/ (roi_align_op.cc, multiclass_nms_op.cc,
yolo_box_op.cc, prior_box_op.cc, box_coder_op.cc, iou_similarity_op.cc) via
the python/paddle/vision/ops.py surface.  TPU-native design: every op is
static-shape dataflow — NMS returns a fixed-size keep vector padded with -1
plus a count (XLA has no dynamic result shapes; the reference's
variable-length LoD output maps to pad+count, SURVEY §7.3 LoD row), and the
O(n^2) IoU matrix + greedy suppression run as one fori_loop on device.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.registry import apply_op
from ..core.tensor import Tensor


def _raw(x):
    return x._data if isinstance(x, Tensor) else jnp.asarray(x)


# ---- IoU ----

def _iou_matrix(a, b):
    """a: [M,4], b: [N,4] xyxy -> [M,N] IoU."""
    area_a = jnp.clip(a[:, 2] - a[:, 0], 0) * jnp.clip(a[:, 3] - a[:, 1], 0)
    area_b = jnp.clip(b[:, 2] - b[:, 0], 0) * jnp.clip(b[:, 3] - b[:, 1], 0)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.clip(rb - lt, 0)
    inter = wh[..., 0] * wh[..., 1]
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


def iou_similarity(x, y, name=None):
    """Ref: iou_similarity_op.cc."""
    return apply_op("iou_similarity", _iou_matrix, (x, y), {})


# ---- RoI align ----

def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """Ref: roi_align_op.cc.  x: [N,C,H,W]; boxes: [R,4] xyxy in input
    coords; boxes_num: [N] rois per image.  Bilinear-sampled [R,C,oh,ow]."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    sr = sampling_ratio if sampling_ratio > 0 else 2

    bn = np.asarray(_raw(boxes_num)).astype(np.int64)
    # roi -> image index (static: boxes_num is host data, like the
    # reference's LoD offsets)
    img_idx = np.repeat(np.arange(len(bn)), bn)

    def fn(xv, bv):
        N, C, H, W = xv.shape
        off = 0.5 if aligned else 0.0

        def one_roi(box, img):
            x1, y1, x2, y2 = (box * spatial_scale) - off
            rw = jnp.maximum(x2 - x1, 1e-6 if aligned else 1.0)
            rh = jnp.maximum(y2 - y1, 1e-6 if aligned else 1.0)
            bin_h, bin_w = rh / oh, rw / ow
            # sr x sr sample points per bin
            iy = (jnp.arange(oh)[:, None] + (jnp.arange(sr)[None, :] + 0.5)
                  / sr)  # [oh, sr]
            ix = (jnp.arange(ow)[:, None] + (jnp.arange(sr)[None, :] + 0.5)
                  / sr)
            ys = y1 + iy * bin_h  # [oh, sr]
            xs = x1 + ix * bin_w  # [ow, sr]

            def bilinear(yy, xx):
                yy = jnp.clip(yy, 0.0, H - 1.0)
                xx = jnp.clip(xx, 0.0, W - 1.0)
                y0 = jnp.floor(yy).astype(jnp.int32)
                x0 = jnp.floor(xx).astype(jnp.int32)
                y1i = jnp.minimum(y0 + 1, H - 1)
                x1i = jnp.minimum(x0 + 1, W - 1)
                ly, lx = yy - y0, xx - x0
                feat = xv[img]  # [C,H,W]
                v00 = feat[:, y0, x0]
                v01 = feat[:, y0, x1i]
                v10 = feat[:, y1i, x0]
                v11 = feat[:, y1i, x1i]
                return (v00 * (1 - ly) * (1 - lx) + v01 * (1 - ly) * lx
                        + v10 * ly * (1 - lx) + v11 * ly * lx)

            # all sample points: [oh*sr] x [ow*sr] grid
            yy = ys.reshape(-1)  # [oh*sr]
            xx = xs.reshape(-1)  # [ow*sr]
            grid_y = jnp.repeat(yy, xx.shape[0])
            grid_x = jnp.tile(xx, yy.shape[0])
            vals = bilinear(grid_y, grid_x)  # [C, oh*sr*ow*sr]
            vals = vals.reshape(-1, oh, sr, ow, sr)
            return vals.mean(axis=(2, 4))  # [C, oh, ow]

        return jax.vmap(one_roi)(bv, jnp.asarray(img_idx))

    return apply_op("roi_align", fn, (x, boxes), {})


def roi_pool(x, boxes, boxes_num, output_size, spatial_scale=1.0, name=None):
    """Ref: roi_pool_op.cc — max-pooled variant via dense sampling."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    oh, ow = output_size
    bn = np.asarray(_raw(boxes_num)).astype(np.int64)
    img_idx = np.repeat(np.arange(len(bn)), bn)

    def fn(xv, bv):
        N, C, H, W = xv.shape

        def one_roi(box, img):
            x1, y1, x2, y2 = jnp.round(box * spatial_scale)
            rw = jnp.maximum(x2 - x1 + 1, 1.0)
            rh = jnp.maximum(y2 - y1 + 1, 1.0)
            # sample a dense fixed grid inside each bin and max-reduce
            # (static-shape stand-in for the exact integer bin walk)
            S = 4
            iy = y1 + (jnp.arange(oh * S) + 0.5) / S * (rh / oh)
            ix = x1 + (jnp.arange(ow * S) + 0.5) / S * (rw / ow)
            yi = jnp.clip(iy.astype(jnp.int32), 0, H - 1)
            xi = jnp.clip(ix.astype(jnp.int32), 0, W - 1)
            feat = xv[img][:, yi][:, :, xi]  # [C, oh*S, ow*S]
            return feat.reshape(-1, oh, S, ow, S).max(axis=(2, 4))

        return jax.vmap(one_roi)(bv, jnp.asarray(img_idx))

    return apply_op("roi_pool", fn, (x, boxes), {})


# ---- NMS ----

def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None, name=None):
    """Ref: multiclass_nms_op.cc greedy suppression.  Returns keep indices
    sorted by score, padded with -1 to the input length (static shape; the
    reference's variable-length output maps to pad+count)."""
    n = int(_raw(boxes).shape[0])

    def fn(bv, *sv):
        scores_v = sv[0] if sv else jnp.arange(n, 0, -1).astype(jnp.float32)
        if category_idxs is not None:
            # offset boxes per category so cross-category IoU is 0
            cat = jnp.asarray(_raw(category_idxs)).astype(jnp.float32)
            span = jnp.max(bv) - jnp.min(bv) + 1.0
            bv = bv + (cat * span)[:, None]
        order = jnp.argsort(-scores_v)
        b_sorted = bv[order]
        iou = _iou_matrix(b_sorted, b_sorted)

        def body(i, keep):
            # suppress i if any higher-scored kept box overlaps too much
            sup = jnp.any((jnp.arange(n) < i) & keep
                          & (iou[i] > iou_threshold))
            return keep.at[i].set(~sup)

        keep = jax.lax.fori_loop(0, n, body, jnp.ones((n,), bool))
        kept_sorted = jnp.where(keep, order, -1)
        # stable-compact: kept first (by score), -1 padding after
        rank = jnp.argsort(~keep, stable=True)
        return kept_sorted[rank]

    args = (boxes,) + ((scores,) if scores is not None else ())
    out = apply_op("nms", fn, args, {})
    if top_k is not None:
        from ..ops.manipulation import slice as _slice

        out = _slice(out, [0], [0], [top_k])
    return out


# ---- YOLO box decoding ----

def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None, scale_x_y=1.0):
    """Ref: yolo_box_op.cc.  x: [N, len(anchors)/2*(5+class_num), H, W];
    img_size: [N,2] (h,w).  Returns (boxes [N,HW*A,4], scores
    [N,HW*A,class_num])."""
    na = len(anchors) // 2

    def fn(xv, imgs):
        N, _, H, W = xv.shape
        pred = xv.reshape(N, na, 5 + class_num, H, W)
        gx = jnp.arange(W).reshape(1, 1, 1, W)
        gy = jnp.arange(H).reshape(1, 1, H, 1)
        sx = jax.nn.sigmoid(pred[:, :, 0]) * scale_x_y \
            - (scale_x_y - 1.0) / 2.0
        sy = jax.nn.sigmoid(pred[:, :, 1]) * scale_x_y \
            - (scale_x_y - 1.0) / 2.0
        bx = (sx + gx) / W
        by = (sy + gy) / H
        aw = jnp.asarray(anchors[0::2], jnp.float32).reshape(1, na, 1, 1)
        ah = jnp.asarray(anchors[1::2], jnp.float32).reshape(1, na, 1, 1)
        input_w = W * downsample_ratio
        input_h = H * downsample_ratio
        bw = jnp.exp(pred[:, :, 2]) * aw / input_w
        bh = jnp.exp(pred[:, :, 3]) * ah / input_h
        conf = jax.nn.sigmoid(pred[:, :, 4])
        probs = jax.nn.sigmoid(pred[:, :, 5:]) * conf[:, :, None]
        # below conf_thresh: box zeroed (reference semantics)
        mask = (conf >= conf_thresh).astype(xv.dtype)
        imh = imgs[:, 0].reshape(N, 1, 1, 1).astype(xv.dtype)
        imw = imgs[:, 1].reshape(N, 1, 1, 1).astype(xv.dtype)
        x1 = (bx - bw / 2) * imw
        y1 = (by - bh / 2) * imh
        x2 = (bx + bw / 2) * imw
        y2 = (by + bh / 2) * imh
        if clip_bbox:
            x1 = jnp.clip(x1, 0, imw - 1)
            y1 = jnp.clip(y1, 0, imh - 1)
            x2 = jnp.clip(x2, 0, imw - 1)
            y2 = jnp.clip(y2, 0, imh - 1)
        # boxes already carry coords LAST ([N,na,H,W,4]); only probs
        # ([N,na,C,H,W]) needs its class axis moved to the end
        boxes = jnp.stack([x1, y1, x2, y2], axis=-1) * mask[..., None]
        boxes = boxes.reshape(N, -1, 4)
        scores = (probs * mask[:, :, None]).transpose(
            0, 1, 3, 4, 2).reshape(N, -1, class_num)
        return boxes, scores

    return apply_op("yolo_box", fn, (x, img_size), {})


# ---- SSD prior boxes ----

def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None):
    """Ref: prior_box_op.cc.  Returns (boxes [H,W,P,4], variances same)."""
    ars = list(aspect_ratios)
    if flip:
        ars += [1.0 / a for a in aspect_ratios if a != 1.0]

    def fn(inp, img):
        H, W = inp.shape[2], inp.shape[3]
        IH, IW = img.shape[2], img.shape[3]
        step_h = steps[1] if steps[1] > 0 else IH / H
        step_w = steps[0] if steps[0] > 0 else IW / W
        cy = (jnp.arange(H) + offset) * step_h
        cx = (jnp.arange(W) + offset) * step_w
        whs = []
        for ms in min_sizes:
            whs.append((ms, ms))
            for a in ars:
                if abs(a - 1.0) < 1e-6:
                    continue
                whs.append((ms * np.sqrt(a), ms / np.sqrt(a)))
            if max_sizes:
                mx = max_sizes[min_sizes.index(ms)]
                whs.append((np.sqrt(ms * mx), np.sqrt(ms * mx)))
        whs = jnp.asarray(whs, jnp.float32)  # [P,2]
        P = whs.shape[0]
        cxg = jnp.broadcast_to(cx[None, :, None], (H, W, P))
        cyg = jnp.broadcast_to(cy[:, None, None], (H, W, P))
        w2 = whs[:, 0][None, None, :] / 2.0
        h2 = whs[:, 1][None, None, :] / 2.0
        out = jnp.stack([(cxg - w2) / IW, (cyg - h2) / IH,
                         (cxg + w2) / IW, (cyg + h2) / IH], axis=-1)
        if clip:
            out = jnp.clip(out, 0.0, 1.0)
        var = jnp.broadcast_to(jnp.asarray(variance, jnp.float32),
                               out.shape)
        return out, var

    return apply_op("prior_box", fn, (input, image), {})


# ---- box coder ----

def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    """Ref: box_coder_op.cc — encode targets against priors or decode
    offsets back to boxes.  prior_box_var may be a per-box [M,4] tensor
    or a 4-element broadcast list; for decode, target_box may be
    [N,M,4] with priors broadcast along `axis` (0 or 1)."""
    norm = 0.0 if box_normalized else 1.0
    if isinstance(prior_box_var, (list, tuple)):
        prior_box_var = Tensor(np.asarray(prior_box_var, np.float32))

    def fn(pb, pbv, tb):
        if pbv.ndim == 1:
            pbv = jnp.broadcast_to(pbv, pb.shape)
        pw = pb[:, 2] - pb[:, 0] + norm
        ph = pb[:, 3] - pb[:, 1] + norm
        pcx = pb[:, 0] + pw / 2
        pcy = pb[:, 1] + ph / 2
        if code_type == "encode_center_size":
            tw = tb[:, 2] - tb[:, 0] + norm
            th = tb[:, 3] - tb[:, 1] + norm
            tcx = tb[:, 0] + tw / 2
            tcy = tb[:, 1] + th / 2
            dx = (tcx - pcx) / pw / pbv[:, 0]
            dy = (tcy - pcy) / ph / pbv[:, 1]
            dw = jnp.log(tw / pw) / pbv[:, 2]
            dh = jnp.log(th / ph) / pbv[:, 3]
            return jnp.stack([dx, dy, dw, dh], axis=-1)
        # decode: `axis` IS the broadcast dim of target_box (reference:
        # axis=0 + TargetBox [N,M,4] + PriorBox [M,4] -> expand dim 0)
        if tb.ndim == 3:
            exp = axis
            pw_, ph_ = jnp.expand_dims(pw, exp), jnp.expand_dims(ph, exp)
            pcx_, pcy_ = jnp.expand_dims(pcx, exp), jnp.expand_dims(pcy, exp)
            pbv_ = jnp.expand_dims(pbv, exp)
        else:
            pw_, ph_, pcx_, pcy_, pbv_ = pw, ph, pcx, pcy, pbv
        dcx = pbv_[..., 0] * tb[..., 0] * pw_ + pcx_
        dcy = pbv_[..., 1] * tb[..., 1] * ph_ + pcy_
        dw = jnp.exp(pbv_[..., 2] * tb[..., 2]) * pw_
        dh = jnp.exp(pbv_[..., 3] * tb[..., 3]) * ph_
        return jnp.stack([dcx - dw / 2, dcy - dh / 2,
                          dcx + dw / 2 - norm, dcy + dh / 2 - norm],
                         axis=-1)

    return apply_op("box_coder", fn, (prior_box, prior_box_var, target_box),
                    {})


def detection_map(detect_res, gt_label, gt_box, detect_splits=None,
                  gt_splits=None, class_num=None, overlap_threshold=0.5,
                  evaluate_difficult=True, ap_version="integral"):
    """VOC mean-average-precision metric (detection_map_op.cc).

    Host-side numpy metric op (like edit_distance/chunk_eval — the
    reference also runs it on CPU):

    - detect_res: (D, 6) rows [label, score, x1, y1, x2, y2]
    - gt_label: (G,) int labels; gt_box: (G, 4) boxes
    - detect_splits / gt_splits: per-image row counts (the LoD offsets of
      the reference); one image when omitted
    - ap_version: "integral" (VOC2010 AUC) or "11point"

    Returns a scalar float32 Tensor (the mAP in [0, 1]).
    """
    from ..core.tensor import Tensor, to_tensor

    det = np.asarray(_raw(detect_res), np.float64).reshape(-1, 6)
    gl = np.asarray(_raw(gt_label)).reshape(-1).astype(np.int64)
    gb = np.asarray(_raw(gt_box), np.float64).reshape(-1, 4)
    d_splits = (np.asarray(_raw(detect_splits)).reshape(-1).astype(int)
                if detect_splits is not None else np.array([det.shape[0]]))
    g_splits = (np.asarray(_raw(gt_splits)).reshape(-1).astype(int)
                if gt_splits is not None else np.array([gb.shape[0]]))
    d_off = np.concatenate([[0], np.cumsum(d_splits)])
    g_off = np.concatenate([[0], np.cumsum(g_splits)])
    n_img = len(d_splits)
    classes = (range(class_num) if class_num is not None
               else sorted(set(gl.tolist())))

    def iou(a, b):
        ix1 = max(a[0], b[0])
        iy1 = max(a[1], b[1])
        ix2 = min(a[2], b[2])
        iy2 = min(a[3], b[3])
        iw, ih = max(ix2 - ix1, 0.0), max(iy2 - iy1, 0.0)
        inter = iw * ih
        ua = ((a[2] - a[0]) * (a[3] - a[1])
              + (b[2] - b[0]) * (b[3] - b[1]) - inter)
        return inter / ua if ua > 0 else 0.0

    aps = []
    for c in classes:
        # gather per-image detections/gts of class c
        scores, tps = [], []
        n_pos = 0
        for i in range(n_img):
            gt_rows = [j for j in range(g_off[i], g_off[i + 1])
                       if gl[j] == c]
            n_pos += len(gt_rows)
            dets = [j for j in range(d_off[i], d_off[i + 1])
                    if int(det[j, 0]) == c]
            dets.sort(key=lambda j: -det[j, 1])
            matched = set()
            for j in dets:
                best, best_iou = None, overlap_threshold
                for g in gt_rows:
                    v = iou(det[j, 2:6], gb[g])
                    if v >= best_iou:
                        best, best_iou = g, v
                scores.append(det[j, 1])
                if best is not None and best not in matched:
                    matched.add(best)
                    tps.append(1.0)
                else:
                    tps.append(0.0)
        if n_pos == 0:
            continue
        order = np.argsort(-np.asarray(scores)) if scores else []
        tp = np.asarray(tps)[order] if len(tps) else np.zeros((0,))
        cum_tp = np.cumsum(tp)
        recall = cum_tp / n_pos
        precision = cum_tp / (np.arange(len(tp)) + 1) if len(tp) \
            else np.zeros((0,))
        if ap_version == "11point":
            ap = 0.0
            for t in np.linspace(0, 1, 11):
                p = precision[recall >= t].max() if (recall >= t).any() \
                    else 0.0
                ap += p / 11.0
        else:  # integral: sum precision deltas at each TP
            ap = 0.0
            prev_r = 0.0
            for k in range(len(tp)):
                if tp[k]:
                    ap += precision[k] * (recall[k] - prev_r)
                    prev_r = recall[k]
        aps.append(ap)
    out = to_tensor(np.asarray(np.mean(aps) if aps else 0.0, np.float32))
    out.stop_gradient = True
    return out


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0,
                  dilation=1, deformable_groups=1, groups=1, mask=None,
                  name=None):
    """paddle.vision.ops.deform_conv2d (DCNv1 when mask is None, DCNv2
    with mask): re-export of the deformable_conv kernel."""
    from ..ops.vision_extra import deformable_conv

    return deformable_conv(x, offset, weight, mask, stride, padding,
                           dilation, deformable_groups, groups, 1, bias)


def _deform_conv2d_layer_cls():
    from ..nn.layer import Layer

    class _DeformConv2D(Layer):
        """Layer form of deform_conv2d (vision/ops.py DeformConv2D); the
        caller supplies offset (and optional mask) at forward time."""

        def __init__(self, in_channels, out_channels, kernel_size,
                     stride=1, padding=0, dilation=1,
                     deformable_groups=1, groups=1, weight_attr=None,
                     bias_attr=None):
            super().__init__()
            from ..ops.nn_ops import _pair

            k = _pair(kernel_size)
            self._stride = stride
            self._padding = padding
            self._dilation = dilation
            self._deformable_groups = deformable_groups
            self._groups = groups
            self.weight = self.create_parameter(
                [out_channels, in_channels // groups, k[0], k[1]],
                attr=weight_attr)
            self.bias = (None if bias_attr is False else
                         self.create_parameter([out_channels],
                                               attr=bias_attr,
                                               is_bias=True))

        def forward(self, x, offset, mask=None):
            return deform_conv2d(
                x, offset, self.weight, self.bias, self._stride,
                self._padding, self._dilation, self._deformable_groups,
                self._groups, mask)

    return _DeformConv2D


_DEFORM_CLS = None


def _get_deform_cls():
    global _DEFORM_CLS
    if _DEFORM_CLS is None:
        _DEFORM_CLS = _deform_conv2d_layer_cls()
        _DEFORM_CLS.__name__ = "DeformConv2D"
    return _DEFORM_CLS


class _DeformMeta(type):
    def __new__(mcls, name, bases, ns):
        # subclassing the facade swaps in the REAL layer class as the
        # base, so user subclasses are ordinary Layer subclasses with
        # their own overrides intact
        if any(getattr(b, "_is_deform_facade", False) for b in bases):
            real_bases = tuple(
                _get_deform_cls() if getattr(b, "_is_deform_facade", False)
                else b for b in bases)
            return type(name, real_bases, ns)
        return super().__new__(mcls, name, bases, ns)

    def __call__(cls, *args, **kwargs):
        return _get_deform_cls()(*args, **kwargs)

    def __instancecheck__(cls, obj):
        return isinstance(obj, _get_deform_cls())


class DeformConv2D(metaclass=_DeformMeta):
    """Stable public type: instances share ONE lazily-built Layer
    subclass, so type(a) is type(b) and isinstance checks work;
    subclassing substitutes the real layer class as the base."""

    _is_deform_facade = True


def yolo_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
              ignore_thresh, downsample_ratio, gt_score=None,
              use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (yolov3_loss_op.cc): decode predictions on the
    grid, match ground-truth boxes to best-IoU anchors, and sum the
    localization + objectness + classification terms per image."""
    import jax.numpy as jnp

    from ..core.registry import apply_op

    an = [(anchors[i], anchors[i + 1]) for i in range(0, len(anchors), 2)]
    mask_an = [an[i] for i in anchor_mask]
    A = len(mask_an)

    def fn(xv, gb, gl, *gs):
        N, C, H, W = xv.shape
        att = 5 + class_num
        p = xv.reshape(N, A, att, H, W)
        tx, ty = p[:, :, 0], p[:, :, 1]
        tw, th = p[:, :, 2], p[:, :, 3]
        tobj = p[:, :, 4]
        tcls = p[:, :, 5:]
        gx = jnp.arange(W).reshape(1, 1, 1, W)
        gy = jnp.arange(H).reshape(1, 1, H, 1)
        in_w = W * downsample_ratio
        in_h = H * downsample_ratio
        # decoded centers/sizes in [0,1] image units
        px = (jax.nn.sigmoid(tx) * scale_x_y
              - 0.5 * (scale_x_y - 1.0) + gx) / W
        py = (jax.nn.sigmoid(ty) * scale_x_y
              - 0.5 * (scale_x_y - 1.0) + gy) / H
        aw = jnp.asarray([a[0] for a in mask_an]).reshape(1, A, 1, 1)
        ah = jnp.asarray([a[1] for a in mask_an]).reshape(1, A, 1, 1)
        pw = jnp.exp(jnp.clip(tw, -10, 10)) * aw / in_w
        ph = jnp.exp(jnp.clip(th, -10, 10)) * ah / in_h

        B = gb.shape[1]
        score = gs[0] if gs else None  # per-gt mixup weights
        losses = jnp.zeros((N,), xv.dtype)
        obj_target = jnp.zeros_like(tobj)
        obj_mask = jnp.ones_like(tobj)
        for b in range(B):
            bx, by, bw, bh = gb[:, b, 0], gb[:, b, 1], gb[:, b, 2], gb[:, b, 3]
            valid = (bw > 1e-6).astype(xv.dtype)  # padded gt rows
            # best anchor for this gt by IoU of (w, h) against anchors
            ious = []
            for (w_a, h_a) in an:
                wa, ha = w_a / in_w, h_a / in_h
                inter = jnp.minimum(bw, wa) * jnp.minimum(bh, ha)
                union = bw * bh + wa * ha - inter
                ious.append(inter / jnp.maximum(union, 1e-10))
            best = jnp.argmax(jnp.stack(ious), axis=0)  # (N,)
            gi = jnp.clip((bx * W).astype(jnp.int32), 0, W - 1)
            gj = jnp.clip((by * H).astype(jnp.int32), 0, H - 1)
            for ai, a_global in enumerate(anchor_mask):
                sel = (best == a_global).astype(xv.dtype) * valid  # (N,)
                if score is not None:
                    sel = sel * score[:, b]
                tx_t = bx * W - gi
                ty_t = by * H - gj
                tw_t = jnp.log(jnp.maximum(
                    bw * in_w / an[a_global][0], 1e-9))
                th_t = jnp.log(jnp.maximum(
                    bh * in_h / an[a_global][1], 1e-9))
                nidx = jnp.arange(N)
                scale = 2.0 - bw * bh  # small boxes weigh more
                px_b = jax.nn.sigmoid(tx[nidx, ai, gj, gi])
                py_b = jax.nn.sigmoid(ty[nidx, ai, gj, gi])
                loc = (jnp.square(px_b - tx_t) + jnp.square(py_b - ty_t)
                       + jnp.square(tw[nidx, ai, gj, gi] - tw_t)
                       + jnp.square(th[nidx, ai, gj, gi] - th_t))
                losses = losses + sel * scale * loc
                cls_logit = tcls[nidx, ai, :, gj, gi]
                onehot = jax.nn.one_hot(gl[:, b], class_num)
                if use_label_smooth:
                    delta = 1.0 / max(class_num, 1)
                    onehot = onehot * (1 - delta) + delta / class_num
                bce = jnp.sum(
                    jnp.maximum(cls_logit, 0) - cls_logit * onehot
                    + jnp.log1p(jnp.exp(-jnp.abs(cls_logit))), axis=-1)
                losses = losses + sel * bce
                obj_target = obj_target.at[nidx, ai, gj, gi].max(sel)
                # ignore high-IoU non-best predictions
                iou_pred = _box_iou_single(
                    px[nidx, ai, gj, gi], py[nidx, ai, gj, gi],
                    pw[nidx, ai, gj, gi], ph[nidx, ai, gj, gi],
                    bx, by, bw, bh)
                ignore = ((iou_pred > ignore_thresh) * (1 - sel) * valid)
                obj_mask = obj_mask.at[nidx, ai, gj, gi].min(1 - ignore)
        obj_bce = (jnp.maximum(tobj, 0) - tobj * obj_target
                   + jnp.log1p(jnp.exp(-jnp.abs(tobj))))
        keep = jnp.maximum(obj_mask, obj_target)
        losses = losses + jnp.sum(obj_bce * keep, axis=(1, 2, 3))
        return losses

    args = (x, gt_box, gt_label) + ((gt_score,)
                                    if gt_score is not None else ())
    return apply_op("yolov3_loss", fn, args, {})


def _box_iou_single(x1, y1, w1, h1, x2, y2, w2, h2):
    import jax.numpy as jnp

    l1, r1 = x1 - w1 / 2, x1 + w1 / 2
    t1, b1 = y1 - h1 / 2, y1 + h1 / 2
    l2, r2 = x2 - w2 / 2, x2 + w2 / 2
    t2, b2 = y2 - h2 / 2, y2 + h2 / 2
    iw = jnp.maximum(jnp.minimum(r1, r2) - jnp.maximum(l1, l2), 0.0)
    ih = jnp.maximum(jnp.minimum(b1, b2) - jnp.maximum(t1, t2), 0.0)
    inter = iw * ih
    return inter / jnp.maximum(w1 * h1 + w2 * h2 - inter, 1e-10)


def decode_jpeg(x, mode="unchanged", name=None):
    raise NotImplementedError(
        "decode_jpeg is GPU-nvjpeg in the reference and intentionally "
        "absent (docs/ABSENT.md); decode host-side via "
        "paddle_tpu.vision.image_load")


def read_file(filename, name=None):
    raise NotImplementedError(
        "read_file is intentionally absent (docs/ABSENT.md); read bytes "
        "host-side (io.dataset reads files directly)")
